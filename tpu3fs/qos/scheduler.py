"""Weighted-fair scheduling of storage IO by traffic class, with
NESTED per-tenant fairness inside each class.

``WeightedFairQueue`` replaces the single FIFO inside each per-target
update worker (storage/update_worker.py) with a two-level stride
scheduler:

1. ACROSS CLASSES (unchanged semantics): each class carries a virtual
   time advancing by cost/weight per pop, and the nonempty class with
   the smallest virtual time runs next — foreground read/write
   (weight 8) outweighs resync/EC-rebuild (2) and migration/GC (1)
   exactly in proportion, work-conserving.
2. WITHIN A CLASS (tpu3fs/tenant): each class holds one FIFO LANE per
   tenant, drained by the same stride rule with the TENANT's weight
   (quota table, tenant/quota.py). Two ``fg`` tenants therefore share
   the class's capacity weight:weight instead of FIFO luck — the greedy
   client that used to starve its same-class peers now only starves
   itself.

Ordering: within one (class, tenant) lane order stays FIFO, so a
client's own writes to one chunk apply in arrival order exactly as
before (a single writer is a single tenant). CROSS-tenant writes to one
chunk carry no ordering promise — they are concurrent clients, ordered
by the engine's version algebra like cross-class writes always were.

Shedding happens at push: a full queue sheds any class, and a
share-bounded class (every background class plus the foreground-weighted
``dataload``/``kvcache``, qos.core.SHARE_BOUNDED_CLASSES) is shed
earlier when it already occupies its configured share of the queue — the
bounded-queue-depth property the overload stress test asserts. A shed
returns the retry-after hint for the OVERLOADED reply.
"""

from __future__ import annotations

import collections
from typing import Dict, Optional, Tuple

from tpu3fs.qos.core import (
    CLASS_ATTRS,
    SHARE_BOUNDED_CLASSES,
    QosConfig,
    TrafficClass,
)
from tpu3fs.tenant.identity import DEFAULT_TENANT


class WfqPolicy:
    """Live view of scheduler knobs over a (hot-updated) QosConfig.

    Reads go straight to the config attributes, so a mgmtd config push
    changes weights/shares/hints for every queue sharing the policy
    without rebuilding anything. Tenant weights come from the process
    tenant registry (tenant/quota.py) — the same hot push that retunes
    quotas retunes lane weights."""

    def __init__(self, config: Optional[QosConfig] = None):
        self.config = config if config is not None else QosConfig()

    def enabled(self) -> bool:
        return bool(self.config.enabled)

    def weight(self, tclass: TrafficClass) -> int:
        return max(1, int(getattr(self.config, CLASS_ATTRS[tclass]).weight))

    def tenant_weight(self, tenant: str) -> int:
        from tpu3fs.tenant.quota import registry

        return registry().weight(tenant or DEFAULT_TENANT)

    def queue_share(self, tclass: TrafficClass) -> float:
        return float(getattr(self.config, CLASS_ATTRS[tclass]).queue_share)

    def retry_after_ms(self) -> int:
        return int(self.config.shed_retry_after_ms)

    # observation hook: the QosManager overrides this to feed the
    # queue-wait distribution recorder; the default is free
    def record_wait(self, tclass: TrafficClass, wait_s: float) -> None:
        pass


class _ClassQueue:
    """One class's nested tenant lanes: FIFO per tenant + per-tenant
    stride state. Not locked — the WeightedFairQueue's owner serializes
    (see below)."""

    __slots__ = ("lanes", "vtime", "depth")

    def __init__(self):
        self.lanes: Dict[str, collections.deque] = {}
        self.vtime: Dict[str, float] = {}
        self.depth = 0

    def push(self, item, tenant: str) -> None:
        lane = self.lanes.get(tenant)
        if lane is None:
            lane = self.lanes[tenant] = collections.deque()
        if tenant not in self.vtime:
            # a newly-active lane starts at the current minimum virtual
            # time among active lanes: no banked credit from idling
            self.vtime[tenant] = min(
                (self.vtime[t] for t, q in self.lanes.items()
                 if q and t in self.vtime), default=0.0)
        lane.append(item)
        self.depth += 1

    def next_tenant(self) -> Optional[str]:
        """The nonempty lane with least virtual time (stride pick)."""
        best = None
        for tenant, lane in self.lanes.items():
            if not lane:
                continue
            vt = self.vtime.get(tenant, 0.0)
            if best is None or vt < best[1]:
                best = (tenant, vt)
        return best[0] if best is not None else None

    def tenants_by_vtime(self):
        active = [(self.vtime.get(t, 0.0), t)
                  for t, q in self.lanes.items() if q]
        active.sort()
        return [t for _, t in active]

    def pop_lane(self, tenant: str, tenant_weight: int):
        lane = self.lanes[tenant]
        item = lane.popleft()
        self.depth -= 1
        cost = getattr(item, "cost", 1)
        self.vtime[tenant] = (self.vtime.get(tenant, 0.0)
                              + cost / max(1, tenant_weight))
        return item


class WeightedFairQueue:
    """Per-class tenant-laned FIFOs + two-level stride-scheduling pop.
    NOT internally locked — the owning update worker already serializes
    access under its condition variable, exactly like the deque it
    replaces."""

    def __init__(self, policy: Optional[WfqPolicy] = None,
                 cap: int = 512):
        self.policy = policy or WfqPolicy()
        self.cap = cap
        self._queues: Dict[TrafficClass, _ClassQueue] = {}
        self._vtime: Dict[TrafficClass, float] = {}
        self._depth = 0

    def __len__(self) -> int:
        return self._depth

    def class_depths(self) -> Dict[TrafficClass, int]:
        return {tc: q.depth for tc, q in self._queues.items() if q.depth}

    def tenant_depths(self) -> Dict[Tuple[TrafficClass, str], int]:
        """Live (class, tenant) -> queued jobs (observability)."""
        out: Dict[Tuple[TrafficClass, str], int] = {}
        for tc, q in self._queues.items():
            for tenant, lane in q.lanes.items():
                if lane:
                    out[(tc, tenant)] = len(lane)
        return out

    def try_push(self, item, tclass: TrafficClass,
                 tenant: str = DEFAULT_TENANT) -> Optional[int]:
        """Append `item` to its (class, tenant) lane; -> None when
        accepted, else the retry-after hint (ms) for the shed reply."""
        base = self.policy.retry_after_ms()
        if self._depth >= self.cap:
            # full queue: scale the hint by how oversubscribed we are so
            # a deep backlog spreads retries wider than a grazing overflow
            return base * 2
        q = self._queues.get(tclass)
        if tclass in SHARE_BOUNDED_CLASSES:
            share = max(1, int(self.cap * self.policy.queue_share(tclass)))
            if q is not None and q.depth >= share:
                return base
        if q is None:
            q = self._queues[tclass] = _ClassQueue()
        if tclass not in self._vtime:
            # a newly-active class starts at the current minimum virtual
            # time: no banked credit from its idle period
            self._vtime[tclass] = min(
                (self._vtime[c] for c, qq in self._queues.items()
                 if qq.depth and c in self._vtime), default=0.0)
        q.push(item, tenant or DEFAULT_TENANT)
        self._depth += 1
        return None

    def _advance_class(self, tclass: TrafficClass, item) -> None:
        cost = getattr(item, "cost", 1)
        self._vtime[tclass] = (self._vtime.get(tclass, 0.0)
                               + cost / self.policy.weight(tclass))

    def pop(self) -> Optional[Tuple[object, TrafficClass]]:
        """Pop the head of the stride-picked tenant lane of the nonempty
        class with least virtual time."""
        best = None
        for tc, q in self._queues.items():
            if not q.depth:
                continue
            vt = self._vtime.get(tc, 0.0)
            if best is None or vt < best[1]:
                best = (tc, vt)
        if best is None:
            return None
        tc, _vt = best
        q = self._queues[tc]
        tenant = q.next_tenant()
        assert tenant is not None
        item = q.pop_lane(tenant, self.policy.tenant_weight(tenant))
        self._depth -= 1
        self._advance_class(tc, item)
        return item, tc

    def pop_matching(self, tclass: TrafficClass, pred) -> Optional[object]:
        """Pop a lane-HEAD job of this class if pred(head) — the
        coalescing probe. Lanes are tried in virtual-time order, so the
        stride-preferred tenant coalesces first; only lane heads are
        eligible, so per-(class, tenant) FIFO order is untouched."""
        q = self._queues.get(tclass)
        if q is None or not q.depth:
            return None
        for tenant in q.tenants_by_vtime():
            lane = q.lanes[tenant]
            if lane and pred(lane[0]):
                item = q.pop_lane(tenant,
                                  self.policy.tenant_weight(tenant))
                self._depth -= 1
                self._advance_class(tclass, item)
                return item
        return None

    def drain(self):
        """Pop everything (stop path); class order, FIFO within lane."""
        out = []
        for q in self._queues.values():
            for lane in q.lanes.values():
                while lane:
                    out.append(lane.popleft())
            q.depth = 0
        self._depth = 0
        return out
