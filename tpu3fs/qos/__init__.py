"""QoS subsystem: admission control, weighted-fair IO scheduling, shedding.

The reference gets crude isolation from per-disk worker pools and RDMA
transmission limits (SURVEY §2.3 UpdateWorker/AioReadWorker, IBSocket); a
multi-tenant tpu3fs makes it a first-class, hot-configurable layer:

- ``core``: the traffic-class taxonomy, context-local tagging, token
  buckets + concurrency gates, the declarative ``QosConfig`` tree and the
  ``AdmissionController`` enforced in RPC dispatch (tpu3fs/rpc/net.py and,
  as a cheap ceiling, native/rpc_net.cpp).
- ``scheduler``: weighted-fair (stride) scheduling of storage IO by
  traffic class, threaded through the per-target update workers.
- ``manager``: per-service bundle (admission + policy + recorders) wired
  into StorageService and the service binaries.

Overload surfaces as the retryable ``Code.OVERLOADED`` carrying a server
retry-after hint (reply field + envelope message), honored by
client/storage_client.py with jittered backoff instead of blind retry.
"""

from tpu3fs.qos.core import (
    BACKGROUND_CLASSES,
    SHARE_BOUNDED_CLASSES,
    AdmissionController,
    ConcurrencyGate,
    QosConfig,
    TokenBucket,
    TrafficClass,
    class_from_flags,
    class_to_flags,
    current_class,
    default_class_for,
    format_retry_after,
    infer_write_class,
    retry_after_ms_of,
    tagged,
)
from tpu3fs.qos.manager import QosManager
from tpu3fs.qos.scheduler import WeightedFairQueue, WfqPolicy

__all__ = [
    "AdmissionController",
    "BACKGROUND_CLASSES",
    "ConcurrencyGate",
    "QosConfig",
    "QosManager",
    "SHARE_BOUNDED_CLASSES",
    "TokenBucket",
    "TrafficClass",
    "WeightedFairQueue",
    "WfqPolicy",
    "class_from_flags",
    "class_to_flags",
    "current_class",
    "default_class_for",
    "format_retry_after",
    "infer_write_class",
    "retry_after_ms_of",
    "tagged",
]
