"""QosManager: one service's QoS bundle (admission + policy + recorders).

The storage binary (and the test fabric) hand a QosManager to
StorageService; it carries

- the ``AdmissionController`` consulted at read/write entry (shared with
  the RPC server when both enforce, so tokens are charged once),
- the ``WfqPolicy`` every per-target update worker schedules by,
- per-class monitor recorders: queue-depth gauges and a queue-wait
  distribution on top of the controller's admit/shed counters,

all driven by ONE ``QosConfig`` tree so a single mgmtd config push
retunes admission, scheduling and shedding together, live.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from tpu3fs.qos.core import (
    CLASS_ATTRS,
    AdmissionController,
    QosConfig,
    TrafficClass,
)
from tpu3fs.qos.scheduler import WfqPolicy


class _ManagedPolicy(WfqPolicy):
    """WfqPolicy that feeds the manager's queue-wait recorder."""

    def __init__(self, config: QosConfig, manager: "QosManager"):
        super().__init__(config)
        self._manager = manager

    def record_wait(self, tclass: TrafficClass, wait_s: float) -> None:
        self._manager.record_wait(tclass, wait_s)


class QosManager:
    def __init__(self, config: Optional[QosConfig] = None,
                 tags: Optional[Dict[str, str]] = None,
                 admission: Optional[AdmissionController] = None):
        from tpu3fs.monitor.recorder import (
            DistributionRecorder,
            ValueRecorder,
        )

        if admission is not None:
            # share the binary's RPC-dispatch controller: tokens for one
            # op are charged once, wherever the op entered
            self.admission = admission
            self.config = config if config is not None else admission.config
        else:
            self.config = config if config is not None else QosConfig()
            self.admission = AdmissionController(self.config, tags)
        self.policy = _ManagedPolicy(self.config, self)
        base = dict(tags or {})
        self._lock = threading.Lock()
        self._depth_gauges: Dict[TrafficClass, ValueRecorder] = {}
        self._wait_recs: Dict[TrafficClass, DistributionRecorder] = {}
        for tc, attr in CLASS_ATTRS.items():
            ctags = {**base, "class": attr}
            self._depth_gauges[tc] = ValueRecorder("qos.queue_depth", ctags)
            self._wait_recs[tc] = DistributionRecorder("qos.queue_wait_us",
                                                       ctags)

    # -- service-entry admission -----------------------------------------
    def try_admit(self, service: str, method: str,
                  tclass: Optional[TrafficClass], cost: float = 1.0,
                  *, tenant: Optional[str] = None):
        """(lease, None) | (None, retry_after_ms); see
        AdmissionController.try_admit."""
        return self.admission.try_admit(service, method, tclass, cost,
                                        tenant=tenant)

    # -- scheduler plumbing ----------------------------------------------
    def record_wait(self, tclass: TrafficClass, wait_s: float) -> None:
        rec = self._wait_recs.get(tclass)
        if rec is not None:
            rec.record(wait_s * 1e6)

    def record_depths(self, depths: Dict[TrafficClass, int]) -> None:
        """Fold one queue's per-class depths into the gauges (called by
        the service on its snapshot path; gauges report last-set)."""
        for tc, gauge in self._depth_gauges.items():
            gauge.set(float(depths.get(tc, 0)))

    def snapshot(self) -> dict:
        return {
            "enabled": bool(self.config.enabled),
            "classes": self.admission.snapshot(),
        }
