"""Reed-Solomon RS(k, m) erasure coding as batched TPU bit-plane matmuls.

Design: a systematic Cauchy generator [I_k ; C] over GF(2^8). Encode/decode
are GF(2^8) matrix products, which we lower to the MXU by expanding the small
coefficient matrix into its (8m x 8k) GF(2) bit matrix and multiplying
bit-planes of the data as int8 (accumulate int32, reduce mod 2) — the
"bit-sliced XOR formulation" TPUs want, since they have no carry-less multiply.

The reference replicates via CRAQ instead of RS (docs/design_notes.md "Data
replication"); RS(k,m) is the added capability from BASELINE.json, and "EC"
exists in the reference only as a chain-table type in the placement solver
(deploy/data_placement/src/model/data_placement.py:30). The encode path plugs
into storage targets behind the same engine switch the reference uses for its
chunk engines (src/storage/store/StorageTarget.h:162).

Layouts: data shards are (..., k, S) uint8; parity (..., m, S); a "shard set"
is the concatenation (..., k+m, S). S is the shard size in bytes.
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu3fs.ops.bitops import pack_bits, unpack_bits
from tpu3fs.ops.gf256 import GF


def _bit_matmul(A_bits: jnp.ndarray, data: jnp.ndarray) -> jnp.ndarray:
    """Apply an (8m, 8k) GF(2) matrix to uint8 data (..., k, S) -> (..., m, S)."""
    bits = unpack_bits(data)  # (..., 8k, S) int8
    acc = jnp.einsum(
        "ij,...js->...is", A_bits, bits, preferred_element_type=jnp.int32
    )
    return pack_bits(acc & 1)


class RSCode:
    """RS(k, m): k data shards, m parity shards, tolerates any m erasures."""

    def __init__(self, k: int, m: int):
        if k < 1 or m < 0 or k + m > 256:
            raise ValueError(f"bad RS parameters k={k} m={m}")
        self.k = k
        self.m = m
        self.parity_matrix = GF.cauchy_parity_matrix(m, k)  # (m, k) GF(2^8)
        self.generator = np.concatenate(
            [np.eye(k, dtype=np.uint8), self.parity_matrix], axis=0
        )  # (k+m, k)
        self._parity_bits = jnp.asarray(
            GF.expand_to_bits(self.parity_matrix).astype(np.int8)
        )
        self._encode_jit = jax.jit(self._encode)
        # per-instance caches keyed on (present, lost) — instance-held so the
        # device matrices/compiled fns die with the RSCode object
        self._reconstruct_mats: dict = {}
        self._reconstruct_fns: dict = {}

    # -- encode ------------------------------------------------------------
    def _encode(self, data: jnp.ndarray) -> jnp.ndarray:
        return _bit_matmul(self._parity_bits, data)

    def encode(self, data: jnp.ndarray) -> jnp.ndarray:
        """(..., k, S) uint8 data -> (..., m, S) parity. Jitted."""
        assert data.shape[-2] == self.k, (data.shape, self.k)
        return self._encode_jit(data)

    def encode_np(self, data: np.ndarray) -> np.ndarray:
        """Gold-path numpy encode via GF tables (slow, exact)."""
        data = np.asarray(data, dtype=np.uint8)
        *lead, k, s = data.shape
        assert k == self.k
        flat = data.reshape(-1, k, s)
        out = np.zeros((flat.shape[0], self.m, s), dtype=np.uint8)
        for i in range(self.m):
            for j in range(k):
                out[:, i, :] ^= GF.mul(self.parity_matrix[i, j], flat[:, j, :])
        return out.reshape(*lead, self.m, s)

    # -- decode ------------------------------------------------------------
    def _reconstruct_matrix(
        self, present: Tuple[int, ...], lost: Tuple[int, ...]
    ) -> np.ndarray:
        """GF matrix R (len(lost), k) with lost = R @ shards[present]."""
        key = (present, lost)
        cached = self._reconstruct_mats.get(key)
        if cached is not None:
            return cached
        assert len(present) == self.k
        sub = self.generator[list(present), :]  # (k, k)
        inv = GF.mat_inv(sub)  # data = inv @ present
        rows = []
        for idx in lost:
            # row of the generator for the lost shard, composed with inv
            rows.append(GF.matmul(self.generator[idx : idx + 1, :], inv)[0])
        R = np.stack(rows, axis=0)
        self._reconstruct_mats[key] = R
        return R

    def reconstruct_fn(
        self, present_idx: Sequence[int], lost_idx: Sequence[int]
    ):
        """Jitted fn mapping (..., k, S) surviving shards -> (..., lost, S).

        The single decode entry point: reconstruct() and the distributed
        rebuild path (tpu3fs.parallel.rebuild) both go through here, so a
        kernel swap (e.g. Pallas) lands in one place.
        """
        present = tuple(int(i) for i in present_idx)
        lost = tuple(int(i) for i in lost_idx)
        key = (present, lost)
        fn = self._reconstruct_fns.get(key)
        if fn is None:
            R = self._reconstruct_matrix(present, lost)
            R_bits = jnp.asarray(GF.expand_to_bits(R).astype(np.int8))
            fn = jax.jit(functools.partial(_bit_matmul, R_bits))
            self._reconstruct_fns[key] = fn
        return fn

    def reconstruct(
        self,
        present_idx: Sequence[int],
        lost_idx: Sequence[int],
        present_shards: jnp.ndarray,
    ) -> jnp.ndarray:
        """Rebuild lost shards from any k surviving shards.

        present_idx: k shard indices in [0, k+m) matching present_shards rows
        present_shards: (..., k, S) uint8
        returns (..., len(lost_idx), S) uint8
        """
        return self.reconstruct_fn(present_idx, lost_idx)(present_shards)

    def reconstruct_np(
        self,
        present_idx: Sequence[int],
        lost_idx: Sequence[int],
        present_shards: np.ndarray,
    ) -> np.ndarray:
        """Gold-path numpy reconstruction."""
        R = self._reconstruct_matrix(
            tuple(int(i) for i in present_idx), tuple(int(i) for i in lost_idx)
        )
        shards = np.asarray(present_shards, dtype=np.uint8)
        *lead, k, s = shards.shape
        flat = shards.reshape(-1, k, s)
        out = np.zeros((flat.shape[0], R.shape[0], s), dtype=np.uint8)
        for i in range(R.shape[0]):
            for j in range(k):
                out[:, i, :] ^= GF.mul(R[i, j], flat[:, j, :])
        return out.reshape(*lead, R.shape[0], s)

    def __repr__(self) -> str:  # pragma: no cover
        return f"RSCode(k={self.k}, m={self.m})"
