"""Reed-Solomon RS(k, m) erasure coding as batched TPU bit-plane matmuls.

Design: a systematic Cauchy generator [I_k ; C] over GF(2^8). Encode/decode
are GF(2^8) matrix products, which we lower to the MXU by expanding the small
coefficient matrix into its (8m x 8k) GF(2) bit matrix and multiplying
bit-planes of the data as int8 (accumulate int32, reduce mod 2) — the
"bit-sliced XOR formulation" TPUs want, since they have no carry-less multiply.

The reference replicates via CRAQ instead of RS (docs/design_notes.md "Data
replication"); RS(k,m) is the added capability from BASELINE.json, and "EC"
exists in the reference only as a chain-table type in the placement solver
(deploy/data_placement/src/model/data_placement.py:30). The encode path plugs
into storage targets behind the same engine switch the reference uses for its
chunk engines (src/storage/store/StorageTarget.h:162).

Layouts: data shards are (..., k, S) uint8; parity (..., m, S); a "shard set"
is the concatenation (..., k+m, S). S is the shard size in bytes.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu3fs.ops.bitops import pack_bits, unpack_bits
from tpu3fs.ops.gf256 import GF


def _bit_matmul(A_bits: jnp.ndarray, data: jnp.ndarray) -> jnp.ndarray:
    """Apply an (8m, 8k) GF(2) matrix to uint8 data (..., k, S) -> (..., m, S)."""
    bits = unpack_bits(data)  # (..., 8k, S) int8
    acc = jnp.einsum(
        "ij,...js->...is", A_bits, bits, preferred_element_type=jnp.int32
    )
    return pack_bits(acc & 1)


def _xor_reduce_shards(shards: jnp.ndarray) -> jnp.ndarray:
    """(..., k, S) uint8 -> (..., 1, S): XOR of the shard rows."""
    out = shards[..., 0, :]
    for j in range(1, shards.shape[-2]):
        out = out ^ shards[..., j, :]
    return out[..., None, :]


class RSCode:
    """RS(k, m): k data shards, m parity shards, tolerates any m erasures."""

    def __init__(self, k: int, m: int):
        if k < 1 or m < 0 or k + m > 256:
            raise ValueError(f"bad RS parameters k={k} m={m}")
        self.k = k
        self.m = m
        cauchy = GF.cauchy_parity_matrix(m, k)  # (m, k) GF(2^8)
        # Column-normalize so parity row 0 is all-ones: C'_ij = C_ij / C_0j.
        # [I ; C D] stays MDS for any invertible diagonal D (every k x k
        # submatrix determinant only picks up unit factors), and an all-ones
        # first parity row makes it a plain XOR of the data shards — so the
        # dominant rebuild case (one lost shard, RAID-style) runs at VPU/HBM
        # byte-XOR speed instead of through the GF(2) bit matmul. Verified
        # exhaustively by the MDS test over erasure patterns.
        if m >= 1:
            scale = np.array([GF.inv(int(c)) for c in cauchy[0]],
                             dtype=np.uint8)
            cauchy = np.stack(
                [GF.mul(row, scale) for row in cauchy], axis=0
            ).astype(np.uint8)
            assert (cauchy[0] == 1).all()
        self.parity_matrix = cauchy
        self.generator = np.concatenate(
            [np.eye(k, dtype=np.uint8), self.parity_matrix], axis=0
        )  # (k+m, k)
        # HOST numpy, not a device array: constructing RSCode must never
        # initialize the jax backend — EC-serving processes (storage
        # servers, FUSE daemons) run the host SIMD path and may have no
        # reachable accelerator at all. jax.jit/einsum accept numpy
        # operands, so device materialization happens lazily on the first
        # actual device-kernel call.
        self._parity_bits = GF.expand_to_bits(self.parity_matrix).astype(
            np.int8)
        # per-instance caches keyed on (present, lost) — instance-held so
        # the device matrices/compiled fns die with the RSCode object
        self._reconstruct_mats: dict = {}
        self._reconstruct_fns: dict = {}
        self._pallas_matrices: dict = {}
        self._einsum_fns: dict = {}
        self._xor_schedule: Optional[list] = None
        self._delta_cols: dict = {}

    # -- kernel selection ---------------------------------------------------
    def _apply_bit_matrix(self, A_bits: jnp.ndarray, key,
                          data: jnp.ndarray,
                          A_sym: np.ndarray = None) -> jnp.ndarray:
        """Apply a symbol-major (8o, 8k) bit matrix via the fastest backend:
        the fused Pallas kernel on TPU; on non-TPU backends the native SIMD
        nibble-table path (when given the symbol matrix and concrete data);
        the jitted einsum form as the last resort and under tracing."""
        from tpu3fs.ops import pallas_rs

        if pallas_rs.backend_supports_pallas():
            A_pm = self._pallas_matrices.get(key)
            if A_pm is None:
                A_pm = pallas_rs.prepare_matrix(np.asarray(A_bits))
                self._pallas_matrices[key] = A_pm
            return pallas_rs.gf2_matmul(A_pm, data)
        if A_sym is not None and not isinstance(data, jax.core.Tracer):
            from tpu3fs.ops import native_ec

            if native_ec.available():
                # plain numpy out: wrapping in a device array here
                # would touch the backend for a pure host computation
                return native_ec.gf_apply(
                    np.asarray(A_sym), np.asarray(data))
        fn = self._einsum_fns.get(key)
        if fn is None:
            fn = jax.jit(functools.partial(_bit_matmul, A_bits))
            self._einsum_fns[key] = fn
        return fn(data)

    # -- encode ------------------------------------------------------------
    def _encode(self, data: jnp.ndarray) -> jnp.ndarray:
        return _bit_matmul(self._parity_bits, data)

    def encode(self, data: jnp.ndarray) -> jnp.ndarray:
        """(..., k, S) uint8 data -> (..., m, S) parity."""
        assert data.shape[-2] == self.k, (data.shape, self.k)
        return self._apply_bit_matrix(self._parity_bits, "encode", data,
                                      A_sym=self.parity_matrix)

    def encode_host(self, data: np.ndarray) -> np.ndarray:
        """Host-side (numpy in, numpy out) encode — the CPU-backend serving
        path. Picks the native SIMD kernel when the library is loadable,
        the numpy LUT gold otherwise. All host-side kernel selection lives
        HERE (stripe.py and callers stay dispatch-free)."""
        from tpu3fs.ops import native_ec

        if native_ec.available():
            return native_ec.gf_apply(self.parity_matrix, data)
        return self.encode_np(data)

    def reconstruct_host(
        self,
        present_idx: Sequence[int],
        lost_idx: Sequence[int],
        present_shards: np.ndarray,
    ) -> np.ndarray:
        """Host-side reconstruction (native SIMD when available)."""
        from tpu3fs.ops import native_ec

        if native_ec.available():
            R = self._reconstruct_matrix(
                tuple(int(i) for i in present_idx),
                tuple(int(i) for i in lost_idx))
            return native_ec.gf_apply(R, np.asarray(present_shards))
        return self.reconstruct_np(present_idx, lost_idx, present_shards)

    def _encode_schedule(self) -> list:
        """XOR-scheduled LUT program for the host encode, cached per code:
        per parity row i, the columns grouped by coefficient value, so

            P_i = XOR_c  MUL[c][ XOR_{j : C_ij == c} D_j ]

        A naive encode pays one 256-entry LUT gather per (i, j) term —
        k*m gathers. Grouping equal coefficients first XOR-accumulates
        their shards at memory speed and gathers ONCE per distinct
        coefficient per row (the XOR-level program optimization of
        PAPERS.md arxiv 1603.05806 applied at LUT-pass granularity);
        row 0 is all-ones by construction, so it costs zero gathers."""
        if self._xor_schedule is None:
            sched = []
            for i in range(self.m):
                by_c: dict = {}
                for j in range(self.k):
                    c = int(self.parity_matrix[i, j])
                    if c:
                        by_c.setdefault(c, []).append(j)
                sched.append(sorted(by_c.items()))
            self._xor_schedule = sched
        return self._xor_schedule

    def encode_np(self, data: np.ndarray) -> np.ndarray:
        """Numpy host encode, XOR-scheduled (see _encode_schedule): shards
        sharing a coefficient XOR-reduce first (memory speed), then one
        256-entry LUT gather per DISTINCT coefficient per row; c==1 groups
        (all of parity row 0 by construction) skip the gather entirely —
        the CPU-backend serving path's gold kernel."""
        data = np.asarray(data, dtype=np.uint8)
        *lead, k, s = data.shape
        assert k == self.k
        flat = data.reshape(-1, k, s)
        out = np.zeros((flat.shape[0], self.m, s), dtype=np.uint8)
        for i, groups in enumerate(self._encode_schedule()):
            for c, cols in groups:
                acc = flat[:, cols[0], :]
                for j in cols[1:]:
                    acc = acc ^ flat[:, j, :]
                if c == 1:
                    out[:, i, :] ^= acc
                else:
                    out[:, i, :] ^= GF.MUL_TABLE[c][acc]
        return out.reshape(*lead, self.m, s)

    # -- delta parity (sub-stripe RMW) --------------------------------------
    def parity_delta_matrix(self, j: int) -> np.ndarray:
        """(m, 1) parity-coefficient column for data shard j, cached —
        the k x m coefficient products of the delta-parity update
        ``P'_i = P_i ^ c_ij * (D'_j ^ D_j)`` (RapidRAID-style in-place
        parity maintenance: a sub-stripe write never re-encodes the
        stripe, it applies the delta through this column)."""
        col = self._delta_cols.get(j)
        if col is None:
            if not 0 <= j < self.k:
                raise ValueError(f"data shard index {j} out of range")
            col = np.ascontiguousarray(
                self.parity_matrix[:, j : j + 1], dtype=np.uint8)
            self._delta_cols[j] = col
        return col

    def delta_parity_host(self, j: int, delta: np.ndarray) -> np.ndarray:
        """Host-side parity delta for a change on data shard j:
        (..., S) uint8 delta (D' ^ D, zero-padded to the shard size)
        -> (..., m, S) rows to XOR into the current parity shards.
        Native SIMD when available, LUT gold otherwise."""
        from tpu3fs.ops import native_ec

        col = self.parity_delta_matrix(j)
        d = np.asarray(delta, dtype=np.uint8)
        lead, s = d.shape[:-1], d.shape[-1]
        if native_ec.available():
            return native_ec.gf_apply(col, d.reshape(*lead, 1, s))
        out = np.empty((*lead, self.m, s), dtype=np.uint8)
        for i in range(self.m):
            c = int(col[i, 0])
            if c == 0:
                out[..., i, :] = 0
            elif c == 1:
                out[..., i, :] = d
            else:
                out[..., i, :] = GF.MUL_TABLE[c][d]
        return out

    def gf_accumulate(self, j: int, data: np.ndarray,
                      acc: np.ndarray) -> np.ndarray:
        """The pipelined-chain-encode hop primitive: XOR data shard j's
        coefficient-scaled contribution into the in-flight parity
        accumulator IN PLACE and return the contribution rows.

        ``data`` is (..., S) uint8 (the hop's raw shard bytes, zero-padded
        to the shard size); ``acc`` is (..., m, S) uint8 and is updated to
        ``acc ^ C[:, j] * data``. Accumulating over j = 0..k-1 yields
        exactly ``encode`` (RapidRAID-style in-chain encoding: parity
        builds hop by hop as the data streams down the chain, arxiv
        1207.6744; the per-hop kernel is the cached coefficient column
        applied through the XOR-program-optimized LUT/native path of
        delta_parity_host, arxiv 2108.02692). The returned (..., m, S)
        contribution is what the hop CRCs for the partial-CRC composition
        (ops.crc32c.crc32c_xor) — returning it costs nothing: it had to
        be materialized to XOR anyway."""
        contrib = self.delta_parity_host(j, data)
        np.bitwise_xor(acc, contrib, out=acc)
        return contrib

    # -- decode ------------------------------------------------------------
    def _reconstruct_matrix(
        self, present: Tuple[int, ...], lost: Tuple[int, ...]
    ) -> np.ndarray:
        """GF matrix R (len(lost), k) with lost = R @ shards[present]."""
        key = (present, lost)
        cached = self._reconstruct_mats.get(key)
        if cached is not None:
            return cached
        assert len(present) == self.k
        sub = self.generator[list(present), :]  # (k, k)
        inv = GF.mat_inv(sub)  # data = inv @ present
        rows = []
        for idx in lost:
            # row of the generator for the lost shard, composed with inv
            rows.append(GF.matmul(self.generator[idx : idx + 1, :], inv)[0])
        R = np.stack(rows, axis=0)
        self._reconstruct_mats[key] = R
        return R

    def reconstruct_fn(
        self, present_idx: Sequence[int], lost_idx: Sequence[int]
    ):
        """Jitted fn mapping (..., k, S) surviving shards -> (..., lost, S).

        The single decode entry point: reconstruct() and the distributed
        rebuild path (tpu3fs.parallel.rebuild) both go through here, so a
        kernel swap (e.g. Pallas) lands in one place.
        """
        present = tuple(int(i) for i in present_idx)
        lost = tuple(int(i) for i in lost_idx)
        key = (present, lost)
        fn = self._reconstruct_fns.get(key)
        if fn is None:
            if self._xor_rebuild_applies(present, lost):
                # single loss covered by the all-ones parity row: the lost
                # shard is the plain XOR of the k survivors — byte XOR at
                # VPU/HBM speed, no GF matmul (the RAID rebuild path).
                # On CPU backends concrete data drops to the native SIMD
                # XOR via the all-ones row of gf_apply.
                jitted = jax.jit(_xor_reduce_shards)
                ones = np.ones((1, self.k), dtype=np.uint8)

                def fn(data, _jitted=jitted, _ones=ones):
                    from tpu3fs.ops import native_ec, pallas_rs

                    if (not pallas_rs.backend_supports_pallas()
                            and not isinstance(data, jax.core.Tracer)
                            and native_ec.available()):
                        return native_ec.gf_apply(
                            _ones, np.asarray(data))
                    return _jitted(data)
            else:
                R = self._reconstruct_matrix(present, lost)
                R_bits = GF.expand_to_bits(R).astype(np.int8)
                fn = functools.partial(
                    self._apply_bit_matrix, R_bits, key,
                    A_sym=R,
                )
            self._reconstruct_fns[key] = fn
        return fn

    def _xor_rebuild_applies(self, present, lost) -> bool:
        """True when lost is one shard rebuildable from parity row 0: the
        survivors are exactly the other k-1 data shards + parity 0 (lost
        data shard), or all k data shards (lost parity 0)."""
        if len(lost) != 1 or self.m < 1:
            return False
        (x,) = lost
        if x > self.k:
            return False
        return set(present) == set(range(self.k + 1)) - {x}

    def reconstruct(
        self,
        present_idx: Sequence[int],
        lost_idx: Sequence[int],
        present_shards: jnp.ndarray,
    ) -> jnp.ndarray:
        """Rebuild lost shards from any k surviving shards.

        present_idx: k shard indices in [0, k+m) matching present_shards rows
        present_shards: (..., k, S) uint8
        returns (..., len(lost_idx), S) uint8
        """
        return self.reconstruct_fn(present_idx, lost_idx)(present_shards)

    def reconstruct_np(
        self,
        present_idx: Sequence[int],
        lost_idx: Sequence[int],
        present_shards: np.ndarray,
    ) -> np.ndarray:
        """Gold-path numpy reconstruction."""
        R = self._reconstruct_matrix(
            tuple(int(i) for i in present_idx), tuple(int(i) for i in lost_idx)
        )
        shards = np.asarray(present_shards, dtype=np.uint8)
        *lead, k, s = shards.shape
        flat = shards.reshape(-1, k, s)
        out = np.zeros((flat.shape[0], R.shape[0], s), dtype=np.uint8)
        for i in range(R.shape[0]):
            for j in range(k):
                c = int(R[i, j])
                if c == 0:
                    continue
                if c == 1:
                    out[:, i, :] ^= flat[:, j, :]
                else:
                    out[:, i, :] ^= GF.MUL_TABLE[c][flat[:, j, :]]
        return out.reshape(*lead, R.shape[0], s)

    def __repr__(self) -> str:  # pragma: no cover
        return f"RSCode(k={self.k}, m={self.m})"
