"""Stripe codec: the device-resident EC data plane the serving path calls.

One stripe = one file chunk split into k data shards of S bytes plus m
parity shards. Encode (RS(k,m) GF(2) bit-matmul, Pallas on TPU) and batched
CRC32C run on device; decode/reconstruct goes through the same
RSCode.reconstruct_fn the rebuild benches and the multi-chip dryrun use, so
a kernel improvement lands everywhere at once.

The reference has no RS path (it replicates via CRAQ, docs/design_notes.md
"Data replication"); "EC" exists there as a chain-table type in the
placement solver (deploy/data_placement/src/model/data_placement.py:30).
This module is the added TPU-native capability from BASELINE.json, gated by
ChainInfo.ec_k/ec_m the way the reference gates engines per target
(src/storage/store/StorageTarget.h:162).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from tpu3fs.ops.crc32c import BatchCrc32c, crc32c, crc32c_batch_host
from tpu3fs.ops.rs import RSCode

# codecs are heavyweight (device matrices + compiled fns): share per-process
_cache_lock = threading.Lock()
_codecs: Dict[Tuple[int, int, int], "StripeCodec"] = {}


def get_codec(k: int, m: int, shard_size: int) -> "StripeCodec":
    key = (k, m, shard_size)
    with _cache_lock:
        codec = _codecs.get(key)
        if codec is None:
            codec = StripeCodec(k, m, shard_size)
            _codecs[key] = codec
        return codec


def _bucket(b: int) -> int:
    """Round a batch size up to the next power of two (shape bucketing for
    the device paths: bounds XLA recompiles at O(log B) per codec)."""
    p = 1
    while p < b:
        p <<= 1
    return p


def aligned_shard_size(n: int) -> int:
    """Round a working shard size up to the same 512B/64B grid
    shard_size_of uses — zero padding is free for RS/CRC math, and the
    alignment keeps the per-(k, m, S) codec cache from fragmenting into one
    compiled kernel per distinct logical tail length."""
    align = 512 if n >= 512 else 64
    return -(-n // align) * align


def shard_size_of(chunk_size: int, k: int) -> int:
    """Shard size for a chunk striped over k data shards (last shard padded).

    Rounded up to a CRC-block/TPU-lane-friendly boundary (512B, or 64B for
    tiny shards) — client and server both derive S through here, so the
    alignment is part of the stripe format."""
    s0 = -(-chunk_size // k)
    align = 512 if s0 >= 512 else 64
    return -(-s0 // align) * align


class StripeCodec:
    """Encode/decode/checksum a batch of stripes on the device."""

    def __init__(self, k: int, m: int, shard_size: int):
        self.k = k
        self.m = m
        self.shard_size = shard_size
        self.rs = RSCode(k, m)
        block = 512 if shard_size % 512 == 0 else shard_size
        self._crc = BatchCrc32c(shard_size, block=block)
        self._host_mode: Optional[bool] = None

    def _use_host(self) -> bool:
        """The serving path stays on host kernels even when a TPU is
        attached: StripeCodec's contract is host bytes in / host bytes out
        (the RPC layer), one stripe batch per request — a synchronous
        device round-trip per call is transfer-bound and loses to the
        native SIMD path by orders of magnitude (measured 0.001 vs ~1+
        GiB/s through a remote-attached chip). The device kernels
        (Pallas bit-matmul + fused CRC) remain the path for
        device-RESIDENT data: RSCode.encode / reconstruct_fn as used by
        tpu3fs.parallel.{rebuild,shuffle} and the benches.
        TPU3FS_STRIPE_DEVICE=1 forces the device path for hosts whose
        accelerator is local enough to win on big batches."""
        if self._host_mode is None:
            import os

            self._host_mode = os.environ.get(
                "TPU3FS_STRIPE_DEVICE", "") != "1"
        return self._host_mode

    # -- encode --------------------------------------------------------------
    def encode_parity(self, data: np.ndarray
                      ) -> Tuple[np.ndarray, np.ndarray]:
        """(B, k, S) uint8 -> (parity (B, m, S), crcs (B, k+m) uint32) —
        the serving-path shape: callers already hold the data-shard bytes,
        so the (B, k+m, S) concatenation encode_batch builds would be a
        multi-MiB copy just to throw away. Honors the same host/device
        policy as encode_batch (TPU3FS_STRIPE_DEVICE=1 keeps the device
        kernels for hosts whose accelerator is local enough to win)."""
        b, k, s = data.shape
        assert k == self.k and s == self.shard_size, (data.shape, self.k)
        if not self._use_host():
            shards, crcs = self.encode_batch(data)
            return shards[:, k:], crcs
        parity = self.rs.encode_host(data)
        crcs = np.empty((b, k + self.m), dtype=np.uint32)
        crcs[:, :k] = crc32c_batch_host(
            np.ascontiguousarray(data).reshape(b * k, s)).reshape(b, k)
        if self.m:
            crcs[:, k:] = crc32c_batch_host(
                np.ascontiguousarray(parity).reshape(b * self.m, s)
            ).reshape(b, self.m)
        return parity, crcs

    def encode_batch(self, data: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """(B, k, S) uint8 -> (shards (B, k+m, S), crcs (B, k+m) uint32),
        both materialized on host for the RPC layer."""
        b, k, s = data.shape
        assert k == self.k and s == self.shard_size, (data.shape, self.k)
        if self._use_host():
            # host kernel selection (native SIMD vs numpy gold) lives in
            # RSCode.encode_host / crc32c_batch_host — one dispatch layer
            parity, crcs_np = self.encode_parity(data)
            shards_np = np.concatenate([data, parity], axis=1)
            return shards_np, crcs_np
        import jax
        import jax.numpy as jnp

        # pad the batch to a power-of-two bucket: XLA compiles one program
        # per input SHAPE, so free-running batch sizes (every distinct run
        # length the file client flushes) would each pay a fresh multi-second
        # compile — with bucketing there are O(log B) programs per codec,
        # reused forever. Zero stripes encode to zero parity, so the pad
        # rows are discarded by the slice below without affecting results.
        bp = _bucket(b)
        pad = np.zeros((bp - b, k, s), dtype=np.uint8) if bp != b else None
        dev_data = jnp.asarray(
            data if pad is None else np.concatenate([data, pad], axis=0))
        parity = self.rs.encode(dev_data)
        shards = jnp.concatenate([dev_data, parity], axis=1)
        crcs = self._crc(shards.reshape(bp * (k + self.m), s))
        shards, crcs = jax.device_get((shards, crcs))
        return (np.asarray(shards)[:b],
                np.asarray(crcs).reshape(bp, k + self.m)[:b])

    def delta_parity(self, j: int, delta) -> np.ndarray:
        """Parity-row deltas for a sub-stripe change on data shard j:
        ``delta`` is D'_j ^ D_j zero-padded to S bytes -> (m, S) rows to
        XOR into the stored parity shards (``P'_i = P_i ^ c_ij * dD``).
        The RMW write path calls this instead of re-encoding the stripe:
        the moved bytes drop from k*S reads + (k+m)*S writes to
        (touched + m) shards each way. Host kernels (native SIMD / LUT
        gold) — the serving-path policy of _use_host applies, and the
        device path has no per-call win at one stripe."""
        d = np.frombuffer(delta, dtype=np.uint8) \
            if not isinstance(delta, np.ndarray) else delta
        assert d.shape[-1] == self.shard_size, (d.shape, self.shard_size)
        return self.rs.delta_parity_host(j, d)

    def hop_accumulate(self, j: int, payloads, acc: np.ndarray) -> np.ndarray:
        """One chain-encode hop over a stripe batch: XOR data shard j's
        coefficient-scaled contribution into the in-flight parity
        accumulators and return the contribution CRCs.

        ``payloads`` is a length-B sequence of the hop's raw (trimmed)
        shard-j bytes — one per stripe of the batch; ``acc`` is the
        (B, m, S) uint8 accumulator frame riding the chain forward,
        updated IN PLACE. Returns (B, m) uint32 CRC32Cs of the
        contribution rows for the per-hop partial-CRC composition
        (crc32c_xor): the tail's validated install then checks the whole
        relay, not just the last wire crossing. Host kernels only — this
        runs inside storage hops (the serving-path policy of _use_host)."""
        B = len(payloads)
        assert acc.shape == (B, self.m, self.shard_size), (acc.shape, B)
        d = np.zeros((B, self.shard_size), dtype=np.uint8)  # copy-ok: pad to S
        for b, p in enumerate(payloads):
            flat = np.frombuffer(p, dtype=np.uint8)
            d[b, : flat.size] = flat
        contrib = self.rs.gf_accumulate(j, d, acc)
        return crc32c_batch_host(
            np.ascontiguousarray(contrib).reshape(B * self.m,
                                                  self.shard_size)
        ).reshape(B, self.m)

    def encode_stripe(self, chunk: bytes) -> Tuple[np.ndarray, np.ndarray]:
        """One chunk (<= k*S bytes, zero-padded) -> ((k+m, S), (k+m,))."""
        buf = np.zeros((self.k, self.shard_size), dtype=np.uint8)
        flat = np.frombuffer(chunk, dtype=np.uint8)
        buf.reshape(-1)[: flat.size] = flat
        shards, crcs = self.encode_batch(buf[None])
        return shards[0], crcs[0]

    # -- decode --------------------------------------------------------------
    def reconstruct_batch(
        self,
        present_idx: Sequence[int],
        lost_idx: Sequence[int],
        present: np.ndarray,
    ) -> np.ndarray:
        """(B, k, S) survivors at present_idx -> (B, len(lost), S) rebuilt.
        The single-chip serving path; the pod-scale variant is
        tpu3fs.parallel.rebuild.rebuild_lost_shard over a mesh (same
        reconstruct_fn underneath)."""
        if self._use_host():
            return self.rs.reconstruct_host(present_idx, lost_idx, present)
        import jax
        import jax.numpy as jnp

        b = present.shape[0]
        bp = _bucket(b)
        if bp != b:  # shape bucketing, see encode_batch
            present = np.concatenate(
                [present,
                 np.zeros((bp - b,) + present.shape[1:], dtype=np.uint8)],
                axis=0)
        fn = self.rs.reconstruct_fn(tuple(present_idx), tuple(lost_idx))
        return np.asarray(jax.device_get(fn(jnp.asarray(present))))[:b]

    def crc_batch(self, shards: np.ndarray) -> np.ndarray:
        """(N, S) uint8 -> (N,) uint32 (device; host CRC on CPU backends)."""
        if self._use_host():
            return crc32c_batch_host(shards)
        import jax

        n = shards.shape[0]
        npad = _bucket(n)
        if npad != n:  # shape bucketing, see encode_batch
            shards = np.concatenate(
                [shards, np.zeros((npad - n, shards.shape[1]),
                                  dtype=np.uint8)], axis=0)
        return np.asarray(jax.device_get(self._crc(shards)))[:n]

    # -- host-side assembly helpers ------------------------------------------
    def assemble(self, data_shards: List[Optional[bytes]], length: int) -> bytes:
        """Concatenate k data shards (None = absent, an error upstream)
        and trim the stripe padding to the chunk's logical length."""
        assert all(s is not None for s in data_shards)
        return b"".join(data_shards)[:length]

    def crc_host(self, shard: bytes) -> int:
        """Host-side single-shard CRC of the STORED (trimmed) bytes — the
        ShardWriteReq.crc wire convention."""
        return crc32c(shard)


def trim_rebuilt_shard(
    rebuilt: bytes, j: int, survivor_lens: Dict[int, int], k: int, S: int
) -> bytes:
    """Trim a rebuilt data shard back to its stored (logical) extent.

    Shards are stored trimmed — shard j holds chunk bytes [j*S, (j+1)*S) up
    to the stripe's logical length — so the rebuilt padded bytes must be
    cut back or the re-installed shard would inflate the stripe's recorded
    length. survivor_lens maps surviving DATA shard index -> stored length.

    Exact cases: any nonempty survivor above j proves shard j was full; a
    nonempty-to-empty boundary below j proves it was empty. The one
    ambiguous case (j is the last nonempty shard, partially filled) falls
    back to trailing-zero trimming: bytes stay exact either way, only the
    recorded length can undershoot if the true content ends in zeros."""
    if j >= k:
        return rebuilt  # parity shards are always stored full
    if any(lj > 0 for i, lj in survivor_lens.items() if i > j and i < k):
        return rebuilt  # a later data shard has content: j was full
    below = [lj for i, lj in survivor_lens.items() if i < j]
    if below and min(below) < S:
        return b""  # an earlier shard is short: logical length < j*S
    return rebuilt.rstrip(b"\x00")
