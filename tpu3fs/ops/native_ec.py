"""Native (C++/SIMD) GF(2^8) erasure-code data plane — the CPU fallback.

On TPU backends the RS/CRC math runs as Pallas/MXU kernels (ops/pallas_rs,
ops/crc32c). On CPU backends the JAX lowering of those kernels is ~50-100x
off the machine, so the serving path drops to `ce_gf_apply` /
`ce_crc32c_batch` in native/chunk_engine.cpp: ISA-L-style PSHUFB nibble-
table multiply-accumulate (AVX2/SSSE3 with scalar fallback) plus the
SSE4.2 hardware CRC, parallelized over a small thread pool. This matches
the reference's CPU-side competence (folly CRC32C at GB/s,
/root/reference/src/fbs/storage/Common.h:66-199); the reference has no RS
path at all — RS(k,m) is the added capability from BASELINE.json.

The nibble tables are built HERE from the same 0x11D field tables the JAX
kernels use (ops/gf256.py), so the C code is field-agnostic and the two
backends are bit-exact by construction (pinned by tests/test_ops.py).
"""

from __future__ import annotations

import ctypes
import threading
from typing import Optional

import numpy as np

from tpu3fs.ops.gf256 import GF

_tables_lock = threading.Lock()
_nib_cache: dict = {}


_lib_cache: list = []  # [CDLL | None]; None = terminal in-process failure


def _lib() -> Optional[ctypes.CDLL]:
    """The shared chunk-engine library (builds on demand), or None.

    Success is cached. A stale .so missing the EC symbols is a TERMINAL
    failure for this process (dlopen dedups by pathname, so a rebuild can
    never surface new symbols in the already-loaded mapping) and is cached
    too — but only after kicking off a rebuild so FRESH processes get the
    symbols. Transient failures (concurrent rebuild, momentary disk
    pressure) are NOT cached and retry on the next call: they must not pin
    the process to the ~100x slower numpy/JAX fallback for its lifetime."""
    if _lib_cache:
        return _lib_cache[0]
    try:
        from tpu3fs.storage import native_engine as ne

        lib = ne._load_lib()
        if not (hasattr(lib, "ce_gf_apply")
                and hasattr(lib, "ce_crc32c_multi")):
            # stale .so predating the EC entry points: rebuild on disk for
            # future processes, then give up in THIS process — the stale
            # mapping is pinned by dlopen for our lifetime
            import os
            import subprocess

            try:
                with ne._lib_lock:
                    subprocess.run(
                        ["make", "-C", os.path.abspath(ne._NATIVE_DIR)],
                        check=True, capture_output=True,
                    )
            except Exception:
                pass
            _lib_cache.append(None)
            return None
        lib.ce_gf_apply.restype = ctypes.c_int
        lib.ce_gf_apply.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_void_p,
        ]
        lib.ce_crc32c_batch.restype = ctypes.c_int
        lib.ce_crc32c_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_uint64, ctypes.c_void_p,
        ]
        lib.ce_crc32c_multi.restype = ctypes.c_int
        lib.ce_crc32c_multi.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
            ctypes.c_void_p,
        ]
        _lib_cache.append(lib)
        return lib
    except Exception:
        return None


def available() -> bool:
    return _lib() is not None


def _nib_tables(matrix: np.ndarray) -> np.ndarray:
    """(r, k) GF matrix -> (r*k, 32) uint8 PSHUFB tables (16 low-nibble
    products then 16 high-nibble products per coefficient)."""
    key = matrix.tobytes()
    with _tables_lock:
        cached = _nib_cache.get(key)
        if cached is not None:
            return cached
        r, k = matrix.shape
        nib = np.zeros((r * k, 32), dtype=np.uint8)
        lo_in = np.arange(16, dtype=np.uint8)
        hi_in = (np.arange(16, dtype=np.uint8) << 4).astype(np.uint8)
        for i in range(r):
            for j in range(k):
                c = int(matrix[i, j])
                nib[i * k + j, :16] = GF.MUL_TABLE[c][lo_in]
                nib[i * k + j, 16:] = GF.MUL_TABLE[c][hi_in]
        if len(_nib_cache) > 256:
            _nib_cache.clear()
        _nib_cache[key] = nib
        return nib


def gf_apply(matrix: np.ndarray, data: np.ndarray) -> np.ndarray:
    """Apply an (r, k) GF(2^8) matrix to (..., k, S) uint8 -> (..., r, S).

    Encode: matrix = RSCode.parity_matrix. Decode: matrix = the
    reconstruction rows. Raises RuntimeError when the library is absent
    (callers gate on available())."""
    lib = _lib()
    if lib is None:
        raise RuntimeError("native EC library unavailable")
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    r, k = matrix.shape
    *lead, kk, S = data.shape
    assert kk == k, (data.shape, k)
    flat = np.ascontiguousarray(data, dtype=np.uint8).reshape(-1, k, S)
    B = flat.shape[0]
    out = np.empty((B, r, S), dtype=np.uint8)
    if B == 0 or S == 0:
        return out.reshape(*lead, r, S)
    nib = _nib_tables(matrix)
    rc = lib.ce_gf_apply(
        nib.ctypes.data, matrix.ctypes.data, k, r,
        flat.ctypes.data, B, S, out.ctypes.data)
    if rc != 0:
        raise RuntimeError(f"ce_gf_apply rc={rc}")
    return out.reshape(*lead, r, S)


def crc32c_batch(rows: np.ndarray) -> np.ndarray:
    """(N, S) uint8 -> (N,) uint32 CRC32C per row (standard init/xorout)."""
    lib = _lib()
    if lib is None:
        raise RuntimeError("native EC library unavailable")
    rows = np.ascontiguousarray(rows, dtype=np.uint8)
    n, s = rows.shape
    out = np.empty(n, dtype=np.uint32)
    if n == 0:
        return out
    rc = lib.ce_crc32c_batch(rows.ctypes.data, n, s, s, out.ctypes.data)
    if rc != 0:
        raise RuntimeError(f"ce_crc32c_batch rc={rc}")
    return out


def crc32c_multi(bufs) -> np.ndarray:
    """Per-buffer CRC32C over a sequence of independently-owned bytes-like
    buffers, one GIL-released pooled crossing (no concatenation copy).
    The staging path of batched CRAQ writes calls this with each op's
    payload — per-op scalar CRC was the dominant term of that pipeline."""
    lib = _lib()
    if lib is None:
        raise RuntimeError("native EC library unavailable")
    n = len(bufs)
    out = np.empty(n, dtype=np.uint32)
    if n == 0:
        return out
    # borrow every buffer's address without copying: bytes via c_char_p,
    # writable buffers (transport receive-frame memoryviews) via
    # from_buffer; read-only non-bytes buffers fall back to one copy
    ptrs = (ctypes.c_void_p * n)()
    keepalive = []
    for i, b in enumerate(bufs):
        if isinstance(b, bytes):
            ref = ctypes.c_char_p(b)
            keepalive.append(ref)
            ptrs[i] = ctypes.cast(ref, ctypes.c_void_p).value
        else:
            try:
                arr = (ctypes.c_char * len(b)).from_buffer(b)
            except (TypeError, ValueError):
                owned = bytes(b)  # copy-ok: read-only non-bytes buffer
                ref = ctypes.c_char_p(owned)
                keepalive.append((owned, ref))
                ptrs[i] = ctypes.cast(ref, ctypes.c_void_p).value
                continue
            keepalive.append(arr)
            ptrs[i] = ctypes.addressof(arr)
    lens = (ctypes.c_uint64 * n)(*map(len, bufs))
    rc = lib.ce_crc32c_multi(ptrs, lens, n, out.ctypes.data)
    del keepalive
    if rc != 0:
        raise RuntimeError(f"ce_crc32c_multi rc={rc}")
    return out
