from tpu3fs.ops.gf256 import GF  # noqa: F401
from tpu3fs.ops.rs import RSCode  # noqa: F401
from tpu3fs.ops.crc32c import crc32c, crc32c_combine, BatchCrc32c  # noqa: F401
