"""CRC32C (Castagnoli) — scalar gold, combine algebra, and batched TPU kernel.

The reference computes CRC32C per chunk on CPU via folly (checksum type in
src/fbs/storage/Common.h:66-199, combine() included). Here the per-byte table
loop is re-expressed as GF(2) linear algebra so a *batch* of fixed-size chunks
is checksummed with two MXU matmuls:

  1. split each chunk into N blocks of BLK bytes; a precomputed (8*BLK, 32)
     matrix maps each block's message bits to the block's raw CRC register;
  2. a precomputed stack of 32x32 shift matrices (powers of the zero-byte
     state-transition matrix A) combines the N block registers into the chunk
     register, which is then corrected for init/xorout.

This works because the CRC register update is affine over GF(2) in (state,
message): raw(init, M) = A^|M| @ init  XOR  raw(0, M), and raw(0, .) is
linear. The same algebra yields crc32c_combine (concatenation), which the
storage write path uses to stitch per-chunk checksums like the reference's
ChecksumInfo::combine.

Bit-exactness is pinned by tests against standard vectors (e.g.
crc32c(b"123456789") == 0xE3069283).
"""

from __future__ import annotations

import functools
import os
from typing import Union

import jax
import jax.numpy as jnp
import numpy as np

from tpu3fs.ops.bitops import (
    np_bits_to_u32,
    np_mat2_mul,
    np_mat2_pow,
    np_u32_to_bits,
    pack_u32,
    unpack_bits_last,
)

_POLY_REFLECTED = 0x82F63B78  # CRC32C, reflected form
_XOROUT = 0xFFFFFFFF


def _make_table() -> np.ndarray:
    table = np.zeros(256, dtype=np.uint32)
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ _POLY_REFLECTED if c & 1 else c >> 1
        table[i] = c
    return table


_TABLE = _make_table()


def _raw_update(state: int, data: bytes) -> int:
    """Advance the raw CRC register (no init/xorout) over data."""
    c = state & 0xFFFFFFFF
    for b in data:
        c = (c >> 8) ^ int(_TABLE[(c ^ b) & 0xFF])
    return c


@functools.lru_cache(maxsize=1)
def _native_crc():
    """Slice-by-8 CRC32C from the native chunk engine, if buildable.

    The hot storage paths checksum every chunk (ref uses folly's hardware
    crc32c); the pure-Python table loop is the correctness gold but ~1000x
    slower, so it stays as the fallback and test oracle only."""
    try:
        import ctypes
        import subprocess

        from tpu3fs.storage import native_engine as ne

        lib = ne._load_lib()  # build+dlopen serialized under its _lib_lock
        if not hasattr(lib, "ce_crc32c_seed"):
            # stale .so predating ce_crc32c_seed: rebuild (serialized under
            # the same lock as _load_lib's build) and load a fresh handle —
            # a cached old lib must not silently degrade every chunk
            # checksum to the ~1000x Python loop
            with ne._lib_lock:
                subprocess.run(
                    ["make", "-C", os.path.abspath(ne._NATIVE_DIR)],
                    check=True, capture_output=True,
                )
                lib = ctypes.CDLL(ne._LIB_PATH)
        fn = lib.ce_crc32c_seed
        fn.restype = ctypes.c_uint32
        fn.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32]
        return fn
    except Exception:
        return None


def crc32c(data: Union[bytes, bytearray, memoryview, np.ndarray], crc: int = 0) -> int:
    """Scalar gold CRC32C with standard init/xorout; chainable via crc arg."""
    if isinstance(data, np.ndarray):
        data = data.astype(np.uint8).tobytes()
    data = bytes(data)
    fast = _native_crc()
    if fast is not None:
        return fast(data, len(data), crc & 0xFFFFFFFF)
    return _raw_update(crc ^ _XOROUT, data) ^ _XOROUT


def crc32c_py(data: Union[bytes, bytearray, memoryview], crc: int = 0) -> int:
    """Pure-Python reference implementation (test oracle)."""
    return _raw_update((crc & 0xFFFFFFFF) ^ _XOROUT, bytes(data)) ^ _XOROUT


def crc32c_batch_host(rows: np.ndarray) -> np.ndarray:
    """Host-side (numpy in/out) per-row CRC32C — the CPU-backend serving
    path. One native crossing with a thread-pooled HW CRC when the library
    is loadable; the scalar loop otherwise. Host-side kernel selection for
    batched CRC lives HERE (mirrors RSCode.encode_host)."""
    rows = np.ascontiguousarray(rows, dtype=np.uint8)
    from tpu3fs.ops import native_ec

    if native_ec.available():
        return native_ec.crc32c_batch(rows)
    return np.fromiter((crc32c(row.tobytes()) for row in rows),
                       dtype=np.uint32, count=rows.shape[0])


@functools.lru_cache(maxsize=1)
def _byte_shift_matrix() -> np.ndarray:
    """A: 32x32 GF(2) matrix advancing the register through one zero byte."""
    A = np.zeros((32, 32), dtype=np.uint8)
    for i in range(32):
        A[:, i] = np_u32_to_bits(_raw_update(1 << i, b"\x00"))
    return A


@functools.lru_cache(maxsize=64)
def _shift_matrix_pow(nbytes: int) -> np.ndarray:
    return np_mat2_pow(_byte_shift_matrix(), nbytes)


def crc32c_combine(crc_a: int, crc_b: int, len_b: int) -> int:
    """CRC of concat(A, B) given crc32c(A), crc32c(B) and len(B) in bytes.

    Derivation: with F = 0xFFFFFFFF and S = A^len_b,
    crc(A||B) = S @ crc(A) XOR crc(B)  (the F terms cancel by linearity).
    """
    if len_b == 0:
        return crc_a
    S = _shift_matrix_pow(int(len_b))
    shifted = np_bits_to_u32((S @ np_u32_to_bits(crc_a).astype(np.int64) & 1))
    return shifted ^ crc_b


@functools.lru_cache(maxsize=64)
def crc32c_zeros(length: int) -> int:
    """CRC32C of ``length`` zero bytes, cached per length.

    The XOR-composition identity (crc32c_xor) needs it once per distinct
    shard size per process; the direct computation through the native
    kernel is a one-time sub-millisecond cost, so no matrix shortcut."""
    if length == 0:
        return 0
    return crc32c(b"\x00" * length)


def crc32c_xor(crc_a: int, crc_b: int, length: int) -> int:
    """CRC of A ^ B for equal-``length`` buffers given their CRCs.

    CRC32C with init/xorout 0xFFFFFFFF is AFFINE over GF(2):
    crc(X) = L(X) ^ f(length) with L linear in the message bits, so
    crc(A^B) = crc(A) ^ crc(B) ^ crc(zeros(length)) — the f terms of A
    and B cancel and one survives via the zero buffer. This is the
    per-hop partial-CRC composition of the pipelined chain encode: a hop
    CRCs only its coefficient-scaled contribution and composes, and the
    final composed value equals the CRC of the fully-accumulated parity
    row iff every hop's XORed bytes matched its CRC'd bytes — the
    engine's validated install then proves the whole relay end to end."""
    return crc_a ^ crc_b ^ crc32c_zeros(length)


@functools.lru_cache(maxsize=16)
def _block_matrix(blk: int) -> np.ndarray:
    """B^T, shape (8*blk, 32): message bits of a blk-byte block -> raw register.

    Column construction uses raw(0, e || 0^d) = A^d @ raw(0, e): start from the
    8 unit responses of the final byte and left-multiply by A per position.
    """
    A = _byte_shift_matrix()
    base = np.zeros((32, 8), dtype=np.uint8)  # columns: bits of last byte
    for t in range(8):
        base[:, t] = np_u32_to_bits(_raw_update(0, bytes([1 << t])))
    B = np.zeros((32, 8 * blk), dtype=np.uint8)
    cur = base
    for p in range(blk - 1, -1, -1):
        B[:, 8 * p : 8 * p + 8] = cur
        if p:
            cur = np_mat2_mul(A, cur)
    return np.ascontiguousarray(B.T)


class BatchCrc32c:
    """Batched CRC32C over fixed-size chunks, MXU-lowered.

    __call__(chunks: (batch, size) uint8) -> (batch,) uint32, bit-exact with
    crc32c(). `size` must be a multiple of `block` (default 512B).
    """

    def __init__(self, size: int, block: int = 512):
        if size % block != 0:
            raise ValueError(f"size {size} not a multiple of block {block}")
        self.size = size
        self.block = block
        self.nblocks = size // block
        B_T = _block_matrix(block).astype(np.int8)  # (8*blk, 32)
        A_blk = np_mat2_pow(_byte_shift_matrix(), block)
        # K[j] = A_blk^(nblocks-1-j): shifts block j's register to the end.
        Ks = np.zeros((self.nblocks, 32, 32), dtype=np.int8)
        cur = np.eye(32, dtype=np.uint8)
        for j in range(self.nblocks - 1, -1, -1):
            Ks[j] = cur
            cur = np_mat2_mul(A_blk, cur)
        # init correction: raw register of `size` zero bytes with init F
        z = np_bits_to_u32(
            np_mat2_pow(_byte_shift_matrix(), size) @ np_u32_to_bits(_XOROUT).astype(np.int64) & 1
        )
        # host numpy: constructing BatchCrc32c must not initialize the
        # jax backend (jit accepts numpy operands; device materialization
        # is lazy, on the first device call)
        self._b_t = B_T
        self._ks = Ks
        self._const = np.uint32(z ^ _XOROUT)
        self._jit = jax.jit(self._compute)

    def compute(self, chunks: jnp.ndarray) -> jnp.ndarray:
        """Traceable (un-jitted) form, for composition inside larger kernels."""
        return self._compute(chunks)

    def _compute(self, chunks: jnp.ndarray) -> jnp.ndarray:
        batch = chunks.shape[0]
        blocks = chunks.reshape(batch, self.nblocks, self.block)
        bits = unpack_bits_last(blocks)  # (batch, N, 8*blk) int8
        regs = (
            jnp.einsum("bnj,jo->bno", bits, self._b_t, preferred_element_type=jnp.int32)
            & 1
        )  # (batch, N, 32)
        out_bits = (
            jnp.einsum(
                "jot,bjt->bo", self._ks, regs.astype(jnp.int8),
                preferred_element_type=jnp.int32,
            )
            & 1
        )  # (batch, 32)
        return pack_u32(out_bits) ^ jnp.uint32(self._const)

    def __call__(self, chunks: jnp.ndarray) -> jnp.ndarray:
        assert chunks.ndim == 2 and chunks.shape[1] == self.size, chunks.shape
        from tpu3fs.ops import pallas_rs

        if (not pallas_rs.backend_supports_pallas()
                and not isinstance(chunks, jax.core.Tracer)):
            # non-TPU backend with concrete data: the HW-CRC batch in
            # native/chunk_engine.cpp is ~100x the jax-CPU matmul lowering
            from tpu3fs.ops import native_ec

            if native_ec.available():
                return native_ec.crc32c_batch(np.asarray(chunks))
        return self._jit(chunks)
