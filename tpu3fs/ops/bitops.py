"""Bit-plane pack/unpack helpers shared by the RS and CRC kernels.

The TPU hot path represents bytes as 8 GF(2) bit-planes so that GF(2^8)/CRC
linear algebra becomes int8 matmuls on the MXU (accumulate in int32, reduce
mod 2). These helpers are pure jnp so XLA can fuse the unpack/pack into the
surrounding matmul; the Pallas kernel in pallas_rs.py fuses them explicitly.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def unpack_bits(x: jnp.ndarray, axis: int = -2) -> jnp.ndarray:
    """uint8 (..., k, S) -> int8 bit-planes (..., 8k, S), LSB-first per symbol.

    Row 8*j+t of the result is bit t of symbol row j, matching
    GF.expand_to_bits column convention.
    """
    assert axis == -2, "bit-plane axis must be the second-to-last"
    x = x.astype(jnp.uint8)
    shifts = jnp.arange(8, dtype=jnp.uint8)
    # (..., k, S) -> (..., k, 8, S)
    bits = (x[..., :, None, :] >> shifts[:, None]) & jnp.uint8(1)
    shape = x.shape[:-2] + (x.shape[-2] * 8, x.shape[-1])
    return bits.astype(jnp.int8).reshape(shape)


def pack_bits(bits: jnp.ndarray, axis: int = -2) -> jnp.ndarray:
    """{0,1} int (..., 8m, S) -> uint8 (..., m, S), inverse of unpack_bits."""
    assert axis == -2
    shape = bits.shape[:-2] + (bits.shape[-2] // 8, 8, bits.shape[-1])
    b = bits.astype(jnp.int32).reshape(shape)
    weights = (jnp.int32(1) << jnp.arange(8, dtype=jnp.int32))[:, None]
    return (b * weights).sum(axis=-2).astype(jnp.uint8)


def unpack_bits_last(x: jnp.ndarray) -> jnp.ndarray:
    """uint8 (..., S) -> int8 (..., 8S) with bit index 8*p+t (LSB-first)."""
    x = x.astype(jnp.uint8)
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (x[..., :, None] >> shifts) & jnp.uint8(1)
    return bits.astype(jnp.int8).reshape(x.shape[:-1] + (x.shape[-1] * 8,))


def pack_u32(bits: jnp.ndarray) -> jnp.ndarray:
    """{0,1} (..., 32) -> uint32 (...), LSB-first."""
    b = bits.astype(jnp.uint32)
    weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
    return (b * weights).sum(axis=-1)


# -- numpy-side GF(2) linear algebra (setup/gold) ---------------------------

def np_unpack_bits(x: np.ndarray, symbol_axis_rows: bool = True) -> np.ndarray:
    x = np.asarray(x, dtype=np.uint8)
    bits = ((x[..., :, None, :] >> np.arange(8, dtype=np.uint8)[:, None]) & 1)
    shape = x.shape[:-2] + (x.shape[-2] * 8, x.shape[-1])
    return bits.astype(np.uint8).reshape(shape)


def np_mat2_mul(A: np.ndarray, B: np.ndarray) -> np.ndarray:
    """GF(2) matrix product of {0,1} uint8 matrices."""
    return (A.astype(np.int64) @ B.astype(np.int64) & 1).astype(np.uint8)


def np_mat2_pow(A: np.ndarray, n: int) -> np.ndarray:
    """GF(2) matrix power by binary exponentiation."""
    result = np.eye(A.shape[0], dtype=np.uint8)
    base = A.copy()
    while n:
        if n & 1:
            result = np_mat2_mul(result, base)
        base = np_mat2_mul(base, base)
        n >>= 1
    return result


def np_u32_to_bits(v: int) -> np.ndarray:
    return ((int(v) >> np.arange(32)) & 1).astype(np.uint8)


def np_bits_to_u32(bits: np.ndarray) -> int:
    return int((bits.astype(np.uint64) << np.arange(32, dtype=np.uint64)).sum())
