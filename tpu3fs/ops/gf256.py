"""GF(2^8) arithmetic and bit-matrix expansion (numpy; setup-time only).

TPUs have no carry-less-multiply primitive, so all hot-path GF(2^8) work is
expressed as GF(2) *bit-plane* linear algebra: multiplication by a constant
``c`` is a linear map on the 8 bits of the operand, so an m x k GF(2^8) matrix
expands to an 8m x 8k binary matrix and "GF matmul" becomes an integer matmul
(mod 2) that runs on the MXU (see ops/rs.py). This module provides the
scalar/table arithmetic used to *build* those matrices and the numpy gold
implementations the JAX/Pallas kernels are tested against.

Polynomial: x^8+x^4+x^3+x^2+1 (0x11D), the conventional RS-256 field.
(The reference has no RS code — replication is CRAQ; RS(k,m) is the added
capability called for by BASELINE.json, gated like
src/storage/store/StorageTarget.h:162's engine switch.)
"""

from __future__ import annotations

import functools

import numpy as np

_POLY = 0x11D


def _build_tables():
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= _POLY
    exp[255:510] = exp[0:255]
    return exp, log


_EXP, _LOG = _build_tables()

# Full 256x256 multiplication table — handy for vectorized gold code.
_a = np.arange(256)
_MUL = np.zeros((256, 256), dtype=np.uint8)
_nz = _a[1:]
_MUL[1:, 1:] = _EXP[(_LOG[_nz][:, None] + _LOG[_nz][None, :]) % 255]


class GF:
    """Namespace of GF(2^8) scalar/array operations over the 0x11D field."""

    POLY = _POLY
    EXP = _EXP
    LOG = _LOG
    MUL_TABLE = _MUL

    @staticmethod
    def mul(a, b):
        """Elementwise GF multiply of uint8 arrays/scalars."""
        return _MUL[np.asarray(a, dtype=np.uint8), np.asarray(b, dtype=np.uint8)]

    @staticmethod
    def inv(a: int) -> int:
        if a == 0:
            raise ZeroDivisionError("GF(2^8) inverse of 0")
        return int(_EXP[255 - _LOG[a]])

    @staticmethod
    def div(a, b):
        b = np.asarray(b)
        if np.any(b == 0):
            raise ZeroDivisionError("GF(2^8) division by 0")
        inv_b = _EXP[255 - _LOG[b]]
        return GF.mul(a, inv_b)

    @staticmethod
    def pow(a: int, n: int) -> int:
        if a == 0:
            return 0 if n else 1
        return int(_EXP[(_LOG[a] * n) % 255])

    # -- matrices ----------------------------------------------------------
    @staticmethod
    def matmul(A: np.ndarray, B: np.ndarray) -> np.ndarray:
        """GF(2^8) matrix product (gold-path; O(n^3) table lookups)."""
        A = np.asarray(A, dtype=np.uint8)
        B = np.asarray(B, dtype=np.uint8)
        prod = _MUL[A[:, :, None], B[None, :, :]]  # (n, k, m)
        return np.bitwise_xor.reduce(prod, axis=1)

    @staticmethod
    def mat_inv(A: np.ndarray) -> np.ndarray:
        """Gauss-Jordan inverse over GF(2^8). Raises if singular."""
        A = np.asarray(A, dtype=np.uint8)
        n = A.shape[0]
        assert A.shape == (n, n)
        aug = np.concatenate([A.copy(), np.eye(n, dtype=np.uint8)], axis=1)
        for col in range(n):
            pivot = None
            for row in range(col, n):
                if aug[row, col]:
                    pivot = row
                    break
            if pivot is None:
                raise np.linalg.LinAlgError("singular GF(2^8) matrix")
            if pivot != col:
                aug[[col, pivot]] = aug[[pivot, col]]
            inv_p = GF.inv(int(aug[col, col]))
            aug[col] = GF.mul(aug[col], inv_p)
            for row in range(n):
                if row != col and aug[row, col]:
                    aug[row] ^= GF.mul(aug[row, col], aug[col])
        return aug[:, n:]

    # -- code constructions ------------------------------------------------
    @staticmethod
    def cauchy_parity_matrix(m: int, k: int) -> np.ndarray:
        """m x k Cauchy matrix C[i,j] = 1/(x_i ^ y_j), x_i=i, y_j=m+j.

        The systematic generator [I_k; C] has the MDS property: any k rows are
        invertible, so any m erasures among k+m shards are recoverable.
        """
        if k + m > 256:
            raise ValueError("k+m must be <= 256 for GF(2^8)")
        xs = np.arange(m, dtype=np.uint8)[:, None]
        ys = (m + np.arange(k, dtype=np.uint8))[None, :]
        diff = xs ^ ys
        return _EXP[255 - _LOG[diff]].astype(np.uint8)

    # -- bit-plane expansion ----------------------------------------------
    @staticmethod
    @functools.lru_cache(maxsize=4096)
    def _const_bit_matrix(c: int) -> bytes:
        # M[u, t] = bit u of (c * 2^t); mul-by-c is GF(2)-linear on bits.
        M = np.zeros((8, 8), dtype=np.uint8)
        for t in range(8):
            prod = int(GF.mul(c, 1 << t))
            for u in range(8):
                M[u, t] = (prod >> u) & 1
        return M.tobytes()

    @staticmethod
    def const_bit_matrix(c: int) -> np.ndarray:
        return np.frombuffer(GF._const_bit_matrix(int(c)), dtype=np.uint8).reshape(8, 8)

    @staticmethod
    def expand_to_bits(A: np.ndarray) -> np.ndarray:
        """Expand an (m, k) GF(2^8) matrix into its (8m, 8k) GF(2) bit matrix.

        Bit index convention: row 8*i+u is output bit u of symbol i; column
        8*j+t is input bit t of symbol j (t = significance, LSB first).
        """
        A = np.asarray(A, dtype=np.uint8)
        m, k = A.shape
        out = np.zeros((8 * m, 8 * k), dtype=np.uint8)
        for i in range(m):
            for j in range(k):
                out[8 * i : 8 * i + 8, 8 * j : 8 * j + 8] = GF.const_bit_matrix(
                    int(A[i, j])
                )
        return out
