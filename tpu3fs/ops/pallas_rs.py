"""Fused Pallas TPU kernel for GF(2) bit-plane matmuls (RS encode/decode).

The jnp path in rs.py (_bit_matmul) materializes three HBM-sized
intermediates per call: the int8 bit-plane expansion (8x the input bytes),
the int32 MXU accumulator (32x the output bytes), and the mod-2 planes.
Measured on chip that makes RS(12,4) encode HBM-bound at a fraction of the
machine. This kernel fuses unpack -> int8 MXU matmul -> mod-2 -> repack
entirely in VMEM, so HBM sees only the uint8 input once and the uint8 output
once — the bandwidth floor of the operation.

Inside the kernel everything stays rank-2 (Mosaic rejects the tiny rank-3
broadcasts the jnp path uses): bit-planes are laid out plane-major (row
t*k + j holds bit t of symbol j), and the coefficient matrix is permuted on
the host to match (see _to_plane_major). rs.RSCode picks this kernel on TPU
backends and falls back to the einsum formulation elsewhere (and interpret
mode covers the kernel logic in CPU tests).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

# lane-dim block of shard bytes processed per grid step; multiple of 128
DEFAULT_BLOCK_S = 4096


def _to_plane_major(A_bits: np.ndarray) -> np.ndarray:
    """Permute an (8m, 8k) symbol-major bit matrix (row i*8+t, col j*8+u —
    the GF.expand_to_bits layout) to plane-major (row t*m+i, col u*k+j)."""
    A = np.asarray(A_bits)
    eight_m, eight_k = A.shape
    m, k = eight_m // 8, eight_k // 8
    out = np.empty_like(A)
    for i in range(m):
        for t in range(8):
            for j in range(k):
                for u in range(8):
                    out[t * m + i, u * k + j] = A[i * 8 + t, j * 8 + u]
    return out


def _gf2_kernel(a_ref, x_ref, o_ref, *, k: int, m: int):
    """One (k, Sb) uint8 block -> (m, Sb) uint8 via the plane-major matrix."""
    # Mosaic doesn't legalize shifts on 8-bit vectors; widen to int32 first
    x = x_ref[0].astype(jnp.int32)                 # (k, Sb)
    planes = [((x >> t) & 1).astype(jnp.int8) for t in range(8)]
    bits = jnp.concatenate(planes, axis=0)         # (8k, Sb) plane-major
    acc = jnp.dot(a_ref[...], bits, preferred_element_type=jnp.int32)
    out = jnp.zeros_like(acc, shape=(m, acc.shape[-1]))
    for t in range(8):
        out = out | ((acc[t * m:(t + 1) * m] & 1) << t)
    o_ref[0] = out.astype(jnp.uint8)


@functools.partial(
    jax.jit, static_argnames=("k", "m", "block_s", "interpret")
)
def _gf2_matmul_3d(A_pm, data, *, k: int, m: int, block_s: int,
                   interpret: bool):
    """(B, k, S) uint8 -> (B, m, S) uint8; S must be a multiple of block_s."""
    B, _, S = data.shape
    grid = (B, S // block_s)
    return pl.pallas_call(
        functools.partial(_gf2_kernel, k=k, m=m),
        out_shape=jax.ShapeDtypeStruct((B, m, S), jnp.uint8),
        grid=grid,
        in_specs=[
            pl.BlockSpec((8 * m, 8 * k), lambda b, s: (0, 0)),
            pl.BlockSpec((1, k, block_s), lambda b, s: (b, 0, s)),
        ],
        out_specs=pl.BlockSpec((1, m, block_s), lambda b, s: (b, 0, s)),
        interpret=interpret,
    )(A_pm, data)


def prepare_matrix(A_bits) -> jnp.ndarray:
    """Host-side: symbol-major (8m, 8k) bit matrix -> device plane-major."""
    return jnp.asarray(_to_plane_major(np.asarray(A_bits)), dtype=jnp.int8)


def gf2_matmul(A_pm: jnp.ndarray, data: jnp.ndarray, *,
               interpret: bool = False,
               block_s: int = DEFAULT_BLOCK_S) -> jnp.ndarray:
    """Apply a prepare_matrix()-laid-out (8m, 8k) GF(2) matrix to
    (..., k, S) uint8 symbols -> (..., m, S). Same math as rs._bit_matmul."""
    eight_m, eight_k = A_pm.shape
    m, k = eight_m // 8, eight_k // 8
    *lead, kk, S = data.shape
    assert kk == k, (data.shape, k)
    B = int(np.prod(lead)) if lead else 1
    x = data.reshape(B, k, S)
    bs = min(block_s, _round_up(S, 128))
    pad = (-S) % bs
    if pad:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, pad)))
    out = _gf2_matmul_3d(A_pm, x, k=k, m=m, block_s=bs,
                         interpret=interpret)
    if pad:
        out = out[:, :, :S]
    return out.reshape(*lead, m, S)


def _round_up(v: int, q: int) -> int:
    return ((v + q - 1) // q) * q


@functools.lru_cache(maxsize=1)
def backend_supports_pallas() -> bool:
    """True when the default backend lowers Pallas TPU kernels."""
    try:
        dev = jax.devices()[0]
        return dev.platform in ("tpu", "axon") or "TPU" in str(dev)
    except Exception:
        return False
