"""Migration job schema: the crash-safe unit of cluster reshaping.

One ``MigrationJob`` moves ONE chain membership: replace ``out_target``
(a member leaving a draining/dead node) with ``new_target`` on
``dst_node``. Jobs are persisted in the mgmtd KV (``KeyPrefix.MIGRATION``,
mirroring the reference's src/migration job service whose state rides the
cluster store) so a SIGKILLed worker — or a failed-over mgmtd — resumes
exactly where the last phase transition committed. Every phase handler
is idempotent re-execution (docs/placement.md "crash matrix").

The phase ladder is strictly monotonic; a job can only move forward (or
to FAILED/CANCELLED). ``phase_order`` gaps are deliberate room for
future intermediate states without renumbering persisted jobs.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass


class JobPhase(enum.IntEnum):
    PENDING = 0     # submitted; chain untouched
    PREPARED = 10   # chain mutated: new target joined (CR) / swapped (EC)
    COPYING = 20    # full-chunk copy onto the syncing target in progress
    SYNCED = 30     # sync-done sent; waiting for mgmtd promotion
    CUTOVER = 40    # new target SERVING; old member dropped from the chain
    DONE = 50       # old target's chunks retired (trash-routed)
    FAILED = 90
    CANCELLED = 91

    @property
    def active(self) -> bool:
        return self < JobPhase.DONE

    @property
    def terminal(self) -> bool:
        return not self.active


@dataclass
class MoveSpec:
    """One planned chain-membership replacement (placement/rebalance.py
    emits these; ``migrationSubmit`` turns them into jobs)."""

    chain_id: int
    out_target: int = 0     # member leaving (0 = pure capacity add)
    dst_node: int = 0
    new_target: int = 0     # 0 = mgmtd allocates a fresh target id


@dataclass
class MigrationJob:
    job_id: int
    chain_id: int
    out_target: int = 0
    new_target: int = 0
    dst_node: int = 0
    is_ec: bool = False
    phase: JobPhase = JobPhase.PENDING
    # claim lease: a worker owns the job until claim_expire; a crashed
    # worker's claim lapses and any worker re-claims (resume)
    worker: str = ""
    claim_expire: float = 0.0
    copied_chunks: int = 0
    copied_bytes: int = 0
    error: str = ""
    submitted_at: float = 0.0
    updated_at: float = 0.0

    @property
    def active(self) -> bool:
        return JobPhase(self.phase).active
