"""Migration: chain-to-chain copies AND the mgmtd-coordinated worker
that executes placement moves crash-safely.

Two layers, both riding the ordinary batched data plane through
``StorageClient`` (pipelining, hedging, deadlines, breaker guards and the
BACKGROUND-class tenant exemption come for free — the pre-PR-3 version
spoke raw ``Messenger`` single-ops):

- ``MigrationService`` — the reference's job service surface
  (src/migration/service/Service.h start/stop/list): copy every committed
  chunk of one chain onto another, batched, under the ``migration`` QoS
  class. Local registry, synchronous ``step()`` batches.

- ``MigrationWorker`` — the cluster-elasticity executor. Jobs are
  ``MigrationJob`` records persisted in the mgmtd KV
  (mgmtd.migration_submit/claim/report); each job replaces ONE chain
  membership and advances through the phase ladder
  PENDING → PREPARED → COPYING → SYNCED → CUTOVER → DONE where every
  transition is one atomic mgmtd transaction and every phase handler is
  idempotent re-execution — SIGKILL the worker (or the destination node)
  at ANY point, restart, and the next claim resumes from the last
  committed phase (docs/placement.md "crash matrix"). CR chains are
  filled by the worker itself: batched committed reads off the chain +
  batched full-replace installs addressed at the syncing member; EC
  chains swap the shard slot at PREPARE and the storage nodes'
  EcResyncWorker decode-rebuilds the new shard (the recovery traffic the
  placement solver's λ-balance spreads), with the worker monitoring and
  cutting over.
"""

from __future__ import annotations

import enum
import itertools
import os
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from tpu3fs.migration.types import JobPhase, MigrationJob
from tpu3fs.mgmtd.types import PublicTargetState
from tpu3fs.storage.craq import ReadReq, ShardWriteReq, WriteReq
from tpu3fs.storage.types import ChunkId
from tpu3fs.utils.result import Code, FsError, err

MIGRATION_SERVICE_ID = 400

#: Every RPC the crash-resumed worker blindly RE-EXECUTES when it
#: re-enters a phase from the top. check_rpc_registry check 8 proves each
#: is bound and either idempotent or documented replay-safe
#: (rpc/idempotency.py REPLAY_SAFE_MUTATIONS) — extending the worker with
#: a new mutation forces you to document why its replay converges.
RESUME_REEXECUTED_METHODS = frozenset({
    ("Mgmtd", "getRoutingInfo"),
    ("Mgmtd", "addChainTarget"),
    ("Mgmtd", "dropChainTarget"),
    ("Mgmtd", "migrationClaim"),
    ("Mgmtd", "migrationReport"),
    # the auto re-plan loop (maybe_replan): list is read-only, submit is
    # conflict-refused per chain and re-derived from live routing
    ("Mgmtd", "migrationList"),
    ("Mgmtd", "migrationSubmit"),
    ("StorageSerde", "dumpChunkMeta"),
    ("StorageSerde", "batchRead"),
    ("StorageSerde", "batchUpdate"),
    ("StorageSerde", "syncDone"),
    # the EC drain direct-copy round (_ec_copy_round)
    ("StorageSerde", "batchReadRebuild"),
    ("StorageSerde", "batchWriteShard"),
})

# -- recorders (single declaration site; docs/observability.md) --------------
from tpu3fs.monitor.recorder import CounterRecorder, ValueRecorder

_rec_copied_chunks = CounterRecorder("migration.copied_chunks")
_rec_copied_bytes = CounterRecorder("migration.copied_bytes")
_rec_jobs_done = CounterRecorder("migration.jobs_done")
_rec_retired_targets = CounterRecorder("migration.retired_targets")
_rec_active = ValueRecorder("migration.active_jobs")


def record_retired_target(n: int = 1) -> None:
    """Storage-node hook: a target whose routing assignment vanished was
    closed + trash-routed (bin/storage_main.py scan_targets)."""
    _rec_retired_targets.add(n)


# ---------------------------------------------------------------------------
# chain-to-chain copy service (ref src/migration/service/Service.h)
# ---------------------------------------------------------------------------

class JobState(enum.IntEnum):
    PENDING = 0
    RUNNING = 1
    STOPPED = 2
    DONE = 3
    FAILED = 4


@dataclass
class Job:
    job_id: int
    src_chain: int
    dst_chain: int
    state: JobState = JobState.PENDING
    copied: int = 0
    total: int = 0
    error: str = ""
    # chunk ids (raw bytes) still to copy; populated on first step
    _queue: List[bytes] = field(default_factory=list, repr=False)
    _scanned: bool = field(default=False, repr=False)


class MigrationService:
    """Job registry + batched chunk-copy executor over a StorageClient."""

    def __init__(self, client):
        self._client = client
        self._jobs: Dict[int, Job] = {}
        self._ids = itertools.count(1)
        self._lock = threading.Lock()

    # -- job control (ref migration/service/Service.h start/stop/list) ------
    def start_job(self, src_chain: int, dst_chain: int) -> int:
        if src_chain == dst_chain:
            raise ValueError("src and dst chains must differ")
        with self._lock:
            job_id = next(self._ids)
            self._jobs[job_id] = Job(job_id, src_chain, dst_chain,
                                     state=JobState.RUNNING)
            return job_id

    def stop_job(self, job_id: int) -> bool:
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None or job.state not in (JobState.PENDING,
                                                JobState.RUNNING):
                return False
            job.state = JobState.STOPPED
            return True

    def list_jobs(self) -> List[Job]:
        with self._lock:
            return list(self._jobs.values())

    def job(self, job_id: int) -> Optional[Job]:
        with self._lock:
            return self._jobs.get(job_id)

    # -- executor -----------------------------------------------------------
    def _head_target(self, chain_id: int):
        routing = self._client._routing()
        chain = routing.chains.get(chain_id)
        if chain is None:
            raise err(Code.CHAIN_NOT_FOUND, f"chain {chain_id}")
        head = chain.head()
        if head is None:
            raise err(Code.TARGET_OFFLINE, f"chain {chain_id} has no serving head")
        node = routing.node_of_target(head.target_id)
        if node is None:
            raise err(Code.TARGET_NOT_FOUND,
                      f"target {head.target_id} not in routing info")
        return head.target_id, node.node_id

    def _scan(self, job: Job) -> None:
        target_id, node_id = self._head_target(job.src_chain)
        metas = self._client.dump_chunkmeta(node_id, target_id)
        job._queue = [m.chunk_id.to_bytes() for m in metas if m.committed_ver > 0]
        job.total = len(job._queue)
        job._scanned = True

    def step(self, job_id: int, batch: int = 64) -> int:
        """Copy up to `batch` chunks as ONE batched read + ONE batched
        full-replace write; returns chunks copied this step. Traffic is
        tagged MIGRATION (tpu3fs/qos) so destination update workers
        schedule it behind foreground IO; an OVERLOADED shed pauses the
        job for the server's retry-after hint and leaves it RUNNING —
        migration self-throttles under pressure instead of failing or
        hammering."""
        from tpu3fs.qos.core import TrafficClass, tagged

        job = self.job(job_id)
        if job is None or job.state != JobState.RUNNING:
            return 0
        with tagged(TrafficClass.MIGRATION):
            return self._step_tagged(job, batch)

    def _step_tagged(self, job: Job, batch: int) -> int:
        try:
            if not job._scanned:
                self._scan(job)
            self._head_target(job.dst_chain)  # dst must be routable
            with self._lock:
                if job.state != JobState.RUNNING:
                    return 0  # concurrent stop_job wins
                raws = job._queue[-batch:]
            if not raws:
                with self._lock:
                    if job.state == JobState.RUNNING:
                        job.state = JobState.DONE
                return 0
            ids = [ChunkId.from_bytes(raw) for raw in raws]
            reads = self._client.batch_read(
                [ReadReq(job.src_chain, cid, 0, -1) for cid in ids])
            writes, widx = [], []
            shed_hint = 0
            for i, rd in enumerate(reads):
                if rd.code in (Code.OVERLOADED, Code.TENANT_THROTTLED):
                    shed_hint = max(shed_hint, rd.retry_after_ms or 10)
                    continue
                if not rd.ok:
                    raise err(rd.code, f"read {ids[i]} failed")
                # full_replace: install the copy as the chunk's entire
                # committed content — a plain CRAQ write would merge with
                # any pre-existing destination chunk (COW overlay) and
                # corrupt it. chunk_size=0 = destination target's size.
                writes.append((job.dst_chain, ids[i], 0, rd.data))
                widx.append(i)
            replies = self._client.batch_write(
                writes, chunk_size=0, full_replace=True) if writes else []
            copied = 0
            done_raws = []
            for k, wr in enumerate(replies):
                i = widx[k]
                if wr.code in (Code.OVERLOADED, Code.TENANT_THROTTLED):
                    shed_hint = max(shed_hint, wr.retry_after_ms or 10)
                    continue
                if not wr.ok:
                    raise err(wr.code, f"write {ids[i]} failed")
                copied += 1
                done_raws.append(raws[i])
                _rec_copied_chunks.add(1)
                _rec_copied_bytes.add(len(writes[k][3]))
            with self._lock:
                done = set(done_raws)
                job._queue = [r for r in job._queue if r not in done]
                job.copied += copied
                if not job._queue and job.state == JobState.RUNNING:
                    job.state = JobState.DONE
            if shed_hint:
                time.sleep(max(shed_hint, 10) / 1000.0)
            return copied
        except FsError as e:
            job.state = JobState.FAILED
            job.error = str(e)
            return 0

    def run_job(self, job_id: int, batch: int = 64, max_steps: int = 10_000) -> Job:
        """Drive one job to completion (or failure/stop)."""
        for _ in range(max_steps):
            self.step(job_id, batch)
            job = self.job(job_id)
            if job is None or job.state != JobState.RUNNING:
                break
        return self.job(job_id)


# ---------------------------------------------------------------------------
# mgmtd-coordinated elasticity worker
# ---------------------------------------------------------------------------

class MigrationWorker:
    """Claims ``MigrationJob``s from mgmtd and executes them phase by
    phase. Stateless between rounds: ALL durable state is the mgmtd job
    record plus the cluster itself, so any worker instance (including a
    restart after SIGKILL) continues any job. ``mgmtd`` is an in-process
    ``Mgmtd`` or an ``MgmtdAdminRpcClient`` — same surface."""

    def __init__(self, mgmtd, client, *, worker_id: str = "",
                 batch_chunks: int = 64, lease_s: float = 30.0,
                 max_jobs: int = 4, auto_replan: bool = False,
                 clock: Callable[[], float] = time.monotonic):
        self._mgmtd = mgmtd
        self._client = client
        self.worker_id = worker_id or f"mig-{os.getpid()}-{uuid.uuid4().hex[:8]}"
        self._batch = batch_chunks
        self._lease_s = lease_s
        self._max_jobs = max_jobs
        self._auto_replan = auto_replan
        self._clock = clock

    # -- driver --------------------------------------------------------------
    def run_once(self) -> int:
        """Claim runnable jobs and advance each by one bounded step.
        Returns the number of jobs that made progress. Transport errors
        (mgmtd failover, dead destination) leave jobs claimed-but-parked;
        the next round — or the next worker after our lease lapses —
        retries."""
        from tpu3fs.qos.core import TrafficClass, tagged

        try:
            jobs = self._mgmtd.migration_claim(
                self.worker_id, max_jobs=self._max_jobs,
                lease_s=self._lease_s)
        except FsError:
            return 0
        # one job per chain at a time is the mgmtd submit invariant;
        # claims arrive id-ordered so waves execute in plan order
        _rec_active.set(len(jobs))
        advanced = 0
        with tagged(TrafficClass.MIGRATION):
            for job in jobs:
                try:
                    if self.step(job):
                        advanced += 1
                except FsError as e:
                    if e.code in (Code.MIGRATION_CONFLICT,
                                  Code.MIGRATION_JOB_NOT_FOUND):
                        continue  # another worker took over
                    if e.code in (Code.MGMTD_CHAIN_NOT_FOUND,
                                  Code.INVALID_ARG):
                        self._report(job, phase=JobPhase.FAILED,
                                     error=str(e))
                        continue
                    # transient (transport, shed, quorum wait): park,
                    # record the reason, retry next round
                    self._report(job, error=str(e))
        if self._auto_replan:
            self.maybe_replan()
        return advanced

    def maybe_replan(self) -> int:
        """Auto re-plan for multi-failure chains: the planner evacuates
        at most ONE member per chain per wave (its quorum invariant is
        local to a single job), so a chain with TWO members on leaving
        nodes previously took one operator wave per member. When every
        submitted job has settled but draining/dead nodes still host
        chain members, submit the next replacement wave ourselves —
        the operator's drain converges unattended. Conservative by
        construction: only fires after at least one operator-submitted
        job exists (the worker never initiates evacuation), never
        auto-FILLS joined nodes (``fill_joined=False`` — joined nodes
        stay eligible as evacuation DESTINATIONS, which matters when an
        evacuated-then-restarted empty node is the only legal home for
        a leaving member, but capacity rebalancing stays an operator
        decision), and a quorum-unsafe or conflicting plan just waits
        for the next round. Returns jobs submitted."""
        from tpu3fs.placement.rebalance import (
            TopologyDelta,
            check_plan,
            plan_rebalance,
        )

        try:
            jobs = self._mgmtd.migration_list()
        except FsError:
            return 0
        if not jobs or any(j.active for j in jobs):
            return 0
        routing = self._routing()
        delta = TopologyDelta.from_routing(routing)
        if not (delta.draining or delta.dead):
            return 0
        plan = plan_rebalance(routing, delta, fill_joined=False)
        if plan.empty or check_plan(routing, plan, delta):
            return 0
        try:
            ids = self._mgmtd.migration_submit(
                [mv.spec() for mv in plan.moves])
        except FsError:
            return 0  # raced a peer worker: its wave wins
        return len(ids)

    def run_until_idle(self, *, rounds: int = 200,
                       tick: Optional[Callable[[], None]] = None,
                       sleep_s: float = 0.0) -> int:
        """Test/CLI driver: rounds until no active jobs remain. ``tick``
        runs the mgmtd background pass between rounds (fabric clusters
        have no tick loop of their own)."""
        done = 0
        for _ in range(rounds):
            self.run_once()
            if tick is not None:
                tick()
            jobs = self._mgmtd.migration_list()
            if not any(j.active for j in jobs):
                return sum(1 for j in jobs
                           if JobPhase(j.phase) == JobPhase.DONE)
            if sleep_s:
                time.sleep(sleep_s)
        raise TimeoutError("migration jobs did not converge")

    # -- one phase step -------------------------------------------------------
    def step(self, job: MigrationJob) -> bool:
        """Advance ``job`` by at most one phase transition (plus one copy
        round). True = progress was made."""
        phase = JobPhase(job.phase)
        if phase == JobPhase.PENDING:
            return self._step_prepare(job)
        if phase == JobPhase.PREPARED:
            return self._step_wait_syncing(job)
        if phase == JobPhase.COPYING:
            return self._step_copy(job)
        if phase == JobPhase.SYNCED:
            return self._step_cutover(job)
        if phase == JobPhase.CUTOVER:
            self._report(job, phase=JobPhase.DONE)
            _rec_jobs_done.add(1)
            return True
        return False

    # -- phase handlers (each idempotent under re-execution) ------------------
    def _routing(self):
        invalidate = getattr(self._client, "_routing_invalidate", None)
        if invalidate is not None:
            invalidate()
        return self._client._routing()

    def _chain(self, routing, job: MigrationJob):
        chain = routing.chains.get(job.chain_id)
        if chain is None:
            raise err(Code.MGMTD_CHAIN_NOT_FOUND, str(job.chain_id))
        return chain

    def _member(self, chain, target_id: int):
        return next((t for t in chain.targets if t.target_id == target_id),
                    None)

    def _step_prepare(self, job: MigrationJob) -> bool:
        # re-execution safe: already-a-member is a mgmtd-side no-op
        self._mgmtd.add_chain_target(
            job.chain_id, job.new_target, job.dst_node,
            replace_of=(job.out_target if job.is_ec else 0))
        self._report(job, phase=JobPhase.PREPARED)
        return True

    def _step_wait_syncing(self, job: MigrationJob) -> bool:
        routing = self._routing()
        chain = self._chain(routing, job)
        member = self._member(chain, job.new_target)
        if member is None:
            # routing lag after a failover: re-prepare (idempotent)
            return self._step_prepare(job)
        if member.public_state == PublicTargetState.SERVING:
            self._report(job, phase=JobPhase.SYNCED)
            return True
        if member.public_state == PublicTargetState.SYNCING:
            self._report(job, phase=JobPhase.COPYING)
            return True
        return False  # WAITING/OFFLINE: node hasn't opened it yet

    def _step_copy(self, job: MigrationJob) -> bool:
        routing = self._routing()
        chain = self._chain(routing, job)
        member = self._member(chain, job.new_target)
        if member is None:
            return self._step_prepare(job)
        if member.public_state == PublicTargetState.SERVING:
            self._report(job, phase=JobPhase.SYNCED)
            return True
        if member.public_state != PublicTargetState.SYNCING:
            return False  # destination bounced: wait for re-promotion
        if job.is_ec:
            # DIRECT shard copy from the outgoing member while it is
            # still alive (1/k the bytes of a decode rebuild); the
            # chain's EcResyncWorker stays the dead-outgoing-target
            # fallback AND the verifier/promoter either way
            return self._ec_copy_round(job, routing, chain)
        return self._copy_round(job, routing, chain)

    def _copy_round(self, job: MigrationJob, routing, chain) -> bool:
        """One bounded CR copy round: diff the destination against the
        serving head, ship one batch of full-replace installs, declare
        sync-done when the diff is empty. Every piece re-runs safely:
        reads are idempotent, installs dedupe by version, sync-done is a
        no-op repeat."""
        head = chain.head()
        if head is None:
            return False  # no serving source: nothing safe to copy from
        head_node = routing.node_of_target(head.target_id)
        writers = chain.writer_chain()
        my_idx = next((i for i, t in enumerate(writers)
                       if t.target_id == job.new_target), None)
        if head_node is None or my_idx is None or my_idx == 0:
            return False
        pred = writers[my_idx - 1].target_id
        src = [m for m in self._client.dump_chunkmeta(
            head_node.node_id, head.target_id) if m.committed_ver > 0]
        have = {m.chunk_id: m for m in self._client.dump_chunkmeta(
            job.dst_node, job.new_target)}
        todo = []
        for m in src:
            mine = have.get(m.chunk_id)
            if (mine is not None and mine.committed_ver >= m.committed_ver
                    and (mine.committed_ver > m.committed_ver
                         or mine.checksum.value == m.checksum.value)):
                continue
            todo.append(m)
        if not todo:
            self._client.sync_done(job.dst_node, job.new_target)
            self._report(job, phase=JobPhase.SYNCED)
            return True
        batch = todo[:self._batch]
        reads = self._client.batch_read(
            [ReadReq(job.chain_id, m.chunk_id, 0, -1) for m in batch])
        reqs, sizes = [], []
        hint = 0
        for m, rd in zip(batch, reads):
            if not rd.ok:
                hint = max(hint, rd.retry_after_ms)
                continue  # re-diffed next round
            reqs.append(WriteReq(
                chain_id=job.chain_id,
                chain_ver=chain.chain_version,
                chunk_id=m.chunk_id,
                offset=0,
                data=rd.data,
                chunk_size=0,   # destination target's configured size
                client_id=f"migration-{job.job_id}",
                update_ver=rd.commit_ver,
                full_replace=True,
                from_target=pred,
            ))
            sizes.append(len(rd.data))
        replies = self._client.batch_sync_write(job.dst_node, reqs)
        copied = nbytes = 0
        for sz, wr in zip(sizes, replies):
            if wr.code in (Code.OVERLOADED, Code.TENANT_THROTTLED):
                hint = max(hint, wr.retry_after_ms or 10)
                continue
            if wr.ok:
                copied += 1
                nbytes += sz
        if copied:
            _rec_copied_chunks.add(copied)
            _rec_copied_bytes.add(nbytes)
            self._report(job, copied_chunks=copied, copied_bytes=nbytes)
        if hint:
            # the destination shed us: self-throttle for its hint — the
            # migration class is exactly the traffic QoS exists to pace
            time.sleep(max(hint, 10) / 1000.0)
        return copied > 0

    def _ec_copy_round(self, job: MigrationJob, routing, chain) -> bool:
        """One bounded EC DIRECT-COPY round: the outgoing member a swap
        detached from the chain (routing keeps its TargetInfo at
        chain_id 0 until the hosting node retires it) holds EXACTLY the
        shard the new member needs — read it target-addressed
        (batch_read_rebuild with chain_id 0) and install it on the
        destination at the source's committed stripe version, moving 1/k
        the bytes a decode rebuild reads. Every piece re-runs safely:
        reads are idempotent, installs version-dedupe, and ANY failure
        (outgoing node dead, target already retired, raced writes) just
        returns False — the chain's EcResyncWorker decode-rebuilds
        whatever this round didn't land and remains the sole promoter,
        so correctness never depends on the fast path."""
        from tpu3fs.ops.stripe import aligned_shard_size

        if not job.out_target:
            return False
        out_info = routing.targets.get(job.out_target)
        out_node = (routing.nodes.get(out_info.node_id)
                    if out_info is not None else None)
        if out_info is None or out_node is None:
            return False  # outgoing member gone: decode rebuild recovers
        try:
            src = [m for m in self._client.dump_chunkmeta(
                out_info.node_id, job.out_target) if m.committed_ver > 0]
            have = {m.chunk_id.to_bytes(): m
                    for m in self._client.dump_chunkmeta(
                        job.dst_node, job.new_target)}
        except FsError:
            return False  # unreachable/retired: decode rebuild recovers
        todo = []
        for m in src:
            if m.length == 0:
                continue  # empty tail shards: the rebuilder's business
            mine = have.get(m.chunk_id.to_bytes())
            if mine is not None and mine.committed_ver >= m.committed_ver:
                continue
            todo.append(m)
        if not todo:
            return False  # nothing left to fast-copy; rebuilder promotes
        batch = todo[:self._batch]
        reads = self._client.batch_read_rebuild(out_info.node_id, [
            ReadReq(0, m.chunk_id, 0, -1, job.out_target) for m in batch])
        reqs, sizes = [], []
        for m, rd in zip(batch, reads):
            if not rd.ok or rd.commit_ver != m.committed_ver:
                continue  # raced/refused: re-diffed next round
            reqs.append(ShardWriteReq(
                chain_id=job.chain_id,
                chain_ver=chain.chain_version,
                target_id=job.new_target,
                chunk_id=m.chunk_id,
                data=rd.data,
                crc=rd.checksum.value,
                update_ver=rd.commit_ver,
                chunk_size=aligned_shard_size(len(rd.data)),
                logical_len=rd.logical_len,
                phase=0,   # proven content installs committed in one step
            ))
            sizes.append(len(rd.data))
        replies = self._client.batch_write_shard(job.dst_node, reqs)
        copied = nbytes = 0
        hint = 0
        for sz, wr in zip(sizes, replies):
            if wr.code in (Code.OVERLOADED, Code.TENANT_THROTTLED):
                hint = max(hint, wr.retry_after_ms or 10)
                continue
            if wr.ok:
                copied += 1
                nbytes += sz
        if copied:
            _rec_copied_chunks.add(copied)
            _rec_copied_bytes.add(nbytes)
            self._report(job, copied_chunks=copied, copied_bytes=nbytes)
        if hint:
            time.sleep(max(hint, 10) / 1000.0)
        return copied > 0

    def _step_cutover(self, job: MigrationJob) -> bool:
        routing = self._routing()
        chain = self._chain(routing, job)
        member = self._member(chain, job.new_target)
        if member is None or member.public_state != PublicTargetState.SERVING:
            if member is not None \
                    and member.public_state == PublicTargetState.SYNCING \
                    and not job.is_ec:
                # destination bounced after sync-done: top the copy back up
                self._copy_round(job, routing, chain)
            return False
        if job.out_target and self._member(chain, job.out_target) is not None:
            # the old member stayed readable until HERE — the new replica
            # serves; quorum floor = the chain's nominal width (every
            # remaining member must be serving for the drop to land)
            self._mgmtd.drop_chain_target(
                job.chain_id, job.out_target,
                min_serving=len(chain.targets) - 1)
        elif job.out_target and job.is_ec:
            # EC swap: the outgoing member left the chain at PREPARE but
            # routing kept it alive for the direct-copy window — RELEASE
            # it now (detach to chain_id 0) so the hosting node's scan
            # retires its data; idempotent under re-execution
            self._mgmtd.drop_chain_target(job.chain_id, job.out_target)
        self._report(job, phase=JobPhase.CUTOVER)
        return True

    def _report(self, job: MigrationJob, *, phase: Optional[JobPhase] = None,
                copied_chunks: int = 0, copied_bytes: int = 0,
                error: str = "") -> None:
        try:
            self._mgmtd.migration_report(
                job.job_id, self.worker_id, phase=phase,
                copied_chunks=copied_chunks, copied_bytes=copied_bytes,
                error=error, lease_s=self._lease_s)
        except FsError as e:
            if e.code in (Code.MIGRATION_CONFLICT,
                          Code.MIGRATION_JOB_NOT_FOUND):
                raise
            # mgmtd hiccup: the phase re-executes next round (safe)
