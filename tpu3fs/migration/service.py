"""Migration service: chain-to-chain data movement with job control.

The reference ships a migration service skeleton (src/migration/main.cpp,
src/migration/service/Service.h:8-23 — start/stop/list jobs over RPC,
src/fbs/migration job schemas). Here the skeleton is filled in with a real
executor: a job copies every committed chunk from a source chain onto a
destination chain through the ordinary CRAQ write path, so migrated data is
fully replicated/versioned on arrival and readers never see partial state.

Jobs run in explicit `step()` batches (driven by a background loop in the
service binary, or synchronously in tests), mirroring the reference's
pull-based job workers.
"""

from __future__ import annotations

import enum
import itertools
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from tpu3fs.storage.craq import Messenger, ReadReq, WriteReq
from tpu3fs.storage.types import ChunkId
from tpu3fs.utils.result import Code, FsError, err

MIGRATION_SERVICE_ID = 400


class JobState(enum.IntEnum):
    PENDING = 0
    RUNNING = 1
    STOPPED = 2
    DONE = 3
    FAILED = 4


@dataclass
class Job:
    job_id: int
    src_chain: int
    dst_chain: int
    state: JobState = JobState.PENDING
    copied: int = 0
    total: int = 0
    error: str = ""
    # chunk ids (raw bytes) still to copy; populated on first step
    _queue: List[bytes] = field(default_factory=list, repr=False)
    _scanned: bool = field(default=False, repr=False)


class MigrationService:
    """Job registry + chunk-copy executor over the storage messenger."""

    def __init__(self, routing_provider: Callable, messenger: Messenger):
        self._routing = routing_provider
        self._send = messenger
        self._jobs: Dict[int, Job] = {}
        self._ids = itertools.count(1)
        self._lock = threading.Lock()

    # -- job control (ref migration/service/Service.h start/stop/list) ------
    def start_job(self, src_chain: int, dst_chain: int) -> int:
        if src_chain == dst_chain:
            raise ValueError("src and dst chains must differ")
        with self._lock:
            job_id = next(self._ids)
            self._jobs[job_id] = Job(job_id, src_chain, dst_chain,
                                     state=JobState.RUNNING)
            return job_id

    def stop_job(self, job_id: int) -> bool:
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None or job.state not in (JobState.PENDING,
                                                JobState.RUNNING):
                return False
            job.state = JobState.STOPPED
            return True

    def list_jobs(self) -> List[Job]:
        with self._lock:
            return list(self._jobs.values())

    def job(self, job_id: int) -> Optional[Job]:
        with self._lock:
            return self._jobs.get(job_id)

    # -- executor -----------------------------------------------------------
    def _head_target(self, chain_id: int):
        routing = self._routing()
        chain = routing.chains.get(chain_id)
        if chain is None:
            raise err(Code.CHAIN_NOT_FOUND, f"chain {chain_id}")
        head = chain.head()
        if head is None:
            raise err(Code.TARGET_OFFLINE, f"chain {chain_id} has no serving head")
        info = routing.targets.get(head.target_id)
        if info is None:
            raise err(Code.TARGET_NOT_FOUND,
                      f"target {head.target_id} not in routing info")
        return head.target_id, info.node_id, chain

    def _scan(self, job: Job) -> None:
        target_id, node_id, _ = self._head_target(job.src_chain)
        metas = self._send(node_id, "dump_chunkmeta", target_id)
        job._queue = [m.chunk_id.to_bytes() for m in metas if m.committed_ver > 0]
        job.total = len(job._queue)
        job._scanned = True

    def step(self, job_id: int, batch: int = 64) -> int:
        """Copy up to `batch` chunks; returns number copied this step.
        Traffic is tagged MIGRATION (tpu3fs/qos) so destination update
        workers schedule it behind foreground IO; an OVERLOADED shed
        pauses the job for the server's retry-after hint and leaves it
        RUNNING — migration self-throttles under pressure instead of
        failing or hammering."""
        from tpu3fs.qos.core import TrafficClass, retry_after_ms_of, tagged

        job = self.job(job_id)
        if job is None or job.state != JobState.RUNNING:
            return 0
        with tagged(TrafficClass.MIGRATION):
            return self._step_tagged(job, batch, retry_after_ms_of)

    def _step_tagged(self, job: Job, batch: int, retry_after_ms_of) -> int:
        try:
            if not job._scanned:
                self._scan(job)
            src_target, src_node, src_chain = self._head_target(job.src_chain)
            _, dst_node, dst_chain = self._head_target(job.dst_chain)
            copied = 0
            while job._queue and copied < batch:
                with self._lock:
                    if job.state != JobState.RUNNING:
                        return copied  # concurrent stop_job wins
                raw = job._queue.pop()
                chunk_id = ChunkId.from_bytes(raw)
                rd = self._send(src_node, "read", ReadReq(
                    chain_id=job.src_chain, chunk_id=chunk_id,
                    target_id=src_target))
                if rd.code == Code.OVERLOADED:
                    job._queue.append(raw)  # keep the chunk for next step
                    self._throttle(rd, retry_after_ms_of)
                    return copied
                if not rd.ok:
                    raise err(rd.code, f"read {chunk_id} failed")
                # full_replace: install the copy as the chunk's entire
                # committed content — a plain CRAQ write would merge with any
                # pre-existing destination chunk (COW overlay) and corrupt it
                wr = self._send(dst_node, "write", WriteReq(
                    chain_id=job.dst_chain,
                    chain_ver=dst_chain.chain_version,
                    chunk_id=chunk_id, offset=0, data=rd.data,
                    chunk_size=0,  # 0 = destination target's configured size
                    client_id=f"migration-{job.job_id}",
                    full_replace=True))
                if wr.code == Code.OVERLOADED:
                    job._queue.append(raw)
                    self._throttle(wr, retry_after_ms_of)
                    return copied
                if not wr.ok:
                    raise err(wr.code, f"write {chunk_id} failed")
                copied += 1
                job.copied += 1
            if not job._queue:
                with self._lock:
                    if job.state == JobState.RUNNING:
                        job.state = JobState.DONE
            return copied
        except FsError as e:
            job.state = JobState.FAILED
            job.error = str(e)
            return 0

    @staticmethod
    def _throttle(reply, retry_after_ms_of) -> None:
        import time

        hint = (getattr(reply, "retry_after_ms", 0)
                or retry_after_ms_of(getattr(reply, "message", "") or ""))
        time.sleep(max(hint, 10) / 1000.0)

    def run_job(self, job_id: int, batch: int = 64, max_steps: int = 10_000) -> Job:
        """Drive one job to completion (or failure/stop)."""
        for _ in range(max_steps):
            self.step(job_id, batch)
            job = self.job(job_id)
            if job is None or job.state != JobState.RUNNING:
                break
        return self.job(job_id)
