from tpu3fs.migration.service import (
    Job,
    JobState,
    MigrationService,
    MigrationWorker,
)
from tpu3fs.migration.types import JobPhase, MigrationJob, MoveSpec

__all__ = ["Job", "JobState", "MigrationService", "MigrationWorker",
           "JobPhase", "MigrationJob", "MoveSpec"]
