from tpu3fs.migration.service import Job, JobState, MigrationService

__all__ = ["Job", "JobState", "MigrationService"]
