"""tpu3fs — a TPU-native distributed storage framework with the capabilities of 3FS.

A brand-new design (not a port) re-expressing the reference's capability surface
(see SURVEY.md) idiomatically for TPU + JAX/XLA/Pallas:

- ``ops``       — data-plane math: GF(2^8) Reed-Solomon and CRC32C as batched
                  bit-plane matmuls on the MXU (ref: per-chunk CPU CRC in
                  src/storage/store/ChunkReplica.cc; RS is added capability).
- ``parallel``  — CRAQ chain fan-out as collective_permute rings over ICI,
                  failed-target rebuild as all-gather + RS-decode matmul,
                  shuffle as all_to_all (ref: RDMA chain forwarding in
                  src/storage/service/StorageOperator.cc).
- ``kv``        — transactional KV abstraction + in-memory engine with conflict
                  detection and versionstamps (ref: src/common/kv, src/fdb).
- ``meta``      — stateless file metadata over transactional KV (ref: src/meta).
- ``mgmtd``     — cluster manager: lease election, heartbeats, chain state
                  machine, routing info (ref: src/mgmtd).
- ``storage``   — chunk stores + CRAQ write/commit state machine (ref:
                  src/storage/{store,chunk_engine,service}).
- ``client``    — Storage/Meta/Mgmtd clients with retry ladders (ref: src/client).
- ``rpc``       — reflection serde RPC with service/method ids (ref:
                  src/common/serde, src/common/net).
- ``fabric``    — single-process multi-node test cluster (ref:
                  tests/lib/UnitTestFabric).
- ``placement`` — chain-table placement solver on device (ref:
                  deploy/data_placement).
- ``usrbio``    — batched zero-copy shared-memory ring API (ref: src/lib/api,
                  src/fuse/IoRing).
- ``monitor``   — metric recorders and collectors (ref: src/common/monitor).
"""

__version__ = "0.1.0"
