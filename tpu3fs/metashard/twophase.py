"""Two-phase cross-partition rename/hardlink: intent records +
prepare/commit with an idempotent crash resolver (docs/metashard.md).

Single-partition meta ops are one KV transaction (MetaStore). A
cross-partition rename mutates TWO owners' serialization domains — the
src directory's dirent (src partition) and the dst directory's dirent
(dst partition) — so it runs as three bounded steps, each one KV
transaction, with a durable INTENT record driving crash recovery:

    A. INTENT   (coordinator = src-partition owner): validate src,
                write ``IntentRecord`` (state=preparing, deadline).
    B. PREPARE  (dst-partition owner, via peer RPC): validate the
                intent is live, create the dst dirent + a
                ``PrepareRecord``. Idempotent per txn_id.
    C. COMMIT   (coordinator): guarded clear of the src dirent (only
                if it still points at the recorded inode) + clear the
                intent — ONE atomic txn. Then best-effort FINISH on the
                dst owner clears the prepare record.

Hardlink mirrors it with the roles swapped: the coordinator is the
dst-parent owner (where the new dirent lands), PREPARE bumps nlink on
the inode's by-inode owner behind a prepare record, COMMIT writes the
dst dirent.

Crash matrix (kill the coordinator at any phase boundary — fault
points ``meta.twophase.intent`` / ``.prepared`` / ``.committed``):

=====================  ======================================================
crashed after          resolver action (``resolve_intents``)
=====================  ======================================================
A (intent only)        dst has no prepare record and the deadline passed:
                       ABORT — clear the intent. Nothing ever showed.
B (intent + prepare)   ROLL FORWARD — re-run C's txn (guarded src clear +
                       intent clear), then clear the prepare record. The
                       dst name already serves; the src name dies exactly
                       once.
C (prepare only)       the intent is gone, so the op COMMITTED — clear the
                       orphan prepare record. (A prepare record never
                       outlives its meaning: for rename the dst dirent
                       stays; for hardlink the nlink bump stays.)
=====================  ======================================================

Every resolver mutation is guarded (dirent cleared only when it still
points at the intent's inode; nlink undone only behind a live prepare
record), so blind re-execution after ANY crash converges —
``TWOPHASE_REEXECUTED_METHODS`` names the surface and
``tools/check_rpc_registry.py`` (check 9) statically holds each entry to
idempotent-or-replay-safe, the migration-resume rule extended to meta.

The resolver needs NO peer transport: all partitions share one
transactional KV, so recovery acts on the KV directly (a dead
coordinator's partitions are being reassigned anyway; txn atomicity
keeps direct recovery sound). Ownership is a serialization/scale
discipline, not the correctness boundary.

``rename_orphan_intent`` (chaos/bugs.py) re-plants the historic bug this
protocol exists to prevent: a resolver that rolls a stale intent forward
WITHOUT the inode guard clears whatever now lives at src — replaying a
crashed rename orphans a newer file (caught by the ``meta_intents``
invariant checker; seed ``tests/chaos_seeds/rename_orphan_intent_*``).
"""

from __future__ import annotations

import secrets
import time
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

from tpu3fs.chaos.bugs import bug_fire
from tpu3fs.kv.kv import IKVEngine, ITransaction, with_transaction
from tpu3fs.meta.types import DirEntry, InodeType, dirent_key
from tpu3fs.rpc.serde import deserialize, serialize
from tpu3fs.utils.fault_injection import inject
from tpu3fs.utils.result import Code, FsError
from tpu3fs.utils.result import err as _err

#: default intent lifetime: PREPARE refuses past it, the resolver only
#: touches intents beyond it (coordinator crash detection by timeout)
INTENT_TTL_S = 5.0

_INTENT_PREFIX = b"MTPI"
_PREPARE_PREFIX = b"MTPP"

#: every (service, method) a crash-resumed two-phase replay re-executes
#: blindly. check_rpc_registry check 9 statically requires each to be
#: classified idempotent or listed in REPLAY_SAFE_MUTATIONS — the
#: migration-worker resume rule (check 8) extended to the meta plane.
TWOPHASE_REEXECUTED_METHODS = (
    ("MetaSerde", "renamePrepare"),
    ("MetaSerde", "renameFinish"),
    ("MetaSerde", "renameResolve"),
)

KIND_RENAME = "rename"
KIND_HARDLINK = "hardlink"

ST_PREPARING = "preparing"


def intent_key(txn_id: str) -> bytes:
    return _INTENT_PREFIX + txn_id.encode()


def prepare_key(txn_id: str) -> bytes:
    return _PREPARE_PREFIX + txn_id.encode()


def intent_scan_range() -> Tuple[bytes, bytes]:
    return _INTENT_PREFIX, _INTENT_PREFIX + b"\xff" * 33


def prepare_scan_range() -> Tuple[bytes, bytes]:
    return _PREPARE_PREFIX, _PREPARE_PREFIX + b"\xff" * 33


def new_txn_id() -> str:
    return secrets.token_hex(16)


@dataclass
class IntentRecord:
    """The coordinator's durable promise (phase A). Holds everything the
    resolver needs to finish or undo the op without re-resolving paths —
    paths may mean something ELSE by recovery time, which is exactly why
    every field is an id."""

    txn_id: str = ""
    kind: str = KIND_RENAME
    state: str = ST_PREPARING
    src_pid: int = 0
    dst_pid: int = 0
    # rename: the dirent being moved; hardlink: the dirent being created
    # lives at (dst_parent, dst_name) and inode_id gains a link
    inode_id: int = 0
    inode_type: int = 0
    src_parent: int = 0
    src_name: str = ""
    dst_parent: int = 0
    dst_name: str = ""
    # directory rename: the inode's parent pointer must follow the move
    is_dir: int = 0
    deadline: float = 0.0


@dataclass
class PrepareRecord:
    """The participant's durable acknowledgement (phase B), written in
    the SAME txn as its side effect — record present <=> effect applied,
    which is what makes prepare idempotent per txn_id."""

    txn_id: str = ""
    kind: str = KIND_RENAME
    coordinator_pid: int = 0
    inode_id: int = 0
    dst_parent: int = 0
    dst_name: str = ""


def _load_intent(txn: ITransaction, txn_id: str) -> Optional[IntentRecord]:
    raw = txn.get(intent_key(txn_id))
    return deserialize(raw, IntentRecord) if raw else None


def _load_prepare(txn: ITransaction, txn_id: str) -> Optional[PrepareRecord]:
    raw = txn.get(prepare_key(txn_id))
    return deserialize(raw, PrepareRecord) if raw else None


class TwoPhaseCoordinator:
    """Drives one cross-partition rename/hardlink over a
    ``ShardedMetaStore``. ``peer_prepare(dst_pid, intent)`` /
    ``peer_finish(dst_pid, txn_id)`` route phase B/finish through the
    participant partition's owner (MetaRpcClient in real clusters); when
    absent — tests, single-process drives, the resolver — phases execute
    locally against the shared KV."""

    def __init__(self, store, *,
                 peer_prepare: Optional[Callable] = None,
                 peer_finish: Optional[Callable] = None,
                 ttl_s: float = INTENT_TTL_S):
        self.store = store
        self._peer_prepare = peer_prepare
        self._peer_finish = peer_finish
        self.ttl_s = ttl_s

    @property
    def _engine(self) -> IKVEngine:
        return self.store.engine

    # -- phase A -------------------------------------------------------------
    def _write_rename_intent(self, src: str, dst: str, user,
                             src_pid: int, dst_pid: int) -> IntentRecord:
        st = self.store

        def op(txn: ITransaction) -> IntentRecord:
            sparent, sname, sinode = st._walk(txn, src, user,
                                              follow_last=False)
            if sname is None or sinode is None:
                raise _err(Code.META_NOT_FOUND, src)
            st._check_dir_writable(sparent, user)
            rec = IntentRecord(
                txn_id=new_txn_id(), kind=KIND_RENAME,
                src_pid=src_pid, dst_pid=dst_pid,
                inode_id=sinode.id, inode_type=int(sinode.type),
                src_parent=sparent.id, src_name=sname,
                is_dir=int(sinode.is_dir()),
                deadline=time.time() + self.ttl_s,
            )
            txn.set(intent_key(rec.txn_id), serialize(rec))
            return rec

        return with_transaction(self._engine, op)

    def _write_hardlink_intent(self, src: str, dst: str, user,
                               src_pid: int, dst_pid: int) -> IntentRecord:
        st = self.store

        def op(txn: ITransaction) -> IntentRecord:
            _, _, sinode = st._walk(txn, src, user)
            if sinode is None:
                raise _err(Code.META_NOT_FOUND, src)
            if sinode.is_dir():
                raise _err(Code.META_IS_DIRECTORY, src)
            dparent, dname, dexist = st._walk(txn, dst, user,
                                              follow_last=False)
            if dname is None or dexist is not None:
                raise _err(Code.META_EXISTS, dst)
            st._check_dir_writable(dparent, user)
            rec = IntentRecord(
                txn_id=new_txn_id(), kind=KIND_HARDLINK,
                src_pid=src_pid, dst_pid=dst_pid,
                inode_id=sinode.id, inode_type=int(sinode.type),
                dst_parent=dparent.id, dst_name=dname,
                deadline=time.time() + self.ttl_s,
            )
            txn.set(intent_key(rec.txn_id), serialize(rec))
            return rec

        return with_transaction(self._engine, op)

    # -- phase B (participant side; also the peer RPC handler body) ----------
    def prepare_rename(self, intent: IntentRecord, dst: str, user) -> None:
        """Create the dst dirent + prepare record on the dst partition.
        Idempotent per txn_id; refuses expired or vanished intents (the
        resolver may already be aborting them)."""
        st = self.store

        def op(txn: ITransaction) -> None:
            if _load_prepare(txn, intent.txn_id) is not None:
                return  # replayed prepare: effect already durable
            live = _load_intent(txn, intent.txn_id)
            if live is None or time.time() > live.deadline:
                raise _err(Code.META_TXN_EXPIRED,
                           f"intent {intent.txn_id} expired/aborted")
            dparent, dname, dexist = st._walk(txn, dst, user,
                                              follow_last=False)
            if dname is None:
                raise _err(Code.META_EXISTS, "/")
            if dexist is not None:
                if dexist.id == intent.inode_id:
                    return  # rename onto itself: no-op
                # cross-partition rename is NO-REPLACE by design: an
                # atomic replace would need the dst inode's teardown
                # staged behind the same intent (docs/metashard.md
                # limitations); callers remove dst first
                raise _err(Code.META_EXISTS, dst)
            st._check_dir_writable(dparent, user)
            st._store_dirent(txn, DirEntry(
                dparent.id, dname, intent.inode_id,
                InodeType(intent.inode_type)))
            txn.set(prepare_key(intent.txn_id), serialize(PrepareRecord(
                txn_id=intent.txn_id, kind=KIND_RENAME,
                coordinator_pid=intent.src_pid, inode_id=intent.inode_id,
                dst_parent=dparent.id, dst_name=dname)))

        with_transaction(self._engine, op)

    def prepare_hardlink(self, intent: IntentRecord) -> None:
        """Bump nlink on the inode's partition behind a prepare record
        (present <=> bumped exactly once)."""
        st = self.store

        def op(txn: ITransaction) -> None:
            if _load_prepare(txn, intent.txn_id) is not None:
                return
            live = _load_intent(txn, intent.txn_id)
            if live is None or time.time() > live.deadline:
                raise _err(Code.META_TXN_EXPIRED,
                           f"intent {intent.txn_id} expired/aborted")
            inode = st._load_inode(txn, intent.inode_id)
            if inode is None or inode.nlink <= 0:
                raise _err(Code.META_NOT_FOUND,
                           f"inode {intent.inode_id}")
            inode.nlink += 1
            inode.ctime = time.time()
            st._store_inode(txn, inode)
            txn.set(prepare_key(intent.txn_id), serialize(PrepareRecord(
                txn_id=intent.txn_id, kind=KIND_HARDLINK,
                coordinator_pid=intent.dst_pid,
                inode_id=intent.inode_id,
                dst_parent=intent.dst_parent,
                dst_name=intent.dst_name)))

        with_transaction(self._engine, op)

    # -- phase C -------------------------------------------------------------
    def _commit_rename(self, rec: IntentRecord, *,
                       guard: bool = True) -> None:
        """Guarded src-dirent clear + intent clear, one atomic txn. The
        guard (src dirent still points at the intent's inode) is what
        makes blind replay safe: a recreated src entry survives a stale
        intent's roll-forward. ``guard=False`` is the planted
        ``rename_orphan_intent`` bug shape — never passed by real code."""
        st = self.store

        def op(txn: ITransaction) -> None:
            if _load_intent(txn, rec.txn_id) is None:
                return  # already committed/aborted: replay no-op
            ent = st._load_dirent(txn, rec.src_parent, rec.src_name)
            if ent is not None and (not guard or ent.inode_id == rec.inode_id):
                txn.clear(dirent_key(rec.src_parent, rec.src_name))
            if rec.is_dir and rec.src_parent != rec.dst_parent:
                # inode-record carve-out: the dir inode's parent pointer
                # may live in a third partition; the shared KV keeps the
                # cross-partition write sound (docs/metashard.md)
                prep = _load_prepare(txn, rec.txn_id)
                inode = st._load_inode(txn, rec.inode_id)
                if inode is not None and prep is not None:
                    inode.parent = prep.dst_parent
                    st._store_inode(txn, inode)
            txn.clear(intent_key(rec.txn_id))

        with_transaction(self._engine, op)

    def _commit_hardlink(self, rec: IntentRecord) -> None:
        st = self.store

        def op(txn: ITransaction) -> None:
            if _load_intent(txn, rec.txn_id) is None:
                return
            if _load_prepare(txn, rec.txn_id) is None:
                raise _err(Code.META_TXN_EXPIRED,
                           f"hardlink {rec.txn_id} unprepared")
            ent = st._load_dirent(txn, rec.dst_parent, rec.dst_name)
            if ent is None:
                st._store_dirent(txn, DirEntry(
                    rec.dst_parent, rec.dst_name, rec.inode_id,
                    InodeType(rec.inode_type)))
            elif ent.inode_id != rec.inode_id:
                raise _err(Code.META_EXISTS, rec.dst_name)
            txn.clear(intent_key(rec.txn_id))

        with_transaction(self._engine, op)

    def _abort(self, rec: IntentRecord) -> None:
        """Clear the intent; undo a hardlink's prepared nlink bump behind
        its prepare record (present <=> bump applied, so the undo is
        exactly-once too)."""
        st = self.store

        def op(txn: ITransaction) -> None:
            if _load_intent(txn, rec.txn_id) is None:
                return
            prep = _load_prepare(txn, rec.txn_id)
            if prep is not None and rec.kind == KIND_HARDLINK:
                inode = st._load_inode(txn, rec.inode_id)
                if inode is not None and inode.nlink > 1:
                    inode.nlink -= 1
                    st._store_inode(txn, inode)
                txn.clear(prepare_key(rec.txn_id))
            if prep is not None and rec.kind == KIND_RENAME:
                ent = st._load_dirent(txn, prep.dst_parent, prep.dst_name)
                if ent is not None and ent.inode_id == rec.inode_id:
                    txn.clear(dirent_key(prep.dst_parent, prep.dst_name))
                txn.clear(prepare_key(rec.txn_id))
            txn.clear(intent_key(rec.txn_id))

        with_transaction(self._engine, op)

    def _finish(self, txn_id: str) -> None:
        def op(txn: ITransaction) -> None:
            txn.clear(prepare_key(txn_id))

        with_transaction(self._engine, op)

    # -- the driving sequence ------------------------------------------------
    def rename(self, src: str, dst: str, user,
               src_pid: int, dst_pid: int) -> None:
        rec = self._write_rename_intent(src, dst, user, src_pid, dst_pid)
        inject("meta.twophase.intent")
        try:
            if self._peer_prepare is not None:
                self._peer_prepare(dst_pid, rec, dst)
            else:
                self.prepare_rename(rec, dst, user)
        except FsError:
            self._abort(rec)
            raise
        inject("meta.twophase.prepared")
        self._commit_rename(rec)
        inject("meta.twophase.committed")
        if self._peer_finish is not None:
            try:
                self._peer_finish(dst_pid, rec.txn_id)
            except FsError:
                pass  # orphan prepare record: the resolver clears it
        else:
            self._finish(rec.txn_id)

    def hard_link(self, src: str, dst: str, user,
                  src_pid: int, dst_pid: int):
        rec = self._write_hardlink_intent(src, dst, user, src_pid, dst_pid)
        inject("meta.twophase.intent")
        ino_pid = rec.src_pid
        try:
            if self._peer_prepare is not None:
                self._peer_prepare(ino_pid, rec, src)
            else:
                self.prepare_hardlink(rec)
        except FsError:
            self._abort(rec)
            raise
        inject("meta.twophase.prepared")
        try:
            self._commit_hardlink(rec)
        except FsError:
            self._abort(rec)
            raise
        inject("meta.twophase.committed")
        if self._peer_finish is not None:
            try:
                self._peer_finish(ino_pid, rec.txn_id)
            except FsError:
                pass
        else:
            self._finish(rec.txn_id)
        return self.store.batch_stat([rec.inode_id])[0]


# -- the idempotent crash resolver -------------------------------------------

def list_intents(engine: IKVEngine) -> List[IntentRecord]:
    def op(txn: ITransaction):
        begin, end = intent_scan_range()
        return [deserialize(p.value, IntentRecord)
                for p in txn.get_range(begin, end, snapshot=True)]

    return with_transaction(engine, op, read_only=True)


def list_prepares(engine: IKVEngine) -> List[PrepareRecord]:
    def op(txn: ITransaction):
        begin, end = prepare_scan_range()
        return [deserialize(p.value, PrepareRecord)
                for p in txn.get_range(begin, end, snapshot=True)]

    return with_transaction(engine, op, read_only=True)


def resolve_intents(store, *, now: Optional[float] = None,
                    force: bool = False,
                    pids: Optional[set] = None) -> int:
    """Converge every dangling two-phase record (the crash matrix above).
    Safe to run anywhere, anytime, repeatedly: every action re-validates
    under its own txn and is guarded, so concurrent resolvers — or a
    resolver racing a live coordinator (hence the deadline gate;
    ``force`` is for tests and quiesce) — never double-apply. Returns
    records resolved. ``pids`` restricts to intents whose coordinator
    partition is in the set (an owner resolving only its own partitions);
    None resolves all (drive quiesce, single-process recovery)."""
    co = TwoPhaseCoordinator(store)
    engine = store.engine
    now = time.time() if now is None else now
    resolved = 0
    for rec in list_intents(engine):
        coord_pid = (rec.src_pid if rec.kind == KIND_RENAME
                     else rec.dst_pid)
        if pids is not None and coord_pid not in pids:
            continue
        if not force and now <= rec.deadline:
            continue  # the coordinator may still be driving it
        prepared = with_transaction(
            engine, lambda txn, t=rec.txn_id: _load_prepare(txn, t),
            read_only=True) is not None
        if not prepared:
            co._abort(rec)
            resolved += 1
            continue
        # roll forward. The inode GUARD on the src-dirent clear is the
        # load-bearing line: without it a stale intent's replay clears
        # whatever now lives at (src_parent, src_name) — the historic
        # rename_orphan_intent bug, re-plantable via chaos/bugs.py.
        guard = not bug_fire("rename_orphan_intent")
        if rec.kind == KIND_RENAME:
            co._commit_rename(rec, guard=guard)
        else:
            co._commit_hardlink(rec)
        co._finish(rec.txn_id)
        resolved += 1
    # orphan prepare records (crash between commit and finish): the
    # intent is gone, so the op committed — the record is litter
    for prep in list_prepares(engine):
        gone = with_transaction(
            engine, lambda txn, t=prep.txn_id: _load_intent(txn, t),
            read_only=True) is None
        if gone:
            co._finish(prep.txn_id)
            resolved += 1
    if resolved:
        from tpu3fs.metashard import metrics

        metrics.intents_resolved.add(resolved)
    return resolved
