"""Partitioned metadata plane: M stateless meta servers over the shared
transactional KV (docs/metashard.md).

- ``partition``: the pure routing math every party (client, server,
  mgmtd, CLI) shares — directory-hash over the parent path for by-path
  ops, partition-tagged inode ids for by-inode ops.
- ``store``: ``ShardedMetaStore`` — ownership-fenced MetaStore facade
  with per-partition inode allocation and the cross-partition two-phase
  rename/hardlink coordinator.
- ``twophase``: intent records, prepare/commit protocol and the
  idempotent crash resolver.
"""

from tpu3fs.metashard.partition import (
    DEFAULT_PARTITIONS,
    partition_of_inode,
    partition_of_path,
    partition_tag,
)
from tpu3fs.metashard.store import ShardedMetaStore
from tpu3fs.metashard.twophase import (
    IntentRecord,
    TwoPhaseCoordinator,
    resolve_intents,
)

__all__ = [
    "DEFAULT_PARTITIONS",
    "partition_of_inode",
    "partition_of_path",
    "partition_tag",
    "ShardedMetaStore",
    "IntentRecord",
    "TwoPhaseCoordinator",
    "resolve_intents",
]
