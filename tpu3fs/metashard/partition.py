"""Partition routing math — the ONE pure function set every party
shares (docs/metashard.md).

The namespace splits into a FIXED number of partitions (set at cluster
bootstrap; ownership moves, the count does not):

- **by-path ops** (create/stat/open/remove/list/...) partition on the
  DIRECTORY HASH of the parent path: every name under one directory maps
  to one partition, so a create storm into a directory serializes on one
  owner and two racing mutations of the same dirent always meet the same
  server. Distinct directories spread by hash.
- **by-inode ops** (close/sync/truncate/set_attr/batch_stat by id)
  partition on the INODE ID: the partitioned allocator bakes the owning
  partition into the high bits of every id it hands out
  (``partition_tag``), so ``partition_of_inode`` is arithmetic, not a
  lookup. ``ShardedMetaStore`` allocates a new file's inode id from the
  partition of the create op itself, so the create and every later
  by-inode op on that file land on the SAME partition.

Hashing is blake2b (stable across processes and Python runs — never
``hash()``, which is salted per-interpreter) over the normalized parent
path, mirroring ``MetaStore._split`` normalization so client and server
agree byte-for-byte.

Correctness does NOT depend on routing: all partitions read one shared
transactional KV, so a mis-routed op (stale table) is fenced by the
owner check and retried, never wrong. Ownership buys serialization
(per-directory mutations meet one server), cache locality, and load
spread — the reference's stateless-meta-over-FDB premise (PAPER.md §0)
is what makes this carve-up safe.
"""

from __future__ import annotations

import hashlib
from typing import List, Optional

#: default partition count (mgmtd ``--meta-partitions`` overrides at
#: bootstrap; must stay fixed for the cluster's life because inode ids
#: bake their partition id in)
DEFAULT_PARTITIONS = 8

#: inode ids are 64-bit; the top 16 bits carry (partition_id + 1) for
#: ids from the partitioned allocator (0 = legacy/unpartitioned id)
PID_SHIFT = 48
_TAG_MASK = (1 << 16) - 1


def partition_tag(pid: int) -> int:
    """The high-bits tag the partitioned inode allocator stamps on ids
    it hands out for partition ``pid``."""
    return (pid + 1) << PID_SHIFT


def partition_of_inode(inode_id: int, nparts: int) -> int:
    """Partition owning by-inode ops for ``inode_id``. Tagged ids decode
    their baked partition; legacy ids (root, pre-metashard trees) spread
    by modulo so they still route deterministically."""
    if nparts <= 1:
        return 0
    tag = (inode_id >> PID_SHIFT) & _TAG_MASK
    if tag:
        return (tag - 1) % nparts
    return inode_id % nparts


def normalize_parts(path: str) -> List[str]:
    """`MetaStore._split` normalization without the length checks: the
    routing hash must agree with the server's resolution for every path
    the server would accept."""
    parts = [p for p in path.split("/") if p and p != "."]
    out: List[str] = []
    for p in parts:
        if p == "..":
            if out:
                out.pop()
        else:
            out.append(p)
    return out


def parent_dir(path: str) -> str:
    """Normalized parent-directory string of ``path`` ("/" for root or
    top-level names)."""
    parts = normalize_parts(path)
    return "/" + "/".join(parts[:-1]) if len(parts) > 1 else "/"


def partition_of_path(path: str, nparts: int) -> int:
    """Partition owning by-path ops on ``path``: directory hash over the
    normalized parent path. Pure and salt-free, so every client, server,
    and the CLI compute the same answer."""
    if nparts <= 1:
        return 0
    digest = hashlib.blake2b(parent_dir(path).encode(), digest_size=8)
    return int.from_bytes(digest.digest(), "big") % nparts


def partition_of_dir(dir_path: str, nparts: int) -> int:
    """Partition owning the CONTENTS of ``dir_path`` (list/scan ops):
    the same hash ``partition_of_path`` applies to children of it."""
    if nparts <= 1:
        return 0
    parts = normalize_parts(dir_path)
    norm = "/" + "/".join(parts) if parts else "/"
    digest = hashlib.blake2b(norm.encode(), digest_size=8)
    return int.from_bytes(digest.digest(), "big") % nparts


def owner_node(routing, pid: int) -> Optional[int]:
    """node_id owning partition ``pid`` per a RoutingInfo snapshot, or
    None when the table is absent/unassigned (single-meta compat)."""
    table = getattr(routing, "meta_partitions", None) or {}
    row = table.get(pid)
    return row.node_id if row is not None and row.node_id else None
