"""Metashard observability — the SINGLE declaration site for every
``meta.partition_*`` recorder (docs/observability.md):

- ``meta.partition_op_us`` (distribution, tag kind=p<pid>): per-partition
  meta op latency, the series the SLO engine judges per-partition p99 on
  (the partition dimension rides the ``kind`` tag — SLO tag keys are a
  fixed vocabulary).
- ``meta.partition_wrong`` (counter): ops fenced with
  META_WRONG_PARTITION — a sustained rate means clients hold stale
  partition tables (routing refresh lag, mid-reassignment churn).
- ``meta.partition_intents_resolved`` (counter): dangling two-phase
  records the crash resolver converged — nonzero after a coordinator
  death, should return to zero at rest.
- ``meta.tenant_mismatch`` (counter): wire-declared tenants that did not
  match the authenticated user's binding (rejected in enforce mode,
  counted-through in permissive compat mode).
"""

from __future__ import annotations

import threading
from typing import Dict

from tpu3fs.monitor.recorder import CounterRecorder, DistributionRecorder

_lock = threading.Lock()
_op_us: Dict[int, DistributionRecorder] = {}

#: ops rejected by the ownership fence (stale client routing)
wrong_partition = CounterRecorder("meta.partition_wrong")
#: two-phase records converged by the crash resolver
intents_resolved = CounterRecorder("meta.partition_intents_resolved")
#: declared-vs-bound tenant mismatches seen by the meta auth layer
tenant_mismatch = CounterRecorder("meta.tenant_mismatch")


def partition_op_us(pid: int) -> DistributionRecorder:
    """The per-partition latency recorder (created once per pid — the
    recorder registry is weak, so holders keep these alive here)."""
    with _lock:
        rec = _op_us.get(pid)
        if rec is None:
            rec = DistributionRecorder("meta.partition_op_us",
                                       {"kind": f"p{pid}"})
            _op_us[pid] = rec
        return rec
