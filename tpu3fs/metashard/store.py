"""ShardedMetaStore: the partitioned metadata plane's server-side store
(docs/metashard.md).

A MetaStore whose ops carry a PARTITION identity:

- every by-path op computes its partition (``partition_of_path``) and
  every by-inode op decodes its partition from the inode id
  (``partition_of_inode``), then FENCES against the owner view — a meta
  server that does not own the op's partition answers
  META_WRONG_PARTITION (retryable; the client refreshes routing and
  re-routes) instead of racing the real owner;
- new inodes are allocated FROM the op's partition: the partitioned
  allocator bakes ``partition_tag(pid)`` into the id's high bits, so a
  create and every later by-inode op on that file (close/sync/truncate)
  land on the SAME partition;
- cross-partition rename/hardlink route through the two-phase
  coordinator (twophase.py) instead of the base single-txn paths;
- per-partition op counts accumulate for the mgmtd heartbeat (the
  ``load`` column of ``admin_cli meta-partitions``).

Correctness never depends on the fence: all partitions share ONE
transactional KV, so the base MetaStore paths stay sound even mis-routed
— ownership buys serialization locality and load spread, exactly the
reference's stateless-meta-over-FDB premise (PAPER.md §0). A
ShardedMetaStore with no ``owner_view`` owns everything (single-process
deployments, tests, the recovery resolver).
"""

from __future__ import annotations

import contextlib
import contextvars
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Set

from tpu3fs.metashard import metrics

from tpu3fs.kv.kv import IKVEngine, ITransaction, with_transaction
from tpu3fs.meta.store import InodeIdAllocator, MetaStore
from tpu3fs.metashard.partition import (
    DEFAULT_PARTITIONS,
    partition_of_dir,
    partition_of_inode,
    partition_of_path,
    partition_tag,
)
from tpu3fs.metashard.twophase import (
    TwoPhaseCoordinator,
    resolve_intents,
)
from tpu3fs.utils.result import Code
from tpu3fs.utils.result import err as _err

#: the partition the CURRENT op allocates inode ids from — a contextvar
#: because allocation happens deep inside base-class txn bodies
#: (_create_in_txn / mkdirs) that this module wraps, not rewrites
_ALLOC_PID: contextvars.ContextVar = contextvars.ContextVar(
    "tpu3fs_alloc_pid", default=None)

_PART_COUNTER_PREFIX = b"INOC"  # per-partition inode id counters


class PartitionedInodeAllocator:
    """Block allocator handing out partition-tagged inode ids. The op's
    partition arrives via ``_ALLOC_PID`` (set by ShardedMetaStore's op
    wrappers); with none set it falls back to the legacy untagged
    allocator so the base MetaStore keeps working standalone."""

    def __init__(self, engine: IKVEngine, block: int = 64):
        self._engine = engine
        self._block = block
        self._legacy = InodeIdAllocator(engine, block)
        self._lock = threading.Lock()
        self._next: Dict[int, int] = {}
        self._limit: Dict[int, int] = {}

    def allocate(self) -> int:
        pid = _ALLOC_PID.get()
        if pid is None:
            return self._legacy.allocate()
        with self._lock:
            if self._next.get(pid, 0) >= self._limit.get(pid, 0):
                key = _PART_COUNTER_PREFIX + struct.pack(">H", pid)

                def grab(txn: ITransaction) -> int:
                    raw = txn.get(key)
                    cur = int(raw) if raw else 1
                    txn.set(key, str(cur + self._block).encode())
                    return cur

                self._next[pid] = with_transaction(self._engine, grab)
                self._limit[pid] = self._next[pid] + self._block
            out = self._next[pid]
            self._next[pid] += 1
            return partition_tag(pid) | out


class ShardedMetaStore(MetaStore):
    """MetaStore facade with partition fencing, partition-tagged inode
    allocation and two-phase cross-partition rename/hardlink.

    ``owner_view``: callable returning the set of partition ids THIS
    process currently owns (meta_main refreshes it from RoutingInfo), or
    None to own everything. ``peer_prepare(pid, intent, path)`` /
    ``peer_finish(pid, txn_id)`` route two-phase participant work through
    the owning peer (MetaRpcClient in real clusters); absent, phases run
    locally against the shared KV.
    """

    def __init__(self, engine: IKVEngine, chain_allocator=None, *,
                 nparts: int = DEFAULT_PARTITIONS,
                 owner_view: Optional[Callable[[], Optional[Set[int]]]] = None,
                 peer_prepare: Optional[Callable] = None,
                 peer_finish: Optional[Callable] = None,
                 intent_ttl_s: float = 5.0,
                 **kw):
        super().__init__(engine, chain_allocator, **kw)
        self.nparts = max(1, nparts)
        self._owner_view = owner_view
        self._ids = PartitionedInodeAllocator(engine)
        self._twophase = TwoPhaseCoordinator(
            self, peer_prepare=peer_prepare, peer_finish=peer_finish,
            ttl_s=intent_ttl_s)
        self._load_lock = threading.Lock()
        self._op_counts: Dict[int, int] = {}

    # -- partition identity --------------------------------------------------
    def pid_of_path(self, path: str) -> int:
        return partition_of_path(path, self.nparts)

    def pid_of_dir(self, dir_path: str) -> int:
        return partition_of_dir(dir_path, self.nparts)

    def pid_of_inode(self, inode_id: int) -> int:
        return partition_of_inode(inode_id, self.nparts)

    def owned_partitions(self) -> Optional[Set[int]]:
        return self._owner_view() if self._owner_view is not None else None

    @contextlib.contextmanager
    def _op(self, pid: int):
        """Fence + account + time + bind the allocation partition for one
        op — the single site feeding ``meta.partition_op_us``."""
        owned = self.owned_partitions()
        if owned is not None and pid not in owned:
            metrics.wrong_partition.add()
            raise _err(Code.META_WRONG_PARTITION,
                       f"partition {pid} not owned (owned: {sorted(owned)})")
        with self._load_lock:
            self._op_counts[pid] = self._op_counts.get(pid, 0) + 1
        token = _ALLOC_PID.set(pid)
        t0 = time.perf_counter()
        try:
            yield pid
        finally:
            _ALLOC_PID.reset(token)
            metrics.partition_op_us(pid).record(
                (time.perf_counter() - t0) * 1e6)

    def snapshot_loads(self) -> Dict[int, int]:
        """Ops per partition since the last snapshot (drained — the meta
        heartbeat turns consecutive snapshots into ops/s for mgmtd)."""
        with self._load_lock:
            out, self._op_counts = self._op_counts, {}
            return out

    # -- by-path ops: fence on the parent-directory hash ---------------------
    def stat(self, path, user=None, **kw):
        args = (user,) if user is not None else ()
        with self._op(self.pid_of_path(path)):
            return super().stat(path, *args, **kw)

    def create(self, path, *a, **kw):
        with self._op(self.pid_of_path(path)):
            return super().create(path, *a, **kw)

    def open(self, path, *a, **kw):
        with self._op(self.pid_of_path(path)):
            return super().open(path, *a, **kw)

    def mkdirs(self, path, *a, **kw):
        with self._op(self.pid_of_path(path)):
            return super().mkdirs(path, *a, **kw)

    def symlink(self, path, *a, **kw):
        with self._op(self.pid_of_path(path)):
            return super().symlink(path, *a, **kw)

    def remove(self, path, *a, **kw):
        with self._op(self.pid_of_path(path)):
            return super().remove(path, *a, **kw)

    def set_attr(self, path, *a, **kw):
        with self._op(self.pid_of_path(path)):
            return super().set_attr(path, *a, **kw)

    def list_dir(self, path, *a, **kw):
        with self._op(self.pid_of_dir(path)):
            return super().list_dir(path, *a, **kw)

    # -- by-inode ops: fence on the id's baked partition ---------------------
    def close(self, inode_id, *a, **kw):
        with self._op(self.pid_of_inode(inode_id)):
            return super().close(inode_id, *a, **kw)

    def sync(self, inode_id, *a, **kw):
        with self._op(self.pid_of_inode(inode_id)):
            return super().sync(inode_id, *a, **kw)

    def truncate(self, path, *a, **kw):
        with self._op(self.pid_of_path(path)):
            return super().truncate(path, *a, **kw)

    # -- batched ops: group per partition, merge per-item results in order ---
    def _grouped(self, keys: List[int]):
        """index groups by partition id, preserving item order."""
        groups: Dict[int, List[int]] = {}
        for i, pid in enumerate(keys):
            groups.setdefault(pid, []).append(i)
        return groups

    def batch_create(self, items, *a, **kw):
        pids = [self.pid_of_path(it.path) for it in items]
        results: List[object] = [None] * len(items)
        for pid, idxs in self._grouped(pids).items():
            with self._op(pid):
                sub = super().batch_create([items[i] for i in idxs], *a, **kw)
            for i, res in zip(idxs, sub):
                results[i] = res
        return results

    def batch_mkdirs(self, paths, *a, **kw):
        pids = [self.pid_of_path(p) for p in paths]
        results: List[object] = [None] * len(paths)
        for pid, idxs in self._grouped(pids).items():
            with self._op(pid):
                sub = super().batch_mkdirs([paths[i] for i in idxs], *a, **kw)
            for i, res in zip(idxs, sub):
                results[i] = res
        return results

    def batch_stat(self, inode_ids, *a, **kw):
        pids = [self.pid_of_inode(i) for i in inode_ids]
        results: List[object] = [None] * len(inode_ids)
        for pid, idxs in self._grouped(pids).items():
            with self._op(pid):
                sub = super().batch_stat([inode_ids[i] for i in idxs],
                                         *a, **kw)
            for i, res in zip(idxs, sub):
                results[i] = res
        return results

    def batch_stat_by_path(self, paths, *a, **kw):
        pids = [self.pid_of_path(p) for p in paths]
        results: List[object] = [None] * len(paths)
        for pid, idxs in self._grouped(pids).items():
            with self._op(pid):
                sub = super().batch_stat_by_path([paths[i] for i in idxs],
                                                 *a, **kw)
            for i, res in zip(idxs, sub):
                results[i] = res
        return results

    def batch_set_attr(self, paths=None, *a, **kw):
        inode_ids = kw.pop("inode_ids", None)
        if paths is not None:
            keys, by_path = list(paths), True
            pids = [self.pid_of_path(p) for p in keys]
        else:
            keys, by_path = list(inode_ids or []), False
            pids = [self.pid_of_inode(i) for i in keys]
        results: List[object] = [None] * len(keys)
        for pid, idxs in self._grouped(pids).items():
            sub_keys = [keys[i] for i in idxs]
            with self._op(pid):
                if by_path:
                    sub = super().batch_set_attr(sub_keys, *a, **kw)
                else:
                    sub = super().batch_set_attr(None, *a,
                                                 inode_ids=sub_keys, **kw)
            for i, res in zip(idxs, sub):
                results[i] = res
        return results

    def batch_close(self, items, *a, **kw):
        pids = [self.pid_of_inode(it.inode_id) for it in items]
        results: List[object] = [None] * len(items)
        for pid, idxs in self._grouped(pids).items():
            with self._op(pid):
                sub = super().batch_close([items[i] for i in idxs], *a, **kw)
            for i, res in zip(idxs, sub):
                results[i] = res
        return results

    # -- cross-partition ops: two-phase --------------------------------------
    def rename(self, src, dst, *a, **kw):
        src_pid = self.pid_of_path(src)
        dst_pid = self.pid_of_path(dst)
        if src_pid == dst_pid:
            with self._op(src_pid):
                return super().rename(src, dst, *a, **kw)
        user = a[0] if a else kw.get("user", None)
        if user is None:
            from tpu3fs.meta.store import ROOT_USER
            user = ROOT_USER
        # the src owner coordinates (it serializes the dirent that must
        # die exactly once); the dst side is the prepared participant
        with self._op(src_pid):
            return self._twophase.rename(src, dst, user, src_pid, dst_pid)

    def hard_link(self, src, dst, *a, **kw):
        user = a[0] if a else kw.get("user", None)
        if user is None:
            from tpu3fs.meta.store import ROOT_USER
            user = ROOT_USER
        dst_pid = self.pid_of_path(dst)
        # the participant partition is the INODE's (nlink lives there),
        # resolved after the walk — but the coordinator fence is by dst
        # path, where the new dirent lands and the client routes to
        with self._op(dst_pid):
            src_inode = super().stat(src, user, follow=False)
            src_pid = self.pid_of_inode(src_inode.id)
            if src_pid == dst_pid:
                return super().hard_link(src, dst, user)
            return self._twophase.hard_link(src, dst, user,
                                            src_pid, dst_pid)

    # -- two-phase participant + recovery surface ----------------------------
    def twophase_prepare(self, intent, dst_path: str, user) -> None:
        """The renamePrepare RPC handler body: phase B on this (the
        participant) partition's owner."""
        pid = (intent.dst_pid if intent.kind == "rename" else intent.src_pid)
        with self._op(pid):
            if intent.kind == "rename":
                self._twophase.prepare_rename(intent, dst_path, user)
            else:
                self._twophase.prepare_hardlink(intent)

    def twophase_finish(self, txn_id: str) -> None:
        self._twophase._finish(txn_id)

    def resolve_intents(self, **kw) -> int:
        """Converge dangling two-phase records (twophase.resolve_intents);
        meta_main's resolver loop calls this with its owned pids."""
        return resolve_intents(self, **kw)
