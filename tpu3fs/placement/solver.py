"""Chain-table placement: the BIBD integer program, solved on device.

Re-expresses deploy/data_placement/src/model/data_placement.py (a Pyomo MILP
solved with HiGHS): choose an incidence of v nodes into b chain groups of
size k, each node serving in exactly r groups, such that the pairwise
co-occurrence λ[i,j] (how many groups nodes i and j share) is balanced —
λ bounds the recovery traffic any single peer absorbs when a node fails
(docs/design_notes.md "Balanced traffic during recovery"; the solver's
`recovery_traffic_factor` distinguishes "CR" chain-replication from "EC"
tables, data_placement.py:30,~92).

Instead of a branch-and-bound MILP, the search is a batched annealer: at each
step a batch of candidate swap moves is scored *in parallel on device* (one
jitted evaluation of all proposed incidence matrices) and the best accepted —
the classic simulated-annealing reformulation of BIBD construction, shaped
for the MXU (scores are b x v matmuls). Falls back to greedy round-robin
whenever the annealer cannot beat it.

check_solution mirrors the reference's validation; gen_chain_table_commands
emits the admin command file like deploy/data_placement/src/setup/
gen_chain_table.py.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class PlacementProblem:
    num_nodes: int           # v
    group_size: int          # k (= replication factor / EC group width k+m)
    targets_per_node: int    # r
    # "CR" chain replication vs "EC" erasure-coded chain tables: EC recovery
    # reads from EVERY surviving group member (factor k-1), CR full-chunk-
    # replace streams one copy (factor 1) — ref data_placement.py:91-92
    chain_table_type: str = "CR"
    # failure domains: domains[i] labels node i (rack/zone/pod), and no
    # group may put more than max_per_domain members under one label —
    # the loss budget a whole-domain kill must fit inside (width-1 for
    # CR quorum survival, ec_m for EC). None = domain-blind (legacy).
    domains: Optional[List[str]] = None
    max_per_domain: Optional[int] = None

    def __post_init__(self):
        v, k, r = self.num_nodes, self.group_size, self.targets_per_node
        if k > v:
            raise ValueError(f"group size {k} > nodes {v}")
        if (v * r) % k != 0:
            raise ValueError(f"v*r={v*r} not divisible by group size {k}")
        if self.chain_table_type not in ("CR", "EC"):
            raise ValueError(f"chain_table_type {self.chain_table_type!r}")
        if (self.domains is None) != (self.max_per_domain is None):
            raise ValueError("domains and max_per_domain go together")
        if self.domains is not None:
            if len(self.domains) != v:
                raise ValueError(
                    f"{len(self.domains)} domain labels for {v} nodes")
            cap = int(self.max_per_domain)
            if cap < 1:
                raise ValueError(f"max_per_domain {cap} < 1")
            from collections import Counter

            counts = Counter(self.domains)
            if sum(min(n, cap) for n in counts.values()) < k:
                raise ValueError(
                    f"infeasible: no {k}-group can respect "
                    f"max_per_domain={cap} over domains {dict(counts)}")
            b = self.num_groups
            for d, n in sorted(counts.items()):
                if n * r > b * cap:
                    raise ValueError(
                        f"infeasible: domain {d!r} holds {n} nodes "
                        f"needing {n * r} group slots, but {b} groups "
                        f"x cap {cap} allow only {b * cap}")

    @property
    def num_groups(self) -> int:  # b
        return self.num_nodes * self.targets_per_node // self.group_size

    @property
    def recovery_traffic_factor(self) -> int:
        """Traffic units a failed target's group emits during recovery
        (ref data_placement.py:91-92)."""
        return self.group_size - 1 if self.chain_table_type == "EC" else 1

    @property
    def max_recovery_traffic_on_peer(self) -> int:
        """Ideal (balanced) per-peer recovery traffic ceiling
        (ref data_placement.py:94-100)."""
        import math

        total = self.targets_per_node * self.recovery_traffic_factor
        return math.ceil(total / max(self.num_nodes - 1, 1))

    @property
    def lambda_lower_bound(self) -> int:
        """ceil of average pairwise co-occurrence: b*k*(k-1) / (v*(v-1))."""
        v, k, b = self.num_nodes, self.group_size, self.num_groups
        num = b * k * (k - 1)
        den = v * (v - 1)
        return -(-num // den)


def _greedy_incidence(problem: PlacementProblem) -> np.ndarray:
    """Round-robin start: group g holds the k consecutive nodes from a
    rolling cursor (mod v) — k <= v guarantees distinct members. With
    domains, the cursor walks a domain-INTERLEAVED ordering (rank within
    domain, then domain) so consecutive windows straddle domains — the
    annealer then only has to repair the remainder windows."""
    v, k, b = problem.num_nodes, problem.group_size, problem.num_groups
    order = np.arange(v)
    if problem.domains is not None:
        buckets: dict = {}
        for i, d in enumerate(problem.domains):
            buckets.setdefault(d, []).append(i)
        depth = max(len(m) for m in buckets.values())
        order = np.array(
            [m[rank] for rank in range(depth)
             for _d, m in sorted(buckets.items()) if rank < len(m)],
            dtype=int)
    M = np.zeros((b, v), dtype=np.int8)
    pos = 0
    for g in range(b):
        for i in range(k):
            M[g, order[(pos + i) % v]] = 1
        pos += k
    return M


def _domain_onehot(problem: PlacementProblem) -> Optional[np.ndarray]:
    """(v, D) one-hot node->domain incidence, None when domain-blind."""
    if problem.domains is None:
        return None
    labels = sorted(set(problem.domains))
    idx = np.array([labels.index(d) for d in problem.domains])
    return np.eye(len(labels), dtype=np.int8)[idx]


def domain_overflow(M: np.ndarray, problem: PlacementProblem) -> int:
    """Total members-over-cap across all (group, domain) cells: 0 iff
    every group respects max_per_domain."""
    onehot = _domain_onehot(problem)
    if onehot is None:
        return 0
    counts = np.asarray(M, dtype=np.int32) @ onehot.astype(np.int32)
    return int(np.maximum(counts - int(problem.max_per_domain), 0).sum())


def _score_np(M: np.ndarray) -> Tuple[int, int]:
    # float64 BLAS then round — numpy integer matmul has no BLAS path
    # and is ~100x slower on 10k-group tables; counts are << 2^53
    Mf = M.astype(np.float64)
    C = (Mf.T @ Mf).astype(np.int64)
    off = C - np.diag(np.diag(C))
    return int(off.max()), int((off * off).sum())


def solve_placement(
    problem: PlacementProblem,
    *,
    steps: int = 300,
    proposals_per_step: int = 128,
    seed: int = 0,
    target_lambda: Optional[int] = None,
    max_peer_traffic: Optional[float] = None,
) -> np.ndarray:
    """-> incidence matrix (b, v) with row sums k and column sums r.

    target_lambda bounds raw co-occurrence; max_peer_traffic bounds
    recovery traffic in the chain-table type's units (EC-vs-CR weighted,
    ref data_placement.py:91-100) — it is converted to the equivalent
    co-occurrence bound, which the annealer minimizes."""
    v, k, b, r = (
        problem.num_nodes,
        problem.group_size,
        problem.num_groups,
        problem.targets_per_node,
    )
    M = _greedy_incidence(problem).astype(np.int8)
    if max_peer_traffic is not None and k > 1:
        # traffic per co-occurrence = factor / (k-1); k=1 groups have no
        # peer traffic at all, so any bound is trivially satisfied
        per_cooc = problem.recovery_traffic_factor / (k - 1)
        traffic_tgt = int(max_peer_traffic / per_cooc)
        target_lambda = (min(target_lambda, traffic_tgt)
                         if target_lambda is not None else traffic_tgt)
    tgt = target_lambda if target_lambda is not None else problem.lambda_lower_bound
    best_max, best_ssq = _score_np(M)
    best_over = domain_overflow(M, problem)
    if (best_over == 0 and best_max <= tgt) or b < 2:
        return M  # already optimal, or a single group has no swap moves

    P = proposals_per_step
    onehot = _domain_onehot(problem)
    cap = int(problem.max_per_domain) if onehot is not None else 0
    onehot_j = (jnp.asarray(onehot, dtype=jnp.int8)
                if onehot is not None else None)

    @jax.jit
    def score_batch(Ms):
        # Ms: (P, b, v) int8 -> (domain overflow, max offdiag, ssq
        # offdiag) per proposal. Overflow leads the lexicographic
        # objective: the domain cap is a constraint, λ a preference.
        C = jnp.einsum("pbv,pbw->pvw", Ms, Ms, preferred_element_type=jnp.int32)
        eye = jnp.eye(v, dtype=jnp.int32)
        off = C * (1 - eye)
        mx = off.max(axis=(1, 2))
        if onehot_j is None:
            over = jnp.zeros_like(mx)
        else:
            counts = jnp.einsum("pbv,vd->pbd", Ms, onehot_j,
                                preferred_element_type=jnp.int32)
            over = jnp.maximum(counts - cap, 0).sum(axis=(1, 2))
        return over, mx, (off * off).sum(axis=(1, 2))

    rng = np.random.default_rng(seed)
    temperature = 1.0
    for _step in range(steps):
        # propose P swap moves FULLY VECTORIZED: for each proposal pick
        # two distinct groups (g1, g2) and exchange one member a ∈ g1∖g2
        # with one c ∈ g2∖g1 — preserving both row sums (k) and column
        # sums (r). Member selection is a weighted argmax over the
        # difference masks; proposals whose groups have no exchangeable
        # members (identical membership) fall back to the current table
        # and simply score as no-ops.
        cand = np.repeat(M[None, :, :], P, axis=0)
        g1 = rng.integers(0, b, P)
        g2 = (g1 + rng.integers(1, b, P)) % b   # distinct by construction
        rows1 = M[g1].astype(bool)              # (P, v)
        rows2 = M[g2].astype(bool)
        only1 = rows1 & ~rows2
        only2 = rows2 & ~rows1
        valid = only1.any(axis=1) & only2.any(axis=1)
        # random member pick inside each mask: argmax of uniform noise
        # restricted to the mask (masked-out entries score -1)
        noise_a = np.where(only1, rng.random((P, v)), -1.0)
        noise_c = np.where(only2, rng.random((P, v)), -1.0)
        a = noise_a.argmax(axis=1)
        c = noise_c.argmax(axis=1)
        pi = np.nonzero(valid)[0]
        cand[pi, g1[pi], a[pi]] = 0
        cand[pi, g1[pi], c[pi]] = 1
        cand[pi, g2[pi], c[pi]] = 0
        cand[pi, g2[pi], a[pi]] = 1
        overs, maxs, ssqs = jax.device_get(score_batch(jnp.asarray(cand)))
        order = np.lexsort((ssqs, maxs, overs))
        bi = order[0]
        # exploration never regresses the hard domain constraint
        accept = (
            (overs[bi], maxs[bi], ssqs[bi]) < (best_over, best_max, best_ssq)
            or (overs[bi] <= best_over
                and rng.random() < 0.02 * temperature)
        )
        if accept:
            M = cand[bi]
            best_over, best_max, best_ssq = (
                int(overs[bi]), int(maxs[bi]), int(ssqs[bi]))
        temperature *= 0.99
        if best_over == 0 and best_max <= tgt:
            break
    return M


def check_solution(
    M: np.ndarray,
    problem: PlacementProblem,
    lambda_max: Optional[int] = None,
    max_peer_traffic: Optional[float] = None,
) -> bool:
    """Validate structure + balanced peer recovery traffic (ref
    check_solution in data_placement.py)."""
    v, k, b, r = (
        problem.num_nodes,
        problem.group_size,
        problem.num_groups,
        problem.targets_per_node,
    )
    M = np.asarray(M)
    if M.shape != (b, v):
        return False
    if not ((M == 0) | (M == 1)).all():
        return False
    if not (M.sum(axis=1) == k).all():
        return False
    if not (M.sum(axis=0) == r).all():
        return False
    if domain_overflow(M, problem) > 0:
        return False
    if lambda_max is not None:
        mx, _ = _score_np(M)
        if mx > lambda_max:
            return False
    if max_peer_traffic is not None:
        # worst per-peer traffic over every single-node failure, in the
        # chain-table type's units (ref check_solution peer traffic)
        worst = max(
            float(peer_recovery_traffic(M, problem, n).max())
            for n in range(v)
        )
        if worst > max_peer_traffic + 1e-9:
            return False
    return True


def recovery_traffic_factor(M: np.ndarray, node: int) -> np.ndarray:
    """Per-peer share of traffic when `node` fails: co-occurrence row
    (how many of the failed node's groups each peer serves)."""
    M = np.asarray(M, dtype=np.int32)
    C = M.T @ M
    row = C[node].copy()
    row[node] = 0
    return row


def peer_recovery_traffic(
    M: np.ndarray, problem: PlacementProblem, node: int
) -> np.ndarray:
    """Per-peer recovery traffic in TRAFFIC UNITS when `node` fails:
    co-occurrence scaled by the chain-table type's recovery factor —
    the quantity the reference's peer_traffic_map reports
    (data_placement.py:296-300). For EC every surviving group member
    streams its shard (factor (k-1)/(k-1) = 1 per co-occurrence); for CR
    one full-chunk copy spreads over the k-1 peers (1/(k-1) each)."""
    row = recovery_traffic_factor(M, node).astype(np.float64)
    # group_size=1 has no peers inside a group: factor is 0, traffic is 0
    return (row * problem.recovery_traffic_factor
            / max(problem.group_size - 1, 1))


def gen_chain_table_commands(
    M: np.ndarray,
    *,
    first_target_id: int = 1000,
    first_chain_id: int = 900_001,
    table_id: int = 1,
    node_ids: Optional[List[int]] = None,
    ec_k: int = 0,
    ec_m: int = 0,
) -> List[str]:
    """Admin command lines (create-target / upload-chains / upload-chain-table)
    like the reference's generated command files. With ec_k/ec_m the chains
    are emitted as EC(k, m) chain tables (group width must be k+m)."""
    M = np.asarray(M)
    b, v = M.shape
    if ec_k:
        width = int(M[0].sum())
        if ec_k + ec_m != width:
            raise ValueError(
                f"EC({ec_k},{ec_m}) needs group width {ec_k + ec_m}, "
                f"placement has {width}")
    node_ids = node_ids or [10 + i for i in range(v)]
    lines: List[str] = []
    chains: List[List[int]] = []
    tid = first_target_id
    for g in range(b):
        members = np.nonzero(M[g])[0]
        targets = []
        for n in members:
            lines.append(
                f"create-target --target-id {tid} --node-id {node_ids[n]} "
                f"--chain-id {first_chain_id + g}"
            )
            targets.append(tid)
            tid += 1
        chains.append(targets)
    ec_suffix = f" --ec-k {ec_k} --ec-m {ec_m}" if ec_k else ""
    for g, targets in enumerate(chains):
        lines.append(
            f"upload-chain --chain-id {first_chain_id + g} --targets "
            + ",".join(map(str, targets)) + ec_suffix
        )
    lines.append(
        f"upload-chain-table --table-id {table_id} --chains "
        + ",".join(str(first_chain_id + g) for g in range(b))
    )
    return lines
