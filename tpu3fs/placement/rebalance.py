"""Incremental rebalance planner: MINIMAL chain diffs for topology deltas.

The full solver (placement/solver.py) lays a balanced table from scratch;
re-running it after a topology change would reshuffle everything — O(all
data) movement for an O(1/N) capacity change. This planner instead takes
the LIVE chain table plus a delta (nodes joined / draining / dead) and
emits the smallest ordered set of per-chain membership replacements that

- empties every draining/dead node (each affected chain gets ONE
  replacement per plan — re-plan after a wave for pathological multi-
  failure chains),
- fills every joined node to its fair share, floor(total/(N+joined)),
  so joining 1 node to an N-node balanced table moves
  ≤ ceil(total/(N+1)) chains (the minimality acceptance bound),
- keeps the pairwise co-occurrence λ (the quantity whose balance bounds
  any one peer's recovery traffic — solver docstring, ref
  deploy/data_placement) within tolerance: destinations are chosen
  greedily to minimize (λ spike with the chain's remaining members,
  resulting node load),
- never plans a move that would drop a chain below its write-quorum
  mid-execution (``check_plan``): CR needs a surviving serving source;
  EC needs every other member SERVING because the swap itself spends the
  chain's one spare redundancy unit.

A NO-OP delta produces an EMPTY plan — the planner never "improves" a
table nobody asked it to touch (operators re-layout with the solver).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from tpu3fs.mgmtd.types import (
    NodeStatus,
    NodeType,
    PublicTargetState,
    RoutingInfo,
)
from tpu3fs.migration.types import MoveSpec
from tpu3fs.monitor.recorder import ValueRecorder

_rec_plan_moves = ValueRecorder("placement.plan_moves")
_rec_lambda = ValueRecorder("placement.lambda_max")

DRAINING_TAG = "draining"


@dataclass
class TopologyDelta:
    joined: List[int] = field(default_factory=list)
    draining: List[int] = field(default_factory=list)
    dead: List[int] = field(default_factory=list)

    @property
    def empty(self) -> bool:
        return not (self.joined or self.draining or self.dead)

    @classmethod
    def from_routing(cls, routing: RoutingInfo) -> "TopologyDelta":
        """Derive the delta an operator usually means: storage nodes that
        are connected but own no chain membership JOINED; nodes tagged
        ``draining=1`` DRAINING; heartbeat-failed nodes still owning
        memberships DEAD."""
        hosting: Dict[int, int] = {}
        for info in routing.targets.values():
            if info.chain_id:
                hosting[info.node_id] = hosting.get(info.node_id, 0) + 1
        joined, draining, dead = [], [], []
        for node in routing.nodes.values():
            if node.type != NodeType.STORAGE:
                continue
            if node.tags.get(DRAINING_TAG):
                if hosting.get(node.node_id):
                    draining.append(node.node_id)
                continue
            if node.status == NodeStatus.HEARTBEAT_FAILED:
                if hosting.get(node.node_id):
                    dead.append(node.node_id)
                continue
            if node.status == NodeStatus.HEARTBEAT_CONNECTED \
                    and not hosting.get(node.node_id):
                joined.append(node.node_id)
        return cls(sorted(joined), sorted(draining), sorted(dead))


@dataclass
class PlannedMove:
    chain_id: int
    out_target: int
    src_node: int
    dst_node: int
    is_ec: bool = False

    def spec(self) -> MoveSpec:
        return MoveSpec(chain_id=self.chain_id, out_target=self.out_target,
                        dst_node=self.dst_node)


@dataclass
class PlanStats:
    lambda_max: int = 0
    lambda_lower_bound: int = 0
    recovery_traffic_factor: int = 1
    per_node: Dict[int, int] = field(default_factory=dict)


@dataclass
class RebalancePlan:
    moves: List[PlannedMove] = field(default_factory=list)
    before: PlanStats = field(default_factory=PlanStats)
    after: PlanStats = field(default_factory=PlanStats)
    #: chains that need ANOTHER wave after this plan lands (several
    #: members on leaving nodes at once): re-plan when this wave is done
    deferred_chains: List[int] = field(default_factory=list)

    @property
    def empty(self) -> bool:
        return not self.moves


def _chain_members(routing: RoutingInfo, chain) -> List[Tuple[int, int]]:
    """[(target_id, node_id)] for a chain, routing-resolved."""
    out = []
    for t in chain.targets:
        info = routing.targets.get(t.target_id)
        out.append((t.target_id, info.node_id if info else 0))
    return out


def incidence_of_routing(
    routing: RoutingInfo, node_ids: List[int],
    chain_ids: Optional[List[int]] = None,
) -> np.ndarray:
    """(chains × nodes) 0/1 incidence of the LIVE table over ``node_ids``
    — the solver's matrix shape, derived from routing instead of laid
    fresh, so solver-side validators (``check_solution`` properties,
    ``recovery_traffic_factor``) apply to the running cluster."""
    chain_ids = chain_ids or sorted(routing.chains)
    idx = {n: i for i, n in enumerate(node_ids)}
    M = np.zeros((len(chain_ids), len(node_ids)), dtype=np.int8)
    for g, cid in enumerate(chain_ids):
        chain = routing.chains[cid]
        for _tid, node in _chain_members(routing, chain):
            if node in idx:
                M[g, idx[node]] = 1
    return M


def _stats(M: np.ndarray, node_ids: List[int], factor: int) -> PlanStats:
    # float64 BLAS then round: integer matmul has no BLAS path in numpy
    # and runs ~100x slower at 10k-chain tables (BENCH_SCALE rebalance);
    # co-occurrence counts are << 2^53 so the float trip is exact
    Mf = M.astype(np.float64)
    C = (Mf.T @ Mf).astype(np.int64)
    off = C - np.diag(np.diag(C))
    width = int(M.sum(axis=1).max()) if len(M) else 0
    b = len(M)
    v = max(len(node_ids), 1)
    lb = 0
    if v > 1 and b:
        num = b * width * (width - 1)
        lb = -(-num // (v * (v - 1)))
    return PlanStats(
        lambda_max=int(off.max()) if off.size else 0,
        lambda_lower_bound=lb,
        recovery_traffic_factor=factor,
        per_node={n: int(M[:, i].sum()) for i, n in enumerate(node_ids)},
    )


def plan_rebalance(
    routing: RoutingInfo,
    delta: Optional[TopologyDelta] = None,
    *,
    chain_ids: Optional[List[int]] = None,
    fill_joined: bool = True,
) -> RebalancePlan:
    """-> minimal ordered move list for ``delta`` (derived from routing
    tags/heartbeats when not given). Pure function of its inputs — safe
    to call for preview (admin_cli placement-plan) and again for apply.

    ``fill_joined=False`` skips the fair-share FILL phase: joined nodes
    still count as eligible EVACUATION destinations (an empty restarted
    node is often the only place a leaving member can go), but no moves
    are planned purely to give them load — the migration worker's auto
    re-plan uses this so capacity rebalancing stays an operator
    decision."""
    delta = delta or TopologyDelta.from_routing(routing)
    chain_ids = chain_ids or sorted(routing.chains)
    chains = {cid: routing.chains[cid] for cid in chain_ids
              if cid in routing.chains}
    factor = 1
    for c in chains.values():
        if c.is_ec:
            factor = max(factor, c.ec_k + c.ec_m - 1)

    leaving = set(delta.draining) | set(delta.dead)
    hosting = set()
    for cid, chain in chains.items():
        for _t, n in _chain_members(routing, chain):
            if n:
                hosting.add(n)
    final_nodes = sorted((hosting | set(delta.joined)) - leaving)
    all_nodes = sorted(hosting | set(delta.joined) | leaving)
    before = _stats(incidence_of_routing(routing, all_nodes, chain_ids),
                    all_nodes, factor)
    plan = RebalancePlan(before=before)
    if delta.empty or not final_nodes:
        plan.after = before
        _rec_plan_moves.set(0)
        return plan

    # working state: membership node-sets per chain + per-node loads +
    # pairwise co-occurrence over final nodes, updated as moves are chosen
    idx = {n: i for i, n in enumerate(final_nodes)}
    nvec = len(final_nodes)
    loads = np.zeros(nvec, dtype=np.int64)
    C = np.zeros((nvec, nvec), dtype=np.int64)
    member_nodes: Dict[int, set] = {}
    for cid, chain in chains.items():
        ns = {n for _t, n in _chain_members(routing, chain) if n in idx}
        member_nodes[cid] = ns
        for n in ns:
            loads[idx[n]] += 1
        for a in ns:
            for b in ns:
                if a != b:
                    C[idx[a], idx[b]] += 1

    # failure-domain labels (mgmtd node tags): a destination may not push
    # any domain past the chain's loss budget — width-1 for CR, ec_m for
    # EC (docs/scale.md). Unlabeled clusters stay domain-blind.
    node_domain = {n.node_id: n.tags["domain"]
                   for n in routing.nodes.values()
                   if n.tags.get("domain")}

    def domain_ok(cid: int, members, dst: int) -> bool:
        dom = node_domain.get(dst)
        if dom is None:
            return True
        chain = chains[cid]
        cap = chain.ec_m if chain.is_ec \
            else max(len(chain.targets) - 1, 1)
        count = 1 + sum(1 for m in members if node_domain.get(m) == dom)
        return count <= cap

    def pick_dst(cid: int) -> Optional[int]:
        """Least-(λ-spike, load) eligible destination for one chain.
        None when every candidate is taken or would breach the chain's
        failure-domain budget — the caller defers the chain."""
        taken = member_nodes[cid]
        best = None
        for n in final_nodes:
            if n in taken or not domain_ok(cid, taken, n):
                continue
            i = idx[n]
            spike = max((C[i, idx[m]] + 1 for m in taken), default=1)
            key = (spike, loads[i], n)
            if best is None or key < best[0]:
                best = (key, n)
        return best[1] if best is not None else None

    def commit(cid: int, out_target: int, src_node: int, dst: int,
               is_ec: bool) -> None:
        taken = member_nodes[cid]
        if src_node in idx:
            loads[idx[src_node]] -= 1
            for m in taken:
                if m != src_node and m in idx:
                    C[idx[src_node], idx[m]] -= 1
                    C[idx[m], idx[src_node]] -= 1
        taken.discard(src_node)
        for m in taken:
            if m in idx:
                C[idx[dst], idx[m]] += 1
                C[idx[m], idx[dst]] += 1
        taken.add(dst)
        loads[idx[dst]] += 1
        plan.moves.append(PlannedMove(cid, out_target, src_node, dst,
                                      is_ec=is_ec))

    # 1) EVACUATE leaving nodes: one replacement per chain per wave
    for cid in sorted(chains):
        chain = chains[cid]
        on_leaving = [(t, n) for t, n in _chain_members(routing, chain)
                      if n in leaving]
        if not on_leaving:
            continue
        out_target, src_node = on_leaving[0]
        dst = pick_dst(cid)
        if dst is None:
            plan.deferred_chains.append(cid)
            continue
        commit(cid, out_target, src_node, dst, chain.is_ec)
        if len(on_leaving) > 1:
            plan.deferred_chains.append(cid)

    # 2) FILL joined nodes to their fair share — and not one chain more
    total = int(loads.sum())
    fair = (total // max(len(final_nodes), 1)) if fill_joined else 0
    moved_chains = {m.chain_id for m in plan.moves}
    for _ in range(total):
        under = [n for n in delta.joined
                 if n in idx and loads[idx[n]] < fair]
        if not under:
            break
        dst = min(under, key=lambda n: (loads[idx[n]], n))
        # donor: most loaded node above the fair ceiling; among its
        # chains pick the one whose move spikes λ least
        best = None
        ceiling = -(-total // len(final_nodes))  # ceil fair share
        for cid in sorted(chains):
            if cid in moved_chains:
                continue  # one move per chain per plan
            chain = chains[cid]
            if dst in member_nodes[cid]:
                continue
            for t, n in _chain_members(routing, chain):
                if n not in idx or n in leaving:
                    continue
                if loads[idx[n]] < ceiling or n in delta.joined:
                    continue
                if not domain_ok(cid, member_nodes[cid] - {n}, dst):
                    continue
                spike = max((C[idx[dst], idx[m]] + 1
                             for m in member_nodes[cid] if m != n
                             and m in idx), default=1)
                key = (-loads[idx[n]], spike, cid)
                if best is None or key < best[0]:
                    best = (key, cid, t, n)
        if best is None:
            break
        _key, cid, out_target, src_node = best
        commit(cid, out_target, src_node, dst, chains[cid].is_ec)
        moved_chains.add(cid)

    # predicted table = working state
    Mafter = np.zeros((len(chains), nvec), dtype=np.int8)
    for g, cid in enumerate(sorted(chains)):
        for n in member_nodes[cid]:
            if n in idx:
                Mafter[g, idx[n]] = 1
    plan.after = _stats(Mafter, final_nodes, factor)
    _rec_plan_moves.set(len(plan.moves))
    _rec_lambda.set(plan.after.lambda_max)
    return plan


def check_plan(routing: RoutingInfo, plan: RebalancePlan,
               delta: Optional[TopologyDelta] = None) -> List[str]:
    """Quorum preflight: problems (empty = safe to apply). A move is safe
    when the chain keeps a usable write/read quorum at EVERY intermediate
    step of its job:

    - CR: at least one member OFF the dead set stays SERVING (the copy
      source; the outgoing member itself counts while draining — it only
      leaves after its replacement serves);
    - EC: every OTHER member SERVING — the shard swap spends the chain's
      only spare redundancy unit, so it must actually be spare.
    """
    delta = delta or TopologyDelta.from_routing(routing)
    dead = set(delta.dead)
    node_domain = {n.node_id: n.tags["domain"]
                   for n in routing.nodes.values()
                   if n.tags.get("domain")}
    problems: List[str] = []
    for mv in plan.moves:
        chain = routing.chains.get(mv.chain_id)
        if chain is None:
            problems.append(f"chain {mv.chain_id}: not in routing")
            continue
        others = [t for t in chain.targets if t.target_id != mv.out_target]
        dst_dom = node_domain.get(mv.dst_node)
        if dst_dom is not None:
            cap = chain.ec_m if chain.is_ec \
                else max(len(chain.targets) - 1, 1)
            stay = [routing.targets[t.target_id].node_id for t in others
                    if t.target_id in routing.targets]
            count = 1 + sum(1 for n in stay
                            if node_domain.get(n) == dst_dom)
            if count > cap:
                problems.append(
                    f"chain {mv.chain_id}: landing {mv.out_target}'s "
                    f"replacement on {mv.dst_node} puts {count} members "
                    f"in domain {dst_dom!r} (budget {cap}) — a single-"
                    f"domain kill would break quorum")
        if chain.is_ec:
            bad = [t.target_id for t in others
                   if t.public_state != PublicTargetState.SERVING]
            if bad:
                problems.append(
                    f"chain {mv.chain_id}: EC swap of {mv.out_target} "
                    f"while members {bad} are not SERVING would drop the "
                    "stripe below its k-quorum")
            continue
        sources = []
        for t in chain.targets:
            info = routing.targets.get(t.target_id)
            node = info.node_id if info else 0
            if node in dead:
                continue
            if t.public_state == PublicTargetState.SERVING:
                sources.append(t.target_id)
        if not sources:
            problems.append(
                f"chain {mv.chain_id}: no surviving SERVING copy source "
                f"for replacing {mv.out_target}")
    return problems
