from tpu3fs.placement.solver import (  # noqa: F401
    PlacementProblem,
    check_solution,
    gen_chain_table_commands,
    solve_placement,
)
