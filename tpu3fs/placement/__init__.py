from tpu3fs.placement.rebalance import (  # noqa: F401
    DRAINING_TAG,
    PlannedMove,
    RebalancePlan,
    TopologyDelta,
    check_plan,
    incidence_of_routing,
    plan_rebalance,
)
from tpu3fs.placement.solver import (  # noqa: F401
    PlacementProblem,
    check_solution,
    gen_chain_table_commands,
    solve_placement,
)
