"""Packed record-file format: fixed header, per-record index, CRC32C.

The on-FS twin of DeepSeek's FFRecord (the companion format the reference
ships for its training data loaders, SURVEY §0): many small samples packed
into one large file so batch reads become a handful of large extents
instead of millions of tiny files — exactly the shape distributed SSD
arrays want (PAPERS.md, online-EC SSD study: random small reads are the
cliff).

Layout (little-endian)::

    [0, 32)                 header: magic "TPRC", version u32,
                            nrecords u64, index_crc u32, 12 reserved bytes
    [32, 32 + 16*n)         index: per record (offset u64, length u32,
                            crc32c u32); offsets are absolute file offsets
    [data_start, ...)       record payloads, back to back, in index order

``index_crc`` covers the raw index bytes, so a truncated or bit-rotted
index fails loudly at open; each record carries its own CRC32C so payload
corruption fails at read (``Code.DATALOAD_CORRUPT``).

Commit protocol: writers stage everything under ``<path>.tmp`` and
publish with a single meta ``rename`` — the ckpt manifest protocol — so a
reader never observes a half-written record file and a crashed packer
leaves only a ``.tmp`` for cleanup.

All IO here is tagged ``TrafficClass.DATALOAD``.
"""

from __future__ import annotations

import struct
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from tpu3fs.client.file_io import FileIoClient
from tpu3fs.meta.store import OpenFlags
from tpu3fs.ops.crc32c import crc32c
from tpu3fs.qos.core import TrafficClass, tagged
from tpu3fs.utils.result import Code, FsError
from tpu3fs.utils.result import err as _err

MAGIC = b"TPRC"
FORMAT_VERSION = 1
TMP_SUFFIX = ".tmp"

_HEADER = struct.Struct("<4sIQI12x")   # magic, version, nrecords, index_crc
_ENTRY = struct.Struct("<QII")         # offset, length, crc32c
HEADER_SIZE = _HEADER.size            # 32
ENTRY_SIZE = _ENTRY.size              # 16

#: numpy view of the index region (offset, length, crc), zero-copy decode
_INDEX_DTYPE = np.dtype([("offset", "<u8"), ("length", "<u4"),
                         ("crc", "<u4")])


def data_start(nrecords: int) -> int:
    return HEADER_SIZE + nrecords * ENTRY_SIZE


class RecordFileWriter:
    """Stream records into ``<path>.tmp``; ``commit()`` publishes.

    With ``num_records`` declared up front, payloads stream straight to
    the staging file (buffered in ~``buffer_bytes`` runs through the
    striped write path) and only the header + index land at commit —
    constant host memory however large the file. Without it, payloads are
    buffered in host memory until commit (fine for small packs; the
    packer CLI always declares the count).
    """

    def __init__(self, meta, fio: FileIoClient, path: str, *,
                 num_records: Optional[int] = None,
                 client_id: str = "dataload-pack",
                 buffer_bytes: int = 4 << 20):
        self._meta = meta
        self._fio = fio
        self.path = path
        self._declared = num_records
        self._client_id = client_id
        self._buffer_cap = max(1, buffer_bytes)
        self._entries: List[Tuple[int, int, int]] = []  # offset, len, crc
        self._pending: List[bytes] = []  # buffered payload run
        self._pending_bytes = 0
        self._pos = 0 if num_records is None else data_start(num_records)
        self._open = None  # (inode, session_id), staged lazily
        self._done = False

    # -- staging ----------------------------------------------------------
    @property
    def tmp_path(self) -> str:
        return self.path + TMP_SUFFIX

    def _stage(self):
        if self._open is None:
            res = self._meta.create(
                self.tmp_path,
                flags=OpenFlags.WRITE | OpenFlags.CREATE | OpenFlags.TRUNC,
                client_id=self._client_id)
            self._open = (res.inode, res.session_id)
        return self._open

    def _flush_pending(self) -> None:
        if not self._pending:
            return
        inode, _ = self._stage()
        blob = b"".join(self._pending)
        off = self._pos - len(blob)
        self._fio.write(inode, off, blob)
        self._pending = []
        self._pending_bytes = 0

    def append(self, payload) -> int:
        """Add one record; returns its record index."""
        if self._done:
            raise _err(Code.INVALID_ARG, "writer already committed/aborted")
        if self._declared is not None and \
                len(self._entries) >= self._declared:
            raise _err(Code.INVALID_ARG,
                       f"more than the declared {self._declared} records")
        payload = bytes(payload)
        self._entries.append((self._pos, len(payload), crc32c(payload)))
        self._pos += len(payload)
        self._pending.append(payload)
        self._pending_bytes += len(payload)
        if self._declared is not None and \
                self._pending_bytes >= self._buffer_cap:
            with tagged(TrafficClass.DATALOAD):
                self._flush_pending()
        return len(self._entries) - 1

    # -- commit / abort ---------------------------------------------------
    def commit(self) -> "RecordFile":
        """Write header + index, close the session, rename into place."""
        if self._done:
            raise _err(Code.INVALID_ARG, "writer already committed/aborted")
        if self._declared is not None and \
                len(self._entries) != self._declared:
            raise _err(Code.INVALID_ARG,
                       f"declared {self._declared} records, "
                       f"appended {len(self._entries)}")
        n = len(self._entries)
        shift = 0 if self._declared is not None else data_start(n)
        index = b"".join(
            _ENTRY.pack(off + shift, length, crc)
            for off, length, crc in self._entries)
        header = _HEADER.pack(MAGIC, FORMAT_VERSION, n, crc32c(index))
        with tagged(TrafficClass.DATALOAD):
            inode, session = self._stage()
            if self._declared is None:
                # buffered mode: everything lands in one pass, payload
                # already offset by the header+index it follows
                self._fio.write(inode, 0, header + index
                                + b"".join(self._pending))
                self._pending = []
                self._pending_bytes = 0
            else:
                self._flush_pending()
                self._fio.write(inode, 0, header + index)
            total = max(self._pos + shift, data_start(n))
            self._meta.close(inode.id, session, length_hint=total,
                             wrote=True)
            self._meta.rename(self.tmp_path, self.path)
        self._done = True
        return RecordFile.open(self._meta, self._fio, self.path)

    def abort(self) -> None:
        """Drop the staging file (crash cleanup is just removing .tmp)."""
        if self._done:
            return
        self._done = True
        if self._open is None:
            return
        inode, session = self._open
        with tagged(TrafficClass.DATALOAD):
            try:
                self._meta.close(inode.id, session)
            except FsError:
                pass
            try:
                self._fio.remove_chunks(inode)
                self._meta.remove(self.tmp_path)
            except FsError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.commit()
        else:
            self.abort()
        return False


class RecordFile:
    """One opened packed record file: decoded index + batched reads."""

    def __init__(self, fio: FileIoClient, inode, path: str,
                 index: np.ndarray):
        self._fio = fio
        self.inode = inode
        self.path = path
        self._index = index

    @classmethod
    def open(cls, meta, fio: FileIoClient, path: str) -> "RecordFile":
        inode = meta.stat(path)
        with tagged(TrafficClass.DATALOAD):
            raw = fio.read(inode, 0, HEADER_SIZE)
        if len(raw) < HEADER_SIZE:
            raise _err(Code.DATALOAD_CORRUPT, f"{path}: short header")
        magic, version, nrec, index_crc = _HEADER.unpack(raw)
        if magic != MAGIC:
            raise _err(Code.DATALOAD_CORRUPT,
                       f"{path}: bad magic {magic!r}")
        if version > FORMAT_VERSION:
            raise _err(Code.DATALOAD_CORRUPT,
                       f"{path}: format {version} > {FORMAT_VERSION}")
        with tagged(TrafficClass.DATALOAD):
            raw_index = fio.read(inode, HEADER_SIZE, nrec * ENTRY_SIZE)
        if len(raw_index) != nrec * ENTRY_SIZE or \
                crc32c(raw_index) != index_crc:
            raise _err(Code.DATALOAD_CORRUPT,
                       f"{path}: index CRC/length mismatch")
        index = np.frombuffer(raw_index, dtype=_INDEX_DTYPE)
        return cls(fio, inode, path, index)

    # -- index ------------------------------------------------------------
    @property
    def num_records(self) -> int:
        return len(self._index)

    def __len__(self) -> int:
        return len(self._index)

    def extent(self, i: int) -> Tuple[int, int]:
        e = self._index[i]
        return int(e["offset"]), int(e["length"])

    def record_crc(self, i: int) -> int:
        return int(self._index[i]["crc"])

    def total_payload_bytes(self) -> int:
        return int(self._index["length"].sum()) if len(self._index) else 0

    # -- reads ------------------------------------------------------------
    def read(self, i: int, *, verify: bool = True) -> bytes:
        return bytes(self.read_batch([i], verify=verify)[0])

    def read_batch(self, indices: Sequence[int], *, verify: bool = True,
                   coalesce_gap: int = 64 << 10,
                   max_span_bytes: int = 8 << 20) -> List[bytes]:
        """Fetch many records as coalesced sorted extents (one
        node-grouped ``batch_read_files`` call), then slice each record
        back out as a zero-copy view of its span."""
        extents = [self.extent(i) for i in indices]
        spans, places = plan_coalesced(extents, gap=coalesce_gap,
                                       max_span=max_span_bytes)
        with tagged(TrafficClass.DATALOAD):
            blobs = self._fio.batch_read_files(
                [(self.inode, off, n) for off, n in spans])
        out: List[bytes] = []
        for idx, (si, rel) in zip(indices, places):
            length = int(self._index[idx]["length"])
            rec = memoryview(blobs[si])[rel:rel + length]
            if len(rec) != length:
                raise _err(Code.DATALOAD_CORRUPT,
                           f"{self.path}[{idx}]: short record")
            if verify and crc32c(rec) != int(self._index[idx]["crc"]):
                raise _err(Code.DATALOAD_CORRUPT,
                           f"{self.path}[{idx}]: record CRC mismatch")
            out.append(rec)  # memoryview; callers copy only if retaining
        return out

    def summary(self) -> Dict[str, object]:
        """Inspect view (admin_cli dataload-inspect)."""
        lengths = self._index["length"]
        return {
            "path": self.path,
            "records": int(len(self._index)),
            "payload_bytes": self.total_payload_bytes(),
            "file_bytes": int(self.inode.length),
            "min_record": int(lengths.min()) if len(lengths) else 0,
            "max_record": int(lengths.max()) if len(lengths) else 0,
            "data_start": data_start(len(self._index)),
        }


def plan_coalesced(extents: Sequence[Tuple[int, int]], *,
                   gap: int = 64 << 10, max_span: int = 8 << 20
                   ) -> Tuple[List[Tuple[int, int]],
                              List[Tuple[int, int]]]:
    """Merge record extents into large sorted read spans.

    -> (spans, places): ``spans`` is the sorted, merged [(offset, length)]
    to fetch; ``places[k] = (span index, offset inside span)`` locates
    input extent k in the fetched spans. Two extents merge when the gap
    between them is at most ``gap`` (over-read is cheaper than another
    IOP until the gap outgrows the seek it saves) and the merged span
    stays within ``max_span`` (bounds both over-read waste and the
    single-reply buffer size). Overlapping/duplicate extents share one
    span.
    """
    if not extents:
        return [], []
    order = sorted(range(len(extents)), key=lambda k: extents[k][0])
    spans: List[List[int]] = []      # [start, end) being built
    places: List[Optional[Tuple[int, int]]] = [None] * len(extents)
    for k in order:
        off, n = extents[k]
        if spans:
            cur = spans[-1]
            new_end = max(cur[1], off + n)
            if off - cur[1] <= gap and new_end - cur[0] <= max_span:
                cur[1] = new_end
                places[k] = (len(spans) - 1, off - cur[0])
                continue
        spans.append([off, off + n])
        places[k] = (len(spans) - 1, off - spans[-1][0])
    return ([(s, e - s) for s, e in spans],
            places)  # type: ignore[return-value]


def encode_record_file(payloads: Sequence[bytes]) -> bytes:
    """The complete file image for a payload list — for callers writing
    through a raw data path (benches over meta-less RPC clusters) and as
    the format oracle in tests. Byte-identical to what
    ``RecordFileWriter`` commits."""
    n = len(payloads)
    pos = data_start(n)
    entries = []
    for p in payloads:
        entries.append(_ENTRY.pack(pos, len(p), crc32c(p)))
        pos += len(p)
    index = b"".join(entries)
    header = _HEADER.pack(MAGIC, FORMAT_VERSION, n, crc32c(index))
    return header + index + b"".join(payloads)


def pack_records(meta, fio: FileIoClient, path: str,
                 records: Iterable[bytes],
                 *, num_records: Optional[int] = None,
                 client_id: str = "dataload-pack") -> "RecordFile":
    """Pack an iterable of payloads into one committed record file."""
    if num_records is None and hasattr(records, "__len__"):
        num_records = len(records)  # type: ignore[arg-type]
    writer = RecordFileWriter(meta, fio, path, num_records=num_records,
                              client_id=client_id)
    try:
        for payload in records:
            writer.append(payload)
    except BaseException:
        writer.abort()
        raise
    return writer.commit()
