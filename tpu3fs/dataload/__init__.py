"""tpu3fs/dataload — the training-side input pipeline.

The headline consumer the reference was built for (PAPER/SURVEY §0:
"training data loaders" lead the workload list; DeepSeek ships the
companion FFRecord format): random batch reads over huge packed datasets
at full storage bandwidth, through the normal client stack — striped
batched chunk IO, atomic-rename commit, the ``dataload`` QoS class,
monitor recorders — no private storage path.

- ``recordio`` — packed record-file format (fixed header, per-record
  offset index + CRC32C, ``.tmp`` → rename commit) and the packer
- ``dataset``  — multi-file global sample index, seeded Feistel-PRP
  per-epoch shuffle (no materialized permutation), dp sharding over the
  process mesh
- ``loader``   — pipelined batch fetcher: coalesced sorted batch reads,
  CRC verify, bounded-byte prefetch, ``jax.device_put`` hand-off
- ``state``    — the four-integer resumable cursor, composing with ckpt
  save sessions (a restored job resumes mid-epoch exactly)

Driven by ``admin_cli dataload-pack|dataload-inspect``,
``bin/dataload_pack_main.py`` and ``benchmarks/dataload_bench.py``.
"""

from __future__ import annotations

from tpu3fs.dataload.dataset import (
    FeistelPermutation,
    IdentityPermutation,
    PackedDataset,
    dp_info,
)
from tpu3fs.dataload.loader import Batch, DataLoader, LoaderConfig
from tpu3fs.dataload.recordio import (
    RecordFile,
    RecordFileWriter,
    pack_records,
    plan_coalesced,
)
from tpu3fs.dataload.state import DataloadState, StateStore

__all__ = [
    "Batch",
    "DataLoader",
    "DataloadState",
    "FeistelPermutation",
    "IdentityPermutation",
    "LoaderConfig",
    "PackedDataset",
    "RecordFile",
    "RecordFileWriter",
    "StateStore",
    "dp_info",
    "pack_records",
    "plan_coalesced",
]
