"""Adaptive coalesce-gap controller: learn the span-merge threshold from
observed batch latency.

``recordio.plan_coalesced`` merges sorted record extents whose gap is
below a threshold — trading over-read wire bytes against per-span round
trips. The 64 KiB default was measured ONCE on one host/record-size
combination (dataload_bench sweep); the right value moves with record
size, transport and storage load. This controller learns it online from
the ``dataload.batch_ms`` signal the loader already measures per batch
(the stage-timing substrate of the tracing PR), with no extra IO:

- a fixed LADDER of candidate gaps is explored round-robin for
  ``probes_per_arm`` batches each (deterministic: no randomness, so the
  convergence test can pin the trajectory exactly);
- after exploration the arm with the best per-byte-normalized EWMA cost
  is exploited;
- every ``reprobe_every`` batches one NEIGHBOR of the current arm is
  probed once (hill climbing), so the controller tracks drift — a
  storage tier that got slower per round trip pushes the gap up, a
  faster one pulls it down — without ever leaving steady state more
  than 1/reprobe_every of the time.

Costs are normalized per payload byte (ms/MiB) so batches of different
sizes share one scale.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence, Tuple

#: candidate gaps: 8 KiB .. 256 KiB around the measured 64 KiB optimum
DEFAULT_LADDER: Tuple[int, ...] = tuple(
    1 << s for s in range(13, 19))  # 8K, 16K, 32K, 64K, 128K, 256K


class GapController:
    """Online hill-climbing tuner for ``coalesce_gap``.

    Protocol: call ``next_gap()`` to get the gap for the upcoming batch,
    then ``observe(gap, batch_ms, nbytes)`` with the measured wall —
    keyed by the gap actually used, so concurrent fetch workers
    attribute correctly whatever order they finish in.
    """

    def __init__(self, ladder: Sequence[int] = DEFAULT_LADDER, *,
                 probes_per_arm: int = 3, ewma: float = 0.3,
                 reprobe_every: int = 64):
        if not ladder:
            raise ValueError("empty gap ladder")
        self._ladder = tuple(sorted(set(int(g) for g in ladder)))
        self._probes_per_arm = max(1, int(probes_per_arm))
        self._alpha = float(ewma)
        self._reprobe_every = max(2, int(reprobe_every))
        self._lock = threading.Lock()
        # per-arm EWMA of ms per MiB (None = never observed)
        self._cost: Dict[int, Optional[float]] = {
            g: None for g in self._ladder}
        self._issued = 0          # next_gap() calls (drives the schedule)
        self._observed = 0
        self._best = self._ladder[len(self._ladder) // 2]
        self._probe_flip = False  # alternate up/down neighbor reprobes

    @property
    def explore_batches(self) -> int:
        """Length of the deterministic exploration phase."""
        return len(self._ladder) * self._probes_per_arm

    @property
    def gap(self) -> int:
        """Current steady-state choice (the exploit arm)."""
        with self._lock:
            return self._best

    def next_gap(self) -> int:
        """The gap the next batch should coalesce with."""
        with self._lock:
            i = self._issued
            self._issued += 1
            if i < self.explore_batches:
                # round-robin exploration: arm changes every batch so a
                # transient host hiccup spreads over arms instead of
                # poisoning one
                return self._ladder[i % len(self._ladder)]
            if (i - self.explore_batches) % self._reprobe_every == \
                    self._reprobe_every - 1:
                # hill-climb probe: one neighbor, alternating sides
                idx = self._ladder.index(self._best)
                self._probe_flip = not self._probe_flip
                nidx = idx + (1 if self._probe_flip else -1)
                if 0 <= nidx < len(self._ladder):
                    return self._ladder[nidx]
            return self._best

    def observe(self, gap: int, batch_ms: float, nbytes: int) -> None:
        """Feed one batch's measured wall back (gap = the value
        next_gap() handed out for it)."""
        if gap not in self._cost or batch_ms <= 0:
            return
        cost = batch_ms / max(1, nbytes) * (1 << 20)  # ms per MiB
        with self._lock:
            prev = self._cost[gap]
            self._cost[gap] = (cost if prev is None
                               else prev + self._alpha * (cost - prev))
            self._observed += 1
            if self._observed >= self.explore_batches:
                known = [(c, g) for g, c in self._cost.items()
                         if c is not None]
                if known:
                    self._best = min(known)[1]

    def snapshot(self) -> Dict[int, Optional[float]]:
        with self._lock:
            return dict(self._cost)
