"""Pipelined training-batch loader: coalesced reads, device hand-off,
bounded prefetch, resumable cursor.

Per step the loader maps the global batch's permuted sample ids to record
extents, COALESCES them into large sorted spans per file (recordio.
plan_coalesced) and fetches all spans as ONE ``batch_read_files`` call —
which node-groups, pipelines and stripes the chunk reads underneath (the
PR 3 read path). Records are sliced back out of the spans as views,
CRC-verified, and assembled into the batch array in a single copy; with a
mesh the batch lands as a global ``jax.Array`` sharded over the ``dp``
axis (``device_put`` onto each replica row's local shards).

A producer thread keeps ``depth`` batches decoded ahead of the training
loop, under BOUNDED-BYTE backpressure (``max_buffered_bytes``): the
pipeline absorbs storage jitter without ever holding more than the
configured budget of host memory, however large the records.

All IO runs under the ``dataload`` QoS class — foreground-weighted but
share-bounded (qos/core.py) — and an ``OVERLOADED`` shed that survives
the storage client's retry ladder pauses the producer for the server's
retry-after hint (self-throttling like the ckpt saver, never failing the
epoch). Recorders: ``dataload.batch_ms`` (fetch+assembly wall),
``dataload.stall_ms`` (time the consumer waited — the number training
actually feels), ``dataload.bytes``, ``dataload.crc_err``,
``dataload.batches``.

The iterator position is four integers (see state.py); ``state()``
snapshots the cursor AFTER the last consumed batch, so a restore neither
repeats nor skips a sample even with batches in flight.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

import numpy as np

from tpu3fs.dataload.dataset import PackedDataset, dp_info
from tpu3fs.dataload.state import DataloadState
from tpu3fs.monitor.recorder import (
    CounterRecorder,
    DistributionRecorder,
    ValueRecorder,
)
from tpu3fs.qos.core import TrafficClass, retry_after_ms_of, tagged
from tpu3fs.utils.result import Code, FsError
from tpu3fs.utils.result import err as _err


@dataclass
class LoaderConfig:
    global_batch: int = 32
    seed: int = 0
    shuffle: bool = True
    # batches outstanding ahead of the consumer — delivered-but-unread
    # plus in flight (>=1); 1 = classic double buffering (fetch K+1
    # while training consumes K)
    depth: int = 2
    # fetch threads: up to min(workers, depth) batches fetch
    # CONCURRENTLY (delivery stays in order) — batch K+1's round trips
    # overlap K's. Default 1: on a single-host python transport the GIL
    # serializes the per-request work and extra threads only contend
    # (measured in dataload_bench); raise it when fetches are genuinely
    # wait-bound (many storage nodes, native transport)
    workers: int = 1
    max_buffered_bytes: int = 256 << 20
    verify_crc: bool = True
    # merge sorted record extents when the gap is below this: 64 KiB
    # measured best on the served read path (dataload_bench sweep —
    # over-read costs wire bytes faster than spans cost round trips
    # beyond that). <= 0 = ADAPTIVE: a GapController (autotune.py)
    # learns the gap online from observed dataload.batch_ms
    coalesce_gap: int = 64 << 10
    max_span_bytes: int = 8 << 20
    # fixed-size sample decode: "" leaves records as raw bytes views
    dtype: str = ""
    sample_shape: Tuple[int, ...] = ()
    # stop after this many epochs (None = run forever)
    epochs: Optional[int] = None
    max_overload_waits: int = 64
    # per-sample transform between fetch and assembly/device_put
    # (decode/augment: bytes-or-view in, bytes or ndarray out; with
    # dtype/sample_shape set, the result must still be `want` bytes or a
    # sample_shape-compatible array). Runs on the producer/fetch threads,
    # overlapped with training like the IO it follows. MUST be a pure
    # per-record function: the resume contract replays samples through it
    # again, so a stateful transform would break resume exactness.
    transform: Optional[Callable] = None
    # invoked on the producer as each epoch STARTS fetching (including
    # the resume epoch) — curriculum schedules flip transforms or
    # difficulty knobs here. Fires once per (loader, epoch); raising
    # fails the loader like a fetch error.
    epoch_callback: Optional[Callable[[int], None]] = None
    # owning tenant (tpu3fs/tenant): loader fetch IO runs under this
    # tenant scope so the envelope carries it, per-tenant quotas charge
    # it and the tenant.* recorders attribute it — a training job is a
    # tenant like any inference client. "" = untenanted (legacy).
    tenant: str = ""


def _rec_nbytes(rec) -> int:
    """Payload bytes of a record in either shape a transform may hand
    back (bytes/memoryview or ndarray)."""
    return rec.nbytes if hasattr(rec, "nbytes") else len(rec)


@dataclass
class Batch:
    epoch: int
    step: int
    ids: List[int]                 # global sample ids, row-major
    data: object                   # np.ndarray | jax.Array | list of views
    nbytes: int = 0
    # dp rows this process fetched (mesh mode; [rank] otherwise)
    rows: List[int] = field(default_factory=list)


class DataLoader:
    """Iterator over dp-sharded, pipelined training batches.

    Two deployment shapes:

    - ``mesh=``: the loader serves every dp replica row with devices in
      THIS process and yields global ``jax.Array`` batches sharded
      ``P("dp")`` over the mesh (requires ``dtype``/``sample_shape``).
    - ``dp_rank``/``dp_size``: one process = one replica; yields that
      replica's microbatch as a host array (or raw record views when no
      ``dtype`` is configured).
    """

    def __init__(self, dataset: PackedDataset,
                 config: Optional[LoaderConfig] = None, *,
                 mesh=None, dp_axis: str = "dp",
                 dp_rank: int = 0, dp_size: int = 1,
                 state: Optional[DataloadState] = None):
        self._ds = dataset
        self.config = config or LoaderConfig()
        cfg = self.config
        if cfg.global_batch <= 0:
            raise _err(Code.INVALID_ARG, "global_batch must be positive")
        self._mesh = mesh
        if mesh is not None:
            if not cfg.dtype or not cfg.sample_shape:
                raise _err(Code.INVALID_ARG,
                           "mesh mode needs dtype + sample_shape "
                           "(device arrays are typed)")
            self._dp_size, rows = dp_info(mesh, dp_axis)
            self._rows = dict(sorted(rows.items()))
        else:
            if not 0 <= dp_rank < max(1, dp_size):
                raise _err(Code.INVALID_ARG,
                           f"dp_rank {dp_rank} outside dp_size {dp_size}")
            self._dp_size = max(1, dp_size)
            self._rows = {dp_rank: []}
        if cfg.global_batch % self._dp_size != 0:
            raise _err(Code.INVALID_ARG,
                       f"global_batch {cfg.global_batch} not divisible "
                       f"by dp_size {self._dp_size}")
        if dataset.steps_per_epoch(cfg.global_batch) == 0:
            raise _err(Code.INVALID_ARG,
                       f"global_batch {cfg.global_batch} exceeds dataset "
                       f"({dataset.num_samples} samples)")
        if state is not None:
            self._check_state(state)
            self._epoch, self._step = state.epoch, state.step
            # mid-epoch cursors past a shrunken epoch roll forward
            steps = dataset.steps_per_epoch(cfg.global_batch)
            if self._step >= steps:
                self._epoch, self._step = self._epoch + 1, 0
        else:
            self._epoch, self._step = 0, 0

        self._mu = threading.Lock()
        self._cond = threading.Condition(self._mu)
        self._buf: List[Batch] = []
        self._buffered_bytes = 0
        self._error: Optional[BaseException] = None
        self._finished = False
        self._stop = threading.Event()
        self._batch_ms = DistributionRecorder("dataload.batch_ms")
        self._stall_ms = DistributionRecorder("dataload.stall_ms")
        self._bytes = CounterRecorder("dataload.bytes")
        self._crc_err = CounterRecorder("dataload.crc_err")
        self._batches = CounterRecorder("dataload.batches")
        # memory observability: decoded-ahead bytes (bounded by
        # max_buffered_bytes — the stalled-consumer tests assert it)
        self._buffered_gauge = ValueRecorder("dataload.buffered_bytes")
        # adaptive coalesce gap (cfg.coalesce_gap <= 0): learned online
        # from the batch_ms signal (dataload/autotune.py)
        self.gap_controller = None
        if cfg.coalesce_gap <= 0:
            from tpu3fs.dataload.autotune import GapController

            self.gap_controller = GapController()
        self._thread = threading.Thread(
            target=self._produce, daemon=True, name="dataload-producer")
        self._thread.start()

    # -- state ------------------------------------------------------------
    def _check_state(self, st: DataloadState) -> None:
        cfg = self.config
        problems = []
        if st.global_batch != cfg.global_batch:
            problems.append(f"global_batch {st.global_batch} != "
                            f"{cfg.global_batch}")
        if st.num_samples != self._ds.num_samples:
            problems.append(f"num_samples {st.num_samples} != "
                            f"{self._ds.num_samples}")
        if st.seed != cfg.seed or st.shuffle != cfg.shuffle:
            problems.append("seed/shuffle differ from the saved epoch "
                            "order")
        if problems:
            # a mismatched domain would silently repeat/lose samples —
            # exactly what resumable state exists to prevent
            raise _err(Code.DATALOAD_STATE_MISMATCH, "; ".join(problems))

    def state(self) -> DataloadState:
        """Cursor AFTER the last batch ``__next__`` returned (prefetched
        but unconsumed batches are NOT counted — they will be refetched
        on resume, never skipped)."""
        with self._mu:
            return DataloadState(
                seed=self.config.seed, epoch=self._epoch, step=self._step,
                global_batch=self.config.global_batch,
                num_samples=self._ds.num_samples,
                shuffle=self.config.shuffle)

    def buffered_bytes(self) -> int:
        with self._mu:
            return self._buffered_bytes

    # -- iteration --------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self) -> Batch:
        t0 = time.perf_counter()
        with self._cond:
            while not self._buf and self._error is None \
                    and not self._finished:
                self._cond.wait(0.5)
            if self._buf:
                batch = self._buf.pop(0)
                self._buffered_bytes -= batch.nbytes
                self._buffered_gauge.set(self._buffered_bytes)
                # consumed-cursor advance (the state() contract)
                steps = self._ds.steps_per_epoch(self.config.global_batch)
                self._epoch, self._step = (
                    (batch.epoch + 1, 0) if batch.step + 1 >= steps
                    else (batch.epoch, batch.step + 1))
                self._cond.notify_all()
            elif self._error is not None:
                raise self._error
            else:
                raise StopIteration
        self._stall_ms.record((time.perf_counter() - t0) * 1e3)
        return batch

    def close(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        self._thread.join(timeout=10)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- producer ---------------------------------------------------------
    def _positions(self):
        cfg = self.config
        steps = self._ds.steps_per_epoch(cfg.global_batch)
        epoch, step = self._epoch, self._step
        while cfg.epochs is None or epoch < cfg.epochs:
            if cfg.epoch_callback is not None:
                # epoch boundary (incl. the resume epoch): no fetch of
                # THIS epoch has started yet (with depth>1, tail fetches
                # of the previous epoch may still be in flight)
                cfg.epoch_callback(epoch)
            perm = self._ds.permutation(cfg.seed, epoch,
                                        shuffle=cfg.shuffle)
            while step < steps:
                yield perm, epoch, step
                step += 1
            epoch, step = epoch + 1, 0

    def _produce(self) -> None:
        """Sliding fetch window: keep up to ``depth`` batches outstanding
        (delivered + in flight), fetching up to min(workers, depth) of
        them concurrently; DELIVERY stays strictly in step order, so the
        consumer (and the resume cursor) never see reordering."""
        cfg = self.config
        workers = max(1, min(cfg.workers, max(1, cfg.depth)))
        pool = None
        if workers > 1:
            from tpu3fs.utils.executor import WorkerPool

            pool = WorkerPool("dataload-fetch", num_workers=workers,
                              queue_cap=max(2, cfg.depth))
        try:
            gen = self._positions()
            pending: List[object] = []  # Futures (pool) or position tuples
            exhausted = False
            while not self._stop.is_set():
                while not exhausted and len(pending) < max(1, cfg.depth) \
                        and (pool is None or len(pending) < workers) \
                        and self.buffered_bytes() \
                        < cfg.max_buffered_bytes:
                    pos = next(gen, None)
                    if pos is None:
                        exhausted = True
                        break
                    pending.append(pool.submit(self._fetch, *pos)
                                   if pool is not None else pos)
                    if pool is None:
                        break  # sync mode: fetch-push one at a time
                if not pending:
                    break
                head = pending.pop(0)
                batch = head.get() if hasattr(head, "get") \
                    else self._fetch(*head)
                if not self._push(batch):
                    return
        except BaseException as e:  # delivered on the consumer's next()
            with self._cond:
                self._error = e
                self._cond.notify_all()
        else:
            with self._cond:
                self._finished = True
                self._cond.notify_all()
        finally:
            if pool is not None:
                pool.shutdown(wait=False)

    def _push(self, batch: Batch) -> bool:
        """Bounded hand-off: at most ``depth`` batches AND (beyond the
        mandatory one) ``max_buffered_bytes`` decoded ahead."""
        cfg = self.config
        depth = max(1, cfg.depth)
        with self._cond:
            while not self._stop.is_set() and self._buf and (
                    len(self._buf) >= depth
                    or self._buffered_bytes + batch.nbytes
                    > cfg.max_buffered_bytes):
                self._cond.wait(0.5)
            if self._stop.is_set():
                return False
            self._buf.append(batch)
            self._buffered_bytes += batch.nbytes
            self._buffered_gauge.set(self._buffered_bytes)
            self._cond.notify_all()
        return True

    # -- fetch + assembly -------------------------------------------------
    def _fetch(self, perm, epoch: int, step: int) -> Batch:
        cfg = self.config
        t0 = time.perf_counter()
        rows = sorted(self._rows)
        ids: List[int] = []
        for r in rows:
            ids.extend(self._ds.batch_ids(perm, step, cfg.global_batch,
                                          dp_rank=r,
                                          dp_size=self._dp_size))
        gap = (self.gap_controller.next_gap()
               if self.gap_controller is not None else cfg.coalesce_gap)
        from tpu3fs.analytics import spans as _spans

        with _spans.root_span("dataload.fetch"):
            recs = self._read_with_backoff(ids, gap)
        if cfg.transform is not None:
            # decode/augment between fetch and assembly — per record, on
            # the fetch thread (overlapped with training like the IO)
            recs = [cfg.transform(r) for r in recs]
        nbytes = sum(_rec_nbytes(r) for r in recs)
        if cfg.dtype:
            data = self._assemble_array(ids, recs)
        else:
            data = recs
        if self._mesh is not None:
            data = self._to_device(data, rows)
        self._bytes.add(nbytes)
        self._batches.add()
        batch_ms = (time.perf_counter() - t0) * 1e3
        self._batch_ms.record(batch_ms)
        if self.gap_controller is not None:
            # feedback: the gap this batch used, its wall, its bytes
            self.gap_controller.observe(gap, batch_ms, nbytes)
        return Batch(epoch=epoch, step=step, ids=ids, data=data,
                     nbytes=nbytes, rows=rows)

    def _read_with_backoff(self, ids: List[int],
                           coalesce_gap: Optional[int] = None):
        cfg = self.config
        gap = coalesce_gap if coalesce_gap is not None else cfg.coalesce_gap
        from tpu3fs.tenant.identity import tenant_scope

        with tagged(TrafficClass.DATALOAD), tenant_scope(cfg.tenant):
            for _ in range(cfg.max_overload_waits):
                try:
                    return self._ds.read_samples(
                        ids, verify=cfg.verify_crc,
                        coalesce_gap=gap,
                        max_span_bytes=cfg.max_span_bytes)
                except FsError as e:
                    if e.code == Code.DATALOAD_CORRUPT:
                        self._crc_err.add()
                        raise
                    if e.code not in (Code.OVERLOADED,
                                      Code.TENANT_THROTTLED):
                        raise
                    # shed past the client's own ladder: self-throttle
                    # for the server's hint instead of failing the epoch
                    hint = retry_after_ms_of(e.status.message) or 50
                    if self._stop.wait(hint / 1000.0):
                        raise
        raise _err(Code.CLIENT_RETRIES_EXHAUSTED,
                   f"dataload batch shed {cfg.max_overload_waits}x")

    def _assemble_array(self, ids: List[int], recs) -> np.ndarray:
        cfg = self.config
        dtype = np.dtype(cfg.dtype)
        shape = tuple(cfg.sample_shape)
        want = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize \
            if shape else dtype.itemsize
        out = np.empty((len(ids),) + shape, dtype=dtype)
        for i, rec in enumerate(recs):
            if _rec_nbytes(rec) != want:
                raise _err(Code.DATALOAD_CORRUPT,
                           f"sample {ids[i]}: {_rec_nbytes(rec)} bytes, "
                           f"want {want} for {dtype}{shape}")
            if isinstance(rec, np.ndarray):
                # transformed record already decoded to an array
                out[i] = rec.reshape(shape)
            else:
                # frombuffer is a view; the assignment below is the
                # batch's ONE assembly copy
                out[i] = np.frombuffer(rec, dtype=dtype).reshape(shape)
        return out

    def _to_device(self, host: np.ndarray, rows: List[int]):
        """Global jax.Array sharded P("dp"): each replica row's
        microbatch device_put onto that row's local shards."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        cfg = self.config
        b = cfg.global_batch // self._dp_size
        gshape = (cfg.global_batch,) + tuple(cfg.sample_shape)
        sharding = NamedSharding(self._mesh, PartitionSpec("dp"))
        row_pos = {r: i for i, r in enumerate(rows)}
        arrays = []
        for r, devices in sorted(self._rows.items()):
            lo = row_pos[r] * b
            micro = host[lo:lo + b]
            for dev in devices:
                arrays.append(jax.device_put(micro, dev))
        return jax.make_array_from_single_device_arrays(
            gshape, sharding, arrays)
