"""Multi-file packed dataset: global sample index, Feistel shuffle, dp
sharding.

A ``PackedDataset`` is an ordered list of record files (recordio.py)
presented as one global sample space ``[0, num_samples)``. Per-epoch
shuffling is a seeded FEISTEL PERMUTATION over that space — a pseudo-
random bijection evaluated point-wise, so no O(N) permutation array is
ever materialized (a 10B-sample corpus shuffles in O(1) memory) and any
position of any epoch is addressable directly, which is what makes
mid-epoch resume exact: the iterator's state is just (seed, epoch,
cursor).

Data-parallel sharding follows the process mesh (parallel/mesh.py): a
global batch of ``global_batch`` consecutive permuted positions splits
into ``dp_size`` contiguous microbatches, replica r taking rows
``[r*b, (r+1)*b)``. Across replicas every epoch covers each (retained)
sample exactly once — the no-dup/no-loss contract the coverage test pins.
The trailing ``num_samples % global_batch`` samples of an epoch are
dropped (the standard drop-last contract), so every epoch has the same
step count on every replica.
"""

from __future__ import annotations

import bisect
from typing import Dict, List, Optional, Sequence, Tuple

from tpu3fs.dataload.recordio import RecordFile
from tpu3fs.utils.result import Code
from tpu3fs.utils.result import err as _err

_M64 = (1 << 64) - 1
_GOLDEN = 0x9E3779B97F4A7C15


def _mix(x: int) -> int:
    """64-bit finalizer (splitmix64): the Feistel round function core."""
    x &= _M64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _M64
    return x ^ (x >> 31)


class FeistelPermutation:
    """Seeded pseudo-random permutation of ``[0, n)``, O(1) memory.

    A balanced Feistel network over the smallest even-bit-width domain
    covering ``n``, with cycle walking to land back inside ``[0, n)``
    (re-encrypting an out-of-range value stays within the power-of-two
    domain, and a permutation of that domain restricted to ``[0, n)`` is
    a permutation of ``[0, n)`` — the standard format-preserving
    construction). Four rounds of a splitmix64-derived round function are
    plenty for shuffling; this is a shuffle, not a cipher.
    """

    ROUNDS = 4

    def __init__(self, n: int, seed: int, epoch: int = 0):
        if n < 0:
            raise _err(Code.INVALID_ARG, f"domain size {n}")
        self.n = n
        half = max(1, ((max(1, n - 1).bit_length()) + 1) // 2)
        self._half_bits = half
        self._mask = (1 << half) - 1
        # per-(seed, epoch, round) subkeys: epochs get unrelated
        # permutations from one seed
        base = _mix((seed & _M64) ^ ((epoch & _M64) * _GOLDEN))
        self._keys = [_mix(base + r * _GOLDEN) for r in range(self.ROUNDS)]

    def _encrypt(self, x: int) -> int:
        hb, mask = self._half_bits, self._mask
        left, right = x >> hb, x & mask
        for key in self._keys:
            left, right = right, left ^ (_mix(right ^ key) & mask)
        return (left << hb) | right

    def __call__(self, i: int) -> int:
        if not 0 <= i < self.n:
            raise _err(Code.INVALID_ARG, f"index {i} outside [0, {self.n})")
        x = self._encrypt(i)
        while x >= self.n:  # cycle walk (expected <2 iterations)
            x = self._encrypt(x)
        return x


class IdentityPermutation:
    """Shuffle-off stand-in with the FeistelPermutation surface."""

    def __init__(self, n: int):
        self.n = n

    def __call__(self, i: int) -> int:
        if not 0 <= i < self.n:
            raise _err(Code.INVALID_ARG, f"index {i} outside [0, {self.n})")
        return i


def dp_info(mesh, axis: str = "dp") -> Tuple[int, Dict[int, list]]:
    """-> (dp_size, {dp index -> local devices of that replica row}).

    The replica rows THIS process participates in, derived from the mesh
    the way the ckpt saver derives shard ownership: a device's replica
    index is its coordinate along ``axis``; all other mesh axes replicate
    the batch (data parallelism shards only the batch dimension).
    """
    if axis not in mesh.shape:
        raise _err(Code.INVALID_ARG,
                   f"mesh has no {axis!r} axis (axes: {list(mesh.shape)})")
    axis_idx = list(mesh.axis_names).index(axis)
    dp_size = int(mesh.shape[axis])
    local = {d.id for d in mesh.local_devices} if hasattr(
        mesh, "local_devices") else {d.id for d in mesh.devices.flat}
    rows: Dict[int, list] = {}
    import numpy as np

    grid = np.asarray(mesh.devices)
    for coord, dev in np.ndenumerate(grid):
        if dev.id in local:
            rows.setdefault(int(coord[axis_idx]), []).append(dev)
    return dp_size, rows


class PackedDataset:
    """Ordered record files as one global, shuffle-addressable index."""

    def __init__(self, meta, fio, paths: Sequence[str]):
        if not paths:
            raise _err(Code.INVALID_ARG, "dataset needs at least one file")
        self._meta = meta
        self._fio = fio
        self.files: List[RecordFile] = [
            RecordFile.open(meta, fio, p) for p in paths
        ]
        self._cum: List[int] = []
        total = 0
        for rf in self.files:
            total += rf.num_records
            self._cum.append(total)

    @property
    def num_samples(self) -> int:
        return self._cum[-1] if self._cum else 0

    def __len__(self) -> int:
        return self.num_samples

    def total_payload_bytes(self) -> int:
        return sum(rf.total_payload_bytes() for rf in self.files)

    def locate(self, gid: int) -> Tuple[int, int]:
        """Global sample id -> (file index, record index in file)."""
        if not 0 <= gid < self.num_samples:
            raise _err(Code.INVALID_ARG,
                       f"sample {gid} outside [0, {self.num_samples})")
        fi = bisect.bisect_right(self._cum, gid)
        base = self._cum[fi - 1] if fi else 0
        return fi, gid - base

    # -- epoch geometry ---------------------------------------------------
    def permutation(self, seed: int, epoch: int, *, shuffle: bool = True):
        if not shuffle:
            return IdentityPermutation(self.num_samples)
        return FeistelPermutation(self.num_samples, seed, epoch)

    def steps_per_epoch(self, global_batch: int) -> int:
        if global_batch <= 0:
            raise _err(Code.INVALID_ARG, f"global_batch {global_batch}")
        return self.num_samples // global_batch

    def batch_ids(self, perm, step: int, global_batch: int,
                  *, dp_rank: Optional[int] = None,
                  dp_size: int = 1) -> List[int]:
        """Sample ids of global step ``step`` under permutation ``perm``
        (a whole global batch, or one replica's contiguous microbatch
        when ``dp_rank`` is given). ``global_batch`` must divide by
        ``dp_size``."""
        if global_batch % max(1, dp_size) != 0:
            raise _err(Code.INVALID_ARG,
                       f"global_batch {global_batch} not divisible by "
                       f"dp_size {dp_size}")
        lo = step * global_batch
        hi = lo + global_batch
        if dp_rank is not None:
            b = global_batch // dp_size
            lo, hi = lo + dp_rank * b, lo + (dp_rank + 1) * b
        return [perm(i) for i in range(lo, hi)]

    def read_samples(self, gids: Sequence[int], *, verify: bool = True,
                     coalesce_gap: int = 64 << 10,
                     max_span_bytes: int = 8 << 20) -> List[bytes]:
        """Convenience non-pipelined fetch (the loader has the fast
        path): coalesced batch read of arbitrary global ids."""
        by_file: Dict[int, List[Tuple[int, int]]] = {}
        for pos, gid in enumerate(gids):
            fi, ri = self.locate(gid)
            by_file.setdefault(fi, []).append((pos, ri))
        out: List[Optional[bytes]] = [None] * len(gids)
        for fi, items in by_file.items():
            recs = self.files[fi].read_batch(
                [ri for _, ri in items], verify=verify,
                coalesce_gap=coalesce_gap, max_span_bytes=max_span_bytes)
            for (pos, _), rec in zip(items, recs):
                out[pos] = rec
        return out  # type: ignore[return-value]
