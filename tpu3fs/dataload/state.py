"""Resumable loader state: (seed, epoch, cursor) + save/restore paths.

Because the epoch order is a pure function of (seed, epoch) — the Feistel
permutation — and sharding is a pure function of (step, dp geometry), the
ENTIRE iterator state is four integers. A restored job replays none of
the consumed prefix and skips none of the remainder: resume exactness is
arithmetic, not bookkeeping.

Two composition paths:

- ``to_leaf()`` / ``from_leaf()``: the state as a tiny uint8 array leaf
  to embed in the training pytree handed to ``ckpt`` save — the loader
  cursor then commits ATOMICALLY with the model weights under the ckpt
  save session (same ``.tmp`` → rename, same manifest CRC), which is the
  property that makes "resume without sample repetition or loss" true
  end-to-end: state and weights cannot diverge by a crash between two
  separate writes.
- ``StateStore``: a standalone atomically-committed state file for
  loaders running outside a checkpoint cycle (eval jobs, packers).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from tpu3fs.qos.core import TrafficClass, tagged
from tpu3fs.rpc.serde import deserialize, serialize
from tpu3fs.utils.result import Code, FsError
from tpu3fs.utils.result import err as _err

STATE_FORMAT_VERSION = 1
_TMP_SUFFIX = ".tmp"


@dataclass
class DataloadState:
    """Position of the NEXT batch the loader will yield."""

    format_version: int = STATE_FORMAT_VERSION
    seed: int = 0
    epoch: int = 0
    step: int = 0            # global batches already consumed this epoch
    global_batch: int = 0
    num_samples: int = 0     # guard: shuffle domain must match on resume
    shuffle: bool = True

    def encode(self) -> bytes:
        return serialize(self, DataloadState)

    @staticmethod
    def decode(raw: bytes) -> "DataloadState":
        try:
            st = deserialize(bytes(raw), DataloadState)
        except Exception as e:
            raise _err(Code.DATALOAD_CORRUPT, f"state decode: {e!r}")
        if st.format_version > STATE_FORMAT_VERSION:
            raise _err(Code.DATALOAD_CORRUPT,
                       f"state format {st.format_version} > "
                       f"{STATE_FORMAT_VERSION}")
        return st

    # -- ckpt-pytree composition -----------------------------------------
    def to_leaf(self) -> np.ndarray:
        """The state as a uint8 array leaf for a checkpoint pytree."""
        return np.frombuffer(self.encode(), dtype=np.uint8).copy()

    @staticmethod
    def from_leaf(leaf) -> "DataloadState":
        return DataloadState.decode(np.asarray(leaf,
                                               dtype=np.uint8).tobytes())


class StateStore:
    """Standalone state file with the ``.tmp`` → rename commit."""

    def __init__(self, meta, fio, path: str, *,
                 client_id: str = "dataload"):
        self._meta = meta
        self._fio = fio
        self.path = path
        self._client_id = client_id

    def save(self, state: DataloadState) -> None:
        from tpu3fs.meta.store import OpenFlags

        tmp = self.path + _TMP_SUFFIX
        raw = state.encode()
        with tagged(TrafficClass.DATALOAD):
            res = self._meta.create(
                tmp, flags=OpenFlags.WRITE | OpenFlags.CREATE
                | OpenFlags.TRUNC, client_id=self._client_id)
            try:
                n = self._fio.write(res.inode, 0, raw)
            except BaseException:
                try:
                    self._meta.close(res.inode.id, res.session_id)
                except FsError:
                    pass
                raise
            self._meta.close(res.inode.id, res.session_id,
                             length_hint=n, wrote=True)
            # POSIX-style rename: atomically replaces a previous state
            # file, so a crash leaves either the old or the new cursor
            self._meta.rename(tmp, self.path)

    def load(self) -> DataloadState:
        with tagged(TrafficClass.DATALOAD):
            inode = self._meta.stat(self.path)
            raw = self._fio.read(inode, 0, inode.length)
        return DataloadState.decode(raw)
