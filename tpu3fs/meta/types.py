"""Metadata schema: inodes, directory entries, file layouts.

Re-expresses the reference's meta schema (src/fbs/meta/Service.h — Inode,
DirEntry, Layout with chain-table ref / chunk size / stripe; key layout
documented in docs/design_notes.md "File metadata on transactional key-value
store"): inodes under "INOD"+id, dirents under "DENT"+parent+name, so a
directory listing is one range scan and path resolution is point gets.

The Layout maps chunk index -> chain: a file stripes over ``stripe_size``
chains drawn from a chain table, starting at a seeded shuffle — the
data-parallel axis of the filesystem (SURVEY.md §0.2).
"""

from __future__ import annotations

import enum
import functools
import random
import struct
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tpu3fs.kv.kv import KeyPrefix

ROOT_INODE_ID = 1

# permission bits (POSIX-style subset)
PERM_R, PERM_W, PERM_X = 4, 2, 1


class InodeType(enum.IntEnum):
    FILE = 1
    DIRECTORY = 2
    SYMLINK = 3


@dataclass
class Acl:
    uid: int = 0
    gid: int = 0
    perm: int = 0o755

    def check(self, uid: int, gid: int, want: int,
              groups: tuple = (), root: bool = False) -> bool:
        """want: bitmask of PERM_R/W/X. uid 0 (or root flag) bypasses."""
        if uid == 0 or root:
            return True
        if uid == self.uid:
            bits = (self.perm >> 6) & 7
        elif gid == self.gid or self.gid in groups:
            bits = (self.perm >> 3) & 7
        else:
            bits = self.perm & 7
        return (bits & want) == want

    def check_user(self, user, want: int) -> bool:
        """Acl check for a User carrying supplementary groups/root flag."""
        return self.check(user.uid, user.gid, want,
                          getattr(user, "groups", ()),
                          getattr(user, "root", False))


@functools.lru_cache(maxsize=4096)
def _shuffled_order(seed: int, n: int) -> tuple:
    """Deterministic stripe permutation, cached — chain_of_chunk is on the
    per-chunk IO path."""
    order = list(range(n))
    random.Random(seed).shuffle(order)
    return tuple(order)


@dataclass
class Layout:
    """Chunk -> chain placement for one file."""

    table_id: int = 1
    chains: List[int] = field(default_factory=list)  # stripe_size chain ids
    chunk_size: int = 1 << 20  # ref default kChunkSize=1MB (fbs/storage/Common.h:118)
    seed: int = 0

    @property
    def stripe_size(self) -> int:
        return len(self.chains)

    def chain_of_chunk(self, chunk_index: int) -> int:
        """Chunk i lives on a seed-shuffled round-robin chain of the stripe
        (ref docs/design_notes.md "Location of file chunks")."""
        order = _shuffled_order(self.seed, len(self.chains))
        return self.chains[order[chunk_index % len(self.chains)]]

    def chunk_of_offset(self, offset: int) -> int:
        return offset // self.chunk_size


@dataclass
class Inode:
    id: int
    type: InodeType
    acl: Acl
    nlink: int = 1
    atime: float = 0.0
    mtime: float = 0.0
    ctime: float = 0.0
    # FILE:
    layout: Optional[Layout] = None
    length: int = 0           # hint; precise on close/fsync (design_notes
                              # "Dynamic file attributes")
    length_hint_ver: int = 0
    # SYMLINK:
    symlink_target: str = ""
    # DIRECTORY:
    parent: int = 0
    locked_by: str = ""  # lockDirectory owner; "" = unlocked
    # extended attributes (ref FuseOps.cc setxattr/getxattr/listxattr/
    # removexattr in the lowlevel ops table, :2580-2613)
    xattrs: Dict[str, bytes] = field(default_factory=dict)

    @staticmethod
    def new_file(id: int, acl: Acl, layout: Layout) -> "Inode":
        now = time.time()
        return Inode(id, InodeType.FILE, acl, 1, now, now, now, layout=layout)

    @staticmethod
    def new_dir(id: int, acl: Acl, parent: int) -> "Inode":
        now = time.time()
        return Inode(id, InodeType.DIRECTORY, acl, 1, now, now, now, parent=parent)

    @staticmethod
    def new_symlink(id: int, acl: Acl, target: str) -> "Inode":
        now = time.time()
        return Inode(
            id, InodeType.SYMLINK, acl, 1, now, now, now, symlink_target=target
        )

    def is_file(self) -> bool:
        return self.type == InodeType.FILE

    def is_dir(self) -> bool:
        return self.type == InodeType.DIRECTORY

    def is_symlink(self) -> bool:
        return self.type == InodeType.SYMLINK


@dataclass
class DirEntry:
    parent: int
    name: str
    inode_id: int
    type: InodeType


@dataclass
class FileSession:
    """A write-open session (ref src/meta/store/FileSession.cc; "INOS" keys).

    Sessions make close/prune idempotent and let mgmtd-side client-session
    expiry reclaim writes of dead clients.
    """

    inode_id: int
    client_id: str
    session_id: str
    opened_at: float = 0.0
    # identity that opened the session: close is authorized against this
    # (the session is the capability granted at open; POSIX checks
    # permission at open, not close), 0 = dev mode / root
    uid: int = 0


# -- key codecs -------------------------------------------------------------

def inode_key(inode_id: int) -> bytes:
    return KeyPrefix.INODE.value + struct.pack(">Q", inode_id)


def dirent_key(parent: int, name: str) -> bytes:
    return KeyPrefix.DIR_ENTRY.value + struct.pack(">Q", parent) + name.encode()


def dirent_scan_range(parent: int) -> tuple:
    base = KeyPrefix.DIR_ENTRY.value + struct.pack(">Q", parent)
    return base, base + b"\xff" * 8


def session_key(inode_id: int, session_id: str) -> bytes:
    return (
        KeyPrefix.INODE_SESSION.value
        + struct.pack(">Q", inode_id)
        + session_id.encode()
    )


def session_scan_range(inode_id: Optional[int] = None) -> tuple:
    if inode_id is None:
        base = KeyPrefix.INODE_SESSION.value
        return base, base + b"\xff" * 9
    base = KeyPrefix.INODE_SESSION.value + struct.pack(">Q", inode_id)
    return base, base + b"\xff" * 8


def idempotent_key(client_id: str, request_id: str,
                   uid: Optional[int] = None) -> bytes:
    """With a uid, the cached result is scoped to that identity: a replay of
    another client's (client_id, request_id) by a different authenticated
    user misses the cache and goes through the normal authorization path
    instead of reading the cached inode."""
    scope = f"{client_id}/{request_id}" if uid is None else \
        f"{client_id}/{request_id}@{uid}"
    return KeyPrefix.IDEMPOTENT.value + scope.encode()


GC_PREFIX = b"GCQU"  # GC queue records (analogue of the ref's GC directories)


def gc_key(inode_id: int) -> bytes:
    return GC_PREFIX + struct.pack(">Q", inode_id)


def gc_scan_range() -> tuple:
    return GC_PREFIX, GC_PREFIX + b"\xff" * 8
