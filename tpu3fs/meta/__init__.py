from tpu3fs.meta.types import (  # noqa: F401
    Acl,
    DirEntry,
    Inode,
    InodeType,
    Layout,
    ROOT_INODE_ID,
)
from tpu3fs.meta.store import MetaStore, OpenFlags  # noqa: F401
