"""Namespace scan + structured meta event log.

Re-expresses src/meta/event/{Event.cc,Scan.cc}: full-namespace iteration over
the raw KV layout (every inode / every dirent, streamed in key order without
loading the tree) for offline jobs — orphan detection, usage accounting,
backup walks — plus a structured event row the meta service appends to an
analytics trace log on each mutating op (the reference streams meta events
the same way its storage path streams StorageEventTrace rows).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Iterator, List, Optional, Tuple

from tpu3fs.analytics.trace import StructuredTraceLog
from tpu3fs.kv.kv import IKVEngine, ITransaction, KeyPrefix, with_transaction
from tpu3fs.meta.types import DirEntry, Inode
from tpu3fs.rpc.serde import deserialize


@dataclass
class MetaEvent:
    """One mutating-op row (ref src/meta/event/Event.cc row types)."""

    ts: float = 0.0
    op: str = ""            # create/mkdir/remove/rename/...
    path: str = ""
    inode_id: int = 0
    uid: int = 0
    detail: str = ""


class MetaEventLog:
    """Append-only structured event stream (rides analytics.trace)."""

    def __init__(self, directory: str, *, flush_rows: int = 256):
        self._log = StructuredTraceLog(
            "meta_events", directory, flush_rows=flush_rows)

    def append(self, op: str, path: str, *, inode_id: int = 0,
               uid: int = 0, detail: str = "") -> None:
        self._log.append(MetaEvent(
            ts=time.time(), op=op, path=path,
            inode_id=inode_id, uid=uid, detail=detail))

    def flush(self) -> None:
        self._log.flush()

    @property
    def paths(self) -> List[str]:
        return self._log.paths


# -- namespace scans ---------------------------------------------------------

_SCAN_BATCH = 512


def _scan_prefix(engine: IKVEngine, prefix: bytes, decode) -> Iterator:
    """Iterate every value under a 4-byte prefix in key order, in bounded
    transaction batches so one scan never pins a huge snapshot."""
    cursor = prefix
    end = prefix + b"\xff" * 16
    while True:
        def op(txn: ITransaction):
            return txn.get_range(cursor, end, limit=_SCAN_BATCH,
                                 snapshot=True)

        pairs = with_transaction(engine, op, read_only=True)
        if not pairs:
            return
        for pair in pairs:
            yield decode(pair.value)
        cursor = pairs[-1].key + b"\x00"


def scan_inodes(engine: IKVEngine) -> Iterator[Inode]:
    """Every inode record, in id order (ref Scan.cc inode walk)."""
    return _scan_prefix(
        engine, KeyPrefix.INODE.value, lambda v: deserialize(v, Inode))


def scan_dirents(engine: IKVEngine) -> Iterator[DirEntry]:
    """Every directory entry, grouped by parent (key order)."""
    return _scan_prefix(
        engine, KeyPrefix.DIR_ENTRY.value, lambda v: deserialize(v, DirEntry))


def find_orphan_inodes(engine: IKVEngine) -> List[Inode]:
    """Inodes unreachable from any dirent (excluding the root): the
    namespace-integrity check admin_cli exposes (ref FindOrphanedChunks'
    meta-side sibling)."""
    referenced = {ent.inode_id for ent in scan_dirents(engine)}
    from tpu3fs.meta.types import ROOT_INODE_ID

    return [
        ino for ino in scan_inodes(engine)
        if ino.id != ROOT_INODE_ID and ino.id not in referenced
        and ino.nlink > 0
    ]


def namespace_stats(engine: IKVEngine) -> dict:
    """One-pass usage accounting over the raw layout."""
    files = dirs = symlinks = 0
    total_length = 0
    for ino in scan_inodes(engine):
        if ino.is_file():
            files += 1
            total_length += ino.length
        elif ino.is_dir():
            dirs += 1
        else:
            symlinks += 1
    return {
        "files": files,
        "dirs": dirs,
        "symlinks": symlinks,
        "total_length": total_length,
    }
