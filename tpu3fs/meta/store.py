"""Metadata store: every FS operation as a transaction over the KV engine.

Re-expresses the reference's meta service (src/meta/store/ops/*): each op
(create/open/mkdirs/remove/rename/...) runs inside one KV transaction via the
retry driver, so concurrent conflicting ops serialize optimistically exactly
like the reference's FDB transactions (src/meta/service/MetaOperator.cc runOp;
src/common/kv/WithTransaction.h retry loop). The service is stateless: any
meta server instance can run any op against the shared KV.

Semantics ported (not code): path walk with symlink depth limits
(src/meta/store/PathResolve.cc), rename loop detection
(src/meta/store/ops/Rename.cc), idempotent remove/close via "IDEM" records
(src/meta/store/Idempotent.h:22-45), write-open sessions ("INOS",
src/meta/store/FileSession.cc), GC queue for deferred chunk reclamation
(src/meta/components/GcManager.cc), eventual-length hints with precise length
on close/fsync (docs/design_notes.md "Dynamic file attributes",
src/meta/components/FileHelper.cc).
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

from tpu3fs.kv.kv import IKVEngine, ITransaction, with_transaction
from tpu3fs.meta.types import (
    Acl,
    DirEntry,
    FileSession,
    Inode,
    InodeType,
    Layout,
    PERM_R,
    PERM_W,
    PERM_X,
    ROOT_INODE_ID,
    dirent_key,
    dirent_scan_range,
    gc_key,
    gc_scan_range,
    idempotent_key,
    inode_key,
    session_key,
    session_scan_range,
)
from tpu3fs.rpc.serde import deserialize, serialize
from tpu3fs.utils.result import Code, FsError
from tpu3fs.utils.result import err as _err

MAX_SYMLINK_DEPTH = 10
MAX_NAME_LEN = 255

_INODE_COUNTER_KEY = b"INOA" + b"counter"


@dataclass
class User:
    uid: int = 0
    gid: int = 0
    groups: tuple = ()
    root: bool = False

    @property
    def is_root(self) -> bool:
        return self.uid == 0 or self.root


ROOT_USER = User(0, 0)


class InodeIdAllocator:
    """Monotonic inode ids handed out in blocks to cut KV conflicts
    (ref src/meta/components/InodeIdAllocator.cc)."""

    def __init__(self, engine: IKVEngine, block: int = 64):
        self._engine = engine
        self._block = block
        self._lock = threading.Lock()
        self._next = 0
        self._limit = 0

    def allocate(self) -> int:
        with self._lock:
            if self._next >= self._limit:
                def grab(txn: ITransaction) -> int:
                    raw = txn.get(_INODE_COUNTER_KEY)
                    cur = int(raw) if raw else ROOT_INODE_ID + 1
                    txn.set(_INODE_COUNTER_KEY, str(cur + self._block).encode())
                    return cur

                self._next = with_transaction(self._engine, grab)
                self._limit = self._next + self._block
            out = self._next
            self._next += 1
            return out


class ChainAllocator:
    """Round-robin + shuffle-seed chain selection for new files
    (ref src/meta/components/ChainAllocator.h)."""

    def __init__(self, table_id: int, chain_ids: List[int]):
        self.table_id = table_id
        self.chain_ids = list(chain_ids)
        self._cursor = 0
        self._lock = threading.Lock()

    def allocate(self, stripe_size: int) -> Tuple[int, List[int], int]:
        with self._lock:
            n = len(self.chain_ids)
            stripe = min(stripe_size, n)
            picked = [
                self.chain_ids[(self._cursor + i) % n] for i in range(stripe)
            ]
            self._cursor = (self._cursor + stripe) % n
            seed = int(time.time_ns()) & 0x7FFFFFFF
            return self.table_id, picked, seed


class OpenFlags:
    READ = 1
    WRITE = 2
    CREATE = 4
    TRUNC = 8
    EXCL = 16
    DIRECTORY = 32


@dataclass
class BatchCloseItem:
    """One close in a batch settle (wire-friendly: -1 = unset)."""

    inode_id: int = 0
    session_id: str = ""
    length_hint: int = -1
    client_id: str = ""
    request_id: str = ""
    wrote: int = -1              # -1 unset / 0 false / 1 true


@dataclass
class BatchCreateItem:
    """One create in a batched open (wire-friendly: 0 = unset). The
    optional explicit layout pins chains the way MetaStore.create's
    ``layout=`` does — the ckpt archiver placing files on EC chains."""

    path: str = ""
    perm: int = 0o644
    flags: int = 0
    chunk_size: int = 0
    stripe: int = 0
    client_id: str = ""
    layout: Optional[Layout] = None


@dataclass
class OpenResult:
    inode: Inode
    session_id: str = ""


@dataclass
class StatFs:
    capacity: int = 0
    used: int = 0
    files: int = 0


class MetaStore:
    """Stateless metadata operations over a transactional KV engine."""

    def __init__(
        self,
        engine: IKVEngine,
        chain_allocator: Optional[ChainAllocator] = None,
        *,
        file_length_hook: Optional[Callable[[Inode], int]] = None,
        truncate_hook: Optional[Callable[[Inode, int], None]] = None,
        space_hook: Optional[Callable[[], Tuple[int, int]]] = None,
        default_chunk_size: int = 1 << 20,
        default_stripe: int = 1,
        event_log=None,
    ):
        self._engine = engine
        self._ids = InodeIdAllocator(engine)
        # optional structured meta event stream (ref src/meta/event/Event.cc)
        self._events = event_log
        self._chains = chain_allocator or ChainAllocator(1, [1])
        # queries storage for the real last-chunk length on close/fsync
        # (ref FileHelper.cc queryLastChunk)
        self._file_length_hook = file_length_hook
        # trims/removes storage chunks past the new EOF (ref: meta truncate
        # goes through the storage client in the reference too)
        self._truncate_hook = truncate_hook
        # cluster (capacity, used) from storage spaceInfo; statFs then
        # reports physical space, not summed logical lengths (ref statFs
        # aggregating storage space)
        self._space_hook = space_hook
        self._default_chunk_size = default_chunk_size
        self._default_stripe = default_stripe
        self._ensure_root()

    @property
    def engine(self) -> IKVEngine:
        """The underlying KV engine (subsystems that keep their own small
        records — e.g. ckpt save sessions — share the meta keyspace)."""
        return self._engine

    # -- low-level codecs ---------------------------------------------------
    def _emit(self, op: str, path: str, *, inode_id: int = 0,
              uid: int = 0, detail: str = "") -> None:
        if self._events is not None:
            try:
                self._events.append(op, path, inode_id=inode_id, uid=uid,
                                    detail=detail)
            except Exception:
                pass  # event stream is best-effort observability

    @staticmethod
    def _load_inode(txn: ITransaction, inode_id: int) -> Optional[Inode]:
        raw = txn.get(inode_key(inode_id))
        return deserialize(raw, Inode) if raw else None

    @staticmethod
    def _store_inode(txn: ITransaction, inode: Inode) -> None:
        txn.set(inode_key(inode.id), serialize(inode))

    @staticmethod
    def _load_dirent(txn: ITransaction, parent: int, name: str) -> Optional[DirEntry]:
        raw = txn.get(dirent_key(parent, name))
        return deserialize(raw, DirEntry) if raw else None

    @staticmethod
    def _store_dirent(txn: ITransaction, ent: DirEntry) -> None:
        txn.set(dirent_key(ent.parent, ent.name), serialize(ent))

    def _ensure_root(self) -> None:
        def init(txn: ITransaction):
            if txn.get(inode_key(ROOT_INODE_ID)) is None:
                root = Inode.new_dir(ROOT_INODE_ID, Acl(0, 0, 0o777), ROOT_INODE_ID)
                self._store_inode(txn, root)

        with_transaction(self._engine, init)

    # -- path resolution (ref src/meta/store/PathResolve.cc) ----------------
    @staticmethod
    def _split(path: str) -> List[str]:
        if not path.startswith("/"):
            raise _err(Code.META_INVALID_PATH, f"path must be absolute: {path}")
        parts = [p for p in path.split("/") if p and p != "."]
        for p in parts:
            if len(p) > MAX_NAME_LEN:
                raise _err(Code.META_NAME_TOO_LONG, p[:32])
        out: List[str] = []
        for p in parts:
            if p == "..":
                if out:
                    out.pop()
            else:
                out.append(p)
        return out

    def _walk(
        self,
        txn: ITransaction,
        path: str,
        user: User,
        *,
        follow_last: bool = True,
        _depth: int = 0,
    ) -> Tuple[Inode, Optional[str], Optional[Inode]]:
        """-> (parent dir inode, last component name or None for '/',
               resolved inode or None)."""
        if _depth > MAX_SYMLINK_DEPTH:
            raise _err(Code.META_TOO_MANY_SYMLINKS, path)
        parts = self._split(path)
        cur = self._load_inode(txn, ROOT_INODE_ID)
        assert cur is not None
        if not parts:
            return cur, None, cur
        parent = cur
        for i, name in enumerate(parts):
            if not parent.is_dir():
                raise _err(Code.META_NOT_DIRECTORY, "/" + "/".join(parts[:i]))
            if not parent.acl.check_user(user, PERM_X):
                raise _err(Code.META_NO_PERMISSION, "/" + "/".join(parts[:i]))
            ent = self._load_dirent(txn, parent.id, name)
            if ent is None:
                if i == len(parts) - 1:
                    return parent, name, None
                raise _err(Code.META_NOT_FOUND, "/" + "/".join(parts[: i + 1]))
            child = self._load_inode(txn, ent.inode_id)
            if child is None:
                raise _err(Code.META_NOT_FOUND, f"dangling dirent {ent.inode_id}")
            last = i == len(parts) - 1
            if child.is_symlink() and (follow_last or not last):
                target = child.symlink_target
                if not target.startswith("/"):
                    target = "/" + "/".join(parts[:i]) + "/" + target
                rest = "/".join(parts[i + 1 :])
                full = target + ("/" + rest if rest else "")
                return self._walk(
                    txn, full, user, follow_last=follow_last, _depth=_depth + 1
                )
            if last:
                return parent, name, child
            parent = child
        raise AssertionError("unreachable")

    # -- ops ---------------------------------------------------------------
    def stat(self, path: str, user: User = ROOT_USER, *, follow: bool = True) -> Inode:
        def op(txn: ITransaction) -> Inode:
            _, _, inode = self._walk(txn, path, user, follow_last=follow)
            if inode is None:
                raise _err(Code.META_NOT_FOUND, path)
            return inode

        return with_transaction(self._engine, op, read_only=True)

    def batch_stat(self, inode_ids: List[int],
                   user: Optional[User] = None) -> List[Optional[Inode]]:
        """With a user, inodes the user lacks read permission on come back
        as None (auth mode: inode-id access skips the path walk, so the
        per-inode read bit is the enforceable check)."""

        def op(txn: ITransaction):
            out = []
            for i in inode_ids:
                ino = self._load_inode(txn, i)
                if (ino is not None and user is not None
                        and not ino.acl.check_user(user, PERM_R)):
                    ino = None
                out.append(ino)
            return out

        return with_transaction(self._engine, op, read_only=True)

    def batch_stat_by_path(
        self, paths: List[str], user: User = ROOT_USER,
        *, txn_batch: int = 64,
    ) -> List[Optional[Inode]]:
        """Walk many paths per read-only transaction instead of one txn
        per path (the kvcache batch_get / prefix-probe shape: 64 stats
        used to pay 64 transaction setups). Missing/forbidden paths come
        back as None."""
        out: List[Optional[Inode]] = []
        for base in range(0, len(paths), txn_batch):
            chunk = paths[base:base + txn_batch]

            def op(txn: ITransaction, _chunk=chunk):
                res: List[Optional[Inode]] = []
                for p in _chunk:
                    try:
                        _, _, inode = self._walk(txn, p, user)
                        res.append(inode)
                    except FsError:
                        res.append(None)
                return res

            out.extend(with_transaction(self._engine, op, read_only=True))
        return out

    def mkdirs(
        self,
        path: str,
        user: User = ROOT_USER,
        perm: int = 0o755,
        *,
        recursive: bool = False,
    ) -> Inode:
        def op(txn: ITransaction) -> Inode:
            return self._mkdirs_in_txn(txn, path, user, perm,
                                       recursive=recursive)

        result = with_transaction(self._engine, op)
        self._emit("mkdir", path, inode_id=result.id, uid=user.uid)
        return result

    def _mkdirs_in_txn(
        self,
        txn: ITransaction,
        path: str,
        user: User,
        perm: int,
        *,
        recursive: bool = False,
        exist_ok: bool = False,
    ) -> Inode:
        """One mkdirs inside an already-open transaction — shared by
        mkdirs() and batch_mkdirs(). All reads and permission checks
        precede the first mutation, so a per-item FsError caught by the
        batch leaves zero buffered writes for that item."""
        parts = self._split(path)
        if not parts:
            raise _err(Code.META_EXISTS, "/")
        parent = self._load_inode(txn, ROOT_INODE_ID)
        created: Optional[Inode] = None
        for i, name in enumerate(parts):
            last = i == len(parts) - 1
            ent = self._load_dirent(txn, parent.id, name)
            if ent is not None:
                child = self._load_inode(txn, ent.inode_id)
                if last:
                    if exist_ok and child is not None and child.is_dir():
                        return child
                    raise _err(Code.META_EXISTS, path)
                if not child.is_dir():
                    raise _err(Code.META_NOT_DIRECTORY, name)
                parent = child
                continue
            if not last and not recursive:
                raise _err(Code.META_NOT_FOUND, name)
            self._check_dir_writable(parent, user)
            child = Inode.new_dir(
                self._ids.allocate(), Acl(user.uid, user.gid, perm), parent.id
            )
            self._store_inode(txn, child)
            self._store_dirent(
                txn, DirEntry(parent.id, name, child.id, InodeType.DIRECTORY)
            )
            parent = child
            created = child
        assert created is not None
        return created

    def batch_mkdirs(
        self,
        paths: List[str],
        user: User = ROOT_USER,
        perm: int = 0o755,
        *,
        recursive: bool = True,
        exist_ok: bool = True,
        txn_batch: int = 64,
    ) -> List[object]:
        """Ensure MANY directories in O(len/txn_batch) KV transactions
        instead of one round trip per directory — the kvcache cold-drain
        shape, where ``_ensure_dir`` used to pay one mkdirs RPC per
        uncached shard directory. ``exist_ok`` returns the existing dir
        inode instead of META_EXISTS (mkdir -p semantics). Each result is
        an Inode or an FsError; per-item failures don't poison their
        batch-mates, and a KV conflict retries the whole chunk via
        with_transaction."""
        results: List[object] = [None] * len(paths)
        for base in range(0, len(paths), txn_batch):
            chunk = list(enumerate(paths[base:base + txn_batch], start=base))

            def op(txn: ITransaction, _chunk=chunk):
                out = []
                for i, p in _chunk:
                    try:
                        out.append((i, self._mkdirs_in_txn(
                            txn, p, user, perm, recursive=recursive,
                            exist_ok=exist_ok)))
                    except FsError as e:
                        out.append((i, e))
                return out

            for i, res in with_transaction(self._engine, op):
                results[i] = res
        for p, res in zip(paths, results):
            if isinstance(res, Inode):
                self._emit("mkdir", p, inode_id=res.id, uid=user.uid)
        return results

    def _check_dir_writable(self, d: Inode, user: User) -> None:
        if not d.acl.check_user(user, PERM_W | PERM_X):
            raise _err(Code.META_NO_PERMISSION, f"dir {d.id}")
        if d.locked_by:
            raise _err(Code.META_NO_PERMISSION, f"dir {d.id} locked by {d.locked_by}")

    def create(
        self,
        path: str,
        user: User = ROOT_USER,
        perm: int = 0o644,
        *,
        flags: int = 0,
        chunk_size: Optional[int] = None,
        stripe: Optional[int] = None,
        client_id: str = "",
        layout: Optional[Layout] = None,
    ) -> OpenResult:
        """Create (and open) a regular file (ref src/meta/store/ops/Open.cc).

        An explicit `layout` overrides the chain allocator — callers that
        must place a file on specific chains (the checkpoint archiver
        re-encoding onto EC chains) pass the full Layout; everyone else
        gets allocator striping."""
        layout = self._resolve_create_layout(chunk_size, stripe, layout)

        def op(txn: ITransaction) -> OpenResult:
            return self._create_in_txn(txn, path, user, perm, flags,
                                       client_id, layout)

        result = with_transaction(self._engine, op)
        self._maybe_truncate_chunks(result, flags)
        self._emit("create", path, inode_id=result.inode.id, uid=user.uid)
        return result

    def _resolve_create_layout(
        self,
        chunk_size: Optional[int],
        stripe: Optional[int],
        layout: Optional[Layout],
    ) -> Layout:
        if layout is None:
            table_id, chains, seed = self._chains.allocate(
                stripe or self._default_stripe)
            return Layout(
                table_id=table_id,
                chains=chains,
                chunk_size=chunk_size or self._default_chunk_size,
                seed=seed,
            )
        if not layout.chains:
            raise _err(Code.META_BAD_LAYOUT, "explicit layout without chains")
        return layout

    def _create_in_txn(
        self,
        txn: ITransaction,
        path: str,
        user: User,
        perm: int,
        flags: int,
        client_id: str,
        layout: Layout,
    ) -> OpenResult:
        parent, name, existing = self._walk(txn, path, user)
        if name is None:
            raise _err(Code.META_IS_DIRECTORY, "/")
        if existing is not None:
            if flags & OpenFlags.EXCL:
                raise _err(Code.META_EXISTS, path)
            return self._do_open(txn, existing, user, flags, client_id)
        self._check_dir_writable(parent, user)
        inode = Inode.new_file(
            self._ids.allocate(), Acl(user.uid, user.gid, perm), layout
        )
        self._store_inode(txn, inode)
        self._store_dirent(
            txn, DirEntry(parent.id, name, inode.id, InodeType.FILE)
        )
        session_id = ""
        if flags & OpenFlags.WRITE:
            session_id = self._add_session(txn, inode.id, client_id,
                                           user.uid)
        return OpenResult(inode, session_id)

    def batch_create(
        self,
        items: List["BatchCreateItem"],
        user: User = ROOT_USER,
        *,
        txn_batch: int = 64,
    ) -> List[object]:
        """Create (and open) MANY regular files in O(len/txn_batch) KV
        transactions — the create fan-in behind KVCacheClient.batch_put
        and the ckpt archiver (one meta transaction per 64 files instead
        of one round trip per file). Each result is an OpenResult or an
        FsError: per-item failures (missing parent, EXCL conflict,
        permission) don't poison their batch-mates; a KV conflict retries
        the whole chunk via with_transaction. Chain allocation happens up
        front per item, so allocator striping is identical to N singleton
        creates."""
        prepped: List[object] = []
        for it in items:
            try:
                prepped.append(self._resolve_create_layout(
                    it.chunk_size or None, it.stripe or None, it.layout))
            except FsError as e:
                prepped.append(e)
        results: List[object] = [None] * len(items)
        for base in range(0, len(items), txn_batch):
            chunk = list(enumerate(items[base:base + txn_batch], start=base))

            def op(txn: ITransaction, _chunk=chunk):
                out = []
                for i, it in _chunk:
                    if isinstance(prepped[i], FsError):
                        out.append((i, prepped[i]))
                        continue
                    try:
                        out.append((i, self._create_in_txn(
                            txn, it.path, user, it.perm, it.flags,
                            it.client_id, prepped[i])))
                    except FsError as e:
                        out.append((i, e))
                return out

            for i, res in with_transaction(self._engine, op):
                results[i] = res
        for it, res in zip(items, results):
            if isinstance(res, OpenResult):
                self._maybe_truncate_chunks(res, it.flags)
                self._emit("create", it.path, inode_id=res.inode.id,
                           uid=user.uid)
        return results

    def open(
        self,
        path: str,
        user: User = ROOT_USER,
        *,
        flags: int = OpenFlags.READ,
        client_id: str = "",
    ) -> OpenResult:
        def op(txn: ITransaction) -> OpenResult:
            _, _, inode = self._walk(txn, path, user)
            if inode is None:
                raise _err(Code.META_NOT_FOUND, path)
            return self._do_open(txn, inode, user, flags, client_id)

        result = with_transaction(self._engine, op)
        self._maybe_truncate_chunks(result, flags)
        return result

    def _maybe_truncate_chunks(self, result: "OpenResult", flags: int) -> None:
        # O_TRUNC reclaims existing chunks through storage, outside the KV
        # transaction (storage truncate is idempotent, so a meta retry is safe)
        if (
            flags & OpenFlags.TRUNC
            and self._truncate_hook is not None
            and result.inode.is_file()
        ):
            self._truncate_hook(result.inode, 0)

    def _do_open(
        self, txn: ITransaction, inode: Inode, user: User, flags: int, client_id: str
    ) -> OpenResult:
        if inode.is_dir() and flags & (OpenFlags.WRITE | OpenFlags.TRUNC):
            raise _err(Code.META_IS_DIRECTORY, str(inode.id))
        want = 0
        if flags & OpenFlags.READ:
            want |= PERM_R
        if flags & OpenFlags.WRITE:
            want |= PERM_W
        if want and not inode.acl.check_user(user, want):
            raise _err(Code.META_NO_PERMISSION, str(inode.id))
        session_id = ""
        if inode.is_file() and flags & OpenFlags.WRITE:
            if flags & OpenFlags.TRUNC and inode.length:
                inode.length = 0
                inode.mtime = time.time()
                self._store_inode(txn, inode)
            session_id = self._add_session(txn, inode.id, client_id, user.uid)
        return OpenResult(inode, session_id)

    def _add_session(self, txn: ITransaction, inode_id: int, client_id: str,
                     uid: int = 0) -> str:
        session_id = uuid.uuid4().hex
        sess = FileSession(inode_id, client_id, session_id, time.time(), uid)
        txn.set(session_key(inode_id, session_id), serialize(sess))
        return session_id

    def list_sessions(self, inode_id: Optional[int] = None) -> List[FileSession]:
        def op(txn: ITransaction):
            begin, end = session_scan_range(inode_id)
            return [
                deserialize(p.value, FileSession)
                for p in txn.get_range(begin, end, snapshot=True)
            ]

        return with_transaction(self._engine, op, read_only=True)

    def close(
        self,
        inode_id: int,
        session_id: str,
        *,
        length_hint: Optional[int] = None,
        client_id: str = "",
        request_id: str = "",
        wrote: Optional[bool] = None,
        user: Optional[User] = None,
    ) -> Inode:
        """Close a write session; settle the precise file length
        (ref src/meta/store/ops/Close; FileHelper queryLastChunk).

        mtime only moves if the session wrote (wrote=True, or unspecified
        with a length hint present) — a read-only open+close must not look
        like a modification."""

        def op(txn: ITransaction) -> Inode:
            return self._close_in_txn(
                txn, inode_id, session_id, length_hint=length_hint,
                client_id=client_id, request_id=request_id, wrote=wrote,
                user=user)

        return with_transaction(self._engine, op)

    def _close_in_txn(
        self,
        txn: ITransaction,
        inode_id: int,
        session_id: str,
        *,
        length_hint: Optional[int] = None,
        client_id: str = "",
        request_id: str = "",
        wrote: Optional[bool] = None,
        user: Optional[User] = None,
    ) -> Inode:
        """One close inside an already-open transaction — shared by close()
        and batch_close() (ref BatchOperation.cc:750 batches exactly these
        inode settles into one transaction)."""
        # ORDER MATTERS for batch_close: every read/permission check and
        # the (possibly RPC-backed, possibly raising) length hook run
        # BEFORE the first mutation, so a per-item FsError caught by the
        # batch leaves zero buffered writes for that item in the shared
        # transaction — a failed item must not half-commit (session gone,
        # length unsettled).
        # the cache key is scoped to the caller's identity in auth mode:
        # a replay of another client's (client_id, request_id) by a
        # different user misses and must pass authorization below
        ckey = idempotent_key(client_id, request_id,
                              None if user is None else user.uid)
        if request_id:
            cached = txn.get(ckey)
            if cached is not None:
                return deserialize(cached, Inode)
        inode = self._load_inode(txn, inode_id)
        if inode is None:
            raise _err(Code.META_NOT_FOUND, str(inode_id))
        skey = session_key(inode_id, session_id)
        if session_id:
            raw = txn.get(skey)
            if raw is None:
                raise _err(Code.META_NO_SESSION, session_id)
            if user is not None:
                # the session is the capability granted at open: closing
                # authorizes against its owner, not the live ACL (a chmod
                # between open and close must not wedge the session)
                sess = deserialize(raw, FileSession)
                if not (user.is_root or sess.uid == user.uid):
                    raise _err(Code.META_NO_PERMISSION, session_id)
        elif user is not None and not inode.acl.check_user(user, PERM_W):
            # sessionless length settle falls back to the ACL
            raise _err(Code.META_NO_PERMISSION, str(inode_id))
        store_inode = False
        if inode.is_file():
            if self._file_length_hook is not None:
                inode.length = self._file_length_hook(inode)  # may raise
            elif length_hint is not None:
                inode.length = max(inode.length, length_hint)
            if wrote or (wrote is None and length_hint is not None):
                inode.mtime = time.time()
            store_inode = True
        # -- mutations (nothing above may raise past here) -------------------
        if session_id:
            txn.clear(skey)
        if store_inode:
            self._store_inode(txn, inode)
        if request_id:
            txn.set(ckey, serialize(inode))
        return inode

    def batch_close(
        self,
        items: List["BatchCloseItem"],
        user: Optional[User] = None,
        *,
        txn_batch: int = 64,
    ) -> List[object]:
        """Settle MANY write sessions' lengths in O(len/txn_batch) KV
        transactions instead of one per file (ref src/meta/store/ops/
        BatchOperation.cc:750 — batched inode updates behind the
        Distributor). Per-item failures (missing inode/session, permission)
        come back as FsError entries without failing their batch-mates;
        a KV conflict retries the whole chunk via with_transaction."""
        results: List[object] = [None] * len(items)
        for base in range(0, len(items), txn_batch):
            chunk = list(enumerate(items[base:base + txn_batch], start=base))

            def op(txn: ITransaction, _chunk=chunk):
                out = []
                for i, it in _chunk:
                    try:
                        out.append((i, self._close_in_txn(
                            txn, it.inode_id, it.session_id,
                            length_hint=(it.length_hint
                                         if it.length_hint >= 0 else None),
                            client_id=it.client_id,
                            request_id=it.request_id,
                            wrote=(None if it.wrote < 0 else bool(it.wrote)),
                            user=user)))
                    except FsError as e:
                        out.append((i, e))
                return out

            for i, res in with_transaction(self._engine, op):
                results[i] = res
        return results

    def sync(self, inode_id: int, *, length_hint: Optional[int] = None,
             user: Optional[User] = None) -> Inode:
        """fsync: refresh the length hint without closing the session.
        With a user, requires write permission on the inode OR a live write
        session the user opened (so a chmod after open cannot wedge an
        in-flight writer's fsync)."""

        def op(txn: ITransaction) -> Inode:
            inode = self._load_inode(txn, inode_id)
            if inode is None:
                raise _err(Code.META_NOT_FOUND, str(inode_id))
            if user is not None and not inode.acl.check_user(user, PERM_W):
                begin, end = session_scan_range(inode_id)
                owns = any(
                    deserialize(p.value, FileSession).uid == user.uid
                    for p in txn.get_range(begin, end, snapshot=True)
                )
                if not owns:
                    raise _err(Code.META_NO_PERMISSION, str(inode_id))
            if inode.is_file():
                if self._file_length_hook is not None:
                    inode.length = self._file_length_hook(inode)
                elif length_hint is not None and length_hint > inode.length:
                    inode.length = length_hint
                inode.length_hint_ver += 1
                self._store_inode(txn, inode)
            return inode

        return with_transaction(self._engine, op)

    def prune_session(self, client_id: str,
                      user: Optional[User] = None, *,
                      admin: bool = False) -> int:
        """Drop all sessions of a dead client (ref SessionManager prune).
        With a user, pruning requires root or the admin flag — it destroys
        other clients' live write sessions."""
        if user is not None and not (user.is_root or admin):
            raise _err(Code.META_NO_PERMISSION,
                       "prune-session requires admin")

        def op(txn: ITransaction) -> int:
            begin, end = session_scan_range()
            dropped = 0
            for pair in txn.get_range(begin, end, snapshot=True):
                sess = deserialize(pair.value, FileSession)
                if sess.client_id == client_id:
                    txn.clear(pair.key)
                    dropped += 1
            return dropped

        return with_transaction(self._engine, op)

    def symlink(self, path: str, target: str, user: User = ROOT_USER) -> Inode:
        def op(txn: ITransaction) -> Inode:
            parent, name, existing = self._walk(txn, path, user, follow_last=False)
            if name is None or existing is not None:
                raise _err(Code.META_EXISTS, path)
            self._check_dir_writable(parent, user)
            inode = Inode.new_symlink(
                self._ids.allocate(), Acl(user.uid, user.gid, 0o777), target
            )
            self._store_inode(txn, inode)
            self._store_dirent(
                txn, DirEntry(parent.id, name, inode.id, InodeType.SYMLINK)
            )
            return inode

        result = with_transaction(self._engine, op)
        self._emit("symlink", path, inode_id=result.id, uid=user.uid,
                   detail=target)
        return result

    def hard_link(self, src: str, dst: str, user: User = ROOT_USER) -> Inode:
        def op(txn: ITransaction) -> Inode:
            _, _, inode = self._walk(txn, src, user)
            if inode is None:
                raise _err(Code.META_NOT_FOUND, src)
            if inode.is_dir():
                raise _err(Code.META_IS_DIRECTORY, src)
            parent, name, existing = self._walk(txn, dst, user, follow_last=False)
            if name is None or existing is not None:
                raise _err(Code.META_EXISTS, dst)
            self._check_dir_writable(parent, user)
            inode.nlink += 1
            inode.ctime = time.time()
            self._store_inode(txn, inode)
            self._store_dirent(txn, DirEntry(parent.id, name, inode.id, inode.type))
            return inode

        return with_transaction(self._engine, op)

    def list_dir(
        self, path: str, user: User = ROOT_USER, *, limit: int = 0, prefix: str = ""
    ) -> List[DirEntry]:
        def op(txn: ITransaction) -> List[DirEntry]:
            _, _, inode = self._walk(txn, path, user)
            if inode is None:
                raise _err(Code.META_NOT_FOUND, path)
            if not inode.is_dir():
                raise _err(Code.META_NOT_DIRECTORY, path)
            if not inode.acl.check_user(user, PERM_R):
                raise _err(Code.META_NO_PERMISSION, path)
            begin, end = dirent_scan_range(inode.id)
            if prefix:
                begin = dirent_key(inode.id, prefix)
            ents = [
                deserialize(p.value, DirEntry)
                for p in txn.get_range(begin, end, limit=limit, snapshot=True)
            ]
            if prefix:
                ents = [e for e in ents if e.name.startswith(prefix)]
            return ents

        return with_transaction(self._engine, op, read_only=True)

    def remove(
        self,
        path: str,
        user: User = ROOT_USER,
        *,
        recursive: bool = False,
        client_id: str = "",
        request_id: str = "",
    ) -> None:
        """Unlink a file (chunks reclaimed by GC) or remove a directory
        (ref src/meta/store/ops/Remove.cc; GcManager)."""

        def op(txn: ITransaction) -> None:
            if request_id:
                if txn.get(idempotent_key(client_id, request_id)) is not None:
                    return
            parent, name, inode = self._walk(txn, path, user, follow_last=False)
            if name is None:
                raise _err(Code.META_INVALID_PATH, "cannot remove /")
            if inode is None:
                raise _err(Code.META_NOT_FOUND, path)
            self._check_dir_writable(parent, user)
            self._remove_inode(txn, parent.id, name, inode, recursive)
            if request_id:
                txn.set(idempotent_key(client_id, request_id), b"1")

        result = with_transaction(self._engine, op)
        self._emit("remove", path, uid=user.uid,
                   detail="recursive" if recursive else "")
        return result

    def _remove_inode(
        self, txn: ITransaction, parent_id: int, name: str, inode: Inode,
        recursive: bool,
    ) -> None:
        if inode.is_dir():
            begin, end = dirent_scan_range(inode.id)
            children = txn.get_range(begin, end, limit=0 if recursive else 1)
            if children and not recursive:
                raise _err(Code.META_NOT_EMPTY, name)
            for pair in children:
                ent = deserialize(pair.value, DirEntry)
                child = self._load_inode(txn, ent.inode_id)
                if child is not None:
                    self._remove_inode(txn, inode.id, ent.name, child, True)
            txn.clear(dirent_key(parent_id, name))
            txn.clear(inode_key(inode.id))
            return
        txn.clear(dirent_key(parent_id, name))
        inode.nlink -= 1
        if inode.nlink > 0:
            inode.ctime = time.time()
            self._store_inode(txn, inode)
            return
        # last link: park in the GC queue; chunks reclaimed asynchronously.
        # The inode record stays (like the ref's GC directories) so open
        # sessions can still close/fstat it; gc_finish deletes it.
        inode.nlink = 0
        inode.ctime = time.time()
        if inode.is_file():
            self._store_inode(txn, inode)
            txn.set(gc_key(inode.id), serialize(inode))
        else:
            txn.clear(inode_key(inode.id))

    def rename(self, src: str, dst: str, user: User = ROOT_USER) -> None:
        """Atomic rename with directory-loop detection
        (ref src/meta/store/ops/Rename.cc)."""

        def op(txn: ITransaction) -> None:
            sparent, sname, sinode = self._walk(txn, src, user, follow_last=False)
            if sname is None or sinode is None:
                raise _err(Code.META_NOT_FOUND, src)
            dparent, dname, dinode = self._walk(txn, dst, user, follow_last=False)
            if dname is None:
                raise _err(Code.META_EXISTS, "/")
            self._check_dir_writable(sparent, user)
            self._check_dir_writable(dparent, user)
            if sinode.is_dir():
                # dst parent must not be inside src (would orphan the subtree)
                cur = dparent
                while True:
                    if cur.id == sinode.id:
                        raise _err(Code.META_LOOP, f"{dst} inside {src}")
                    if cur.id == ROOT_INODE_ID:
                        break
                    cur = self._load_inode(txn, cur.parent)
                    if cur is None:
                        break
            if dinode is not None:
                if dinode.id == sinode.id:
                    return
                self._remove_inode(txn, dparent.id, dname, dinode, False)
            txn.clear(dirent_key(sparent.id, sname))
            self._store_dirent(txn, DirEntry(dparent.id, dname, sinode.id, sinode.type))
            if sinode.is_dir() and sparent.id != dparent.id:
                sinode.parent = dparent.id
                self._store_inode(txn, sinode)

        result = with_transaction(self._engine, op)
        self._emit("rename", src, uid=user.uid, detail=dst)
        return result

    def set_attr(
        self,
        path: str,
        user: User = ROOT_USER,
        *,
        perm: Optional[int] = None,
        uid: Optional[int] = None,
        gid: Optional[int] = None,
        atime: Optional[float] = None,
        mtime: Optional[float] = None,
    ) -> Inode:
        def op(txn: ITransaction) -> Inode:
            _, _, inode = self._walk(txn, path, user)
            if inode is None:
                raise _err(Code.META_NOT_FOUND, path)
            if not user.is_root and user.uid != inode.acl.uid:
                raise _err(Code.META_NO_PERMISSION, path)
            if perm is not None:
                inode.acl.perm = perm
            if uid is not None:
                if not user.is_root:
                    raise _err(Code.META_NO_PERMISSION, "chown requires root")
                inode.acl.uid = uid
            if gid is not None:
                inode.acl.gid = gid
            if atime is not None:
                inode.atime = atime
            if mtime is not None:
                inode.mtime = mtime
            inode.ctime = time.time()
            self._store_inode(txn, inode)
            return inode

        return with_transaction(self._engine, op)

    def batch_set_attr(
        self,
        paths: Optional[List[str]] = None,
        user: User = ROOT_USER,
        *,
        inode_ids: Optional[List[int]] = None,
        atime: Optional[float] = None,
        mtime: Optional[float] = None,
        txn_batch: int = 64,
    ) -> List[object]:
        """Settle atime/mtime on MANY inodes in O(len/txn_batch) KV
        transactions instead of one per item — the KVCache touch-on-get
        path, where every batched read otherwise pays one metadata round
        trip per hit. Address by path, or by inode id (``inode_ids``) to
        skip the path walks entirely when the caller already statted —
        like ``sync``, id addressing is the capability the stat handed
        out. Times only (ownership changes stay single-op: chmod/chown
        want per-path error surfaces). Per-item failures come back as
        FsError entries without failing their batch-mates."""
        if (paths is None) == (inode_ids is None):
            raise _err(Code.INVALID_ARG,
                       "batch_set_attr takes paths OR inode_ids")
        items: List[object] = list(paths if paths is not None
                                   else inode_ids)
        results: List[object] = [None] * len(items)
        for base in range(0, len(items), txn_batch):
            chunk = list(enumerate(items[base:base + txn_batch],
                                   start=base))

            def op(txn: ITransaction, _chunk=chunk):
                out = []
                for i, item in _chunk:
                    try:
                        # checks before mutation, like _close_in_txn: a
                        # failed item must leave no buffered writes
                        if isinstance(item, str):
                            _, _, inode = self._walk(txn, item, user)
                        else:
                            inode = self._load_inode(txn, int(item))
                        if inode is None:
                            raise _err(Code.META_NOT_FOUND, str(item))
                        if not user.is_root and user.uid != inode.acl.uid:
                            raise _err(Code.META_NO_PERMISSION, str(item))
                        if atime is not None:
                            inode.atime = atime
                        if mtime is not None:
                            inode.mtime = mtime
                        inode.ctime = time.time()
                        self._store_inode(txn, inode)
                        out.append((i, inode))
                    except FsError as e:
                        out.append((i, e))
                return out

            for i, res in with_transaction(self._engine, op):
                results[i] = res
        return results

    # -- extended attributes (ref fuse_lowlevel_ops setxattr/getxattr/
    # listxattr/removexattr, FuseOps.cc:2580-2613) --------------------------
    XATTR_CREATE = 1   # fail with META_EXISTS if the name exists
    XATTR_REPLACE = 2  # fail with META_NO_XATTR if the name is absent

    def set_xattr(self, path: str, name: str, value: bytes,
                  user: User = ROOT_USER, *, flags: int = 0) -> Inode:
        if not name or len(name) > 255 or len(value) > 64 << 10:
            raise _err(Code.INVALID_ARG, f"xattr {name!r}")

        def op(txn: ITransaction) -> Inode:
            _, _, inode = self._walk(txn, path, user)
            if inode is None:
                raise _err(Code.META_NOT_FOUND, path)
            if not inode.acl.check_user(user, PERM_W):
                raise _err(Code.META_NO_PERMISSION, path)
            # XATTR_CREATE/XATTR_REPLACE checked INSIDE the transaction:
            # create-exclusive xattr protocols (lock/claim via xattrs)
            # need the check and the write to be atomic
            if (flags & self.XATTR_CREATE) and name in inode.xattrs:
                raise _err(Code.META_EXISTS, f"xattr {name} on {path}")
            if (flags & self.XATTR_REPLACE) and name not in inode.xattrs:
                raise _err(Code.META_NO_XATTR, f"xattr {name} on {path}")
            inode.xattrs[name] = bytes(value)
            inode.ctime = time.time()
            self._store_inode(txn, inode)
            return inode

        return with_transaction(self._engine, op)

    def get_xattr(self, path: str, name: str,
                  user: User = ROOT_USER) -> bytes:
        inode = self.stat(path, user)
        if name not in inode.xattrs:
            raise _err(Code.META_NO_XATTR, f"xattr {name} on {path}")
        return inode.xattrs[name]

    def list_xattrs(self, path: str, user: User = ROOT_USER) -> List[str]:
        return sorted(self.stat(path, user).xattrs)

    def remove_xattr(self, path: str, name: str,
                     user: User = ROOT_USER) -> Inode:
        def op(txn: ITransaction) -> Inode:
            _, _, inode = self._walk(txn, path, user)
            if inode is None:
                raise _err(Code.META_NOT_FOUND, path)
            if not inode.acl.check_user(user, PERM_W):
                raise _err(Code.META_NO_PERMISSION, path)
            if name not in inode.xattrs:
                raise _err(Code.META_NO_XATTR, f"xattr {name} on {path}")
            del inode.xattrs[name]
            inode.ctime = time.time()
            self._store_inode(txn, inode)
            return inode

        return with_transaction(self._engine, op)

    def truncate(self, path: str, length: int, user: User = ROOT_USER) -> Inode:
        def op(txn: ITransaction) -> Inode:
            _, _, inode = self._walk(txn, path, user)
            if inode is None:
                raise _err(Code.META_NOT_FOUND, path)
            if not inode.is_file():
                raise _err(Code.META_NOT_FILE, path)
            if not inode.acl.check_user(user, PERM_W):
                raise _err(Code.META_NO_PERMISSION, path)
            inode.length = length
            inode.mtime = time.time()
            self._store_inode(txn, inode)
            return inode

        inode = with_transaction(self._engine, op)
        if self._truncate_hook is not None:
            self._truncate_hook(inode, length)
        return inode

    def get_real_path(self, path: str, user: User = ROOT_USER) -> str:
        def op(txn: ITransaction) -> str:
            parent, name, inode = self._walk(txn, path, user)
            if inode is None:
                raise _err(Code.META_NOT_FOUND, path)
            if inode.id == ROOT_INODE_ID:
                return "/"
            # walk parent pointers up for the directory part
            segs = [name] if name else []
            cur = parent
            while cur.id != ROOT_INODE_ID:
                begin, end = dirent_scan_range(cur.parent)
                found = None
                for pair in txn.get_range(begin, end, snapshot=True):
                    ent = deserialize(pair.value, DirEntry)
                    if ent.inode_id == cur.id:
                        found = ent.name
                        break
                if found is None:
                    raise _err(Code.META_NOT_FOUND, f"orphan dir {cur.id}")
                segs.append(found)
                nxt = self._load_inode(txn, cur.parent)
                if nxt is None:
                    break
                cur = nxt
            return "/" + "/".join(reversed(segs))

        return with_transaction(self._engine, op, read_only=True)

    def lock_directory(self, path: str, owner: str, user: User = ROOT_USER) -> None:
        """Restrict modifications of a directory to one owner
        (ref MetaSerde lockDirectory)."""

        def op(txn: ITransaction) -> None:
            _, _, inode = self._walk(txn, path, user)
            if inode is None or not inode.is_dir():
                raise _err(Code.META_NOT_DIRECTORY, path)
            if inode.locked_by and inode.locked_by != owner:
                # changing or clearing someone else's lock needs privilege
                # (root or the directory owner)
                if not user.is_root and user.uid != inode.acl.uid:
                    raise _err(
                        Code.META_NO_PERMISSION, f"locked by {inode.locked_by}"
                    )
            inode.locked_by = owner
            self._store_inode(txn, inode)

        return with_transaction(self._engine, op)

    def stat_fs(self) -> StatFs:
        def op(txn: ITransaction) -> StatFs:
            begin = inode_key(0)
            end = inode_key(2**64 - 1)
            files = used = 0
            for pair in txn.get_range(begin, end, snapshot=True):
                inode = deserialize(pair.value, Inode)
                if inode.is_file():
                    files += 1
                    used += inode.length
            return StatFs(capacity=0, used=used, files=files)

        sf = with_transaction(self._engine, op, read_only=True)
        if self._space_hook is not None:
            capacity, used = self._space_hook()
            sf.capacity = capacity
            sf.used = used
        return sf

    # -- GC (ref src/meta/components/GcManager.cc) --------------------------
    def gc_scan(self, limit: int = 64) -> List[Inode]:
        """Inodes waiting for chunk reclamation."""

        def op(txn: ITransaction):
            begin, end = gc_scan_range()
            return [
                deserialize(p.value, Inode)
                for p in txn.get_range(begin, end, limit=limit, snapshot=True)
            ]

        return with_transaction(self._engine, op, read_only=True)

    def gc_finish(self, inode_id: int) -> None:
        """Called after storage confirmed chunk removal: drop the GC record
        and the parked inode."""

        def op(txn: ITransaction) -> None:
            txn.clear(gc_key(inode_id))
            txn.clear(inode_key(inode_id))

        return with_transaction(self._engine, op)

    def has_sessions(self, inode_id: int) -> bool:
        return bool(self.list_sessions(inode_id))
