"""Distributor: rendezvous-hash assignment of inodes to meta servers.

Re-expresses the reference's meta Distributor component
(src/meta/components/Distributor.h:29-60, Distributor.cc:320): meta servers
are stateless, but per-inode *serialized* work (dynamic file-length updates,
session pruning for one inode) is sharded so exactly one server owns each
inode at a time. Ownership is rendezvous (highest-random-weight) hashing over
the set of live servers, which minimizes reshuffling when membership changes.

Liveness is tracked through heartbeat records in the shared KV store under
the "METS" prefix (the reference keeps its server map under the "META" key
prefix, src/common/kv/KeyPrefix-def.h). A server whose record is older than
the timeout drops out of the hash ring on the next `active_servers` read.
"""

from __future__ import annotations

import hashlib
import struct
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

from tpu3fs.kv.kv import IKVEngine, ITransaction, with_transaction
from tpu3fs.rpc.serde import deserialize, serialize

_PREFIX = b"METS"


def _server_key(server_id: int) -> bytes:
    return _PREFIX + struct.pack("<q", server_id)


def _scan_range() -> tuple:
    return _PREFIX, _PREFIX + b"\xff" * 9


@dataclass
class ServerRecord:
    server_id: int = 0
    last_heartbeat: float = 0.0


def rendezvous_owner(server_ids: List[int], inode_id: int) -> Optional[int]:
    """Highest-random-weight choice of owner for one inode."""
    best, best_weight = None, b""
    for sid in sorted(server_ids):
        weight = hashlib.blake2b(
            struct.pack("<qq", sid, inode_id), digest_size=8
        ).digest()
        if best is None or weight > best_weight:
            best, best_weight = sid, weight
    return best


class Distributor:
    def __init__(
        self,
        engine: IKVEngine,
        server_id: int,
        *,
        timeout_s: float = 30.0,
        clock: Callable[[], float] = time.time,
    ):
        self._engine = engine
        self.server_id = server_id
        self._timeout_s = timeout_s
        self._clock = clock

    # -- membership ---------------------------------------------------------
    def heartbeat(self) -> None:
        now = self._clock()

        def op(txn: ITransaction) -> None:
            txn.set(
                _server_key(self.server_id),
                serialize(ServerRecord(self.server_id, now)),
            )

        with_transaction(self._engine, op)

    def leave(self) -> None:
        def op(txn: ITransaction) -> None:
            txn.clear(_server_key(self.server_id))

        with_transaction(self._engine, op)

    def active_servers(self) -> List[int]:
        now = self._clock()
        cutoff = now - self._timeout_s

        def op(txn: ITransaction) -> List[int]:
            begin, end = _scan_range()
            out = []
            for pair in txn.get_range(begin, end, limit=0):
                rec = deserialize(pair.value, ServerRecord)
                if rec.last_heartbeat >= cutoff:
                    out.append(rec.server_id)
            return out

        return with_transaction(self._engine, op)

    # -- ownership ----------------------------------------------------------
    def owner(self, inode_id: int) -> Optional[int]:
        return rendezvous_owner(self.active_servers(), inode_id)

    def is_owner(self, inode_id: int) -> bool:
        return self.owner(inode_id) == self.server_id
