"""Cluster SLO engine: hot-configurable rules over windowed aggregates,
multi-window burn-rate alerting, and the ``SloGate`` hard-gate helper.

The collector's ``WindowedAggregator`` (monitor/agg.py) makes every
metric queryable as rate/last/min/max/p50/p90/p99 over any window; this
module JUDGES those aggregates. Rules arrive as ONE spec string riding
the same config machinery as ``[qos]``/``[tenants]``/``[faults]`` —
``[slo] spec=...`` hot-updates the engine live (for the collector
binary, which boots one-phase, ``admin_cli slo set`` pushes the section
through the core ``hotUpdateConfig`` RPC).

Spec grammar — entries separated by ``;``, fields by ``,``::

    rule=read_p99,metric=storage.read.latency_us,agg=p99,max=50000,
        fast_s=10,slow_s=60,severity=degraded;
    rule=shed_rate,metric=qos.shed,agg=rate,max=25;
    rule=rss_ceiling,metric=memory.rss_kb,agg=last,max=4194304;
    rule=node_alive,metric=memory.rss_kb,absent_s=45

- ``agg``: which aggregate to bound — ``p50|p90|p99`` (digest
  quantiles), ``rate`` (value sum / window), ``last`` (gauge), ``sum``,
  ``count``, ``min``, ``max``, ``mean``;
- ``max=`` / ``min=``: the bound (at least one, unless ``absent_s``);
- ``absent_s=N``: an ABSENCE rule — breaches when no matching series
  has reported for N seconds (grace-armed: a freshly configured rule
  waits N seconds before it may fire, so boot doesn't flap);
- tag filters (``class= node= tenant= service= kind= chain= target=``)
  restrict the rule to matching series; each matching series is judged
  separately, so the breach NAMES the offending node/class/tenant;
- MULTI-WINDOW BURN RATE: ``fast_s`` (default 15) is the firing window,
  ``slow_s`` (default 60) the resolve window. The state machine::

      ok --breach(fast)--> pending --persists for_s--> firing
      firing --clean(fast) AND clean(slow)--> ok (resolved)

  A momentary recovery inside a dirty slow window keeps the alert
  FIRING (flap suppression); ``for_s`` (default 0) delays firing until
  the fast-window breach has persisted.
- ``severity=degraded|critical`` (default degraded) sets how a firing
  rule colors the single cluster verdict: OK / DEGRADED / CRITICAL.

Every transition is itself a sample (``slo.alert_pending`` /
``slo.alert_firing`` / ``slo.alert_resolved`` counters tagged
``kind=<rule>``), so alert history lands in the same store the rules
read — and the flight recorder's ring (monitor/flight.py) keeps the
recent transitions for postmortems.

``SloGate`` is the reusable hard gate: drive scripts and benches point
it at a live collector and ``assert_ok()`` raises with the firing rules
when the cluster is not clean — ad-hoc p99 math in every script
replaced by the rules the operators already watch.
"""

from __future__ import annotations

import collections
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from tpu3fs.monitor.agg import AggRow, WindowedAggregator
from tpu3fs.monitor.recorder import (
    CounterRecorder,
    DistributionRecorder,
    ValueRecorder,
)
from tpu3fs.utils.config import Config, ConfigItem

_RULE_RE = re.compile(r"^[a-z0-9_-]{1,64}$")
_METRIC_RE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)+$")
_AGGS = ("p50", "p90", "p99", "rate", "last", "sum", "count", "min",
         "max", "mean")
_SEVERITIES = ("degraded", "critical")
_TAG_KEYS = ("service", "class", "tenant", "chain", "node", "kind",
             "target")

#: the shipped default rule set (the drive script and the production-day
#: soak start from these; tools/check_recorder_registry.py statically
#: verifies every metric name herein exists in the recorder registry)
DEFAULT_CLUSTER_SPEC = (
    "rule=read_p99,metric=storage.read.latency_us,agg=p99,max=50000,"
    "fast_s=10,slow_s=30;"
    "rule=write_p99,metric=storage.write.latency_us,agg=p99,max=200000,"
    "fast_s=10,slow_s=30;"
    "rule=shed_rate,metric=qos.shed,agg=rate,max=50,fast_s=10,slow_s=30;"
    "rule=push_loss,metric=monitor.push_dropped,agg=rate,max=1,"
    "fast_s=30,slow_s=60;"
    "rule=node_alive,metric=memory.rss_kb,absent_s=90"
)


@dataclass
class SloRule:
    name: str
    metric: str = ""
    agg: str = "p99"
    max_bound: Optional[float] = None
    min_bound: Optional[float] = None
    absent_s: float = 0.0
    fast_s: float = 15.0
    slow_s: float = 60.0
    for_s: float = 0.0
    severity: str = "degraded"
    tags: Dict[str, str] = field(default_factory=dict)

    def describe(self) -> str:
        if self.absent_s > 0:
            cond = f"absent>{self.absent_s:g}s"
        else:
            parts = []
            if self.max_bound is not None:
                parts.append(f"{self.agg}<={self.max_bound:g}")
            if self.min_bound is not None:
                parts.append(f"{self.agg}>={self.min_bound:g}")
            cond = " and ".join(parts)
        tags = "".join(f",{k}={v}" for k, v in sorted(self.tags.items()))
        return f"{self.metric}{tags} {cond}"


def parse_slo_spec(spec: str) -> Dict[str, SloRule]:
    """Parse an ``[slo] spec=`` string; malformed entries raise
    ValueError (a config push must reject bad specs atomically)."""
    out: Dict[str, SloRule] = {}
    for entry in (spec or "").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        fields: Dict[str, str] = {}
        for part in entry.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"slo spec field without '=': {part!r}")
            k, v = part.split("=", 1)
            fields[k.strip()] = v.strip()
        name = fields.pop("rule", "")
        if not _RULE_RE.match(name):
            raise ValueError(f"slo spec entry with bad rule=: {entry!r}")
        if name in out:
            raise ValueError(f"slo rule {name!r} listed twice")
        metric = fields.pop("metric", "")
        if not _METRIC_RE.match(metric):
            raise ValueError(
                f"slo rule {name!r}: bad metric name {metric!r}")
        tags = {k: fields.pop(k) for k in list(fields)
                if k in _TAG_KEYS}
        try:
            rule = SloRule(
                name=name, metric=metric,
                agg=fields.pop("agg", "p99"),
                max_bound=(float(fields.pop("max"))
                           if "max" in fields else None),
                min_bound=(float(fields.pop("min"))
                           if "min" in fields else None),
                absent_s=float(fields.pop("absent_s", 0.0)),
                fast_s=float(fields.pop("fast_s", 15.0)),
                slow_s=float(fields.pop("slow_s", 60.0)),
                for_s=float(fields.pop("for_s", 0.0)),
                severity=fields.pop("severity", "degraded"),
                tags=tags,
            )
        except ValueError as e:
            raise ValueError(f"slo rule {name!r}: {e}")
        if fields:
            raise ValueError(
                f"slo rule {name!r}: unknown fields {sorted(fields)}")
        if rule.agg not in _AGGS:
            raise ValueError(
                f"slo rule {name!r}: agg must be one of {_AGGS}")
        if rule.severity not in _SEVERITIES:
            raise ValueError(
                f"slo rule {name!r}: severity must be one of "
                f"{_SEVERITIES}")
        if rule.absent_s < 0 or rule.fast_s <= 0 or rule.for_s < 0:
            raise ValueError(f"slo rule {name!r}: out of range")
        if rule.slow_s < rule.fast_s:
            raise ValueError(
                f"slo rule {name!r}: slow_s < fast_s (the resolve "
                "window must contain the firing window)")
        if rule.absent_s == 0 and rule.max_bound is None \
                and rule.min_bound is None:
            raise ValueError(
                f"slo rule {name!r}: needs max=, min= or absent_s=")
        out[name] = rule
    return out


def _check_spec(spec: str) -> bool:
    try:
        parse_slo_spec(spec)
        return True
    except ValueError:
        return False


class SloConfig(Config):
    """The hot-updatable ``[slo]`` section the collector binary carries
    (monitor_main). Empty spec = no rules, verdict always OK."""

    enabled = ConfigItem(True, hot=True)
    spec = ConfigItem("", hot=True, checker=_check_spec,
                      doc="semicolon-separated SLO rules; see docs/slo.md")
    eval_period_s = ConfigItem(2.0, hot=True, checker=lambda v: v > 0)


# verdict ladder (the single cluster verdict slo.health reports)
VERDICTS = ("OK", "DEGRADED", "CRITICAL")


@dataclass
class RuleState:
    """One rule's live state (the sloStatus wire row)."""

    rule: str = ""
    severity: str = "degraded"
    state: str = "ok"          # ok | pending | firing
    since: float = 0.0         # when the current state was entered
    value: float = 0.0         # worst observed aggregate, last eval
    bound: str = ""            # human condition (rule.describe())
    message: str = ""          # offender detail (tags of the worst series)
    fired_count: int = 0


@dataclass
class TransitionRow:
    ts: float = 0.0
    rule: str = ""
    transition: str = ""       # pending | firing | resolved | cleared
    value: float = 0.0
    message: str = ""


class SloEngine:
    """Continuous rule evaluation over a WindowedAggregator."""

    def __init__(self, agg: WindowedAggregator, *,
                 now_fn: Callable[[], float] = time.time):
        self._agg = agg
        self._now = now_fn
        self._lock = threading.Lock()
        self._rules: Dict[str, SloRule] = {}
        self._states: Dict[str, RuleState] = {}
        self._armed: Dict[str, float] = {}   # rule -> configure ts
        self.transitions: collections.deque = collections.deque(
            maxlen=256)
        self._on_firing: List[Callable[[RuleState], None]] = []
        # single declaration site per alert-state sample name; per-rule
        # instances tag kind=<rule> (the fixed tag vocabulary)
        self._recs: Dict[Tuple[str, str], CounterRecorder] = {}
        self._rules_firing = ValueRecorder("slo.rules_firing")
        self._health = ValueRecorder("slo.health")
        self._eval_ms = DistributionRecorder("slo.eval_ms")

    # -- config --------------------------------------------------------------
    def configure(self, spec: str) -> None:
        """Install a rule set; same-named rules keep their alert state
        (a threshold retune must not silently resolve a live alert)."""
        rules = parse_slo_spec(spec)
        now = self._now()
        with self._lock:
            self._rules = rules
            for name in list(self._states):
                if name not in rules:
                    del self._states[name]
            for name, rule in rules.items():
                self._armed.setdefault(name, now)
                st = self._states.get(name)
                if st is None:
                    self._states[name] = RuleState(
                        rule=name, severity=rule.severity, since=now,
                        bound=rule.describe())
                else:
                    st.severity = rule.severity
                    st.bound = rule.describe()
            for name in list(self._armed):
                if name not in rules:
                    del self._armed[name]

    def add_firing_callback(self, fn: Callable[[RuleState], None]) -> None:
        """Called (outside the lock) on every transition INTO firing —
        the flight-recorder dump trigger."""
        self._on_firing.append(fn)

    @property
    def rules(self) -> Dict[str, SloRule]:
        with self._lock:
            return dict(self._rules)

    # -- evaluation ----------------------------------------------------------
    def _observe(self, rule: SloRule, window_s: float,
                 now: float) -> Tuple[bool, float, str]:
        """-> (breach, worst value, offender message) for one window."""
        rows = self._agg.query(rule.metric, rule.tags, window_s,
                               until=now)
        if rule.absent_s > 0:
            newest = max((r.last_ts for r in rows), default=0.0)
            armed = self._armed.get(rule.name, now)
            # grace: a freshly armed rule may not fire until absent_s
            # has elapsed since arming (boot must not flap)
            ref = max(newest, armed)
            silent = now - ref
            return silent >= rule.absent_s, silent, (
                "no matching series has ever reported" if newest == 0.0
                else f"last sample {silent:.1f}s ago")
        breach = False
        worst = 0.0
        msg = ""
        for row in rows:
            if row.count == 0:
                continue  # no data in the window: not a violation
            value = self._value_of(rule, row)
            hi = rule.max_bound is not None and value > rule.max_bound
            lo = rule.min_bound is not None and value < rule.min_bound
            if hi or lo:
                if not breach or (hi and value > worst) \
                        or (lo and value < worst):
                    worst = value
                    tags = ",".join(f"{k}={v}" for k, v in
                                    sorted(row.tags.items()))
                    msg = (f"{rule.agg}={value:g} "
                           f"{'>' if hi else '<'} "
                           f"{rule.max_bound if hi else rule.min_bound:g}"
                           + (f" [{tags}]" if tags else ""))
                breach = True
            elif not breach:
                # report the worst non-breaching value for visibility
                if rule.max_bound is not None:
                    worst = max(worst, value)
                else:
                    worst = min(worst, value) if msg else value
                    msg = " "
        return breach, worst, msg.strip()

    @staticmethod
    def _value_of(rule: SloRule, row: AggRow) -> float:
        agg = rule.agg
        if agg == "rate":
            return row.rate
        if agg == "last":
            return row.last
        if agg == "sum":
            return row.vsum
        if agg == "count":
            return float(row.count)
        if agg == "min":
            return row.vmin
        if agg == "max":
            return row.vmax
        if agg == "mean":
            return row.vsum / row.count if row.count else 0.0
        return getattr(row, agg)  # p50 | p90 | p99

    def evaluate(self, now: Optional[float] = None) -> Dict[str, RuleState]:
        """One evaluation pass over every rule; returns the state map."""
        t0 = time.perf_counter()
        now = self._now() if now is None else now
        fired: List[RuleState] = []
        with self._lock:
            for name, rule in self._rules.items():
                st = self._states[name]
                breach_f, value, msg = self._observe(rule, rule.fast_s,
                                                     now)
                st.value = value
                if msg:
                    st.message = msg
                if st.state == "ok":
                    if breach_f:
                        self._transition(st, "pending", now, value, msg)
                        if rule.for_s <= 0:
                            self._transition(st, "firing", now, value,
                                             msg)
                            fired.append(st)
                elif st.state == "pending":
                    if not breach_f:
                        self._transition(st, "cleared", now, value, msg,
                                         to_state="ok")
                    elif now - st.since >= rule.for_s:
                        self._transition(st, "firing", now, value, msg)
                        fired.append(st)
                elif st.state == "firing":
                    breach_s, _, _ = self._observe(rule, rule.slow_s,
                                                   now)
                    # FLAP SUPPRESSION: resolving needs BOTH windows
                    # clean — a dirty slow window keeps the alert firing
                    # through momentary recoveries
                    if not breach_f and not breach_s:
                        self._transition(st, "resolved", now, value,
                                         msg, to_state="ok")
            firing = [s for s in self._states.values()
                      if s.state == "firing"]
            self._rules_firing.set(float(len(firing)))
            self._health.set(float(VERDICTS.index(self._verdict_locked())))
            states = {n: RuleState(**vars(s))
                      for n, s in self._states.items()}
        self._eval_ms.record((time.perf_counter() - t0) * 1e3)
        for st in fired:
            for fn in self._on_firing:
                try:
                    fn(st)
                except Exception:
                    pass  # a dump hook must never stop evaluation
        return states

    def _transition(self, st: RuleState, kind: str, now: float,
                    value: float, msg: str, *,
                    to_state: Optional[str] = None) -> None:
        st.state = to_state if to_state is not None else kind
        st.since = now
        if kind == "firing":
            st.fired_count += 1
        row = TransitionRow(ts=now, rule=st.rule, transition=kind,
                            value=value, message=msg)
        self.transitions.append(row)
        if kind in ("pending", "firing", "resolved"):
            self._rec(st.rule, kind).add()
        try:
            from tpu3fs.monitor.flight import flight

            flight().record("alert", ts=now, rule=st.rule,
                            transition=kind, value=value, message=msg)
        except Exception:
            pass

    def _rec(self, rule: str, kind: str) -> CounterRecorder:
        rec = self._recs.get((rule, kind))
        if rec is None:
            tags = {"kind": rule}
            if kind == "pending":
                rec = CounterRecorder("slo.alert_pending", tags)
            elif kind == "firing":
                rec = CounterRecorder("slo.alert_firing", tags)
            else:
                rec = CounterRecorder("slo.alert_resolved", tags)
            self._recs[(rule, kind)] = rec
        return rec

    # -- verdict -------------------------------------------------------------
    def _verdict_locked(self) -> str:
        worst = 0
        for st in self._states.values():
            if st.state != "firing":
                continue
            worst = max(worst,
                        2 if st.severity == "critical" else 1)
        return VERDICTS[worst]

    def health(self) -> Tuple[str, List[RuleState]]:
        """-> (verdict, firing rule states)."""
        with self._lock:
            firing = [RuleState(**vars(s))
                      for s in self._states.values()
                      if s.state == "firing"]
            return self._verdict_locked(), firing

    def snapshot(self) -> Dict[str, RuleState]:
        with self._lock:
            return {n: RuleState(**vars(s))
                    for n, s in self._states.items()}


def apply_slo_config(cfg: SloConfig, engine: SloEngine) -> None:
    """Bind an [slo] config section to an engine and follow hot pushes
    (monitor_main calls this once at boot)."""
    def _apply(_node=None):
        try:
            engine.configure(cfg.spec if cfg.enabled else "")
        except ValueError:
            pass  # checker already rejected; belt and braces

    _apply()
    cfg.add_callback(_apply)


# -- the hard gate -----------------------------------------------------------


class SloGateError(AssertionError):
    """Raised by SloGate.assert_ok with the firing rules in the text."""


class SloGate:
    """Reusable SLO gate for drive scripts and benches: point it at a
    live collector and assert cluster health as a hard pass/fail —
    every script judging the cluster through the SAME rules the
    operators watch, instead of ad-hoc p99 math.

        gate = SloGate("127.0.0.1:9123")
        gate.assert_ok()                       # all rules
        gate.assert_ok(rules=["read_p99"])     # a subset
        gate.wait_verdict("DEGRADED", timeout=15)
    """

    def __init__(self, collector, client=None):
        from tpu3fs.rpc.net import RpcClient

        if isinstance(collector, str):
            host, _, port = collector.rpartition(":")
            collector = (host or "127.0.0.1", int(port))
        self._addr = tuple(collector)
        self._client = client or RpcClient()

    def status(self, *, evaluate: bool = True):
        from tpu3fs.monitor.collector import (
            COLLECTOR_SERVICE_ID,
            SloStatusReq,
            SloStatusRsp,
        )

        return self._client.call(
            self._addr, COLLECTOR_SERVICE_ID, 4,
            SloStatusReq(evaluate=evaluate), SloStatusRsp)

    def check(self, rules: Optional[List[str]] = None) -> Tuple[bool, str]:
        """-> (ok, detail). ok iff no selected rule is pending/firing."""
        rsp = self.status()
        bad = [r for r in rsp.rules
               if r.state != "ok" and (rules is None or r.rule in rules)]
        if not bad:
            return True, f"verdict {rsp.verdict}: all rules ok"
        detail = "; ".join(
            f"{r.rule} {r.state} ({r.bound}; observed {r.value:g}"
            + (f"; {r.message}" if r.message else "") + ")"
            for r in bad)
        return False, f"verdict {rsp.verdict}: {detail}"

    def assert_ok(self, rules: Optional[List[str]] = None) -> str:
        ok, detail = self.check(rules)
        if not ok:
            raise SloGateError(f"SLO gate failed: {detail}")
        return detail

    def wait_verdict(self, want: str, *, timeout: float = 30.0,
                     poll_s: float = 0.5):
        """Block until the cluster verdict reaches ``want`` (exact
        match); returns the status reply. Raises SloGateError on
        timeout with the last status in the text."""
        deadline = time.time() + timeout
        rsp = None
        while time.time() < deadline:
            rsp = self.status()
            if rsp.verdict == want:
                return rsp
            time.sleep(poll_s)
        got = rsp.verdict if rsp is not None else "(no reply)"
        firing = ", ".join(r.rule for r in rsp.rules
                           if r.state == "firing") if rsp else ""
        raise SloGateError(
            f"verdict never reached {want} within {timeout:.0f}s "
            f"(last {got}; firing: {firing or 'none'})")
