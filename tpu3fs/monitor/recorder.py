"""Metric recorders + collection plumbing.

Re-expresses src/common/monitor (Recorder.h:32 — counter, distribution,
OperationRecorder latency family, tag sets; Monitor.cc periodic collection)
and the monitor_collector service (src/monitor_collector/
MonitorCollectorService.h:24-31 — services push Sample batches, the collector
batch-commits to ClickHouse). Here: thread-safe recorders register in a
Monitor registry; collect() snapshots-and-resets; sinks are pluggable (JSONL
file, RPC collector, or the ClickHouse schema in deploy/sql for a real
deployment).
"""

from __future__ import annotations

import json
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class Sample:
    name: str
    ts: float
    tags: Dict[str, str]
    value: float = 0.0
    count: int = 0
    # distribution extras
    min: float = 0.0
    max: float = 0.0
    mean: float = 0.0
    p50: float = 0.0
    p90: float = 0.0
    p99: float = 0.0


class _Recorder:
    def __init__(self, name: str, tags: Optional[Dict[str, str]] = None,
                 monitor: Optional["Monitor"] = None):
        self.name = name
        self.tags = dict(tags or {})
        self._lock = threading.Lock()
        (monitor or Monitor.default()).register(self)

    def collect(self, now: float) -> List[Sample]:  # pragma: no cover
        raise NotImplementedError


class CounterRecorder(_Recorder):
    """Monotonic event counter, reported as a delta per collection window."""

    def __init__(self, name, tags=None, monitor=None):
        super().__init__(name, tags, monitor)
        self._value = 0

    def add(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    def collect(self, now: float) -> List[Sample]:
        with self._lock:
            v, self._value = self._value, 0
        if v == 0:
            return []
        return [Sample(self.name, now, self.tags, value=float(v), count=int(v))]


class ValueRecorder(_Recorder):
    """Gauge: reports the last set() value each collection window
    (ref monitor::ValueRecorder — disk capacity/free, queue depths)."""

    def __init__(self, name, tags=None, monitor=None):
        super().__init__(name, tags, monitor)
        self._value: Optional[float] = None

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def collect(self, now: float) -> List[Sample]:
        with self._lock:
            if self._value is None:
                return []
            v = self._value
        return [Sample(self.name, now, self.tags, value=v, count=1)]


class DistributionRecorder(_Recorder):
    """Value distribution via reservoir sampling (the reference uses TDigest;
    a bounded reservoir gives the same quantile reporting contract)."""

    RESERVOIR = 1024

    def __init__(self, name, tags=None, monitor=None):
        super().__init__(name, tags, monitor)
        self._reset()

    def _reset(self):
        self._count = 0
        self._sum = 0.0
        self._min = float("inf")
        self._max = float("-inf")
        self._sample: List[float] = []

    def record(self, value: float) -> None:
        with self._lock:
            self._count += 1
            self._sum += value
            self._min = min(self._min, value)
            self._max = max(self._max, value)
            if len(self._sample) < self.RESERVOIR:
                self._sample.append(value)
            else:
                i = random.randrange(self._count)
                if i < self.RESERVOIR:
                    self._sample[i] = value

    def collect(self, now: float) -> List[Sample]:
        with self._lock:
            if self._count == 0:
                return []
            count, total = self._count, self._sum
            mn, mx = self._min, self._max
            sample = sorted(self._sample)
            self._reset()

        def q(p: float) -> float:
            return sample[min(len(sample) - 1, int(p * len(sample)))]

        return [
            Sample(
                self.name, now, self.tags,
                value=total, count=count, min=mn, max=mx,
                mean=total / count, p50=q(0.5), p90=q(0.9), p99=q(0.99),
            )
        ]


class LatencyRecorder:
    """Operation wrapper: success/failure counts + latency distribution
    (ref monitor::OperationRecorder)."""

    def __init__(self, name, tags=None, monitor=None):
        self.succeeded = CounterRecorder(f"{name}.succeeded", tags, monitor)
        self.failed = CounterRecorder(f"{name}.failed", tags, monitor)
        self.latency = DistributionRecorder(f"{name}.latency_us", tags, monitor)

    class _Op:
        def __init__(self, rec: "LatencyRecorder"):
            self._rec = rec
            self.ok = True

        def __enter__(self):
            self._t0 = time.perf_counter()
            return self

        def fail(self):
            self.ok = False

        def __exit__(self, exc_type, exc, tb):
            dt_us = (time.perf_counter() - self._t0) * 1e6
            if exc_type is not None or not self.ok:
                self._rec.failed.add()
            else:
                self._rec.succeeded.add()
            self._rec.latency.record(dt_us)
            return False

    def record(self) -> "_Op":
        return LatencyRecorder._Op(self)


class Monitor:
    """Registry + collection loop + sinks."""

    _default: Optional["Monitor"] = None
    _default_lock = threading.Lock()

    def __init__(self):
        self._recorders: List[_Recorder] = []
        self._lock = threading.Lock()
        self._sinks = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    @classmethod
    def default(cls) -> "Monitor":
        with cls._default_lock:
            if cls._default is None:
                cls._default = Monitor()
            return cls._default

    def register(self, rec: _Recorder) -> None:
        import weakref

        with self._lock:
            # weak registration: recorders die with their owning service, so
            # short-lived services (tests, restarts) don't leak registry slots
            self._recorders.append(weakref.ref(rec))

    def add_sink(self, sink) -> None:
        if sink not in self._sinks:  # re-registration must not double-write
            self._sinks.append(sink)

    def collect(self) -> List[Sample]:
        now = time.time()
        out: List[Sample] = []
        with self._lock:
            live = []
            for ref in self._recorders:
                rec = ref()
                if rec is not None:
                    live.append(ref)
            self._recorders = live
            recorders = [ref() for ref in live]
        for rec in recorders:
            if rec is not None:
                out.extend(rec.collect(now))
        for sink in self._sinks:
            try:
                sink.write(out)
            except Exception as e:  # a flaky sink must not stop collection
                import sys

                print(f"monitor sink error: {e!r}", file=sys.stderr)
        return out

    def start(self, period_s: float = 10.0) -> None:
        def loop():
            while not self._stop.wait(period_s):
                try:
                    self.collect()
                except Exception as e:  # keep the collection thread alive
                    import sys

                    print(f"monitor collect error: {e!r}", file=sys.stderr)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)


class JsonlSink:
    """Append samples to a JSONL file (stand-in for the ClickHouse writer;
    schema for a real deployment in deploy/sql/tpu3fs-monitor.sql)."""

    def __init__(self, path: str):
        self._path = path
        self._lock = threading.Lock()

    def write(self, samples: List[Sample]) -> None:
        if not samples:
            return
        with self._lock, open(self._path, "a") as f:
            for s in samples:
                f.write(json.dumps(s.__dict__) + "\n")


class MemorySink:
    def __init__(self):
        self.samples: List[Sample] = []

    def write(self, samples: List[Sample]) -> None:
        self.samples.extend(samples)


class SqliteSink:
    """QUERYABLE sample store — the ClickHouse-writer stand-in with an
    actual query path (ref src/common/monitor/ClickHouseClient.cc +
    deploy/sql/3fs-monitor.sql; the reference's operators query the sink,
    so a write-only file is not parity). One table, batch inserts, WAL
    journaling; thread-safe via one connection per call."""

    SCHEMA = (
        "CREATE TABLE IF NOT EXISTS samples ("
        " ts REAL, name TEXT, value REAL, count INTEGER,"
        " min REAL, max REAL, mean REAL, p50 REAL, p90 REAL, p99 REAL,"
        " tags TEXT)",
        "CREATE INDEX IF NOT EXISTS idx_samples_name_ts"
        " ON samples(name, ts)",
    )

    def __init__(self, path: str):
        self._path = path
        self._lock = threading.Lock()
        with self._connect() as db:
            for stmt in self.SCHEMA:
                db.execute(stmt)

    def _connect(self):
        import sqlite3

        db = sqlite3.connect(self._path, timeout=30.0)
        db.execute("PRAGMA journal_mode=WAL")
        return db

    def write(self, samples: List[Sample]) -> None:
        if not samples:
            return
        rows = [
            (s.ts, s.name, s.value, s.count, s.min, s.max, s.mean,
             s.p50, s.p90, s.p99, json.dumps(s.tags, sort_keys=True))
            for s in samples
        ]
        with self._lock, self._connect() as db:
            db.executemany(
                "INSERT INTO samples VALUES (?,?,?,?,?,?,?,?,?,?,?)", rows)

    def db_bytes(self) -> int:
        """On-disk footprint (main db + WAL), the retained-bytes gauge."""
        import os

        total = 0
        for suffix in ("", "-wal", "-shm"):
            try:
                total += os.path.getsize(self._path + suffix)
            except OSError:
                pass
        return total

    def compact(self, retention_s: float = 0.0,
                max_bytes: int = 0) -> int:
        """Age/size-capped retention pass: drop raw rows older than the
        retention horizon (they're already rolled up in the collector's
        windowed aggregator), then, while the db still exceeds
        ``max_bytes``, drop the oldest remaining rows in slices. Returns
        rows removed. 0 on either knob disables that axis."""
        import time as _time

        removed = 0
        with self._lock, self._connect() as db:
            if retention_s and retention_s > 0:
                cur = db.execute("DELETE FROM samples WHERE ts < ?",
                                 (_time.time() - retention_s,))
                removed += cur.rowcount
        if removed:
            self._reclaim()
        if max_bytes and max_bytes > 0:
            # size cap: estimate the over-budget row fraction, delete
            # that many OLDEST rows, reclaim, re-check — bounded passes
            # so a misconfigured tiny cap can't loop forever
            for _ in range(6):
                cur_bytes = self.db_bytes()
                if cur_bytes <= max_bytes:
                    break
                with self._lock, self._connect() as db:
                    n = db.execute(
                        "SELECT COUNT(*) FROM samples").fetchone()[0]
                    if n == 0:
                        break
                    frac = 1.0 - max_bytes / cur_bytes
                    k = min(n, max(n // 8, int(n * frac)))
                    row = db.execute(
                        "SELECT ts FROM samples ORDER BY ts LIMIT 1"
                        " OFFSET ?", (k,)).fetchone()
                    if row is None:
                        c = db.execute("DELETE FROM samples")
                    else:
                        c = db.execute(
                            "DELETE FROM samples WHERE ts < ?",
                            (row[0],))
                    removed += c.rowcount
                    if c.rowcount == 0:
                        break
                self._reclaim()
        return removed

    def _reclaim(self) -> None:
        """DELETE leaves pages free inside the file; checkpoint + VACUUM
        so the retained-bytes gauge (and the disk) actually shrink."""
        with self._lock:
            db = self._connect()
            try:
                db.execute("PRAGMA wal_checkpoint(TRUNCATE)")
                db.isolation_level = None  # VACUUM needs autocommit
                db.execute("VACUUM")
            finally:
                db.close()

    def query(self, name_prefix: str = "", since: float = 0.0,
              until: float = 0.0, limit: int = 1000) -> List[Sample]:
        """Newest-first samples filtered by name prefix + time window."""
        q = ("SELECT ts, name, value, count, min, max, mean, p50, p90,"
             " p99, tags FROM samples"
             " WHERE name LIKE ? ESCAPE '\\' AND ts >= ?")
        escaped = (name_prefix.replace("\\", "\\\\")
                   .replace("%", "\\%").replace("_", "\\_"))
        params: list = [escaped + "%", since]
        if until:
            q += " AND ts <= ?"
            params.append(until)
        q += " ORDER BY ts DESC LIMIT ?"
        params.append(max(1, limit))
        with self._lock, self._connect() as db:
            rows = db.execute(q, params).fetchall()
        return [
            Sample(name=r[1], ts=r[0], tags=json.loads(r[10]), value=r[2],
                   count=r[3], min=r[4], max=r[5], mean=r[6], p50=r[7],
                   p90=r[8], p99=r[9])
            for r in rows
        ]
