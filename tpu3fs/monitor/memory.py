"""Process memory counters for the monitoring stack.

Re-expresses the reference's src/memory counters (jemalloc/mimalloc
allocated-memory stats pushed through monitor::Recorder): here the process
allocator is CPython's (no global override to hook), so the gauges come from
/proc/self/status (RSS, peak, virtual) plus optional per-engine accounting
(native chunk-engine used bytes), published through the same ValueRecorder
path every other metric rides.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from tpu3fs.monitor.recorder import ValueRecorder

_FIELDS = {
    "VmRSS": "memory.rss_kb",
    "VmHWM": "memory.rss_peak_kb",
    "VmSize": "memory.vsize_kb",
    "VmData": "memory.data_kb",
}


def read_proc_status(path: str = "/proc/self/status") -> Dict[str, int]:
    """-> {metric_name: kB} for the tracked VM fields."""
    out: Dict[str, int] = {}
    try:
        with open(path) as f:
            for line in f:
                key, _, rest = line.partition(":")
                if key in _FIELDS:
                    out[_FIELDS[key]] = int(rest.split()[0])
    except OSError:
        pass
    return out


class MemoryMonitor:
    """Publishes memory gauges; optional extra sources (e.g. a native chunk
    engine's used_size) are polled alongside (ref src/memory counters)."""

    def __init__(self, tags: Optional[Dict[str, str]] = None, *,
                 monitor=None):
        self._tags = tags or {}
        self._monitor = monitor
        self._gauges: Dict[str, ValueRecorder] = {}
        self._sources: List = []  # (metric_name, fn) pairs

    def add_source(self, metric: str, fn: Callable[[], float]) -> None:
        self._sources.append((metric, fn))

    def _gauge(self, name: str) -> ValueRecorder:
        g = self._gauges.get(name)
        if g is None:
            g = ValueRecorder(name, dict(self._tags), monitor=self._monitor)
            self._gauges[name] = g
        return g

    def poll_once(self) -> Dict[str, float]:
        vals: Dict[str, float] = dict(read_proc_status())
        for metric, fn in self._sources:
            try:
                vals[metric] = float(fn())
            except Exception:
                continue  # a dead source must not break the poll loop
        for name, v in vals.items():
            self._gauge(name).set(v)
        return vals
