"""Flight recorder: a bounded in-process black box in every binary.

Production postmortems need "what was this process doing right before
it went wrong" — and the trace/monitor pipeline, built for live
operation, ships its data AWAY on a period, so the last seconds before
a crash or an SLO breach are exactly the ones most likely lost. The
flight recorder keeps them: a bounded ring (deque, O(ring_events)
memory by construction) of

- recent SLOW-OP SPANS (fed by the tracer's slow-op flush hook —
  spans.py calls every registered hook with the op's accumulated
  events whenever an op crosses ``slow_op_ms``);
- recent SAMPLES (the recorder pipeline's collect output, via the
  ``sample_sink()`` Monitor sink);
- CONFIG-PUSH events (mgmtd heartbeat pushes and core
  ``hotUpdateConfig`` RPCs — "what changed right before it broke");
- ALERT events (SLO state-machine transitions, collector process).

Dump triggers (all write one JSONL file under the configured dir):

- SLO breach: the collector bumps ``dump_epoch`` in its write-RPC Ack
  when a rule fires; every binary's ``BufferedCollectorSink`` sees the
  bump on its next push and dumps locally — the whole fleet snapshots
  its black boxes within one push period of the breach;
- fatal signal: the app's SIGTERM/SIGINT handler dumps before stopping,
  and SIGUSR2 dumps WITHOUT stopping (kill -USR2 = "show me");
- on demand: the core service's ``flightDump`` RPC / ``admin_cli
  flight-dump``.

Dump rows are flat JSON objects tagged ``kind`` (span/sample/config/
alert/meta); ``analytics.assemble.load_flight`` merges the dumps of N
processes back into one timeline, joining span rows through the PR 8
trace machinery (trace ids cross process boundaries).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Dict, List, Optional

from tpu3fs.utils.config import Config, ConfigItem


class FlightConfig(Config):
    """The per-binary ``[flight]`` section (hot-updatable)."""

    enabled = ConfigItem(True, hot=True)
    # dump directory; "" = ring still records, dumps need an explicit
    # path (flightDump RPC) — so tests/dev don't spray files
    dir = ConfigItem("", hot=True)
    ring_events = ConfigItem(4096, hot=True, checker=lambda v: v >= 16)


class FlightRecorder:
    """Process-global bounded event ring + dumper."""

    def __init__(self, *, ring_events: int = 4096):
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=int(ring_events))
        self.enabled = True
        self.service = "proc"
        self.node = 0
        self.dump_dir = ""
        self.dumps = 0
        self._rec = None  # lazy flight.dumps counter

    def configure(self, *, service: Optional[str] = None,
                  node: Optional[int] = None,
                  dump_dir: Optional[str] = None,
                  ring_events: Optional[int] = None,
                  enabled: Optional[bool] = None) -> "FlightRecorder":
        with self._lock:
            if service is not None:
                self.service = service
            if node is not None:
                self.node = int(node)
            if dump_dir is not None:
                self.dump_dir = dump_dir
            if enabled is not None:
                self.enabled = bool(enabled)
            if ring_events is not None and \
                    int(ring_events) != self._ring.maxlen:
                self._ring = collections.deque(
                    self._ring, maxlen=int(ring_events))
        return self

    # -- feeds ---------------------------------------------------------------
    def record(self, kind: str, **fields) -> None:
        if not self.enabled:
            return
        fields["kind"] = kind
        fields.setdefault("ts", time.time())
        # deque.append is GIL-atomic; feeds come from many threads
        self._ring.append(fields)

    def record_spans(self, events) -> None:
        """Tracer slow-op hook: one row per accumulated span event."""
        if not self.enabled:
            return
        for ev in events:
            row = dict(ev.__dict__)
            row["kind"] = "span"
            self._ring.append(row)

    def sample_sink(self) -> "_FlightSampleSink":
        """A Monitor sink keeping the most recent samples in the ring
        (memoized: N apps in one process install ONE sink)."""
        sink = getattr(self, "_sample_sink", None)
        if sink is None:
            sink = _FlightSampleSink(self)
            self._sample_sink = sink
        return sink

    # -- dump ----------------------------------------------------------------
    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def dump(self, path: Optional[str] = None, *,
             reason: str = "manual") -> str:
        """Write the ring to one JSONL file; returns its path (empty
        when no dir is configured and none was given)."""
        rows = self.snapshot()
        if path is None:
            if not self.dump_dir:
                return ""
            os.makedirs(self.dump_dir, exist_ok=True)
            path = os.path.join(
                self.dump_dir,
                f"flight-{self.service}-{self.node}-{os.getpid()}"
                f"-{time.time():.3f}.jsonl")
        else:
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
        meta = {"kind": "meta", "ts": time.time(), "reason": reason,
                "service": self.service, "node": self.node,
                "pid": os.getpid(), "events": len(rows)}
        with open(path, "w") as f:
            f.write(json.dumps(meta) + "\n")
            for row in rows:
                try:
                    f.write(json.dumps(row) + "\n")
                except (TypeError, ValueError):
                    f.write(json.dumps(
                        {"kind": row.get("kind", "?"),
                         "ts": row.get("ts", 0.0),
                         "repr": repr(row)}) + "\n")
        self.dumps += 1
        self._count_dump()
        return path

    def _count_dump(self) -> None:
        rec = self._rec
        if rec is None:
            from tpu3fs.monitor.recorder import CounterRecorder

            rec = CounterRecorder("flight.dumps")
            self._rec = rec
        rec.add()


class _FlightSampleSink:
    """Monitor sink -> flight ring (compact rows, value+count only:
    the collector keeps the full-fidelity copy; the black box keeps
    what fits)."""

    def __init__(self, flight: FlightRecorder):
        self._flight = flight

    def write(self, samples) -> None:
        fl = self._flight
        if not fl.enabled:
            return
        for s in samples:
            fl._ring.append({
                "kind": "sample", "ts": s.ts, "name": s.name,
                "tags": s.tags, "value": s.value, "count": s.count,
                "p99": s.p99,
            })


_FLIGHT = FlightRecorder()


def flight() -> FlightRecorder:
    return _FLIGHT


def apply_flight_config(cfg: FlightConfig, *, service: str, node: int,
                        target: Optional[FlightRecorder] = None) -> None:
    """Bind a [flight] config section (and follow its hot updates)."""
    fl = target if target is not None else _FLIGHT

    def _apply(_node=None):
        fl.configure(service=service, node=node, dump_dir=cfg.dir,
                     ring_events=int(cfg.ring_events),
                     enabled=bool(cfg.enabled))

    _apply()
    cfg.add_callback(_apply)
