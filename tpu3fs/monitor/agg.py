"""Windowed streaming aggregation for the monitor collector.

The collector used to be a dumb sample buffer: ``write`` appended rows,
``query`` returned raw rows, and every consumer (admin_cli top, the SLO
engine, drive scripts) re-implemented its own p99 math with a raw-row
scan. This module gives the collector a real time-series layer (the
operator-facing analytical store the reference feeds from
monitor_collector — SURVEY §0 batch-commit to ClickHouse, here kept
queryable in-process):

- per-(name, tags) SERIES with ring-buffer retention: time is cut into
  fixed ``bucket_s`` slots, a series keeps the last ``slots`` of them,
  and every slot holds streaming rollups — value sum + sample count
  (rate for counters), last value by timestamp (gauges), min/max, and a
  FIXED-CENTROID digest of the distribution so p50/p90/p99 are
  queryable over ANY window without raw-row scans;
- ``FixedDigest``: sparse log-spaced buckets (growth ``_GROWTH`` per
  bucket => bounded relative quantile error, ~half the growth factor).
  Incoming ``Sample`` rows are already per-push-window distribution
  summaries (count/min/p50/p90/p99/max from the reservoir recorders);
  ``add_summary`` re-spreads that mass over the inter-quantile segments
  at their geometric midpoints, which merges across windows and
  processes without raw values. Centroid positions are FIXED (a pure
  function of the bucket index), so digests merge by adding counts;
- BOUNDED MEMORY BY CONSTRUCTION: at most ``max_series`` series are
  tracked (new ones beyond the cap are dropped and counted on
  ``monitor.agg_dropped``), each series holds at most ``slots`` slots,
  and each slot's digest is sparse (entries only for buckets its
  summaries touched). ``stats()`` feeds the collector's ``monitor.*``
  self-gauges.

``query(name, tags, window_s)`` returns one ``AggRow`` per matching
series — the shape the ``aggQuery`` RPC ships and the SLO engine
evaluates.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from tpu3fs.monitor.recorder import Sample

# log-spaced digest geometry: buckets cover (1e-3 .. ~3e13) with ~9%
# relative width; values outside clamp to the edge buckets
_MIN_VALUE = 1e-3
_GROWTH = 1.18
_NBUCKETS = 224
_LOG_G = math.log(_GROWTH)


def _bucket_of(v: float) -> int:
    if v <= _MIN_VALUE:
        return 0
    i = int(math.log(v / _MIN_VALUE) / _LOG_G)
    return i if i < _NBUCKETS else _NBUCKETS - 1


def _value_of(i: int) -> float:
    # geometric midpoint of the bucket — the fixed centroid
    return _MIN_VALUE * (_GROWTH ** (i + 0.5))


class FixedDigest:
    """Sparse fixed-centroid histogram: {bucket index: weight}."""

    __slots__ = ("counts", "total")

    def __init__(self):
        self.counts: Dict[int, float] = {}
        self.total = 0.0

    def add(self, value: float, weight: float = 1.0) -> None:
        if weight <= 0.0:
            return
        i = _bucket_of(value)
        self.counts[i] = self.counts.get(i, 0.0) + weight
        self.total += weight

    def add_summary(self, count: int, mn: float, p50: float, p90: float,
                    p99: float, mx: float) -> None:
        """Spread one reservoir summary's mass over its inter-quantile
        segments (each at the segment's geometric midpoint), so merged
        windows keep queryable percentiles."""
        if count <= 0:
            return
        pts = [mn, p50, p90, p99, mx]
        # quantile points must be monotone; recorder summaries are, but
        # a hostile pusher must not corrupt the digest
        for k in range(1, len(pts)):
            if pts[k] < pts[k - 1]:
                pts[k] = pts[k - 1]
        masses = (0.50, 0.40, 0.09, 0.01)
        for (lo, hi), m in zip(zip(pts, pts[1:]), masses):
            mid = math.sqrt(max(lo, _MIN_VALUE) * max(hi, _MIN_VALUE)) \
                if hi > _MIN_VALUE else lo
            self.add(mid, m * count)

    def merge(self, other: "FixedDigest") -> None:
        for i, w in other.counts.items():
            self.counts[i] = self.counts.get(i, 0.0) + w
        self.total += other.total

    def quantile(self, q: float) -> float:
        if self.total <= 0.0:
            return 0.0
        want = min(max(q, 0.0), 1.0) * self.total
        acc = 0.0
        for i in sorted(self.counts):
            acc += self.counts[i]
            if acc >= want:
                return _value_of(i)
        return _value_of(max(self.counts))

    def __len__(self) -> int:
        return len(self.counts)


class _Slot:
    """Rollups of one time bucket of one series."""

    __slots__ = ("start", "vsum", "count", "last", "last_ts",
                 "vmin", "vmax", "digest")

    def __init__(self, start: float):
        self.start = start
        self.vsum = 0.0
        self.count = 0
        self.last = 0.0
        self.last_ts = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")
        self.digest: Optional[FixedDigest] = None


def series_key(name: str, tags: Dict[str, str]) -> Tuple:
    return (name,) + tuple(sorted(tags.items()))


class _Series:
    __slots__ = ("name", "tags", "slots", "last_ts", "last_value")

    def __init__(self, name: str, tags: Dict[str, str]):
        self.name = name
        self.tags = dict(tags)
        self.slots: Dict[int, _Slot] = {}  # slot index -> rollups
        self.last_ts = 0.0
        self.last_value = 0.0


@dataclass
class AggRow:
    """One series' rollup over a query window (the aggQuery wire row)."""

    name: str = ""
    tags: Dict[str, str] = field(default_factory=dict)
    window_s: float = 0.0
    count: int = 0          # samples folded into the window
    vsum: float = 0.0       # sum of sample values (counter deltas)
    rate: float = 0.0       # vsum / window_s (counter rate)
    last: float = 0.0       # newest value in the window (gauge)
    last_ts: float = 0.0    # newest sample timestamp of the SERIES
    vmin: float = 0.0
    vmax: float = 0.0
    p50: float = 0.0        # digest quantiles; 0 when no distribution
    p90: float = 0.0
    p99: float = 0.0


class WindowedAggregator:
    """Bounded in-memory rollup store keyed (name, sorted tags)."""

    def __init__(self, *, bucket_s: float = 2.0, slots: int = 150,
                 max_series: int = 8192):
        self.bucket_s = float(bucket_s)
        self.slots = int(slots)
        self.max_series = int(max_series)
        self._series: Dict[Tuple, _Series] = {}
        self._lock = threading.Lock()
        self.dropped = 0        # series beyond the cap (not samples)
        self.ingested = 0

    # -- ingest --------------------------------------------------------------
    def ingest(self, samples: List[Sample]) -> None:
        if not samples:
            return
        with self._lock:
            for s in samples:
                key = series_key(s.name, s.tags or {})
                ser = self._series.get(key)
                if ser is None:
                    if len(self._series) >= self.max_series:
                        self.dropped += 1
                        continue
                    ser = _Series(s.name, s.tags or {})
                    self._series[key] = ser
                self._ingest_one(ser, s)
                self.ingested += 1

    def _ingest_one(self, ser: _Series, s: Sample) -> None:
        idx = int(s.ts // self.bucket_s)
        slot = ser.slots.get(idx)
        if slot is None:
            if len(ser.slots) >= self.slots:
                # ring retention: evict the oldest slot(s)
                for old in sorted(ser.slots)[:len(ser.slots)
                                             - self.slots + 1]:
                    del ser.slots[old]
            slot = _Slot(idx * self.bucket_s)
            ser.slots[idx] = slot
        slot.vsum += s.value
        slot.count += int(s.count) or 1
        if s.ts >= slot.last_ts:
            slot.last_ts = s.ts
            slot.last = s.value
        if s.ts >= ser.last_ts:
            ser.last_ts = s.ts
            ser.last_value = s.value
        # distribution summaries carry quantiles; plain counters/gauges
        # don't (their digest stays unallocated — bounded by shape)
        if s.count > 0 and (s.p99 or s.p90 or s.p50 or s.max != s.min):
            if slot.digest is None:
                slot.digest = FixedDigest()
            slot.digest.add_summary(s.count, s.min, s.p50, s.p90,
                                    s.p99, s.max)
            slot.vmin = min(slot.vmin, s.min)
            slot.vmax = max(slot.vmax, s.max)
        else:
            slot.vmin = min(slot.vmin, s.value)
            slot.vmax = max(slot.vmax, s.value)

    # -- query ---------------------------------------------------------------
    def query(self, name: str = "", tags: Optional[Dict[str, str]] = None,
              window_s: float = 60.0, *, until: float = 0.0,
              prefix: bool = False) -> List[AggRow]:
        """Rollups per matching series over [until - window_s, until].

        ``name`` matches exactly (or as a prefix with ``prefix=True``);
        empty matches all. ``tags`` entries must all match the series'
        tags exactly (series may carry more)."""
        until = until or time.time()
        since = until - window_s
        lo = int(since // self.bucket_s)
        hi = int(until // self.bucket_s)
        out: List[AggRow] = []
        with self._lock:
            for ser in self._series.values():
                if name and not (ser.name.startswith(name) if prefix
                                 else ser.name == name):
                    continue
                if tags and any(ser.tags.get(k) != v
                                for k, v in tags.items()):
                    continue
                row = AggRow(name=ser.name, tags=dict(ser.tags),
                             window_s=window_s, last_ts=ser.last_ts)
                digest: Optional[FixedDigest] = None
                vmin, vmax = float("inf"), float("-inf")
                newest = 0.0
                for idx in range(lo, hi + 1):
                    slot = ser.slots.get(idx)
                    if slot is None:
                        continue
                    row.vsum += slot.vsum
                    row.count += slot.count
                    vmin = min(vmin, slot.vmin)
                    vmax = max(vmax, slot.vmax)
                    if slot.last_ts >= newest:
                        newest = slot.last_ts
                        row.last = slot.last
                    if slot.digest is not None:
                        if digest is None:
                            digest = FixedDigest()
                        digest.merge(slot.digest)
                if row.count:
                    row.rate = row.vsum / max(window_s, 1e-9)
                    row.vmin = 0.0 if vmin == float("inf") else vmin
                    row.vmax = 0.0 if vmax == float("-inf") else vmax
                if digest is not None and digest.total > 0:
                    row.p50 = digest.quantile(0.50)
                    row.p90 = digest.quantile(0.90)
                    row.p99 = digest.quantile(0.99)
                out.append(row)
        out.sort(key=lambda r: (r.name, sorted(r.tags.items())))
        return out

    # -- self-observability --------------------------------------------------
    def stats(self) -> Dict[str, float]:
        with self._lock:
            nslots = 0
            nbuckets = 0
            for ser in self._series.values():
                nslots += len(ser.slots)
                for slot in ser.slots.values():
                    if slot.digest is not None:
                        nbuckets += len(slot.digest)
            return {
                "series": float(len(self._series)),
                "slots": float(nslots),
                # approximate resident bytes: slot fixed fields +
                # sparse digest entries (the bound the self-gauge ships)
                "bytes": float(len(self._series) * 120 + nslots * 96
                               + nbuckets * 64),
                "dropped_series": float(self.dropped),
                "ingested": float(self.ingested),
            }
