"""monitor_collector: the central sample-ingest service + push client.

Re-expresses src/monitor_collector (MonitorCollectorService.h:24-31): every
server's Monitor pushes Sample batches over RPC; the collector buffers and
batch-commits (4096 per flush, like the reference) to its sink — JSONL here,
ClickHouse via deploy/sql/tpu3fs-monitor.sql in a real deployment.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import List

from tpu3fs.monitor.recorder import Sample
from tpu3fs.rpc.net import RpcClient, RpcServer, ServiceDef

COLLECTOR_SERVICE_ID = 5  # ref fbs/monitor_collector
FLUSH_BATCH = 4096


@dataclass
class SampleBatch:
    samples: List[Sample] = field(default_factory=list)


@dataclass
class Ack:
    accepted: int = 0


class CollectorService:
    def __init__(self, sink):
        self._sink = sink
        self._buffer: List[Sample] = []
        self._lock = threading.Lock()

    def write(self, batch: SampleBatch) -> Ack:
        with self._lock:
            self._buffer.extend(batch.samples)
            if len(self._buffer) >= FLUSH_BATCH:
                self._flush_locked()
        return Ack(len(batch.samples))

    def _flush_locked(self) -> None:
        buf, self._buffer = self._buffer, []
        self._sink.write(buf)

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def query(self, req: "QueryReq") -> SampleBatch:
        """Operator query over the sink (flushes first so recent samples
        are visible); requires a queryable sink (SqliteSink)."""
        self.flush()
        if not hasattr(self._sink, "query"):
            return SampleBatch([])
        return SampleBatch(self._sink.query(
            req.name_prefix, req.since, req.until, req.limit))


@dataclass
class QueryReq:
    name_prefix: str = ""
    since: float = 0.0
    until: float = 0.0
    limit: int = 1000


def bind_collector_service(server: RpcServer, service: CollectorService) -> None:
    s = ServiceDef(COLLECTOR_SERVICE_ID, "MonitorCollector")
    s.method(1, "write", SampleBatch, Ack, service.write)
    s.method(2, "query", QueryReq, SampleBatch, service.query)
    server.add_service(s)


class CollectorSink:
    """Monitor sink pushing to a remote collector (ref
    MonitorCollectorClient)."""

    def __init__(self, addr, client: RpcClient | None = None):
        self._addr = addr
        self._client = client or RpcClient()

    def write(self, samples: List[Sample]) -> None:
        if not samples:
            return
        self._client.call(
            self._addr, COLLECTOR_SERVICE_ID, 1, SampleBatch(list(samples)), Ack
        )


class BufferedCollectorSink:
    """Collector push with BOUNDED buffering across outages.

    The plain CollectorSink raises on every push while the collector is
    down, and Monitor.collect only logs sink errors — samples collected
    during an outage were simply lost. Here samples queue up to
    ``cap_samples``; every write() attempts to drain the whole backlog
    (oldest first, FLUSH_BATCH per RPC), overflow drops the OLDEST
    samples (the newest window is the one an operator debugging the
    outage needs) and counts them on ``monitor.push_dropped`` so the
    loss itself is observable once the collector returns.

    ``addr`` may be a (host, port) tuple or a zero-arg callable
    returning one / None — the hot-config shape (a config push can point
    every service at a collector, or away from a dead one, live).
    """

    def __init__(self, addr, client: RpcClient | None = None,
                 cap_samples: int = 65536):
        import collections

        from tpu3fs.monitor.recorder import CounterRecorder

        self._addr = addr
        self._client = client or RpcClient()
        self._buf = collections.deque()
        self._cap = int(cap_samples)
        self._lock = threading.Lock()
        self.dropped = CounterRecorder("monitor.push_dropped")
        self.pushed = CounterRecorder("monitor.push_samples")

    def _resolve_addr(self):
        addr = self._addr() if callable(self._addr) else self._addr
        if not addr:
            return None
        if isinstance(addr, str):
            host, _, port = addr.rpartition(":")
            try:
                return (host or "127.0.0.1", int(port))
            except ValueError:
                return None
        return tuple(addr)

    def backlog(self) -> int:
        with self._lock:
            return len(self._buf)

    def write(self, samples: List[Sample]) -> None:
        with self._lock:
            self._buf.extend(samples)
            over = len(self._buf) - self._cap
            if over > 0:
                for _ in range(over):
                    self._buf.popleft()
                self.dropped.add(over)
            addr = self._resolve_addr()
            if addr is None:
                return  # unconfigured: buffer (bounded) until pointed
            while self._buf:
                batch = [self._buf.popleft()
                         for _ in range(min(FLUSH_BATCH, len(self._buf)))]
                try:
                    self._client.call(addr, COLLECTOR_SERVICE_ID, 1,
                                      SampleBatch(batch), Ack)
                except Exception:
                    # collector outage: keep the batch for the next period
                    self._buf.extendleft(reversed(batch))
                    raise
                self.pushed.add(len(batch))
