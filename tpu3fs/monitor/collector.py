"""monitor_collector: the central sample-ingest service + push client.

Re-expresses src/monitor_collector (MonitorCollectorService.h:24-31): every
server's Monitor pushes Sample batches over RPC; the collector buffers and
batch-commits (4096 per flush, like the reference) to its sink — JSONL here,
ClickHouse via deploy/sql/tpu3fs-monitor.sql in a real deployment.

Beyond the reference's dumb buffer, the collector is a TIME-SERIES +
VERDICT service: every ingested batch also streams into a
``WindowedAggregator`` (monitor/agg.py — per-series ring retention with
rate/last/percentile rollups queryable over any window via the
``aggQuery`` RPC), and an ``SloEngine`` (monitor/slo.py) continuously
judges those aggregates against hot-pushed ``[slo]`` rules, answering
the single cluster verdict over the ``sloStatus`` RPC. When a rule
FIRES, the collector bumps ``dump_epoch``; the Ack of every subsequent
push carries it (trailing serde field — old peers ignore it), and each
binary's ``BufferedCollectorSink`` reacts by dumping its local flight
recorder — the whole fleet snapshots its black boxes within one push
period of a breach.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tpu3fs.monitor.agg import AggRow, WindowedAggregator
from tpu3fs.monitor.recorder import Sample
from tpu3fs.monitor.slo import RuleState, SloEngine, TransitionRow
from tpu3fs.rpc.net import RpcClient, RpcServer, ServiceDef

COLLECTOR_SERVICE_ID = 5  # ref fbs/monitor_collector
FLUSH_BATCH = 4096


@dataclass
class SampleBatch:
    samples: List[Sample] = field(default_factory=list)


@dataclass
class Ack:
    accepted: int = 0
    # flight-recorder dump generation (trailing field: old peers ignore
    # it, new peers on old collectors default 0 = never). The SLO
    # engine bumps it on a firing transition; pushers that see it grow
    # dump their local black box.
    dump_epoch: int = 0


@dataclass
class AggQueryReq:
    """Windowed-rollup query (see agg.WindowedAggregator.query)."""

    name: str = ""
    tags: Dict[str, str] = field(default_factory=dict)
    window_s: float = 60.0
    until: float = 0.0         # 0 = now
    prefix: bool = False       # name is a prefix, not exact


@dataclass
class AggQueryRsp:
    rows: List[AggRow] = field(default_factory=list)


@dataclass
class SloStatusReq:
    evaluate: bool = True      # run an evaluation pass before answering


@dataclass
class SloStatusRsp:
    verdict: str = "OK"
    firing: List[str] = field(default_factory=list)
    rules: List[RuleState] = field(default_factory=list)
    transitions: List[TransitionRow] = field(default_factory=list)
    evaluated_ts: float = 0.0


class CollectorService:
    def __init__(self, sink, *, aggregator: Optional[WindowedAggregator]
                 = None, slo: Optional[SloEngine] = None):
        self._sink = sink
        self.aggregator = aggregator
        self.slo = slo
        self._buffer: List[Sample] = []
        self._lock = threading.Lock()
        self._dump_epoch = 0
        self._ingested = 0          # cumulative, for the ingest-rate gauge
        if slo is not None:
            slo.add_firing_callback(lambda _st: self.request_flight_dump())

    def write(self, batch: SampleBatch) -> Ack:
        # aggregation first and OUTSIDE the buffer lock: the rollup
        # store has its own lock and must see samples even when the
        # sink is slow
        if self.aggregator is not None:
            self.aggregator.ingest(batch.samples)
        with self._lock:
            self._ingested += len(batch.samples)
            self._buffer.extend(batch.samples)
            if len(self._buffer) >= FLUSH_BATCH:
                self._flush_locked()
        return Ack(len(batch.samples), self._dump_epoch)

    def _flush_locked(self) -> None:
        buf, self._buffer = self._buffer, []
        self._sink.write(buf)

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    @property
    def ingested(self) -> int:
        with self._lock:
            return self._ingested

    # -- flight-dump trigger -------------------------------------------------
    def request_flight_dump(self) -> int:
        """Bump the dump generation: every pusher that observes the new
        epoch on its next Ack dumps its local flight recorder."""
        with self._lock:
            self._dump_epoch += 1
            return self._dump_epoch

    @property
    def dump_epoch(self) -> int:
        return self._dump_epoch

    # -- queries -------------------------------------------------------------
    def query(self, req: "QueryReq") -> SampleBatch:
        """Operator query over the sink (flushes first so recent samples
        are visible); requires a queryable sink (SqliteSink)."""
        self.flush()
        if not hasattr(self._sink, "query"):
            return SampleBatch([])
        return SampleBatch(self._sink.query(
            req.name_prefix, req.since, req.until, req.limit))

    def agg_query(self, req: AggQueryReq) -> AggQueryRsp:
        if self.aggregator is None:
            return AggQueryRsp([])
        return AggQueryRsp(self.aggregator.query(
            req.name, req.tags, req.window_s, until=req.until,
            prefix=req.prefix))

    def slo_status(self, req: SloStatusReq) -> SloStatusRsp:
        import time as _time

        if self.slo is None:
            return SloStatusRsp()
        if req.evaluate:
            self.slo.evaluate()
        verdict, firing = self.slo.health()
        return SloStatusRsp(
            verdict=verdict,
            firing=[s.rule for s in firing],
            rules=sorted(self.slo.snapshot().values(),
                         key=lambda s: s.rule),
            transitions=list(self.slo.transitions)[-64:],
            evaluated_ts=_time.time(),
        )


@dataclass
class QueryReq:
    name_prefix: str = ""
    since: float = 0.0
    until: float = 0.0
    limit: int = 1000


def bind_collector_service(server: RpcServer, service: CollectorService) -> None:
    s = ServiceDef(COLLECTOR_SERVICE_ID, "MonitorCollector")
    s.method(1, "write", SampleBatch, Ack, service.write)
    s.method(2, "query", QueryReq, SampleBatch, service.query)
    s.method(3, "aggQuery", AggQueryReq, AggQueryRsp, service.agg_query)
    s.method(4, "sloStatus", SloStatusReq, SloStatusRsp,
             service.slo_status)
    server.add_service(s)


class CollectorSink:
    """Monitor sink pushing to a remote collector (ref
    MonitorCollectorClient)."""

    def __init__(self, addr, client: RpcClient | None = None):
        self._addr = addr
        self._client = client or RpcClient()

    def write(self, samples: List[Sample]) -> None:
        if not samples:
            return
        self._client.call(
            self._addr, COLLECTOR_SERVICE_ID, 1, SampleBatch(list(samples)), Ack
        )


class LocalCollectorSink:
    """Monitor sink feeding an in-process CollectorService directly —
    the collector binary drinks its own telemetry (slo.* transitions,
    monitor.* self-gauges) with zero RPCs."""

    def __init__(self, service: CollectorService):
        self._service = service

    def write(self, samples: List[Sample]) -> None:
        if samples:
            self._service.write(SampleBatch(list(samples)))


class BufferedCollectorSink:
    """Collector push with BOUNDED buffering across outages.

    The plain CollectorSink raises on every push while the collector is
    down, and Monitor.collect only logs sink errors — samples collected
    during an outage were simply lost. Here samples queue up to
    ``cap_samples``; every write() attempts to drain the whole backlog
    (oldest first, FLUSH_BATCH per RPC), overflow drops the OLDEST
    samples (the newest window is the one an operator debugging the
    outage needs) and counts them on ``monitor.push_dropped`` so the
    loss itself is observable once the collector returns.

    ``addr`` may be a (host, port) tuple or a zero-arg callable
    returning one / None — the hot-config shape (a config push can point
    every service at a collector, or away from a dead one, live).

    Two push-storm defenses ride along:

    - ``backoff``: consecutive failed drains grow a multiplier (2x per
      failure, capped 8x) the push loop applies to its period, so N
      binaries don't hammer a dead collector in lockstep; one success
      resets it.
    - flight-dump epochs: when an Ack's ``dump_epoch`` grows past the
      first one observed, the registered ``on_dump`` callback fires
      (the SLO-breach black-box trigger). The FIRST ack only baselines
      — a fresh process must not dump for a breach that predates it.
    """

    BACKOFF_CAP = 8.0

    def __init__(self, addr, client: RpcClient | None = None,
                 cap_samples: int = 65536):
        import collections

        from tpu3fs.monitor.recorder import CounterRecorder

        self._addr = addr
        self._client = client or RpcClient()
        self._buf = collections.deque()
        self._cap = int(cap_samples)
        self._lock = threading.Lock()
        self.dropped = CounterRecorder("monitor.push_dropped")
        self.pushed = CounterRecorder("monitor.push_samples")
        self._fails = 0
        self._seen_epoch: Optional[int] = None
        self._on_dump = None

    def _resolve_addr(self):
        addr = self._addr() if callable(self._addr) else self._addr
        if not addr:
            return None
        if isinstance(addr, str):
            host, _, port = addr.rpartition(":")
            try:
                return (host or "127.0.0.1", int(port))
            except ValueError:
                return None
        return tuple(addr)

    def backlog(self) -> int:
        with self._lock:
            return len(self._buf)

    @property
    def backoff(self) -> float:
        """Period multiplier for the push loop: 1.0 while the collector
        answers, doubling per consecutive failed drain up to 8x."""
        return min(self.BACKOFF_CAP, 2.0 ** self._fails)

    def on_dump(self, fn) -> None:
        """Register the flight-dump callback, fn(reason: str)."""
        self._on_dump = fn

    def _observe_epoch(self, epoch: int) -> None:
        if self._seen_epoch is None:
            self._seen_epoch = epoch  # baseline, never dump on first ack
            return
        if epoch > self._seen_epoch:
            self._seen_epoch = epoch
            fn = self._on_dump
            if fn is not None:
                try:
                    fn(f"collector dump_epoch {epoch}")
                except Exception:
                    pass  # a dump hook must never break the push loop

    def write(self, samples: List[Sample]) -> None:
        with self._lock:
            self._buf.extend(samples)
            over = len(self._buf) - self._cap
            if over > 0:
                for _ in range(over):
                    self._buf.popleft()
                self.dropped.add(over)
            addr = self._resolve_addr()
            if addr is None:
                return  # unconfigured: buffer (bounded) until pointed
            while self._buf:
                batch = [self._buf.popleft()
                         for _ in range(min(FLUSH_BATCH, len(self._buf)))]
                try:
                    ack = self._client.call(addr, COLLECTOR_SERVICE_ID, 1,
                                            SampleBatch(batch), Ack)
                except Exception:
                    # collector outage: keep the batch for the next period
                    self._buf.extendleft(reversed(batch))
                    self._fails += 1
                    raise
                self._fails = 0
                self.pushed.add(len(batch))
                self._observe_epoch(int(getattr(ack, "dump_epoch", 0)))
