"""monitor_collector: the central sample-ingest service + push client.

Re-expresses src/monitor_collector (MonitorCollectorService.h:24-31): every
server's Monitor pushes Sample batches over RPC; the collector buffers and
batch-commits (4096 per flush, like the reference) to its sink — JSONL here,
ClickHouse via deploy/sql/tpu3fs-monitor.sql in a real deployment.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import List

from tpu3fs.monitor.recorder import Sample
from tpu3fs.rpc.net import RpcClient, RpcServer, ServiceDef

COLLECTOR_SERVICE_ID = 5  # ref fbs/monitor_collector
FLUSH_BATCH = 4096


@dataclass
class SampleBatch:
    samples: List[Sample] = field(default_factory=list)


@dataclass
class Ack:
    accepted: int = 0


class CollectorService:
    def __init__(self, sink):
        self._sink = sink
        self._buffer: List[Sample] = []
        self._lock = threading.Lock()

    def write(self, batch: SampleBatch) -> Ack:
        with self._lock:
            self._buffer.extend(batch.samples)
            if len(self._buffer) >= FLUSH_BATCH:
                self._flush_locked()
        return Ack(len(batch.samples))

    def _flush_locked(self) -> None:
        buf, self._buffer = self._buffer, []
        self._sink.write(buf)

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def query(self, req: "QueryReq") -> SampleBatch:
        """Operator query over the sink (flushes first so recent samples
        are visible); requires a queryable sink (SqliteSink)."""
        self.flush()
        if not hasattr(self._sink, "query"):
            return SampleBatch([])
        return SampleBatch(self._sink.query(
            req.name_prefix, req.since, req.until, req.limit))


@dataclass
class QueryReq:
    name_prefix: str = ""
    since: float = 0.0
    until: float = 0.0
    limit: int = 1000


def bind_collector_service(server: RpcServer, service: CollectorService) -> None:
    s = ServiceDef(COLLECTOR_SERVICE_ID, "MonitorCollector")
    s.method(1, "write", SampleBatch, Ack, service.write)
    s.method(2, "query", QueryReq, SampleBatch, service.query)
    server.add_service(s)


class CollectorSink:
    """Monitor sink pushing to a remote collector (ref
    MonitorCollectorClient)."""

    def __init__(self, addr, client: RpcClient | None = None):
        self._addr = addr
        self._client = client or RpcClient()

    def write(self, samples: List[Sample]) -> None:
        if not samples:
            return
        self._client.call(
            self._addr, COLLECTOR_SERVICE_ID, 1, SampleBatch(list(samples)), Ack
        )
