from tpu3fs.monitor.recorder import (  # noqa: F401
    CounterRecorder,
    DistributionRecorder,
    LatencyRecorder,
    Monitor,
    Sample,
)
