"""admin_cli: cluster administration + FS shell.

Re-expresses src/client/cli/admin (dispatcher Dispatcher.cc:296, ~60
commands): topology bootstrap (create-target / upload-chain /
upload-chain-table, the files gen_chain_table emits), cluster inspection
(list-nodes/chains/targets, routing-info), target maintenance
(offline-target), FS operations (ls/mkdir/stat/rm/mv/touch/read/write/
truncate/checksum), GC, config render/hot-update, the placement solver, and
a storage bench (ref benchmarks/storage_bench). Runs as a REPL or one-shot;
drives any object exposing the mgmtd/meta/client surfaces (the in-process
fabric or RPC clients — same dispatcher either way).
"""

from __future__ import annotations

import shlex
import sys
import time
from typing import Callable, Dict, List, Optional

from tpu3fs.meta.store import OpenFlags
from tpu3fs.mgmtd.types import LocalTargetState
from tpu3fs.ops.crc32c import crc32c
from tpu3fs.utils.result import FsError


class AdminCli:
    def __init__(self, fabric):
        """fabric: a Fabric (or compatible: .mgmtd, .meta, .file_client(),
        .storage_client(), .routing(), .run_gc(), .nodes)."""
        self.fab = fabric
        self._migration_svc = None
        self._commands: Dict[str, Callable[[List[str]], str]] = {}
        for name in dir(self):
            if name.startswith("cmd_"):
                self._commands[name[4:].replace("_", "-")] = getattr(self, name)

    # -- driver --------------------------------------------------------------
    def run(self, line: str) -> str:
        args = shlex.split(line)
        if not args:
            return ""
        cmd = args[0]
        fn = self._commands.get(cmd)
        if fn is None:
            return f"unknown command: {cmd} (try help)"
        try:
            return fn(args[1:])
        except FsError as e:
            return f"error: {e.status}"
        except (ValueError, IndexError, KeyError, TypeError, AttributeError) as e:
            return f"usage error: {e!r}"

    def repl(self, stdin=None, stdout=None) -> None:  # pragma: no cover
        stdin = stdin or sys.stdin
        stdout = stdout or sys.stdout
        for line in stdin:
            out = self.run(line.strip())
            if out:
                print(out, file=stdout)

    @staticmethod
    def _flag(args: List[str], name: str, default=None):
        if name in args:
            return args[args.index(name) + 1]
        return default

    # -- inspection ----------------------------------------------------------
    def cmd_help(self, args: List[str]) -> str:
        return "commands: " + ", ".join(sorted(self._commands))

    def cmd_list_nodes(self, args: List[str]) -> str:
        ri = self.fab.routing()
        lines = ["NODE  TYPE      STATUS                LAST_HB"]
        for n in sorted(ri.nodes.values(), key=lambda n: n.node_id):
            lines.append(
                f"{n.node_id:<5} {n.type.name:<9} {n.status.name:<21} "
                f"{n.last_heartbeat:.0f}"
            )
        return "\n".join(lines)

    def cmd_list_chains(self, args: List[str]) -> str:
        ri = self.fab.routing()
        lines = ["CHAIN    VER  TARGETS (state)"]
        for c in sorted(ri.chains.values(), key=lambda c: c.chain_id):
            ts = " ".join(
                f"{t.target_id}({t.public_state.name})" for t in c.targets
            )
            lines.append(f"{c.chain_id:<8} {c.chain_version:<4} {ts}")
        return "\n".join(lines)

    def cmd_list_targets(self, args: List[str]) -> str:
        ri = self.fab.routing()
        lines = ["TARGET  NODE  CHAIN    PUBLIC   LOCAL"]
        for t in sorted(ri.targets.values(), key=lambda t: t.target_id):
            lines.append(
                f"{t.target_id:<7} {t.node_id:<5} {t.chain_id:<8} "
                f"{t.public_state.name:<8} {t.local_state.name}"
            )
        return "\n".join(lines)

    def cmd_list_chain_tables(self, args: List[str]) -> str:
        ri = self.fab.routing()
        return "\n".join(
            f"table {t.table_id} v{t.version}: {t.chain_ids}"
            for t in ri.chain_tables.values()
        )

    def cmd_routing_info(self, args: List[str]) -> str:
        ri = self.fab.routing()
        return (
            f"version {ri.version}: {len(ri.nodes)} nodes, "
            f"{len(ri.chains)} chains, {len(ri.targets)} targets, "
            f"{len(getattr(ri, 'meta_partitions', {}) or {})} meta "
            f"partitions"
        )

    def cmd_meta_partitions(self, args: List[str]) -> str:
        """meta-partitions — the partitioned metadata plane's ownership
        table as mgmtd publishes it on RoutingInfo (docs/metashard.md):
        partition id, owning META node, fencing epoch, and the owner's
        last-reported per-partition load."""
        ri = self.fab.routing()
        parts = getattr(ri, "meta_partitions", None) or {}
        if not parts:
            return "no meta partition table published (legacy meta plane)"
        lines = ["PART  OWNER  EPOCH  LOAD(ops/s)"]
        for pid in sorted(parts):
            row = parts[pid]
            lines.append(f"{pid:<5} {row.node_id:<6} {row.epoch:<6} "
                         f"{row.load:.1f}")
        return "\n".join(lines)

    # -- topology ------------------------------------------------------------
    def cmd_create_target(self, args: List[str]) -> str:
        tid = int(self._flag(args, "--target-id"))
        node = int(self._flag(args, "--node-id", 0))
        self.fab.mgmtd.create_target(tid, node_id=node)
        return f"target {tid} created on node {node}"

    def cmd_upload_chain(self, args: List[str]) -> str:
        cid = int(self._flag(args, "--chain-id"))
        targets = [int(x) for x in self._flag(args, "--targets").split(",")]
        ec_k = int(self._flag(args, "--ec-k", 0))
        ec_m = int(self._flag(args, "--ec-m", 0))
        self.fab.mgmtd.upload_chain(cid, targets, ec_k=ec_k, ec_m=ec_m)
        kind = f"EC({ec_k},{ec_m})" if ec_k else "CR"
        return f"chain {cid} uploaded with {len(targets)} targets ({kind})"

    def cmd_upload_chain_table(self, args: List[str]) -> str:
        tid = int(self._flag(args, "--table-id", 1))
        chains = [int(x) for x in self._flag(args, "--chains").split(",")]
        self.fab.mgmtd.upload_chain_table(tid, chains)
        return f"chain table {tid} uploaded with {len(chains)} chains"

    def cmd_offline_target(self, args: List[str]) -> str:
        """Mark a target's local state offline and run the chain updater
        (ref OfflineTarget admin command)."""
        tid = int(self._flag(args, "--target-id"))
        for node in self.fab.nodes.values():
            node.service.offline_target(tid)
        self.fab.tick()
        return f"target {tid} offlined; routing v{self.fab.routing().version}"

    def cmd_rotate_lastsrv(self, args: List[str]) -> str:
        self.fab.tick()
        return "chain update pass complete"

    def cmd_solve_placement(self, args: List[str]) -> str:
        from tpu3fs.placement import (
            PlacementProblem,
            gen_chain_table_commands,
            solve_placement,
        )

        ec_k = int(self._flag(args, "--ec-k", 0))
        ec_m = int(self._flag(args, "--ec-m", 0))
        p = PlacementProblem(
            num_nodes=int(self._flag(args, "--nodes")),
            group_size=int(self._flag(args, "--group-size")),
            targets_per_node=int(self._flag(args, "--targets-per-node")),
            chain_table_type="EC" if ec_k else "CR",
        )
        traffic = self._flag(args, "--max-peer-traffic")
        M = solve_placement(
            p,
            steps=int(self._flag(args, "--steps", 200)),
            max_peer_traffic=float(traffic) if traffic else None,
        )
        return "\n".join(gen_chain_table_commands(M, ec_k=ec_k, ec_m=ec_m))

    # -- maintenance / parity sweeps (ref src/client/cli/admin: Bench,
    # ReadBench, Checksum, FindOrphanedChunks, RecursiveChown) --------------
    def cmd_bench(self, args: List[str]) -> str:
        """Raw storage write bench over the chain table (ref Bench.cc):
        bench [--chunks N] [--size BYTES] [--file-id ID]."""
        chunks = int(self._flag(args, "--chunks", 64))
        size = int(self._flag(args, "--size", 65536))
        file_id = int(self._flag(args, "--file-id", 909_090))
        ri = self.fab.routing()
        chains = [c.chain_id for c in ri.chains.values() if not c.is_ec]
        if not chains:
            return "no CR chains to bench"
        client = self.fab.storage_client()
        payload = b"\xab" * size
        from tpu3fs.storage.types import ChunkId as _Cid

        t0 = time.perf_counter()
        writes = [(chains[i % len(chains)], _Cid(file_id, i), 0, payload)
                  for i in range(chunks)]
        replies = client.batch_write(writes, chunk_size=size)
        dt = time.perf_counter() - t0
        failed = sum(1 for r in replies if not r.ok)
        return (f"wrote {chunks - failed}/{chunks} x {size}B in {dt:.3f}s "
                f"({chunks * size / dt / 1e6:.1f} MB/s), {failed} failed")

    def cmd_read_bench(self, args: List[str]) -> str:
        """Raw storage read bench (ref ReadBench.cc): read the chunks
        `bench` wrote: read-bench [--chunks N] [--file-id ID]."""
        chunks = int(self._flag(args, "--chunks", 64))
        file_id = int(self._flag(args, "--file-id", 909_090))
        ri = self.fab.routing()
        chains = [c.chain_id for c in ri.chains.values() if not c.is_ec]
        if not chains:
            return "no CR chains to bench"
        client = self.fab.storage_client()
        from tpu3fs.client.storage_client import ReadReq as _RR
        from tpu3fs.storage.types import ChunkId as _Cid

        t0 = time.perf_counter()
        replies = client.batch_read([
            _RR(chains[i % len(chains)], _Cid(file_id, i), 0, -1)
            for i in range(chunks)
        ])
        dt = time.perf_counter() - t0
        got = sum(len(r.data) for r in replies if r.ok)
        failed = sum(1 for r in replies if not r.ok)
        return (f"read {got} bytes from {chunks - failed}/{chunks} chunks "
                f"in {dt:.3f}s ({got / dt / 1e6:.1f} MB/s), {failed} failed")

    def cmd_verify_checksums(self, args: List[str]) -> str:
        """Cross-replica checksum sweep (ref Checksum.cc): every committed
        chunk's (version, crc) must agree across its chain's replicas.
        verify-checksums [--chain ID]."""
        only = self._flag(args, "--chain")
        ri = self.fab.routing()
        checked = mismatches = 0
        lines: List[str] = []
        for chain in ri.chains.values():
            if only and chain.chain_id != int(only):
                continue
            if chain.is_ec:
                continue  # EC shards differ by design; engine CRCs are
                # validated at install time (expected_crc)
            per_replica: Dict[int, Dict[bytes, tuple]] = {}
            for t in chain.targets:
                node = ri.node_of_target(t.target_id)
                if node is None:
                    continue
                try:
                    metas = self.fab.send(
                        node.node_id, "dump_chunkmeta", t.target_id)
                except FsError:
                    continue
                per_replica[t.target_id] = {
                    m.chunk_id.to_bytes(): (m.committed_ver,
                                            m.checksum.value)
                    for m in metas if m.committed_ver > 0
                }
            all_keys = set().union(*per_replica.values()) \
                if per_replica else set()
            for key in all_keys:
                states = {tid: rep.get(key) for tid, rep in
                          per_replica.items()}
                committed = {v for v in states.values() if v is not None}
                checked += 1
                if len(committed) > 1:
                    mismatches += 1
                    lines.append(
                        f"chain {chain.chain_id} chunk {key.hex()}: "
                        + ", ".join(f"t{tid}={v}" for tid, v in
                                    states.items()))
        head = f"checked {checked} chunks, {mismatches} mismatches"
        return head if not lines else head + "\n" + "\n".join(lines[:50])

    def cmd_find_orphaned_chunks(self, args: List[str]) -> str:
        """Chunks whose file id has no inode (ref FindOrphanedChunks.cc):
        find-orphaned-chunks [--remove]."""
        remove = "--remove" in args
        ri = self.fab.routing()
        # file id -> set of chain ids holding its chunks
        seen: Dict[int, set] = {}
        for chain in ri.chains.values():
            for t in chain.targets:
                node = ri.node_of_target(t.target_id)
                if node is None:
                    continue
                try:
                    metas = self.fab.send(
                        node.node_id, "dump_chunkmeta", t.target_id)
                except FsError:
                    continue
                for m in metas:
                    seen.setdefault(m.chunk_id.file_id,
                                    set()).add(chain.chain_id)
        file_ids = sorted(seen)
        orphans: List[int] = []
        for base in range(0, len(file_ids), 256):
            batch = file_ids[base:base + 256]
            inodes = self.fab.meta.batch_stat(batch)
            orphans.extend(
                fid for fid, ino in zip(batch, inodes) if ino is None)
        removed = 0
        if remove:
            # StorageClient.remove_file_chunks knows the fan-out rules
            # (CR: head + chain forward; EC: every node of the chain) —
            # reuse it instead of hand-rolling target selection
            client = self.fab.storage_client()
            for fid in orphans:
                for chain_id in seen[fid]:
                    try:
                        client.remove_file_chunks(chain_id, fid)
                        removed += 1
                    except FsError:
                        continue
        out = f"{len(orphans)} orphaned file ids: {orphans[:20]}"
        if remove:
            out += f"; removed chunks of {removed} (file, chain) pairs"
        return out

    def cmd_chown(self, args: List[str]) -> str:
        """chown [-R] UID[:GID] PATH (ref RecursiveChown.cc)."""
        recursive = "-R" in args
        rest = [a for a in args if a != "-R"]
        spec, path = rest[0], rest[1]
        uid_s, _, gid_s = spec.partition(":")
        uid = int(uid_s)
        gid = int(gid_s) if gid_s else None
        count = 0

        def apply(p: str) -> None:
            nonlocal count
            self.fab.meta.set_attr(p, uid=uid, gid=gid)
            count += 1
            if recursive:
                try:
                    ents = self.fab.meta.list_dir(p)
                except FsError:
                    return
                for e in ents:
                    apply(p.rstrip("/") + "/" + e.name)

        apply(path)
        return f"chowned {count} inode(s) to {uid}" + \
            (f":{gid}" if gid is not None else "")

    def cmd_query_metrics(self, args: List[str]) -> str:
        """Query the monitor sink (ref: operators query ClickHouse):
        query-metrics --db PATH [--name PREFIX] [--limit N]
        or --collector HOST:PORT to query a live monitor service."""
        name = self._flag(args, "--name", "")
        limit = int(self._flag(args, "--limit", 20))
        coll = self._flag(args, "--collector")
        if coll:
            from tpu3fs.monitor.collector import (
                COLLECTOR_SERVICE_ID,
                QueryReq,
                SampleBatch,
            )
            from tpu3fs.rpc.net import RpcClient

            host, port = coll.rsplit(":", 1)
            rsp = RpcClient().call(
                (host, int(port)), COLLECTOR_SERVICE_ID, 2,
                QueryReq(name_prefix=name, limit=limit), SampleBatch)
            samples = rsp.samples
        else:
            from tpu3fs.monitor.recorder import SqliteSink

            db = self._flag(args, "--db")
            if not db:
                return ("usage: query-metrics "
                        "(--db <sqlite-file> | --collector <host:port>) "
                        "[--name PREFIX] [--limit N]")
            samples = SqliteSink(db).query(name, limit=limit)
        if not samples:
            return "no samples"
        return "\n".join(
            f"{s.ts:.1f} {s.name} value={s.value} count={s.count} "
            f"p99={s.p99:.1f} tags={s.tags}"
            for s in samples)

    def cmd_qos(self, args: List[str]) -> str:
        """Per-node QoS view (tpu3fs/qos): per-class admission limits,
        live in-flight counts and update-queue depths.
        qos [--node N]"""
        want = self._flag(args, "--node")
        lines = []
        for node_id in sorted(getattr(self.fab, "nodes", {})):
            if want is not None and int(want) != node_id:
                continue
            service = self.fab.nodes[node_id].service
            snap = service.qos_snapshot()
            lines.append(f"node {node_id}: qos "
                         f"{'enabled' if snap.get('enabled') else 'disabled'}")
            classes = snap.get("classes", {})
            if classes:
                lines.append("  CLASS       RATE     BURST  INFLIGHT/CAP"
                             "  WEIGHT  QSHARE  QDEPTH")
                depths = snap.get("queue_depths", {})
                for name, c in classes.items():
                    cap = c["max_inflight"] or "-"
                    rate = c["rate"] or "-"
                    lines.append(
                        f"  {name:<11} {str(rate):<8} {c['burst']:<6.0f} "
                        f"{c['inflight']}/{cap:<11} {c['weight']:<7} "
                        f"{c['queue_share']:<7.2f} {depths.get(name, 0)}")
            else:
                depths = snap.get("queue_depths", {})
                if depths:
                    lines.append(f"  queue depths: {depths}")
        return "\n".join(lines) if lines else "no storage nodes"

    # -- distributed tracing (tpu3fs/analytics/spans.py + assemble.py) -------
    @staticmethod
    def _load_trace_dirs(args: List[str]):
        """--dir D[,D2,...] (span files or directories, recursive)."""
        from tpu3fs.analytics import assemble

        spec = None
        if "--dir" in args:
            spec = args[args.index("--dir") + 1]
        elif args and not args[0].startswith("--"):
            spec = args[0]
        if not spec:
            raise ValueError("usage: --dir <span-dir[,span-dir...]>")
        rows = assemble.load_spans(spec.split(","))
        return assemble, rows

    def cmd_trace_show(self, args: List[str]) -> str:
        """One trace as a cross-process span tree with the per-stage
        latency breakdown and stage coverage.
        trace-show --dir D[,D...] [--trace TRACE_ID | --op OP]
        (default: the slowest assembled trace)"""
        assemble, rows = self._load_trace_dirs(args)
        trees = assemble.assemble_traces(rows)
        if not trees:
            return "no traces found"
        want = self._flag(args, "--trace")
        if want:
            tree = trees.get(want)
            if tree is None:
                return f"trace {want} not found ({len(trees)} traces)"
            return assemble.format_trace(tree)
        op = self._flag(args, "--op")
        ranked = assemble.top_traces(trees, len(trees))
        if op:
            ranked = [t for t in ranked
                      if t.root is not None and t.root.get("op") == op]
            if not ranked:
                return f"no trace with root op {op}"
        return assemble.format_trace(ranked[0])

    def cmd_trace_top(self, args: List[str]) -> str:
        """Slowest traced ops + per-stage percentile breakdown over every
        loaded span file; --by-tenant adds the per-tenant op rollup.
        trace-top --dir D[,D...] [--n N] [--by-tenant]"""
        assemble, rows = self._load_trace_dirs(args)
        trees = assemble.assemble_traces(rows)
        if not trees:
            return "no traces found"
        return assemble.format_top(trees, rows,
                                   n=int(self._flag(args, "--n", 10)),
                                   by_tenant="--by-tenant" in args)

    def cmd_top(self, args: List[str]) -> str:
        """Live cluster top from monitor_collector output: per-class
        admitted/shed rates, queue depths, per-subsystem GiB/s, memory
        gauges. top --collector HOST:PORT [--window SEC] [--watch SEC]
        (--watch polls until interrupted; default prints once)"""
        coll = self._flag(args, "--collector") or (
            args[0] if args and not args[0].startswith("--") else None)
        if not coll:
            return ("usage: top --collector <host:port> [--window SEC] "
                    "[--watch SEC]")
        window = float(self._flag(args, "--window", 60))
        watch = self._flag(args, "--watch")
        out = self._top_once(coll, window)
        if watch is None:
            return out
        import time as _time  # pragma: no cover - interactive loop

        try:
            while True:
                print(out)
                _time.sleep(float(watch))
                out = self._top_once(coll, window)
        except KeyboardInterrupt:
            return out

    @staticmethod
    def _agg_rows(coll: str, window: float, prefix: str = ""):
        """Windowed rollups from the collector's aggQuery RPC — the
        cheap path `top`/`tenant-top` prefer (one pre-aggregated row
        per series instead of a raw-sample scan, and the SAME rollups
        the SLO engine judges). Returns None when the collector is too
        old to know the method (raw-scan fallback)."""
        from tpu3fs.monitor.collector import (
            AggQueryReq,
            AggQueryRsp,
            COLLECTOR_SERVICE_ID,
        )
        from tpu3fs.rpc.net import RpcClient

        host, port = coll.rsplit(":", 1)
        try:
            rsp = RpcClient().call(
                (host, int(port)), COLLECTOR_SERVICE_ID, 3,
                AggQueryReq(name=prefix, prefix=True, window_s=window),
                AggQueryRsp)
        except FsError:
            return None  # old collector: no aggQuery
        return rsp.rows

    def _top_once(self, coll: str, window: float) -> str:
        rows = self._agg_rows(coll, window)
        if rows:  # old collector (None) or no rollups: raw-scan fallback
            return self._top_from_agg(rows, window)
        return self._top_once_raw(coll, window)

    def _top_from_agg(self, rows, window: float) -> str:
        def is_gauge(name: str) -> bool:
            return self._is_gauge_name(name)

        counters: Dict[tuple, float] = {}
        gauges: Dict[tuple, tuple] = {}
        nsamples = 0
        for r in rows:
            if r.count == 0 and not r.last_ts:
                continue
            nsamples += r.count
            key = (r.name, r.tags.get("class", ""),
                   r.tags.get("node", ""))
            if is_gauge(r.name):
                cur = gauges.get(key)
                if cur is None or r.last_ts >= cur[0]:
                    gauges[key] = (r.last_ts, r.last)
            elif r.count:
                counters[key] = counters.get(key, 0.0) + r.vsum
        return self._render_top(counters, gauges, window, nsamples,
                                source="aggQuery rollups")

    def _top_once_raw(self, coll: str, window: float) -> str:
        import json as _json
        import time as _time

        from tpu3fs.monitor.collector import (
            COLLECTOR_SERVICE_ID,
            QueryReq,
            SampleBatch,
        )
        from tpu3fs.rpc.net import RpcClient

        host, port = coll.rsplit(":", 1)
        since = _time.time() - window
        rsp = RpcClient().call(
            (host, int(port)), COLLECTOR_SERVICE_ID, 2,
            QueryReq(since=since, limit=100000), SampleBatch)
        counters: Dict[tuple, float] = {}
        gauges: Dict[tuple, tuple] = {}
        for s in rsp.samples:
            tags = s.tags if isinstance(s.tags, dict) else _json.loads(
                s.tags or "{}")
            key = (s.name, tags.get("class", ""), tags.get("node", ""))
            if self._is_gauge_name(s.name):
                cur = gauges.get(key)
                if cur is None or s.ts >= cur[0]:
                    gauges[key] = (s.ts, s.value)
            else:
                counters[key] = counters.get(key, 0.0) + s.value
        return self._render_top(counters, gauges, window,
                                len(rsp.samples), source="raw samples")

    @staticmethod
    def _is_gauge_name(name: str) -> bool:
        # ValueRecorder names (last-value semantics): the memory
        # observability set + the pre-existing gauge families.
        # Everything else reports per-window deltas (counters).
        return name.startswith(("mem.", "memory.", "mgmtd.", "monitor.agg",
                                "monitor.retained", "monitor.ingest",
                                "slo.rules_firing", "slo.health",
                                "storage.disk_info",
                                "storage.allocate")) \
            or name in ("kvcache.dirty_bytes", "kvcache.host_bytes",
                        "kvcache.leases", "dataload.buffered_bytes",
                        "qos.queue_depth", "ec.rebuild_mibps",
                        "ec.encode_gibps", "tenant.kvcache_bytes",
                        "usrbio.agent_depth")

    def _render_top(self, counters: Dict[tuple, float],
                    gauges: Dict[tuple, tuple], window: float,
                    nsamples: int, *, source: str) -> str:
        lines = [f"cluster top  (window {window:.0f}s, "
                 f"{nsamples} samples, {source})"]
        qos = [(k, v) for k, v in counters.items()
               if k[0] in ("qos.admitted", "qos.shed")]
        if qos:
            lines.append(f"  {'CLASS':<12} {'NODE':<6} {'ADMIT/s':>10} "
                         f"{'SHED/s':>10}")
            combos = sorted({(k[1], k[2]) for k, _ in qos})
            for cls, node in combos:
                a = counters.get(("qos.admitted", cls, node), 0.0)
                d = counters.get(("qos.shed", cls, node), 0.0)
                lines.append(f"  {cls or '-':<12} {node or '-':<6} "
                             f"{a / window:>10.1f} {d / window:>10.1f}")
        tput = [(k, v) for k, v in counters.items()
                if k[0].endswith((".bytes", "_bytes")) and v > 0]
        if tput:
            lines.append(f"  {'THROUGHPUT':<28} {'GiB/s':>10}")
            for (name, cls, node), v in sorted(tput):
                lines.append(
                    f"  {name + (f'[{cls}]' if cls else ''):<28} "
                    f"{v / window / (1 << 30):>10.4f}")
        if gauges:
            lines.append(f"  {'GAUGE':<28} {'NODE':<6} {'VALUE':>14}")
            for (name, cls, node), (_, v) in sorted(gauges.items()):
                lines.append(f"  {name:<28} {node or '-':<6} {v:>14.0f}")
        return "\n".join(lines)

    # -- multi-tenant fairness (tpu3fs/tenant; docs/tenancy.md) --------------
    def cmd_tenant_quota(self, args: List[str]) -> str:
        """Tenant quota table (tpu3fs/tenant):
        tenant-quota [show] [--tenant NAME] — THIS process's registry:
                  quotas + live per-tenant totals
        tenant-quota set --spec "tenant=a,weight=4,bytes_per_s=...;..."
                  [--node-type storage] — merge a [tenants] section into
                  the node type's pushed config (heartbeats deliver it;
                  every node of that type retunes buckets + lane weights
                  live)
        tenant-quota clear [--node-type storage] — push an empty table"""
        from tpu3fs.tenant.quota import parse_spec, registry

        if args and args[0] in ("set", "clear"):
            sub, rest = args[0], args[1:]
            spec = "" if sub == "clear" else self._flag(rest, "--spec", "")
            table = parse_spec(spec)  # validate BEFORE pushing
            nt = self._node_type_flag(rest)
            blob = self.fab.mgmtd.get_config(nt)
            content = self._merge_section_toml(
                blob.content, "tenants", {"spec": spec})
            ver = self.fab.mgmtd.set_config(nt, content)
            return (f"pushed {len(table)} tenant quota row(s) to "
                    f"{nt.name} config v{ver} (heartbeats deliver "
                    f"within one interval)")
        want = self._flag(args, "--tenant")
        snap = registry().snapshot()
        lines = [f"{'TENANT':<16} {'WEIGHT':>6} {'BYTES/S':>12} "
                 f"{'IOPS':>8} {'KV_BUDGET':>12} {'KV_RES':>10} "
                 f"{'ADMIT':>8} {'SHED':>6} {'BYTES':>12}"]
        for name, row in snap.items():
            if want is not None and name != want:
                continue
            star = "" if row["explicit"] else "*"
            lines.append(
                f"{name + star:<16} {row['weight']:>6} "
                f"{row['bytes_per_s']:>12.0f} {row['iops']:>8.0f} "
                f"{row['kvcache_bytes']:>12} {row['kv_resident']:>10} "
                f"{row['admitted']:>8} {row['shed']:>6} "
                f"{row['bytes']:>12}")
        lines.append("(* = default-quota tenant, no explicit row)")
        return "\n".join(lines)

    def cmd_tenant_top(self, args: List[str]) -> str:
        """Live per-tenant cluster view from monitor_collector output:
        admitted/shed rates by kind, bytes GiB/s, queue-wait p99,
        kvcache resident gauges — "who is hurting whom".
        tenant-top --collector HOST:PORT [--window SEC]"""
        import json as _json
        import time as _time

        from tpu3fs.monitor.collector import (
            COLLECTOR_SERVICE_ID,
            QueryReq,
            SampleBatch,
        )
        from tpu3fs.rpc.net import RpcClient

        coll = self._flag(args, "--collector") or (
            args[0] if args and not args[0].startswith("--") else None)
        if not coll:
            return ("usage: tenant-top --collector <host:port> "
                    "[--window SEC]")
        window = float(self._flag(args, "--window", 60))
        counters: Dict[tuple, float] = {}
        waits: Dict[str, float] = {}
        kv: Dict[str, tuple] = {}
        nsamples = 0
        agg_rows = self._agg_rows(coll, window, prefix="tenant.")
        if agg_rows:  # empty/None: raw-scan fallback below
            # preferred path: the collector's windowed rollups (exactly
            # what the SLO engine judges; no raw-row scan)
            for r in agg_rows:
                if r.count == 0:
                    continue
                nsamples += r.count
                tenant = r.tags.get("tenant", "-")
                if r.name == "tenant.queue_wait_us":
                    waits[tenant] = max(waits.get(tenant, 0.0), r.p99)
                elif r.name == "tenant.kvcache_bytes":
                    cur = kv.get(tenant)
                    if cur is None or r.last_ts >= cur[0]:
                        kv[tenant] = (r.last_ts, r.last)
                else:
                    key = (r.name, tenant, r.tags.get("kind", ""))
                    counters[key] = counters.get(key, 0.0) + r.vsum
        else:  # old collector: raw-sample scan fallback
            host, port = coll.rsplit(":", 1)
            rsp = RpcClient().call(
                (host, int(port)), COLLECTOR_SERVICE_ID, 2,
                QueryReq(name_prefix="tenant.",
                         since=_time.time() - window,
                         limit=100000), SampleBatch)
            nsamples = len(rsp.samples)
            for s in rsp.samples:
                tags = s.tags if isinstance(s.tags, dict) else _json.loads(
                    s.tags or "{}")
                tenant = tags.get("tenant", "-")
                if s.name == "tenant.queue_wait_us":
                    waits[tenant] = max(waits.get(tenant, 0.0), s.p99)
                elif s.name == "tenant.kvcache_bytes":
                    cur = kv.get(tenant)
                    if cur is None or s.ts >= cur[0]:
                        kv[tenant] = (s.ts, s.value)
                else:
                    key = (s.name, tenant, tags.get("kind", ""))
                    counters[key] = counters.get(key, 0.0) + s.value
        tenants = sorted({k[1] for k in counters}
                         | set(waits) | set(kv))
        if not tenants:
            return f"no tenant samples in the last {window:.0f}s"
        lines = [f"tenant top  (window {window:.0f}s, "
                 f"{nsamples} samples)",
                 f"  {'TENANT':<16} {'ADMIT/s':>9} {'SHED/s':>8} "
                 f"{'by-kind':<26} {'GiB/s':>8} {'QWAITp99':>10} "
                 f"{'KV_RES':>10}"]
        for tenant in tenants:
            admit = counters.get(("tenant.admitted", tenant, ""), 0.0)
            sheds = {k[2]: v for k, v in counters.items()
                     if k[0] == "tenant.shed" and k[1] == tenant}
            shed_total = sum(sheds.values())
            by_kind = ",".join(f"{k}={v:.0f}"
                               for k, v in sorted(sheds.items()) if v)
            gib = counters.get(("tenant.bytes", tenant, ""), 0.0) \
                / window / (1 << 30)
            wait_ms = waits.get(tenant, 0.0) / 1e3
            kres = int(kv.get(tenant, (0, 0))[1])
            lines.append(
                f"  {tenant:<16} {admit / window:>9.1f} "
                f"{shed_total / window:>8.1f} {by_kind:<26} "
                f"{gib:>8.4f} {wait_ms:>9.2f}ms {kres:>10}")
        return "\n".join(lines)

    # -- SLO engine + flight recorder (tpu3fs/monitor/slo.py, flight.py;
    # docs/slo.md) -----------------------------------------------------------
    def _collector_flag(self, args: List[str]) -> str:
        coll = self._flag(args, "--collector") or (
            args[0] if args and not args[0].startswith("--")
            and ":" in args[0] else None)
        if not coll:
            raise ValueError("--collector <host:port> is required")
        return coll

    def _slo_status(self, coll: str):
        from tpu3fs.monitor.slo import SloGate

        return SloGate(coll).status()

    def cmd_slo(self, args: List[str]) -> str:
        """SLO rule engine control (monitor/slo.py):
        slo show --collector HOST:PORT — rules + live states
        slo set --collector HOST:PORT --spec "rule=...;..." — validate,
                then hot-push the [slo] section through the collector's
                core hotUpdateConfig RPC (the collector boots one-phase;
                --spec default pushes slo.DEFAULT_CLUSTER_SPEC)
        slo clear --collector HOST:PORT — push an empty rule set"""
        from tpu3fs.monitor.slo import DEFAULT_CLUSTER_SPEC, parse_slo_spec

        if not args:
            return "usage: slo show|set|clear --collector host:port ..."
        sub, rest = args[0], args[1:]
        if sub in ("set", "clear"):
            spec = "" if sub == "clear" else self._flag(rest, "--spec", "")
            if spec == "default":
                spec = DEFAULT_CLUSTER_SPEC
            rules = parse_slo_spec(spec)  # validate BEFORE pushing
            coll = self._collector_flag(rest)
            from tpu3fs.rpc.net import RpcClient
            from tpu3fs.rpc.services import (
                CORE_SERVICE_ID,
                Empty,
                StrReply,
            )

            content = self._merge_section_toml("", "slo", {"spec": spec})
            host, port = coll.rsplit(":", 1)
            RpcClient().call((host, int(port)), CORE_SERVICE_ID, 3,
                             StrReply(content), Empty)
            return (f"pushed {len(rules)} slo rule(s) to collector "
                    f"{coll} (engine reconfigured live; same-named "
                    f"rules keep their alert state)")
        if sub == "show":
            return self.cmd_slo_show(rest)
        return "usage: slo show|set|clear --collector host:port ..."

    def cmd_slo_show(self, args: List[str]) -> str:
        """slo-show --collector HOST:PORT: every rule with its condition,
        alert state and last observed value."""
        rsp = self._slo_status(self._collector_flag(args))
        if not rsp.rules:
            return f"verdict {rsp.verdict}: no slo rules configured"
        lines = [f"verdict {rsp.verdict}"
                 + (f"  (firing: {', '.join(rsp.firing)})"
                    if rsp.firing else ""),
                 f"{'RULE':<18} {'SEV':<9} {'STATE':<8} {'VALUE':>12} "
                 f"{'FIRED':>5}  CONDITION"]
        for r in rsp.rules:
            lines.append(
                f"{r.rule:<18} {r.severity:<9} {r.state:<8} "
                f"{r.value:>12.6g} {r.fired_count:>5}  {r.bound}"
                + (f"  [{r.message}]" if r.message and r.state != "ok"
                   else ""))
        return "\n".join(lines)

    def cmd_alerts(self, args: List[str]) -> str:
        """alerts --collector HOST:PORT: firing rules + the recent
        alert state-machine transitions (newest last)."""
        rsp = self._slo_status(self._collector_flag(args))
        lines = [f"verdict {rsp.verdict}: "
                 f"{len(rsp.firing)} firing"
                 + (f" ({', '.join(rsp.firing)})" if rsp.firing else "")]
        for t in rsp.transitions:
            lines.append(f"  {t.ts:.3f} {t.rule} -> {t.transition} "
                         f"value={t.value:g}"
                         + (f" ({t.message})" if t.message else ""))
        if len(lines) == 1:
            lines.append("  (no transitions recorded)")
        return "\n".join(lines)

    def cmd_health(self, args: List[str]) -> str:
        """health --collector HOST:PORT: the single cluster verdict —
        OK / DEGRADED / CRITICAL, naming the firing rules."""
        rsp = self._slo_status(self._collector_flag(args))
        if rsp.verdict == "OK":
            return f"OK ({len(rsp.rules)} rules clean)"
        firing = [r for r in rsp.rules if r.state == "firing"]
        detail = "; ".join(
            f"{r.rule}: {r.message or r.bound}" for r in firing)
        return f"{rsp.verdict}: {detail}"

    def cmd_flight_dump(self, args: List[str]) -> str:
        """Dump a process's flight-recorder black box to disk:
        flight-dump --addr HOST:PORT [--path P] — any service binary,
                    via its core flightDump RPC
        flight-dump --local [--path P] — THIS process's ring"""
        path = self._flag(args, "--path", "")
        if "--local" in args:
            from tpu3fs.monitor.flight import flight

            out = flight().dump(path or None, reason="admin_cli")
            return (f"dumped {len(flight().snapshot())} events to {out}"
                    if out else "no flight dir configured (use --path)")
        addr = self._flag(args, "--addr") or (
            args[0] if args and not args[0].startswith("--") else None)
        if not addr:
            return ("usage: flight-dump (--addr <host:port> | --local) "
                    "[--path P]")
        from tpu3fs.rpc.net import RpcClient
        from tpu3fs.rpc.services import (
            CORE_SERVICE_ID,
            FlightDumpReq,
            FlightDumpRsp,
        )

        host, port = addr.rsplit(":", 1)
        rsp = RpcClient().call((host, int(port)), CORE_SERVICE_ID, 7,
                               FlightDumpReq(path=path), FlightDumpRsp)
        if not rsp.path:
            return (f"{addr}: ring holds {rsp.events} events but no "
                    "flight dir is configured (pass --path)")
        return f"{addr}: dumped {rsp.events} events to {rsp.path}"

    def cmd_flight_show(self, args: List[str]) -> str:
        """flight-show --dir D[,D...]: merge N processes' flight dumps
        into one timeline (alerts, config pushes) + the slowest
        cross-process span trees rebuilt from the dumped slow-op
        spans."""
        from tpu3fs.analytics import assemble

        spec = self._flag(args, "--dir") or (
            args[0] if args and not args[0].startswith("--") else None)
        if not spec:
            return "usage: flight-show --dir <dump-dir[,dump-dir...]>"
        rows = assemble.load_flight(spec.split(","))
        return assemble.format_flight(rows)

    def cmd_ec_status(self, args: List[str]) -> str:
        """Per-EC-chain health: shard -> target/state map, degraded
        summary, and with --counts the per-target stripe counts
        (dump_chunkmeta), rebuild progress of SYNCING shards and the
        file ids currently served degraded.
        ec-status [--chain ID] [--counts]"""
        want = self._flag(args, "--chain")
        deep = "--counts" in args
        routing = self.fab.routing()
        lines = []
        for cid, chain in sorted(routing.chains.items()):
            if not chain.is_ec:
                continue
            if want is not None and int(want) != cid:
                continue
            states = [t.public_state.name for t in chain.targets]
            degraded = sum(1 for s in states if s != "SERVING")
            syncing = sum(1 for s in states if s == "SYNCING")
            head = (f"chain {cid} EC({chain.ec_k},{chain.ec_m}) "
                    f"v{chain.chain_version}: ")
            if degraded == 0:
                head += "healthy"
            else:
                head += f"DEGRADED ({degraded} shard(s) not serving"
                if syncing:
                    head += f", {syncing} rebuilding"
                head += ")"
            lines.append(head)
            metas = {}
            if deep:
                for t in chain.targets:
                    node = routing.node_of_target(t.target_id)
                    if node is None:
                        continue
                    try:
                        metas[t.target_id] = self.fab.send(
                            node.node_id, "dump_chunkmeta", t.target_id)
                    except FsError:
                        metas[t.target_id] = None
            # shard positions come from preferred_order (chain_sm may
            # rotate `targets`; the shard layout never moves)
            for j in range(chain.ec_k + chain.ec_m):
                t = chain.target_of_shard(j)
                if t is None:
                    lines.append(f"  shard {j}: no target")
                    continue
                node = routing.node_of_target(t.target_id)
                kind = "data" if j < chain.ec_k else "parity"
                extra = ""
                if deep:
                    got = metas.get(t.target_id)
                    extra = f"  stripes={len(got) if got is not None else '?'}"
                lines.append(
                    f"  shard {j} ({kind:<6}) target {t.target_id} node "
                    f"{node.node_id if node else '?'} "
                    f"{t.public_state.name}{extra}")
            if deep and degraded:
                # rebuild progress: the recovering shard's stripe count vs
                # the fullest serving peer; degraded files = files whose
                # stripes a serving peer still holds (reads decode inline)
                serving_ids = {t.target_id for t in chain.targets
                               if t.public_state.name == "SERVING"}
                peer_counts = [len(v) for tid, v in metas.items()
                               if v is not None and tid in serving_ids]
                goal = max(peer_counts, default=0)
                for t in chain.targets:
                    if t.public_state.name != "SYNCING":
                        continue
                    have = metas.get(t.target_id)
                    have_n = len(have) if have is not None else 0
                    lines.append(f"  rebuild: target {t.target_id} "
                                 f"{have_n}/{goal} stripes")
                files = sorted({m.chunk_id.file_id
                                for tid, v in metas.items()
                                if v is not None and tid in serving_ids
                                for m in v})
                if files:
                    shown = ", ".join(str(f) for f in files[:8])
                    more = ("" if len(files) <= 8
                            else f" (+{len(files) - 8} more)")
                    lines.append(
                        f"  degraded files: {shown}{more}")
        return "\n".join(lines) if lines else "no EC chains"

    # -- cluster fault plane (utils/fault_injection.py) ----------------------
    @staticmethod
    def _merge_faults_toml(content: str, spec: str, seed: int) -> str:
        """Merge a [faults] section into an existing pushed-config blob
        (set_config replaces the whole blob; operators must not lose the
        qos/trace sections they pushed earlier)."""
        return AdminCli._merge_section_toml(content, "faults",
                                            {"spec": spec, "seed": seed})

    @staticmethod
    def _merge_section_toml(content: str, section: str,
                            items: Dict[str, object]) -> str:
        """Merge one [section] of scalar items into a pushed-config blob,
        preserving every other section (faults/tenants share this)."""
        from tpu3fs.utils.config import tomllib

        data = tomllib.loads(content) if content else {}
        data.setdefault(section, {})
        data[section].update(items)

        def render(d: dict, prefix: str = "") -> List[str]:
            lines = []
            for k in sorted(d):
                v = d[k]
                if isinstance(v, dict):
                    continue
                if isinstance(v, bool):
                    lines.append(f"{k} = {'true' if v else 'false'}")
                elif isinstance(v, (int, float)):
                    lines.append(f"{k} = {v!r}")
                else:
                    s = str(v).replace("\\", "\\\\").replace('"', '\\"')
                    lines.append(f'{k} = "{s}"')
            for k in sorted(d):
                v = d[k]
                if isinstance(v, dict):
                    lines.append("")
                    lines.append(f"[{prefix}{k}]")
                    lines.extend(render(v, f"{prefix}{k}."))
            return lines

        return "\n".join(render(data)).strip() + "\n"

    def cmd_fault(self, args: List[str]) -> str:
        """Cluster fault plane (gray-failure chaos tooling):
        fault set --spec "point=...,kind=...,..." [--seed N]
                  [--node-type storage] — merge a [faults] section into
                  the node type's pushed config (heartbeats deliver it,
                  every node of that type arms the rules live)
        fault clear [--node-type storage] — push an empty spec
        fault show [--node-type storage] [--collector H:P [--window S]]
                  — pushed spec + local plane with PER-RULE fire counts;
                  --collector adds the cluster-wide faults.fired rollup
                  (every node's firings by kind+point), so a chaos soak
                  can assert its schedule actually fired
        fault local --spec ... [--seed N] — arm THIS process's plane"""
        from tpu3fs.utils.fault_injection import parse_spec, plane

        if not args:
            return "usage: fault set|clear|show|local ..."
        sub, rest = args[0], args[1:]
        if sub == "local":
            spec = self._flag(rest, "--spec", "")
            seed = int(self._flag(rest, "--seed", 0))
            plane().configure(spec, seed)
            return (f"local fault plane: {len(plane().snapshot())} rule(s) "
                    f"armed")
        if sub == "show":
            lines = []
            for r in plane().snapshot():
                lines.append(f"local rule: point={r['point']} "
                             f"kind={r['kind']} fired={r['fired']}"
                             + (f"/{r['times']}" if r['times'] >= 0 else ""))
            lines.append(f"local fired total: {plane().fired_total}")
            coll = self._flag(rest, "--collector", "")
            if coll:
                window = float(self._flag(rest, "--window", 120.0))
                rows = self._agg_rows(coll, window, prefix="faults.fired")
                fired = {}
                for row in rows or []:
                    key = (row.tags.get("kind", "?"),
                           row.tags.get("point", "?"))
                    fired[key] = fired.get(key, 0.0) + row.vsum
                if fired:
                    lines.append(f"cluster faults.fired (last {window:g}s):")
                    for (kind, point), n in sorted(fired.items()):
                        lines.append(f"  {point:<28} {kind:<10} {int(n)}")
                else:
                    lines.append(
                        f"cluster faults.fired (last {window:g}s): none")
            nt = self._node_type_flag(rest)
            try:
                blob = self.fab.mgmtd.get_config(nt)
            except (FsError, AttributeError):
                blob = None
            if blob is not None and blob.content:
                import re as _re

                m = _re.search(r'^spec\s*=\s*"(.*)"$', blob.content,
                               _re.MULTILINE)
                lines.append(f"pushed {nt.name} config v{blob.version} "
                             f"spec: {m.group(1) if m else '(none)'}")
            return "\n".join(lines)
        if sub in ("set", "clear"):
            spec = "" if sub == "clear" else self._flag(rest, "--spec", "")
            seed = int(self._flag(rest, "--seed", 0))
            rules = parse_spec(spec)  # validate BEFORE pushing
            nt = self._node_type_flag(rest)
            blob = self.fab.mgmtd.get_config(nt)
            content = self._merge_faults_toml(blob.content, spec, seed)
            ver = self.fab.mgmtd.set_config(nt, content)
            return (f"pushed {len(rules)} fault rule(s) to {nt.name} "
                    f"config v{ver} (heartbeats deliver within one "
                    f"interval)")
        return "usage: fault set|clear|show|local ..."

    def _node_type_flag(self, args: List[str]):
        from tpu3fs.mgmtd.types import NodeType

        return NodeType[self._flag(args, "--node-type", "storage").upper()]

    # -- FS shell ------------------------------------------------------------
    def cmd_ls(self, args: List[str]) -> str:
        path = args[0] if args else "/"
        ents = self.fab.meta.list_dir(path)
        return "\n".join(f"{e.type.name[:4].lower():<5} {e.name}" for e in ents)

    def cmd_mkdir(self, args: List[str]) -> str:
        recursive = "-p" in args
        path = [a for a in args if not a.startswith("-")][0]
        self.fab.meta.mkdirs(path, recursive=recursive)
        return f"created {path}"

    def cmd_stat(self, args: List[str]) -> str:
        inode = self.fab.meta.stat(args[0])
        kind = inode.type.name.lower()
        out = (
            f"{args[0]}: {kind} inode={inode.id} nlink={inode.nlink} "
            f"perm={oct(inode.acl.perm)} uid={inode.acl.uid} "
            f"length={inode.length}"
        )
        if inode.layout:
            out += (
                f"\nlayout: chains={inode.layout.chains} "
                f"chunk_size={inode.layout.chunk_size} seed={inode.layout.seed}"
            )
        return out

    def cmd_touch(self, args: List[str]) -> str:
        res = self.fab.meta.create(args[0], client_id="admin_cli")
        return f"created inode {res.inode.id}"

    def cmd_rm(self, args: List[str]) -> str:
        recursive = "-r" in args
        path = [a for a in args if not a.startswith("-")][0]
        self.fab.meta.remove(path, recursive=recursive)
        return f"removed {path}"

    def cmd_mv(self, args: List[str]) -> str:
        self.fab.meta.rename(args[0], args[1])
        return f"renamed {args[0]} -> {args[1]}"

    def cmd_truncate(self, args: List[str]) -> str:
        self.fab.meta.truncate(args[0], int(args[1]))
        return f"truncated {args[0]} to {args[1]}"

    def cmd_write(self, args: List[str]) -> str:
        path, text = args[0], args[1]
        res = self.fab.meta.create(path, flags=OpenFlags.WRITE,
                                   client_id="admin_cli")
        fio = self.fab.file_client()
        n = fio.write(res.inode, 0, text.encode())
        self.fab.meta.close(res.inode.id, res.session_id,
                            length_hint=n, wrote=True)
        return f"wrote {n} bytes"

    def cmd_read(self, args: List[str]) -> str:
        path = args[0]
        offset = int(self._flag(args, "--offset", 0))
        length = int(self._flag(args, "--length", 256))
        inode = self.fab.meta.stat(path)
        data = self.fab.file_client().read(inode, offset, length)
        try:
            return data.decode()
        except UnicodeDecodeError:
            return data.hex()

    def cmd_checksum(self, args: List[str]) -> str:
        inode = self.fab.meta.stat(args[0])
        data = self.fab.file_client().read(inode, 0, inode.length)
        return f"crc32c={crc32c(data):#010x} length={len(data)}"

    def cmd_stat_fs(self, args: List[str]) -> str:
        fs = self.fab.meta.stat_fs()
        return f"files={fs.files} used={fs.used}"

    def cmd_gc_run(self, args: List[str]) -> str:
        return f"gc reclaimed {self.fab.run_gc()} files"

    # -- namespace scans (ref src/meta/event/Scan.cc; DumpInodes admin cmds) -
    def cmd_scan_stats(self, args: List[str]) -> str:
        from tpu3fs.meta.scan import namespace_stats

        st = namespace_stats(self.fab.kv)
        return (f"files={st['files']} dirs={st['dirs']} "
                f"symlinks={st['symlinks']} bytes={st['total_length']}")

    def cmd_find_orphans(self, args: List[str]) -> str:
        from tpu3fs.meta.scan import find_orphan_inodes

        orphans = find_orphan_inodes(self.fab.kv)
        if not orphans:
            return "no orphan inodes"
        return "\n".join(f"inode {o.id} nlink={o.nlink}" for o in orphans)

    # -- users (ref src/core/user UserStore; admin_cli user commands) --------
    def _users(self):
        from tpu3fs.core.user import UserStore

        return UserStore(self.fab.kv)

    def cmd_user_add(self, args: List[str]) -> str:
        uid = int(args[0])
        has_name = len(args) > 1 and not args[1].startswith("-")
        name = args[1] if has_name else f"user{uid}"
        rec = self._users().add_user(
            uid, name,
            gid=int(self._flag(args, "--gid", uid)),
            admin="--admin" in args, root="--root" in args,
        )
        return f"user {rec.uid} ({rec.name}) token={rec.token}"

    def cmd_user_list(self, args: List[str]) -> str:
        rows = [
            f"{r.uid:<6} {r.name:<16} gid={r.gid} admin={r.admin} root={r.root}"
            for r in self._users().list_users()
        ]
        return "\n".join(rows) if rows else "(no users)"

    def cmd_user_remove(self, args: List[str]) -> str:
        ok = self._users().remove_user(int(args[0]))
        return "removed" if ok else "no such user"

    def cmd_user_rotate_token(self, args: List[str]) -> str:
        return f"new token: {self._users().rotate_token(int(args[0]))}"

    # -- trash (ref hf3fs_utils/trash.py + trash_cleaner) --------------------
    def cmd_trash_put(self, args: List[str]) -> str:
        from tpu3fs.utils import trash as _trash

        keep = int(self._flag(args, "--keep", 3 * 86400))
        dest = _trash.move_to_trash(self.fab.meta, args[0], keep_s=keep)
        return f"moved to {dest}"

    def cmd_trash_list(self, args: List[str]) -> str:
        from tpu3fs.utils import trash as _trash

        rows = [
            f"{e.path} orig={e.orig_name} expires={e.expire_ts}"
            for e in _trash.list_trash(self.fab.meta)
        ]
        return "\n".join(rows) if rows else "(trash empty)"

    def cmd_trash_clean(self, args: List[str]) -> str:
        from tpu3fs.utils import trash as _trash

        n = _trash.TrashCleaner(self.fab.meta).clean_once()
        self.fab.run_gc()
        return f"purged {n} expired entries"

    # -- migration (ref src/migration job control) ---------------------------
    def _migration(self):
        if self._migration_svc is None:
            from tpu3fs.migration import MigrationService

            self._migration_svc = MigrationService(self.fab.storage_client())
        return self._migration_svc

    # -- elasticity: placement planning / rebalance / drain ------------------
    def _topology_delta(self, args: List[str]):
        from tpu3fs.placement import TopologyDelta

        def ids(flag):
            raw = self._flag(args, flag)
            return [int(x) for x in raw.split(",")] if raw else []

        join, drain, dead = ids("--join"), ids("--drain"), ids("--dead")
        if join or drain or dead:
            return TopologyDelta(joined=join, draining=drain, dead=dead)
        return TopologyDelta.from_routing(self.fab.routing())

    @staticmethod
    def _render_plan(plan, delta) -> List[str]:
        lines = [
            f"delta: join={delta.joined} drain={delta.draining} "
            f"dead={delta.dead}",
            f"moves: {len(plan.moves)}"
            + (f" (+{len(plan.deferred_chains)} chains deferred to a "
               "later wave)" if plan.deferred_chains else ""),
        ]
        for mv in plan.moves:
            kind = "EC" if mv.is_ec else "CR"
            lines.append(
                f"  chain {mv.chain_id} [{kind}]: target {mv.out_target} "
                f"node {mv.src_node} -> node {mv.dst_node}")
        b, a = plan.before, plan.after
        lines.append(
            f"lambda: {b.lambda_max} -> {a.lambda_max} "
            f"(lower bound {a.lambda_lower_bound}); recovery traffic "
            f"factor {a.recovery_traffic_factor} => worst peer "
            f"{b.lambda_max * b.recovery_traffic_factor} -> "
            f"{a.lambda_max * a.recovery_traffic_factor} units")
        lines.append("chains/node after: " + " ".join(
            f"{n}:{c}" for n, c in sorted(plan.after.per_node.items())))
        return lines

    def cmd_placement_plan(self, args: List[str]) -> str:
        """Preview the incremental rebalance diff + predicted λ/traffic:
        placement-plan [--join N,..] [--drain N,..] [--dead N,..]
        (no flags = delta derived from routing tags/heartbeats)."""
        from tpu3fs.placement import check_plan, plan_rebalance

        delta = self._topology_delta(args)
        plan = plan_rebalance(self.fab.routing(), delta)
        lines = self._render_plan(plan, delta)
        problems = check_plan(self.fab.routing(), plan, delta)
        for p in problems:
            lines.append(f"QUORUM PROBLEM: {p}")
        return "\n".join(lines)

    def cmd_rebalance(self, args: List[str]) -> str:
        """Plan and (with --apply) submit migration jobs for the current
        topology delta: rebalance [--apply] [--join/--drain/--dead N,..]."""
        from tpu3fs.placement import check_plan, plan_rebalance

        delta = self._topology_delta(args)
        routing = self.fab.routing()
        plan = plan_rebalance(routing, delta)
        lines = self._render_plan(plan, delta)
        problems = check_plan(routing, plan, delta)
        if problems:
            return "\n".join(lines + [f"QUORUM PROBLEM: {p}"
                                      for p in problems]
                             + ["refused: plan violates quorum"])
        if plan.empty:
            return "\n".join(lines + ["nothing to do"])
        if "--apply" not in args:
            return "\n".join(lines + ["(preview; re-run with --apply)"])
        ids = self.fab.mgmtd.migration_submit(
            [mv.spec() for mv in plan.moves])
        return "\n".join(lines + [f"submitted jobs: {ids}"])

    def cmd_drain(self, args: List[str]) -> str:
        """Mark a node draining and plan its evacuation; --apply submits:
        drain --node N [--apply] [--undo]. Refuses when any chain would
        drop below its write-quorum (check_plan)."""
        from tpu3fs.placement import DRAINING_TAG

        node = int(self._flag(args, "--node"))
        if "--undo" in args:
            self.fab.mgmtd.set_node_tags(node, {DRAINING_TAG: ""})
            return f"node {node} draining flag cleared"
        self.fab.mgmtd.set_node_tags(node, {DRAINING_TAG: "1"})
        out = self.cmd_rebalance(args)
        if "--apply" not in args:
            # preview must not leave the drain armed
            self.fab.mgmtd.set_node_tags(node, {DRAINING_TAG: ""})
            return out
        if "submitted jobs" not in out:
            # refused (quorum) or undeliverable (no eligible destination
            # for some chain): do not leave a drain half-armed
            self.fab.mgmtd.set_node_tags(node, {DRAINING_TAG: ""})
            return out + f"\ndrain of node {node} refused, ROLLED BACK"
        return out

    def cmd_migrate_status(self, args: List[str]) -> str:
        """Cluster migration jobs from the mgmtd KV (crash-safe state)."""
        jobs = self.fab.mgmtd.migration_list()
        if not jobs:
            return "(no jobs)"
        lines = ["JOB  CHAIN    PHASE     OUT->NEW (node)      "
                 "COPIED              WORKER"]
        for j in jobs:
            from tpu3fs.migration import JobPhase

            lines.append(
                f"{j.job_id:<4} {j.chain_id:<8} "
                f"{JobPhase(j.phase).name:<9} "
                f"{j.out_target}->{j.new_target} (n{j.dst_node})"
                f"{'':<6} {j.copied_chunks} chunks/"
                f"{j.copied_bytes}B{'':<4} {j.worker}"
                + (f"  ERR={j.error}" if j.error else ""))
        return "\n".join(lines)

    def cmd_migrate_start(self, args: List[str]) -> str:
        svc = self._migration()
        job_id = svc.start_job(int(args[0]), int(args[1]))
        job = svc.run_job(job_id)
        return (f"job {job_id}: {job.state.name.lower()} "
                f"copied={job.copied}/{job.total}"
                + (f" error={job.error}" if job.error else ""))

    def cmd_migrate_list(self, args: List[str]) -> str:
        rows = [
            f"job {j.job_id}: {j.src_chain}->{j.dst_chain} "
            f"{j.state.name.lower()} {j.copied}/{j.total}"
            for j in self._migration().list_jobs()
        ]
        return "\n".join(rows) if rows else "(no jobs)"

    def cmd_migrate_stop(self, args: List[str]) -> str:
        ok = self._migration().stop_job(int(args[0]))
        return "stopped" if ok else "not running"

    # -- file-level bench (ref benchmarks/storage_bench) ---------------------
    def cmd_fs_bench(self, args: List[str]) -> str:
        num = int(self._flag(args, "--chunks", 16))
        size = int(self._flag(args, "--size", 1 << 16))
        fio = self.fab.file_client()
        res = self.fab.meta.create("/.bench", flags=OpenFlags.WRITE,
                                   client_id="bench")
        payload = bytes(size)
        t0 = time.perf_counter()
        for i in range(num):
            fio.write(res.inode, i * size, payload)
        w = time.perf_counter() - t0
        inode = self.fab.meta.close(res.inode.id, res.session_id)
        t0 = time.perf_counter()
        for i in range(num):
            fio.read(inode, i * size, size)
        r = time.perf_counter() - t0
        self.fab.meta.remove("/.bench")
        self.fab.run_gc()
        mb = num * size / 1e6
        return (
            f"write {mb / w:.1f} MB/s, read {mb / r:.1f} MB/s "
            f"({num} x {size}B chunks)"
        )

    # -- forensic dumps (ref DumpInodes/DumpDirEntries/DumpChunkMeta/
    # DumpChains/DumpChainTable/DumpSession in src/client/cli/admin/) ------
    def cmd_dump_inodes(self, args: List[str]) -> str:
        """dump-inodes FILE: JSONL of EVERY inode record, straight off the
        KV scan (ref DumpInodes.cc) — includes unlinked-but-open and
        orphaned inodes a path walk would miss, which is the point of a
        forensic dump."""
        import json as _json

        from tpu3fs.meta.scan import scan_inodes

        n = 0
        with open(args[0], "w") as f:
            for ino in scan_inodes(self.fab.kv):
                f.write(_json.dumps({
                    "id": ino.id, "type": ino.type.name,
                    "parent": ino.parent,
                    "length": getattr(ino, "length", 0),
                    "nlink": ino.nlink, "uid": ino.acl.uid,
                    "gid": ino.acl.gid, "perm": ino.acl.perm,
                    "mtime": ino.mtime, "ctime": ino.ctime,
                }) + "\n")
                n += 1
        return f"dumped {n} inodes to {args[0]}"

    def cmd_dump_dentries(self, args: List[str]) -> str:
        """dump-dentries FILE: JSONL of every directory-entry record,
        straight off the KV scan (ref DumpDirEntries.cc)."""
        import json as _json

        from tpu3fs.meta.scan import scan_dirents

        n = 0
        with open(args[0], "w") as f:
            for ent in scan_dirents(self.fab.kv):
                f.write(_json.dumps({
                    "parent_id": ent.parent, "name": ent.name,
                    "inode_id": ent.inode_id, "type": ent.type.name,
                }) + "\n")
                n += 1
        return f"dumped {n} dentries to {args[0]}"

    def cmd_dump_chunkmeta(self, args: List[str]) -> str:
        """dump-chunkmeta TARGET_ID FILE: JSONL chunk metadata of one
        storage target (ref DumpChunkMeta.cc)."""
        import json as _json

        target_id, out_path = int(args[0]), args[1]
        routing = self.fab.routing()
        node = routing.node_of_target(target_id)
        if node is None:
            return f"target {target_id} not in routing"
        metas = self.fab.send(node.node_id, "dump_chunkmeta", target_id)
        with open(out_path, "w") as f:
            for m in metas:
                f.write(_json.dumps({
                    "chunk": [m.chunk_id.file_id, m.chunk_id.index],
                    "committed_ver": m.committed_ver,
                    "pending_ver": m.pending_ver,
                    "chain_ver": m.chain_ver, "length": m.length,
                    "crc": m.checksum.value,
                }) + "\n")
        return f"dumped {len(metas)} chunk metas to {out_path}"

    def cmd_dump_chains(self, args: List[str]) -> str:
        """dump-chains FILE: routing chain snapshot (ref DumpChains.cc)."""
        import json as _json

        routing = self.fab.routing()
        blob = {
            str(cid): {
                "version": c.chain_version,
                "ec": [c.ec_k, c.ec_m] if c.is_ec else None,
                "targets": [[t.target_id, t.public_state.name]
                            for t in c.targets],
            } for cid, c in sorted(routing.chains.items())
        }
        with open(args[0], "w") as f:
            _json.dump(blob, f, indent=1)
        return f"dumped {len(blob)} chains to {args[0]}"

    def cmd_dump_chain_table(self, args: List[str]) -> str:
        """dump-chain-table FILE [TABLE_ID] (ref DumpChainTable.cc)."""
        import json as _json

        routing = self.fab.routing()
        tables = routing.chain_tables
        want = int(args[1]) if len(args) > 1 else None
        blob = {str(tid): {"version": t.version, "chains": list(t.chain_ids)}
                for tid, t in tables.items()
                if want is None or tid == want}
        with open(args[0], "w") as f:
            _json.dump(blob, f, indent=1)
        return f"dumped {len(blob)} chain tables to {args[0]}"

    def cmd_dump_sessions(self, args: List[str]) -> str:
        """dump-sessions [FILE]: live file write sessions
        (ref DumpSession.cc)."""
        import json as _json

        rows = [{"inode": s.inode_id, "client": s.client_id,
                 "session": s.session_id}
                for s in self.fab.meta.list_sessions()]
        if args:
            with open(args[0], "w") as f:
                for r in rows:
                    f.write(_json.dumps(r) + "\n")
            return f"dumped {len(rows)} sessions to {args[0]}"
        return "\n".join(
            f"inode={r['inode']} client={r['client']} "
            f"session={r['session']}" for r in rows) or "(none)"

    def cmd_list_clients(self, args: List[str]) -> str:
        """Distinct client ids holding write sessions
        (ref ListClients.cc)."""
        clients = sorted({s.client_id
                          for s in self.fab.meta.list_sessions()})
        return "\n".join(clients) or "(none)"

    def cmd_list_gc(self, args: List[str]) -> str:
        """Pending GC queue entries (ref ListGc.cc)."""
        limit = int(args[0]) if args else 64
        inodes = self.fab.meta.gc_scan(limit=limit)
        return "\n".join(
            f"inode={i.id} length={getattr(i, 'length', 0)}"
            for i in inodes) or "(empty)"

    def cmd_get_real_path(self, args: List[str]) -> str:
        """Resolve symlinks to the canonical path
        (ref GetRealPath.cc)."""
        return self.fab.meta.get_real_path(args[0])

    def cmd_decode_user_token(self, args: List[str]) -> str:
        """Resolve a bearer token to its user record
        (ref DecodeUserToken.cc)."""
        rec = self._users().authenticate(args[0])
        if rec is None:
            return "invalid token"
        return (f"uid={rec.uid} name={rec.name} gid={rec.gid} "
                f"groups={rec.groups} admin={rec.admin} root={rec.root}")

    def cmd_fill_zero(self, args: List[str]) -> str:
        """fill-zero PATH BYTES: materialize zeros (ref FillZero.cc)."""
        path, nbytes = args[0], int(args[1])
        res = self.fab.meta.create(path, flags=OpenFlags.WRITE,
                                   client_id="cli")
        fio = self.fab.file_client()
        step = 1 << 20
        for off in range(0, nbytes, step):
            fio.write(res.inode, off, b"\x00" * min(step, nbytes - off))
        self.fab.meta.close(res.inode.id, client_id="cli",
                            session_id=res.session_id)
        return f"filled {nbytes} zero bytes into {path}"

    def cmd_create_range(self, args: List[str]) -> str:
        """create-range PREFIX N: create N empty files
        (ref CreateRange.cc)."""
        prefix, n = args[0], int(args[1])
        for i in range(n):
            res = self.fab.meta.create(f"{prefix}{i}", client_id="cli")
            self.fab.meta.close(res.inode.id, client_id="cli",
                                session_id=res.session_id)
        return f"created {n} files at {prefix}0..{prefix}{n - 1}"

    # -- checkpoints (tpu3fs/ckpt) -------------------------------------------
    def _ckpt(self, args: List[str]):
        from tpu3fs.ckpt import CheckpointManager

        root = self._flag(args, "--root", "/ckpt")
        return CheckpointManager(self.fab.meta, self.fab.file_client(),
                                 root=root, client_id="admin_cli")

    def cmd_ckpt_list(self, args: List[str]) -> str:
        """ckpt-list [--root /ckpt]: committed steps (+ staging dirs)."""
        from tpu3fs.ckpt.manifest import parse_staging

        mgr = self._ckpt(args)
        lines = ["STEP      FILES  BYTES       CREATED"]
        for step in mgr.steps():
            try:
                m = mgr.manifest(step)
                lines.append(f"{step:<9} {len(m.shards) + 1:<6} "
                             f"{m.total_bytes():<11} {m.created:.0f}")
            except FsError as e:
                lines.append(f"{step:<9} ?      ?           ({e.status})")
        try:
            staging = [
                e.name for e in self.fab.meta.list_dir(mgr.root)
                if parse_staging(e.name) is not None
            ]
        except FsError:
            staging = []
        if staging:
            lines.append("staging (crashed saves, swept by ckpt GC): "
                         + " ".join(sorted(staging)))
        return "\n".join(lines) if len(lines) > 1 or staging \
            else "(no checkpoints)"

    def cmd_ckpt_inspect(self, args: List[str]) -> str:
        """ckpt-inspect STEP [--root /ckpt]: manifest summary."""
        step = int([a for a in args if not a.startswith("-")][0])
        mgr = self._ckpt(args)
        m = mgr.manifest(step)
        lines = [
            f"step {m.step}: {len(m.leaves)} leaves, {len(m.shards)} shards,"
            f" {m.total_bytes()} bytes, created {m.created:.0f}",
        ]
        if m.mesh:
            lines.append("mesh: " + " ".join(
                f"{k}={v}" for k, v in m.mesh.items()))
        for i, leaf in enumerate(m.leaves):
            nsh = len(m.shards_of_leaf(i))
            spec = ",".join(s or "." for s in leaf.spec) or "-"
            lines.append(f"  {leaf.key or '<root>'}: {leaf.dtype} "
                         f"{tuple(leaf.shape)} sharded[{spec}] x{nsh}")
        return "\n".join(lines)

    # -- training data loader (tpu3fs/dataload) ------------------------------
    def cmd_dataload_pack(self, args: List[str]) -> str:
        """dataload-pack OUT LOCAL_FILE... [--from-dir DIR]: pack local
        sample files into a packed record file (one record per file)."""
        import argparse as _argparse

        from tpu3fs.bin.dataload_pack_main import run as _pack_run

        from_dir = self._flag(args, "--from-dir", "")
        rest = []
        skip = False
        for i, a in enumerate(args):
            if skip:
                skip = False
                continue
            if a == "--from-dir":
                skip = True
                continue
            rest.append(a)
        if not rest:
            return "usage: dataload-pack OUT LOCAL_FILE... [--from-dir DIR]"
        ns = _argparse.Namespace(out=rest[0], files=rest[1:],
                                 from_dir=from_dir, inspect="")
        import io as _io

        buf = _io.StringIO()
        rc = _pack_run(self.fab, ns, out=buf)
        return buf.getvalue().strip() if rc == 0 else f"pack failed ({rc})"

    def cmd_dataload_inspect(self, args: List[str]) -> str:
        """dataload-inspect PATH [--records N]: packed-file summary (+
        the first N record extents/CRCs)."""
        from tpu3fs.dataload.recordio import RecordFile

        path = [a for a in args if not a.startswith("-")][0]
        show = int(self._flag(args, "--records", 0))
        rf = RecordFile.open(self.fab.meta, self.fab.file_client(), path)
        s = rf.summary()
        lines = [
            f"{path}: {s['records']} records, {s['payload_bytes']} payload "
            f"bytes ({s['file_bytes']} on disk), record size "
            f"{s['min_record']}..{s['max_record']}"
        ]
        for i in range(min(show, rf.num_records)):
            off, n = rf.extent(i)
            lines.append(f"  [{i}] offset={off} length={n} "
                         f"crc={rf.record_crc(i):#010x}")
        return "\n".join(lines)

    # -- inference KV cache (tpu3fs/kvcache) ---------------------------------
    def cmd_kvcache_stats(self, args: List[str]) -> str:
        """kvcache-stats [--root /kvcache]: fs-tier entries, bytes, lease
        count, oldest/newest touch ages — the capacity-planning view."""
        from tpu3fs.kvcache import KVCacheGC

        root = self._flag(args, "--root", "/kvcache")
        gc = KVCacheGC(self.fab.meta, root=root)
        now = time.time()
        entries = gc.scan_entries(now)
        if not entries:
            return f"{root}: empty"
        total = sum(length for _, length, _, _ in entries)
        leased = sum(1 for _, _, is_leased, _ in entries if is_leased)
        oldest = min(mtime for mtime, _, _, _ in entries)
        newest = max(mtime for mtime, _, _, _ in entries)
        return (f"{root}: entries={len(entries)} bytes={total} "
                f"leased={leased} oldest_age_s={now - oldest:.0f} "
                f"newest_age_s={now - newest:.0f}")

    def cmd_kvcache_gc(self, args: List[str]) -> str:
        """kvcache-gc [--root /kvcache] [--ttl S] [--capacity-bytes N]
        [--max-shards N]: one GC pass — TTL scan, then capacity-target
        LRU eviction when a bytes budget is given. Lease-pinned entries
        survive both."""
        from tpu3fs.kvcache import KVCacheGC

        cap = self._flag(args, "--capacity-bytes")
        gc = KVCacheGC(
            self.fab.meta,
            root=self._flag(args, "--root", "/kvcache"),
            ttl_s=float(self._flag(args, "--ttl", 3600.0)),
            max_shards=int(self._flag(args, "--max-shards", 64)),
            capacity_bytes=int(cap) if cap is not None else None,
        )
        ttl_removed = gc.run_once()
        cap_removed = gc.capacity_pass()
        run_gc = getattr(self.fab, "run_gc", None)
        if run_gc is not None:  # live clusters reclaim via the meta GC scan
            run_gc()
        out = f"ttl pass removed {ttl_removed}"
        if cap is not None:
            out += f"; capacity pass removed {cap_removed}"
        return out

    def cmd_serving(self, args: List[str]) -> str:
        """serving [--stats]: the mgmtd serving directory (fleet KVCache
        peer endpoints, docs/serving.md); --stats also calls each live
        endpoint's servingStats — host-tier residency + the peer-fill
        protocol's outcome counters."""
        ri = self.fab.routing()
        serving = getattr(ri, "serving", {}) or {}
        if not serving:
            return "serving directory: empty"
        lines = [f"serving directory ({len(serving)} endpoints, "
                 f"routing v{ri.version}):"]
        stats = "--stats" in args
        peers = None
        if stats:
            from tpu3fs.rpc.net import RpcClient
            from tpu3fs.serving.service import ServingPeerClient

            peers = ServingPeerClient(RpcClient(), usrbio=False)
        for node_id, ep in sorted(serving.items()):
            line = (f"  node {node_id:<5} {ep.host}:{ep.port} "
                    f"ttl={ep.ttl_s:.0f}s")
            if peers is not None:
                try:
                    s = peers.stats(ep)
                    line += (f" host={s.host_entries}e/{s.host_bytes}B "
                             f"peer_hits={s.peer_hits} "
                             f"peer_misses={s.peer_misses} "
                             f"storage_fills={s.storage_fills} "
                             f"coalesced={s.coalesced} "
                             f"demotions={s.demotions} stale={s.stale_detected}")
                except FsError as e:
                    line += f" unreachable ({e.code.name})"
            lines.append(line)
        return "\n".join(lines)

    def cmd_ckpt_rm(self, args: List[str]) -> str:
        """ckpt-rm STEP [--root /ckpt] [--keep SECONDS]: evict one step
        through the trash subsystem (recoverable until expiry)."""
        step = int([a for a in args if not a.startswith("-")][0])
        mgr = self._ckpt(args)
        mgr.gc.trash_keep_s = int(self._flag(args, "--keep",
                                             mgr.gc.trash_keep_s))
        mgr.remove(step)
        return f"step {step} moved to trash"



class RpcFabricView:
    """Live-cluster adapter for AdminCli: exposes the same .mgmtd / .meta /
    .routing() / .file_client() / .storage_client() surfaces as the
    in-process Fabric, backed by RPC clients — the admin_cli connects to a
    running cluster exactly like the reference's (ForAdmin/ForClient mgmtd
    role split, src/client/mgmtd/MgmtdClient.cc)."""

    def __init__(self, mgmtd_addr, token: str = "", client_id: str = "admin"):
        import itertools
        import uuid

        from tpu3fs.client.file_io import FileIoClient
        from tpu3fs.client.storage_client import StorageClient
        from tpu3fs.mgmtd.types import NodeType
        from tpu3fs.rpc.net import RpcClient
        from tpu3fs.rpc.services import (
            MetaRpcClient,
            MgmtdAdminRpcClient,
            RpcMessenger,
        )

        self._rpc = RpcClient()
        self._client_id = client_id
        # storage clients need UNIQUE wire ids (like Fabric's client-N):
        # the server's exactly-once channel table is keyed (client id,
        # channel, seq) — two client INSTANCES sharing one id restart
        # their channel seqs and the server silently dedupes the second
        # client's writes as replays (found by the live dataload drive:
        # a fresh client's 9-byte state write "succeeded" without
        # landing). The uuid part keeps two operator PROCESSES with the
        # same client_id apart as well.
        self._storage_id_base = f"{client_id}-{uuid.uuid4().hex[:8]}"
        self._storage_seq = itertools.count(1)
        self.mgmtd = MgmtdAdminRpcClient(mgmtd_addr, self._rpc)
        self._messenger = RpcMessenger(self.mgmtd.refresh_routing, self._rpc)
        self._StorageClient = StorageClient
        self._FileIoClient = FileIoClient
        meta_addrs = [
            (n.host, n.port)
            for n in self.routing().nodes.values()
            if n.type == NodeType.META and n.host
        ]
        self.meta = (
            MetaRpcClient(meta_addrs, self._rpc,
                          client_id=client_id, token=token)
            if meta_addrs else None
        )

    def routing(self):
        return self.mgmtd.refresh_routing()

    def tick(self) -> None:
        self.mgmtd.tick()

    def send(self, node_id: int, method: str, payload):
        """Storage-node RPC by node id (the Fabric.send signature), for
        maintenance sweeps like verify-checksums / find-orphaned-chunks."""
        return self._messenger(node_id, method, payload)

    def storage_client(self, **kw):
        return self._StorageClient(
            f"{self._storage_id_base}-{next(self._storage_seq)}",
            self.mgmtd.refresh_routing, self._messenger, **kw)

    def file_client(self, **kw):
        return self._FileIoClient(self.storage_client(**kw))


def main(argv: Optional[List[str]] = None) -> int:  # pragma: no cover
    """One-shot or REPL — against a fresh local fabric (dev mode) or a live
    cluster via --connect HOST:PORT (operator mode)."""
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "--connect":
        usage = "usage: cli --connect HOST:PORT [--token TOKEN] [command...]"
        try:
            host, port_s = argv[1].rsplit(":", 1)
            port = int(port_s)
            token = ""
            rest = argv[2:]
            if rest[:1] == ["--token"]:
                token, rest = rest[1], rest[2:]
        except (IndexError, ValueError):
            print(usage, file=sys.stderr)
            return 2
        cli = AdminCli(RpcFabricView((host, port), token=token))
        argv = rest
    else:
        from tpu3fs.fabric import Fabric

        cli = AdminCli(Fabric())
    if argv:
        print(cli.run(" ".join(argv)))
        return 0
    cli.repl()
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
