"""On-disk layout shared by every kvcache module: shard paths, the array
wire format, the lease xattr.

One module owns the formats so the fs tier (cache.py), the host tier
(tier.py), the prefix-block store (blocks.py), the lease manager
(leases.py) and the GC can never drift apart on what an entry looks
like.
"""

from __future__ import annotations

import hashlib
import struct
import time
from typing import Optional, Tuple

import numpy as np

from tpu3fs.utils.result import Code
from tpu3fs.utils.result import err as _err

_HEADER = struct.Struct("<8sII")  # dtype name, ndim, magic
#: Array-header magic. Its real job is STALENESS detection for cached
#: inodes: a content-addressed entry GC'd out from under a client-side
#: inode cache reads back as all zeros (removed chunks are holes), which
#: fails the magic check deterministically — the reader invalidates and
#: re-stats instead of serving zeros as KV state.
ARRAY_MAGIC = 0x4B564131  # "KVA1"
_DIM = struct.Struct("<Q")

#: xattr carrying a pin lease: b"<expire_ts> <owner>". GC skips entries
#: whose lease has not expired — an active decode can never lose its
#: prefix blocks to TTL or capacity eviction underneath it.
LEASE_XATTR = "kvcache.lease"


def shard_path(root: str, key: str) -> str:
    """Entry path: two hex levels (256x256 dirs) keep listings short at
    billions of entries."""
    h = hashlib.blake2b(key.encode(), digest_size=16).hexdigest()
    return f"{root}/{h[:2]}/{h[2:4]}/{h}"


# -- array wire format (decoder-layer KV tensors) ----------------------------

def encode_array(array) -> bytes:
    """dtype+shape header then raw bytes: zero parsing beyond a 16-byte
    prefix, so inference servers can device_put the payload directly."""
    arr = np.asarray(array)
    name = arr.dtype.str.encode().ljust(8, b"\0")
    header = _HEADER.pack(name, arr.ndim, ARRAY_MAGIC)
    dims = b"".join(_DIM.pack(d) for d in arr.shape)
    return header + dims + arr.tobytes()


def decode_array(raw) -> np.ndarray:
    """Inverse of encode_array; `raw` may be a zero-copy transport view
    (the result is a frombuffer VIEW over it, no payload copy). Raises
    KVCACHE_STALE on an all-hole read (see ARRAY_MAGIC), KVCACHE_CORRUPT
    on any other malformed header."""
    if len(raw) < _HEADER.size:
        raise _err(Code.KVCACHE_CORRUPT, f"{len(raw)}-byte array entry")
    name, ndim, magic = _HEADER.unpack_from(raw, 0)
    if magic != ARRAY_MAGIC:
        if magic == 0 and name == b"\0" * 8:
            raise _err(Code.KVCACHE_STALE, "zero-hole read (entry GC'd)")
        raise _err(Code.KVCACHE_CORRUPT, f"bad magic {magic:#x}")
    off = _HEADER.size
    shape = tuple(
        _DIM.unpack_from(raw, off + i * _DIM.size)[0] for i in range(ndim)
    )
    off += ndim * _DIM.size
    try:
        dtype = np.dtype(name.rstrip(b"\0").decode())
    except (TypeError, UnicodeDecodeError) as e:
        raise _err(Code.KVCACHE_CORRUPT, f"dtype {name!r}: {e!r}")
    return np.frombuffer(raw, dtype=dtype, offset=off).reshape(shape)


def zero_hole(raw) -> bool:
    """True when a read came back as the all-zero hole a GC'd entry
    leaves behind — the cheap staleness probe for servers that relay raw
    entry bytes without decoding them (the serving host's serve-through
    path validates HERE before shipping to a peer: serving zeros-as-KV
    across the fleet is the one unforgivable outcome)."""
    if len(raw) < _HEADER.size:
        return False
    name, _, magic = _HEADER.unpack_from(raw, 0)
    return magic == 0 and name == b"\0" * 8


# -- lease encoding ----------------------------------------------------------

def encode_lease(expire_ts: float, owner: str) -> bytes:
    # repr round-trips exactly: unpin compares the decoded expiry against
    # the lease handle's to tell its own pin from a longer one stacked on
    # the same (content-addressed) entry
    return f"{expire_ts!r} {owner}".encode()


def decode_lease(raw: bytes) -> Tuple[float, str]:
    """-> (expire_ts, owner); a malformed value reads as expired."""
    try:
        ts_s, _, owner = bytes(raw).decode().partition(" ")
        return float(ts_s), owner
    except (ValueError, UnicodeDecodeError):
        return 0.0, ""


def lease_active(inode, now: Optional[float] = None) -> bool:
    """Whether an entry inode carries an unexpired pin lease. The lease
    rides the inode's xattrs, so every GC stat() already has it — the
    check costs no extra metadata round trip."""
    raw = getattr(inode, "xattrs", {}).get(LEASE_XATTR)
    if raw is None:
        return False
    expire_ts, _ = decode_lease(raw)
    return expire_ts > (time.time() if now is None else now)
