"""tpu3fs/kvcache — the inference KV-cache serving tier.

The third headline workload of the reference (README.md:17,45-51 — KV
tensors of previous tokens cached in files, ~40 GiB/s cached-KV reads,
GC remove-op IOPS), grown into a serving subsystem:

- ``cache``  — the durable fs tier: sharded entry namespace, striped
  batched gets, BATCHED touch-on-get LRU refresh, and a GC with TTL
  scans + capacity-target LRU eviction (lease-respecting)
- ``tier``   — bounded host-RAM hot tier (LRU) + write-back dirty buffer
  with a background flusher; host hits never touch the wire
- ``blocks`` — content-addressed prefix-block store: KV pages keyed by a
  rolling prefix-hash chain, so shared prompt prefixes dedupe to shared
  fs entries; ``match_prefix`` longest-prefix lookup, device-ready
  ``get_blocks``
- ``leases`` — pin/unpin xattr leases: active decodes are never GC'd
  out from under themselves
- ``layout`` — the shared on-disk formats (shard paths, array codec,
  lease encoding)

All IO rides the ``kvcache`` QoS class (foreground-weighted,
share-bounded). Driven by ``admin_cli kvcache-stats|kvcache-gc`` and
``benchmarks/kvcache_bench.py``; docs/kvcache.md has the contracts.
"""

from tpu3fs.kvcache.blocks import (  # noqa: F401
    PrefixBlockStore,
    PrefixMatch,
    chain_keys,
)
from tpu3fs.kvcache.cache import KVCacheClient, KVCacheGC  # noqa: F401
from tpu3fs.kvcache.layout import (  # noqa: F401
    decode_array,
    encode_array,
    shard_path,
)
from tpu3fs.kvcache.leases import Lease, LeaseManager  # noqa: F401
from tpu3fs.kvcache.tier import HostTier, TieredKVCache  # noqa: F401

__all__ = [
    "HostTier",
    "KVCacheClient",
    "KVCacheGC",
    "Lease",
    "LeaseManager",
    "PrefixBlockStore",
    "PrefixMatch",
    "TieredKVCache",
    "chain_keys",
    "decode_array",
    "encode_array",
    "shard_path",
]
