from tpu3fs.kvcache.cache import KVCacheClient, KVCacheGC  # noqa: F401
