"""Content-addressed prefix-block store: prompt-prefix KV dedup.

Decoder KV tensors are cached as fixed-size TOKEN-BLOCK pages keyed by a
rolling prefix-hash chain:

    key[0] = H(root, tokens[0:B])
    key[i] = H(key[i-1], tokens[i*B:(i+1)*B])

A block's key therefore commits to the ENTIRE token prefix up to and
including it — two requests sharing a prompt prefix derive the same chain
of keys and dedupe to the same fs entries (vLLM-style prefix caching, but
the page table is the filesystem namespace: nothing to synchronize
between inference processes). Divergent suffixes fork the chain at the
first differing block; partial trailing blocks are never stored (their
tokens recompute in one step's prefill).

Because keys are content-addressed, entries are IMMUTABLE: the host tier
(tier.py) can cache them forever without staleness, a double store is
idempotent, and ``match_prefix`` is pure presence-probing — one batched
stat for the whole chain, then the longest present prefix.

``get_blocks`` returns device-ready arrays: the fs bytes decode as
zero-copy views (layout.decode_array) and ``device=`` hands each block to
``jax.device_put`` so a serving loop can feed attention kernels directly.
"""

from __future__ import annotations

import hashlib
import struct
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from tpu3fs.kvcache.layout import decode_array, encode_array
from tpu3fs.utils.result import Code, FsError
from tpu3fs.utils.result import err as _err

_TOKEN = struct.Struct("<q")
_ROOT = b"tpu3fs-kvblock-v1"


def _digest(parent: bytes, token_ids: Sequence[int]) -> bytes:
    h = hashlib.blake2b(parent, digest_size=16)
    for t in token_ids:
        h.update(_TOKEN.pack(t))
    return h.digest()


def chain_keys(token_ids: Sequence[int], block_tokens: int,
               *, salt: bytes = b"") -> List[str]:
    """Keys of every FULL block of the sequence, in chain order. The
    trailing ``len % block_tokens`` tokens have no key (never stored)."""
    if block_tokens <= 0:
        raise _err(Code.INVALID_ARG, f"block_tokens {block_tokens}")
    parent = _ROOT + salt
    keys: List[str] = []
    for lo in range(0, len(token_ids) - block_tokens + 1, block_tokens):
        parent = _digest(parent, token_ids[lo:lo + block_tokens])
        keys.append(parent.hex())
    return keys


@dataclass
class PrefixMatch:
    """Longest stored prefix of a token sequence."""

    tokens: int = 0                       # matched token count (blocks*B)
    blocks: int = 0                       # matched full blocks
    keys: List[str] = field(default_factory=list)   # their chain keys


class PrefixBlockStore:
    """Prefix-hash-chained KV block pages over any cache with the
    get/put/batch surface (``KVCacheClient`` or ``TieredKVCache``)."""

    def __init__(self, cache, *, block_tokens: int = 16,
                 salt: bytes = b"", leases=None):
        if block_tokens <= 0:
            raise _err(Code.INVALID_ARG, f"block_tokens {block_tokens}")
        self._cache = cache
        self.block_tokens = block_tokens
        self._salt = salt
        self._leases = leases

    @property
    def cache(self):
        return self._cache

    def block_keys(self, token_ids: Sequence[int]) -> List[str]:
        return chain_keys(token_ids, self.block_tokens, salt=self._salt)

    # -- lookup -------------------------------------------------------------
    def match_prefix(self, token_ids: Sequence[int]) -> PrefixMatch:
        """Longest-prefix lookup: ONE batched presence probe over the
        whole chain, then the longest run of present blocks from the
        start. (A mid-chain hole ends the match — later blocks' KV
        depends on the missing tokens' positions being resident.)"""
        keys = self.block_keys(token_ids)
        if not keys:
            return PrefixMatch()
        present = self._cache.batch_contains(keys)
        n = 0
        for hit in present:
            if not hit:
                break
            n += 1
        return PrefixMatch(tokens=n * self.block_tokens, blocks=n,
                           keys=keys[:n])

    # -- writes -------------------------------------------------------------
    def append_blocks(self, token_ids: Sequence[int], kv_blocks,
                      *, start_block: int = 0,
                      write_through: Optional[bool] = None) -> int:
        """Store per-block KV arrays for blocks [start_block,
        start_block + len(kv_blocks)) of the sequence; returns blocks
        actually WRITTEN. Already-present keys are skipped (one batched
        probe), so two sessions extending a shared prefix store each
        shared block exactly once — content addressing makes the racy
        double-store idempotent anyway (same key, same bytes)."""
        keys = self.block_keys(token_ids)
        want = keys[start_block:start_block + len(kv_blocks)]
        if len(want) != len(kv_blocks):
            raise _err(Code.INVALID_ARG,
                       f"{len(kv_blocks)} blocks at {start_block} but the "
                       f"sequence only chains {len(keys)} full blocks")
        present = self._cache.batch_contains(want)
        items = [(key, encode_array(arr))
                 for key, arr, hit in zip(want, kv_blocks, present)
                 if not hit]
        if not items:
            return 0
        # drain as ONE batched put (KVCacheClient.batch_put: one
        # batch_create + one striped batch write + one batch_close for
        # the whole drain) — the last per-block serial-create path
        # (ROADMAP carried follow-up; regression-pinned in
        # tests/test_kvcache.py)
        batched = getattr(self._cache, "batch_put", None)
        if batched is not None and len(items) > 1:
            if write_through is None:
                batched(items)
            else:
                try:
                    batched(items, write_through=write_through)
                except TypeError:  # fs-tier cache: always through
                    batched(items)
        else:
            for key, raw in items:
                if write_through is None:
                    self._cache.put(key, raw)
                else:
                    self._cache.put(key, raw, write_through=write_through)
        return len(items)

    # -- reads --------------------------------------------------------------
    def get_blocks(self, token_ids: Sequence[int], *,
                   count: Optional[int] = None, device=None) -> List:
        """Fetch the sequence's first `count` blocks (default: every full
        block) as arrays — host-tier hits from RAM, all misses as ONE
        striped batch underneath. Missing blocks come back as None (the
        caller re-prefills that suffix). With ``device=``, each block is
        handed off via ``jax.device_put``."""
        keys = self.block_keys(token_ids)
        if count is not None:
            keys = keys[:count]
        blobs = self._cache.batch_get(keys)
        out: List = [None] * len(blobs)
        for i, raw in enumerate(blobs):
            if raw is None:
                continue
            arr = self._decode(keys[i], raw)  # zero-copy view or None
            if arr is not None and device is not None:
                import jax

                arr = jax.device_put(arr, device)
            out[i] = arr
        return out

    def _decode(self, key: str, raw):
        """Decode one block; a KVCACHE_STALE read (cached inode outlived
        a GC'd entry — zero-hole payload) invalidates and re-probes ONCE
        so the caller sees a plain miss, never zeros-as-KV."""
        try:
            return decode_array(raw)
        except FsError as e:
            if e.code != Code.KVCACHE_STALE:
                raise
        invalidate = getattr(self._cache, "invalidate", None)
        if invalidate is None:
            return None
        invalidate(key)
        raw = self._cache.get(key)
        if raw is None:
            return None
        return decode_array(raw)

    # -- leases -------------------------------------------------------------
    def pin_prefix(self, match: PrefixMatch, ttl_s: Optional[float] = None):
        """Pin a matched prefix's blocks for the decode's lifetime (needs
        a LeaseManager wired at construction)."""
        if self._leases is None:
            raise _err(Code.INVALID_ARG,
                       "PrefixBlockStore built without a LeaseManager")
        return self._leases.pin(match.keys, ttl_s)
