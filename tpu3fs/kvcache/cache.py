"""KVCache for LLM inference over the cluster (ref README.md:17,45-51).

The reference positions 3FS as a DRAM-alternative KV cache: decoder-layer
key/value tensors of previous tokens are cached in files, read back at up to
40 GiB/s, and reclaimed by a GC whose remove-op IOPS the README charts. The
reference implements this as a usage pattern over the normal file API — so
does this build, as a typed client:

- entries live under a cache root, sharded two hex levels deep (256×256
  dirs) so directory listings stay short at billions of entries;
- put() writes value bytes through the striped chunk path and closes with
  the write session so lengths settle;
- get()/batch_get() are chunk-batched reads (batch_read groups chunk IOs by
  node exactly like the training data loaders do);
- touch-on-get refreshes an entry's mtime so the TTL GC is an LRU;
- KVCacheGC scans shards round-robin and removes expired entries — the
  remove-op counter mirrors the README's GC IOPS chart.

JAX arrays ride along via put_array/get_array (dtype+shape header, zero
parsing beyond a 16-byte prefix) so inference servers can device_put the
result straight onto a TPU.
"""

from __future__ import annotations

import hashlib
import struct
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from tpu3fs.client.file_io import FileIoClient
from tpu3fs.meta.store import MetaStore, OpenFlags
from tpu3fs.monitor.recorder import CounterRecorder, LatencyRecorder
from tpu3fs.utils.result import Code, FsError

_HEADER = struct.Struct("<8sII")  # dtype name, ndim, reserved
_MAGIC_DIMS = struct.Struct("<Q")


def _shard_path(root: str, key: str) -> str:
    h = hashlib.blake2b(key.encode(), digest_size=16).hexdigest()
    return f"{root}/{h[:2]}/{h[2:4]}/{h}"


class KVCacheClient:
    """Typed cache surface over (MetaStore, FileIoClient)."""

    def __init__(
        self,
        meta: MetaStore,
        fio: FileIoClient,
        *,
        root: str = "/kvcache",
        client_id: str = "kvcache",
        touch_on_get: bool = True,
    ):
        self._meta = meta
        self._fio = fio
        self.root = root.rstrip("/") or "/kvcache"
        self._client_id = client_id
        self._touch = touch_on_get
        self._dir_lock = threading.Lock()
        self._dirs_made: set = set()
        self._hits = CounterRecorder("kvcache.hits")
        self._misses = CounterRecorder("kvcache.misses")
        self._read_bytes = CounterRecorder("kvcache.read_bytes")
        self._write_bytes = CounterRecorder("kvcache.write_bytes")
        self._get_rec = LatencyRecorder("kvcache.get")
        self._put_rec = LatencyRecorder("kvcache.put")

    # -- plumbing -----------------------------------------------------------
    def _ensure_dir(self, path: str) -> None:
        parent = path.rsplit("/", 1)[0]
        with self._dir_lock:
            if parent in self._dirs_made:
                return
        try:
            self._meta.mkdirs(parent, recursive=True)
        except FsError as e:
            if e.code != Code.META_EXISTS:
                raise
        with self._dir_lock:
            self._dirs_made.add(parent)

    # -- byte API -----------------------------------------------------------
    def put(self, key: str, value: bytes) -> None:
        with self._put_rec.record():
            path = _shard_path(self.root, key)
            self._ensure_dir(path)
            res = self._meta.create(
                path, flags=OpenFlags.WRITE | OpenFlags.CREATE
                | OpenFlags.TRUNC,
                client_id=self._client_id,
            )
            try:
                n = self._fio.write(res.inode, 0, value)
            except BaseException:
                # failed write must not leak the open write session
                try:
                    self._meta.close(res.inode.id, res.session_id)
                except FsError:
                    pass
                raise
            self._meta.close(res.inode.id, res.session_id,
                             length_hint=n, wrote=True)
            self._write_bytes.add(n)

    def get(self, key: str) -> Optional[bytes]:
        with self._get_rec.record() as op:
            path = _shard_path(self.root, key)
            try:
                inode = self._meta.stat(path)
            except FsError:
                self._misses.add()
                op.fail()
                return None
            data = self._fio.read(inode, 0, inode.length)
            self._hits.add()
            self._read_bytes.add(len(data))
            if self._touch:
                try:  # LRU refresh; losing the race to GC is harmless
                    self._meta.set_attr(path, mtime=time.time())
                except (FsError, TypeError):
                    pass
            return data

    def batch_get(self, keys: Sequence[str]) -> List[Optional[bytes]]:
        """Stat all keys, then read every hit as ONE node-grouped chunk
        batch (StorageClient.batch_read underneath)."""
        paths = [_shard_path(self.root, k) for k in keys]
        inodes = self._meta.batch_stat_by_path(paths)
        hits = [(i, ino) for i, ino in enumerate(inodes) if ino is not None]
        self._misses.add(len(keys) - len(hits))
        out: List[Optional[bytes]] = [None] * len(keys)
        if not hits:
            return out
        blobs = self._fio.batch_read_files(
            [(ino, 0, ino.length) for _, ino in hits])
        now = time.time()
        for (i, ino), blob in zip(hits, blobs):
            out[i] = blob
            self._hits.add()
            self._read_bytes.add(len(blob))
            if self._touch:
                try:  # same LRU contract as get()
                    self._meta.set_attr(paths[i], mtime=now)
                except FsError:
                    pass
        return out

    def remove(self, key: str) -> bool:
        path = _shard_path(self.root, key)
        try:
            self._meta.remove(path)
            return True
        except FsError:
            return False

    def contains(self, key: str) -> bool:
        try:
            self._meta.stat(_shard_path(self.root, key))
            return True
        except FsError:
            return False

    # -- array API (decoder-layer KV tensors) -------------------------------
    def put_array(self, key: str, array) -> None:
        arr = np.asarray(array)
        name = arr.dtype.str.encode().ljust(8, b"\0")
        header = _HEADER.pack(name, arr.ndim, 0)
        dims = b"".join(_MAGIC_DIMS.pack(d) for d in arr.shape)
        self.put(key, header + dims + arr.tobytes())

    def get_array(self, key: str):
        raw = self.get(key)
        if raw is None:
            return None
        name, ndim, _ = _HEADER.unpack_from(raw, 0)
        off = _HEADER.size
        shape = tuple(
            _MAGIC_DIMS.unpack_from(raw, off + i * _MAGIC_DIMS.size)[0]
            for i in range(ndim)
        )
        off += ndim * _MAGIC_DIMS.size
        dtype = np.dtype(name.rstrip(b"\0").decode())
        return np.frombuffer(raw, dtype=dtype, offset=off).reshape(shape)


class KVCacheGC:
    """TTL garbage collector (ref README.md:48 — GC remove-op IOPS).

    Scans shard directories round-robin, removing entries whose mtime is
    older than ttl_s. Each run_once() visits at most max_shards shards so a
    GC pass never monopolizes the metadata service; removals go through the
    normal remove path (chunks reclaimed by meta GC scan)."""

    def __init__(
        self,
        meta: MetaStore,
        *,
        root: str = "/kvcache",
        ttl_s: float = 3600.0,
        max_shards: int = 64,
        client_id: str = "kvcache-gc",
    ):
        self._meta = meta
        self.root = root.rstrip("/") or "/kvcache"
        self.ttl_s = ttl_s
        self.max_shards = max_shards
        self._client_id = client_id
        self._cursor: Tuple[int, int] = (0, 0)
        self._removes = CounterRecorder("kvcache.gc.removes")
        self._scans = CounterRecorder("kvcache.gc.scans")

    def _list(self, path: str) -> List[str]:
        try:
            return [e.name for e in self._meta.list_dir(path)]
        except FsError:
            return []

    def run_once(self, now: Optional[float] = None) -> int:
        """Scan up to max_shards leaf dirs; returns entries removed.

        Sub-shard lists are fetched lazily per top dir as the cursor reaches
        it, so a pass costs 1 (root) + tops-touched + leafs-visited list_dir
        calls — never a full enumeration of the whole shard tree up front."""
        now = time.time() if now is None else now
        removed = 0
        tops = sorted(self._list(self.root))
        if not tops:
            return 0
        ti = self._cursor[0] % len(tops)
        si = self._cursor[1]
        visited = 0
        tops_touched = 0
        seen_leafs = set()  # each leaf scanned at most once per pass
        wrapped = False
        while (visited < self.max_shards and tops_touched <= len(tops)
               and not wrapped):
            top = tops[ti]
            subs = sorted(self._list(f"{self.root}/{top}"))
            while si < len(subs) and visited < self.max_shards:
                key = (top, subs[si])
                if key in seen_leafs:
                    wrapped = True  # full cycle: stop, cursor stays here
                    break
                seen_leafs.add(key)
                leaf = f"{self.root}/{top}/{subs[si]}"
                si += 1
                visited += 1
                self._scans.add()
                for name in self._list(leaf):
                    path = f"{leaf}/{name}"
                    try:
                        inode = self._meta.stat(path)
                    except FsError:
                        continue
                    if now - inode.mtime >= self.ttl_s:
                        try:
                            self._meta.remove(path)
                            removed += 1
                            self._removes.add()
                        except FsError:
                            pass  # concurrent remove/touch: next pass decides
            if not wrapped and si >= len(subs):
                ti = (ti + 1) % len(tops)
                si = 0
                tops_touched += 1
        self._cursor = (ti, si)
        return removed
