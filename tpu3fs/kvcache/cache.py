"""KVCache fs tier for LLM inference over the cluster (ref README.md:17,
45-51).

The reference positions 3FS as a DRAM-alternative KV cache: decoder-layer
key/value tensors of previous tokens are cached in files, read back at up to
40 GiB/s, and reclaimed by a GC whose remove-op IOPS the README charts. The
reference implements this as a usage pattern over the normal file API — so
does this build. This module is the durable tier of the serving stack
(docs/kvcache.md): ``tier.TieredKVCache`` puts a host-RAM hot tier in front
of it and ``blocks.PrefixBlockStore`` a content-addressed prefix-hash
keyspace on top.

- entries live under a cache root, sharded two hex levels deep (256×256
  dirs) so directory listings stay short at billions of entries;
- put() writes value bytes through the striped chunk path and closes with
  the write session so lengths settle;
- get()/batch_get() are chunk-batched reads (batch_read groups chunk IOs by
  node exactly like the training data loaders do);
- touch-on-get refreshes an entry's mtime so the GC is an LRU — BATCHED
  (MetaStore.batch_set_attr): a 64-key batch_get refreshes all its hits in
  one metadata transaction, not 64 round trips;
- all IO is tagged ``TrafficClass.KVCACHE`` (foreground-weighted,
  share-bounded — qos/core.py);
- KVCacheGC reclaims in two modes: TTL round-robin shard scans, and a
  capacity-target pass evicting oldest-touched entries until the tier fits
  a bytes budget. Both respect pin leases (leases.py) — the remove-op
  counter mirrors the README's GC IOPS chart.

JAX arrays ride along via put_array/get_array (layout.encode_array: dtype+
shape header, zero parsing beyond a 16-byte prefix) so inference servers
can device_put the result straight onto a TPU.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import List, Optional, Sequence, Tuple

from tpu3fs.client.file_io import FileIoClient
from tpu3fs.kvcache.layout import (
    decode_array,
    encode_array,
    lease_active,
    shard_path,
)
from tpu3fs.meta.store import MetaStore, OpenFlags
from tpu3fs.monitor.recorder import CounterRecorder, LatencyRecorder
from tpu3fs.qos.core import TrafficClass, tagged
from tpu3fs.utils.result import Code, FsError


def _shard_path(root: str, key: str) -> str:
    # back-compat alias (tests and older callers import it from here)
    return shard_path(root, key)


class KVCacheClient:
    """Typed cache surface over (MetaStore, FileIoClient)."""

    def __init__(
        self,
        meta: MetaStore,
        fio: FileIoClient,
        *,
        root: str = "/kvcache",
        client_id: str = "kvcache",
        touch_on_get: bool = True,
        inode_cache: int = 0,
        touch_coalesce_s: float = 0.0,
        tenant: str = "",
    ):
        """inode_cache > 0 enables a bounded client-side inode cache of
        that many entries: repeat gets skip the stat walk and touch by
        inode id (walk-free batch_set_attr), so a hot serving set pays
        only its storage reads. ONLY sound for immutable, staleness-
        detectable namespaces — content-addressed block entries, whose
        array-header magic turns a GC'd entry's zero-hole read into
        KVCACHE_STALE (blocks.py invalidates and re-stats). Leave 0 for
        mutable byte-API use: a cached inode cannot see another client's
        overwrite lengths.

        touch_coalesce_s > 0 takes the LRU touch off the read critical
        path: touched ids accumulate client-side and drain as ONE
        batch_set_attr at most once per interval (flush_touches() forces
        it). The GC's mtime axis lags by at most the interval — pair it
        with a GC ttl comfortably above it (any sane TTL is)."""
        self._meta = meta
        self._fio = fio
        self.root = root.rstrip("/") or "/kvcache"
        self._client_id = client_id
        # owning tenant (tpu3fs/tenant): every op runs under this scope
        # (so the wire carries it and quotas charge it) — set explicitly
        # because the write-back flusher calls batch_put from a
        # background thread that inherits NO producer context
        self._tenant = tenant or ""
        self._touch_on_get = touch_on_get
        self._dir_lock = threading.Lock()
        self._dirs_made: set = set()
        self._ino_lock = threading.Lock()
        self._ino_cap = int(inode_cache)
        self._inodes: "OrderedDict[str, object]" = OrderedDict()
        self._touch_coalesce_s = float(touch_coalesce_s)
        self._touch_lock = threading.Lock()
        self._pending_ids: set = set()
        self._pending_paths: set = set()
        self._last_touch_flush = time.monotonic()
        self._hits = CounterRecorder("kvcache.hits")
        self._misses = CounterRecorder("kvcache.misses")
        self._read_bytes = CounterRecorder("kvcache.read_bytes")
        self._write_bytes = CounterRecorder("kvcache.write_bytes")
        self._get_rec = LatencyRecorder("kvcache.get")
        self._put_rec = LatencyRecorder("kvcache.put")

    # -- plumbing -----------------------------------------------------------
    def _tenant_ctx(self):
        from tpu3fs.tenant.identity import tenant_scope

        return tenant_scope(self._tenant)

    def _charge_resident(self, nbytes: int) -> None:
        """Per-tenant kvcache resident-bytes estimate (tpu3fs/tenant):
        incremental from the writer; the GC daemon's scans set the
        authoritative figure (bin/kvcache_gc_main.py)."""
        from tpu3fs.tenant.identity import current_tenant
        from tpu3fs.tenant.quota import registry

        tenant = self._tenant or current_tenant()
        if tenant:
            registry().charge_kvcache(tenant, nbytes)

    def _check_resident_budget(self) -> None:
        """Writer-side kvcache budget gate: a tenant whose resident bytes
        exceed its quota sheds TENANT_THROTTLED before creating more
        entries — eviction (GC capacity pass) is what brings it back
        under (docs/tenancy.md)."""
        from tpu3fs.tenant.identity import current_tenant
        from tpu3fs.tenant.quota import registry
        from tpu3fs.utils.result import Status

        tenant = self._tenant or current_tenant()
        if tenant and registry().kvcache_over(tenant):
            registry().shed_kvcache(tenant)
            raise FsError(Status(
                Code.TENANT_THROTTLED,
                f"retry_after_ms=1000 (tenant {tenant} over its kvcache "
                f"resident budget)"))

    def _ensure_dir(self, path: str) -> None:
        parent = path.rsplit("/", 1)[0]
        with self._dir_lock:
            if parent in self._dirs_made:
                return
        try:
            self._meta.mkdirs(parent, recursive=True)
        except FsError as e:
            if e.code != Code.META_EXISTS:
                raise
        with self._dir_lock:
            self._dirs_made.add(parent)

    def _ensure_dirs(self, paths: Sequence[str]) -> None:
        """Directory fan-in for the drain: ONE batch_mkdirs RPC (fanned
        per meta partition by the routed client) for every uncached
        parent, instead of one serial mkdirs round trip each — the other
        meta-bound half of the write-back flush number."""
        parents: List[str] = []
        with self._dir_lock:
            seen = set()
            for p in paths:
                parent = p.rsplit("/", 1)[0]
                if parent not in self._dirs_made and parent not in seen:
                    seen.add(parent)
                    parents.append(parent)
        if not parents:
            return
        batched = getattr(self._meta, "batch_mkdirs", None)
        if batched is None:
            for parent in parents:
                self._ensure_dir(parent + "/x")
            return
        for parent, res in zip(parents,
                               batched(parents, recursive=True,
                                       exist_ok=True)):
            if isinstance(res, FsError) and res.code != Code.META_EXISTS:
                raise res
        with self._dir_lock:
            self._dirs_made.update(parents)

    def _touch(self, paths: Sequence[str], now: float,
               inode_ids: Optional[Sequence[int]] = None) -> None:
        """LRU refresh, batched; losing a race to GC is harmless. With
        inode ids the touch is walk-free; with coalescing it leaves the
        read critical path entirely (one drain per interval). The one
        exception guard for every touch path (get/batch_get used to
        differ): FsError from concurrent removes, TypeError from meta
        doubles without time kwargs."""
        if self._touch_coalesce_s > 0:
            with self._touch_lock:
                if inode_ids is not None:
                    self._pending_ids.update(inode_ids)
                else:
                    self._pending_paths.update(paths)
                if (time.monotonic() - self._last_touch_flush
                        < self._touch_coalesce_s):
                    return
            self.flush_touches(now)
            return
        self._touch_now(paths, now, inode_ids)

    def flush_touches(self, now: Optional[float] = None) -> None:
        """Drain coalesced touches as one batched settle."""
        now = time.time() if now is None else now
        with self._touch_lock:
            ids, self._pending_ids = self._pending_ids, set()
            paths, self._pending_paths = self._pending_paths, set()
            self._last_touch_flush = time.monotonic()
        if ids:
            self._touch_now([], now, sorted(ids))
        if paths:
            self._touch_now(sorted(paths), now)

    def _touch_now(self, paths: Sequence[str], now: float,
                   inode_ids: Optional[Sequence[int]] = None) -> None:
        batched = getattr(self._meta, "batch_set_attr", None)
        if batched is not None and inode_ids is not None:
            try:
                batched(inode_ids=list(inode_ids), mtime=now)
                return
            except TypeError:  # meta without id addressing: use paths
                pass
            except FsError:
                return
        try:
            if batched is not None:
                batched(paths, mtime=now)
            else:  # minimal meta double: per-path fallback
                for p in paths:
                    self._meta.set_attr(p, mtime=now)
        except (FsError, TypeError):
            pass

    # -- inode cache (immutable namespaces only; see __init__) --------------
    def _cached_inode(self, key: str):
        if self._ino_cap <= 0:
            return None
        with self._ino_lock:
            ino = self._inodes.get(key)
            if ino is not None:
                self._inodes.move_to_end(key)
            return ino

    def _cache_inode(self, key: str, inode) -> None:
        if self._ino_cap <= 0:
            return
        with self._ino_lock:
            self._inodes[key] = inode
            self._inodes.move_to_end(key)
            while len(self._inodes) > self._ino_cap:
                self._inodes.popitem(last=False)

    def invalidate(self, key: Optional[str] = None) -> None:
        """Drop cached inode state (one key, or all with None) — blocks.py
        calls this on a KVCACHE_STALE decode before re-statting."""
        with self._ino_lock:
            if key is None:
                self._inodes.clear()
            else:
                self._inodes.pop(key, None)

    # -- byte API -----------------------------------------------------------
    def put(self, key: str, value: bytes) -> None:
        with self._put_rec.record(), tagged(TrafficClass.KVCACHE), \
                self._tenant_ctx():
            self._check_resident_budget()
            path = shard_path(self.root, key)
            self._ensure_dir(path)
            res = self._meta.create(
                path, flags=OpenFlags.WRITE | OpenFlags.CREATE
                | OpenFlags.TRUNC,
                client_id=self._client_id,
            )
            try:
                n = self._fio.write(res.inode, 0, value)
            except BaseException:
                # failed write must not leak the open write session
                try:
                    self._meta.close(res.inode.id, res.session_id)
                except FsError:
                    pass
                raise
            settled = self._meta.close(res.inode.id, res.session_id,
                                       length_hint=n, wrote=True)
            self._cache_inode(key, settled)
            self._write_bytes.add(n)
            self._charge_resident(n)

    def batch_put(self, items) -> None:
        """Write many (key, value) entries as ONE node-grouped striped
        batch (FileIoClient.batch_write_files) and settle the sessions in
        one batch_close — the write-back flusher's drain path, mirroring
        batch_get's shape. Creates fan IN too: one batch_create RPC for
        the whole drain (O(len/64) server transactions) instead of N
        serial create round trips — the meta-bound half of the write-back
        flush number. Raises on the first failed entry."""
        from tpu3fs.meta.store import BatchCloseItem, BatchCreateItem

        items = list(items)
        if not items:
            return
        with self._put_rec.record(), tagged(TrafficClass.KVCACHE), \
                self._tenant_ctx():
            self._check_resident_budget()
            opened: List[Tuple[str, object]] = []
            try:
                paths = [shard_path(self.root, key) for key, _ in items]
                self._ensure_dirs(paths)
                batch_create = getattr(self._meta, "batch_create", None)
                if batch_create is not None:
                    flags = (OpenFlags.WRITE | OpenFlags.CREATE
                             | OpenFlags.TRUNC)
                    created = batch_create([
                        BatchCreateItem(path=p, flags=flags,
                                        client_id=self._client_id)
                        for p in paths])
                    for (key, _), res in zip(items, created):
                        if isinstance(res, FsError):
                            raise res
                        opened.append((key, res))
                else:
                    for (key, _), path in zip(items, paths):
                        opened.append((key, self._meta.create(
                            path, flags=OpenFlags.WRITE | OpenFlags.CREATE
                            | OpenFlags.TRUNC,
                            client_id=self._client_id)))
                counts = self._fio.batch_write_files(
                    [(res.inode, 0, value)
                     for (_, res), (_, value) in zip(opened, items)])
            except BaseException:
                for _, res in opened:
                    try:
                        self._meta.close(res.inode.id, res.session_id)
                    except FsError:
                        pass
                raise
            closes = [BatchCloseItem(
                inode_id=res.inode.id, session_id=res.session_id,
                length_hint=n, client_id=self._client_id, wrote=1)
                for (_, res), n in zip(opened, counts)]
            batch_close = getattr(self._meta, "batch_close", None)
            settled = (batch_close(closes) if batch_close is not None else
                       [self._meta.close(c.inode_id, c.session_id,
                                         length_hint=c.length_hint,
                                         wrote=True) for c in closes])
            for (key, _), res, n in zip(opened, settled, counts):
                if isinstance(res, FsError):
                    raise res
                self._cache_inode(key, res)
                self._write_bytes.add(n)
                self._charge_resident(n)

    def get(self, key: str) -> Optional[bytes]:
        with self._get_rec.record() as op, tagged(TrafficClass.KVCACHE), \
                self._tenant_ctx():
            path = shard_path(self.root, key)
            inode = self._cached_inode(key)
            if inode is None:
                try:
                    inode = self._meta.stat(path)
                except FsError:
                    self._misses.add()
                    op.fail()
                    return None
                self._cache_inode(key, inode)
            data = self._fio.read(inode, 0, inode.length)
            self._hits.add()
            self._read_bytes.add(len(data))
            if self._touch_on_get:
                self._touch([path], time.time(), inode_ids=[inode.id])
            return data

    def get_cached(self, key: str) -> Optional[bytes]:
        """Read ONLY via an already-cached inode — zero metadata round
        trips, None when the inode is not cached. The serving host's
        serve-through path (tpu3fs/serving/service.py): a peer asking for
        a block this process recently wrote can be answered for one
        storage read with no meta traffic. The caller MUST staleness-check
        the payload (layout.zero_hole) — a GC'd entry reads back as an
        all-zero hole through a cached inode."""
        inode = self._cached_inode(key)
        if inode is None:
            return None
        with tagged(TrafficClass.KVCACHE), self._tenant_ctx():
            try:
                data = self._fio.read(inode, 0, inode.length)
            except FsError:
                self.invalidate(key)
                return None
            self._read_bytes.add(len(data))
            return data

    def batch_get(self, keys: Sequence[str]) -> List[Optional[bytes]]:
        """Stat all keys, then read every hit as ONE node-grouped chunk
        batch (StorageClient.batch_read underneath) and refresh every
        hit's mtime as ONE batched touch."""
        with tagged(TrafficClass.KVCACHE), self._tenant_ctx():
            paths = [shard_path(self.root, k) for k in keys]
            inodes: List[object] = [self._cached_inode(k) for k in keys]
            unknown = [i for i, ino in enumerate(inodes) if ino is None]
            if unknown:
                fresh = self._meta.batch_stat_by_path(
                    [paths[i] for i in unknown])
                for i, ino in zip(unknown, fresh):
                    inodes[i] = ino
                    if ino is not None:
                        self._cache_inode(keys[i], ino)
            hits = [(i, ino) for i, ino in enumerate(inodes)
                    if ino is not None]
            self._misses.add(len(keys) - len(hits))
            out: List[Optional[bytes]] = [None] * len(keys)
            if not hits:
                return out
            blobs = self._fio.batch_read_files(
                [(ino, 0, ino.length) for _, ino in hits])
            for (i, ino), blob in zip(hits, blobs):
                out[i] = blob
                self._hits.add()
                self._read_bytes.add(len(blob))
            if self._touch_on_get:
                self._touch([paths[i] for i, _ in hits], time.time(),
                            inode_ids=[ino.id for _, ino in hits])
            return out

    def remove(self, key: str) -> bool:
        path = shard_path(self.root, key)
        self.invalidate(key)
        try:
            with tagged(TrafficClass.KVCACHE):
                self._meta.remove(path)
            return True
        except FsError:
            return False

    def contains(self, key: str) -> bool:
        try:
            self._meta.stat(shard_path(self.root, key))
            return True
        except FsError:
            return False

    def batch_contains(self, keys: Sequence[str]) -> List[bool]:
        """Presence of many keys via one batched stat — the prefix-match
        probe (blocks.match_prefix) where per-key stats would make prefix
        lookup O(chain length) round trips."""
        paths = [shard_path(self.root, k) for k in keys]
        with tagged(TrafficClass.KVCACHE):
            inodes = self._meta.batch_stat_by_path(paths)
        return [ino is not None for ino in inodes]

    # -- array API (decoder-layer KV tensors) -------------------------------
    def put_array(self, key: str, array) -> None:
        self.put(key, encode_array(array))

    def get_array(self, key: str):
        raw = self.get(key)
        if raw is None:
            return None
        return decode_array(raw)


class KVCacheGC:
    """Garbage collector (ref README.md:48 — GC remove-op IOPS), two modes:

    - ``run_once()``: TTL scan — shard directories round-robin, removing
      entries whose mtime is older than ttl_s. Each pass visits at most
      max_shards shards so it never monopolizes the metadata service.
    - ``capacity_pass()``: capacity-target LRU eviction — scan the tier,
      and while it exceeds ``capacity_bytes``, remove entries in
      oldest-touched order (touch-on-get makes mtime the LRU axis).

    Both modes skip entries under an active pin lease (leases.py): an
    inference session holding a lease on its prefix blocks can never lose
    them mid-decode, however old or over-budget the tier is. Removals go
    through the normal remove path (chunks reclaimed by meta GC scan).
    """

    def __init__(
        self,
        meta: MetaStore,
        *,
        root: str = "/kvcache",
        ttl_s: float = 3600.0,
        max_shards: int = 64,
        capacity_bytes: Optional[int] = None,
        client_id: str = "kvcache-gc",
    ):
        self._meta = meta
        self.root = root.rstrip("/") or "/kvcache"
        self.ttl_s = ttl_s
        self.max_shards = max_shards
        self.capacity_bytes = capacity_bytes
        self._client_id = client_id
        self._cursor: Tuple[int, int] = (0, 0)
        self._removes = CounterRecorder("kvcache.gc.removes")
        self._scans = CounterRecorder("kvcache.gc.scans")
        self._lease_skips = CounterRecorder("kvcache.gc.lease_skips")

    def _list(self, path: str) -> List[str]:
        try:
            return [e.name for e in self._meta.list_dir(path)]
        except FsError:
            return []

    def _try_remove(self, path: str) -> bool:
        try:
            self._meta.remove(path)
            self._removes.add()
            return True
        except FsError:
            return False  # concurrent remove/touch: next pass decides

    def run_once(self, now: Optional[float] = None) -> int:
        """Scan up to max_shards leaf dirs; returns entries removed.

        Sub-shard lists are fetched lazily per top dir as the cursor reaches
        it, so a pass costs 1 (root) + tops-touched + leafs-visited list_dir
        calls — never a full enumeration of the whole shard tree up front."""
        now = time.time() if now is None else now
        removed = 0
        tops = sorted(self._list(self.root))
        if not tops:
            return 0
        ti = self._cursor[0] % len(tops)
        si = self._cursor[1]
        visited = 0
        tops_touched = 0
        seen_leafs = set()  # each leaf scanned at most once per pass
        wrapped = False
        while (visited < self.max_shards and tops_touched <= len(tops)
               and not wrapped):
            top = tops[ti]
            subs = sorted(self._list(f"{self.root}/{top}"))
            while si < len(subs) and visited < self.max_shards:
                key = (top, subs[si])
                if key in seen_leafs:
                    wrapped = True  # full cycle: stop, cursor stays here
                    break
                seen_leafs.add(key)
                leaf = f"{self.root}/{top}/{subs[si]}"
                si += 1
                visited += 1
                self._scans.add()
                for name in self._list(leaf):
                    path = f"{leaf}/{name}"
                    try:
                        inode = self._meta.stat(path)
                    except FsError:
                        continue
                    if now - inode.mtime < self.ttl_s:
                        continue
                    if lease_active(inode, now):
                        self._lease_skips.add()
                        continue
                    if self._try_remove(path):
                        removed += 1
            if not wrapped and si >= len(subs):
                ti = (ti + 1) % len(tops)
                si = 0
                tops_touched += 1
        self._cursor = (ti, si)
        return removed

    def scan_entries(self, now: Optional[float] = None):
        """Full-tier enumeration -> [(mtime, length, leased, path)] —
        shared by capacity_pass and the admin CLI stats view."""
        now = time.time() if now is None else now
        out = []
        for top in self._list(self.root):
            for sub in self._list(f"{self.root}/{top}"):
                leaf = f"{self.root}/{top}/{sub}"
                for name in self._list(leaf):
                    path = f"{leaf}/{name}"
                    try:
                        inode = self._meta.stat(path)
                    except FsError:
                        continue
                    out.append((inode.mtime, inode.length,
                                lease_active(inode, now), path))
        return out

    def capacity_pass(self, now: Optional[float] = None,
                      capacity_bytes: Optional[int] = None) -> int:
        """Evict oldest-touched unleased entries until the tier's total
        bytes fit the budget; returns entries removed. A tier that cannot
        fit (everything leased) stops at the leased floor rather than
        violating a lease."""
        budget = self.capacity_bytes if capacity_bytes is None \
            else capacity_bytes
        if budget is None:
            return 0
        now = time.time() if now is None else now
        entries = self.scan_entries(now)
        total = sum(length for _, length, _, _ in entries)
        if total <= budget:
            return 0
        removed = 0
        for mtime, length, leased, path in sorted(entries):
            if total <= budget:
                break
            if leased:
                self._lease_skips.add()
                continue
            if self._try_remove(path):
                total -= length
                removed += 1
        return removed
