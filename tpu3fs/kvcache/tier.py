"""Two-tier serving cache: bounded host-RAM hot tier over the fs tier.

The serving-path arithmetic: a decode step needs its prefix KV in device
memory in single-digit milliseconds; the fs tier answers in
storage-round-trip time. So reads go through a HOST-RAM LRU first —

- **hits are RAM-only**: no metadata stat, no storage RPC, nothing on the
  wire (the property tests/test_kvcache.py pins);
- **misses fill as ONE striped batch** (`KVCacheClient.batch_get` →
  `batch_read_files` → the PR 3 pipelined node-grouped fan-out), then
  land in the tier for the session's next step;
- **puts write back**: the value is visible to readers immediately (tier
  + dirty buffer) and a background flush thread pushes it through the fs
  tier. The dirty buffer is BOUNDED (``dirty_max_bytes``): a producer
  outrunning storage blocks at the bound instead of growing host memory
  without limit. Durability-sensitive callers pass
  ``write_through=True`` and get the synchronous fs put.

Consistency is client-local, like the readahead prefetcher: one process's
tier does not see another process's overwrites until the entry ages out
of the tier. Content-addressed block keys (blocks.py) sidestep this
entirely — a key's value never changes, so staleness cannot be observed.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

from tpu3fs.kvcache.cache import KVCacheClient
from tpu3fs.kvcache.layout import decode_array, encode_array
from tpu3fs.monitor.recorder import CounterRecorder, ValueRecorder
from tpu3fs.utils.result import Code, FsError, Status


class HostTier:
    """Thread-safe bounded-bytes LRU of value buffers.

    With a ``refcount_of`` callable installed (the serving fleet's
    shared-block refcounts, tpu3fs/serving/fleet.py), eviction prefers
    UNSHARED entries: a viral shared prefix (many live decode chains
    reference its blocks) should outlive the unshared tail blocks of a
    single finished request, whatever pure recency says. The scan is
    bounded (``evict_scan``) so eviction stays O(1)-ish; when every
    scanned entry is shared, plain LRU applies — capacity wins over
    sharing, never the reverse."""

    def __init__(self, capacity_bytes: int, *, evict_scan: int = 8):
        self.capacity_bytes = int(capacity_bytes)
        self.evict_scan = max(1, int(evict_scan))
        #: optional key -> live-chain refcount (entries with count > 1
        #: are "shared"); installed by FleetKVCache
        self.refcount_of = None
        self._mu = threading.Lock()
        self._entries: "OrderedDict[str, bytes]" = OrderedDict()
        self._bytes = 0

    def get(self, key: str) -> Optional[bytes]:
        with self._mu:
            v = self._entries.get(key)
            if v is not None:
                self._entries.move_to_end(key)
            return v

    def contains(self, key: str) -> bool:
        with self._mu:
            return key in self._entries

    def put(self, key: str, value) -> int:
        """Insert (LRU-most); returns entries evicted to fit. A value
        larger than the whole tier is not cached at all (evicting
        everything for one entry would thrash the hot set)."""
        n = len(value)
        if n > self.capacity_bytes:
            return 0
        evicted = 0
        with self._mu:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= len(old)
            self._entries[key] = value
            self._bytes += n
            while self._bytes > self.capacity_bytes and self._entries:
                v = self._evict_one_locked()
                self._bytes -= len(v)
                evicted += 1
        return evicted

    def _evict_one_locked(self) -> bytes:
        """Pop one victim (value returned for byte accounting): the first
        UNSHARED entry within the scan window from the LRU end, else the
        plain LRU head."""
        rc = self.refcount_of
        if rc is not None:
            for i, key in enumerate(self._entries):
                if i >= self.evict_scan:
                    break
                try:
                    shared = rc(key) > 1
                except Exception:
                    shared = False
                if not shared:
                    return self._entries.pop(key)
        _, v = self._entries.popitem(last=False)
        return v

    def remove(self, key: str) -> bool:
        with self._mu:
            v = self._entries.pop(key, None)
            if v is None:
                return False
            self._bytes -= len(v)
            return True

    def clear(self) -> None:
        with self._mu:
            self._entries.clear()
            self._bytes = 0

    @property
    def bytes(self) -> int:
        with self._mu:
            return self._bytes

    def __len__(self) -> int:
        with self._mu:
            return len(self._entries)


class TieredKVCache:
    """Host-RAM hot tier + bounded write-back buffer over a
    ``KVCacheClient`` fs tier. Same get/put surface, so the prefix-block
    store (blocks.py) runs on either."""

    def __init__(self, cache: KVCacheClient, *,
                 capacity_bytes: int = 256 << 20,
                 dirty_max_bytes: int = 64 << 20,
                 write_through: bool = False,
                 flush_batch: int = 16,
                 flush_error_budget: int = 16):
        self._fs = cache
        self.tier = HostTier(capacity_bytes)
        self.write_through = write_through
        self.dirty_max_bytes = int(dirty_max_bytes)
        self._flush_batch = max(1, flush_batch)
        # error budget: after this many CONSECUTIVE failed flush cycles
        # the buffer is POISONED — put() raises KVCACHE_FLUSH_POISONED to
        # the producer instead of buffering (and eventually blocking)
        # silently forever against a dead storage tier. One successful
        # flush clears the poison (carried follow-up from PR 5).
        self.flush_error_budget = max(1, int(flush_error_budget))
        self._flush_fail_streak = 0
        self._mu = threading.Lock()
        self._cond = threading.Condition(self._mu)
        self._dirty: "OrderedDict[str, bytes]" = OrderedDict()
        self._dirty_bytes = 0
        self._stop = threading.Event()
        self._host_hits = CounterRecorder("kvcache.host_hits")
        self._host_misses = CounterRecorder("kvcache.host_misses")
        self._fill_bytes = CounterRecorder("kvcache.fill_bytes")
        self._evictions = CounterRecorder("kvcache.host_evictions")
        self._flush_bytes = CounterRecorder("kvcache.flush_bytes")
        self._flush_err = CounterRecorder("kvcache.flush_err")
        self._dirty_gauge = ValueRecorder("kvcache.dirty_bytes")
        # host-tier residency gauge (memory observability: admin_cli top
        # + the bounded-memory assertions in tests/test_kvcache.py)
        self._host_gauge = ValueRecorder("kvcache.host_bytes")
        self._flusher = threading.Thread(
            target=self._flush_loop, daemon=True, name="kvcache-flush")
        self._flusher.start()

    def _note_host(self) -> None:
        self._host_gauge.set(self.tier.bytes)

    @property
    def root(self) -> str:
        return self._fs.root

    @property
    def fs(self) -> KVCacheClient:
        return self._fs

    # -- reads --------------------------------------------------------------
    def _local(self, key: str) -> Optional[bytes]:
        """Tier, then dirty buffer: a dirty value evicted from the tier
        must still be readable (read-your-writes) without touching fs."""
        v = self.tier.get(key)
        if v is not None:
            return v
        with self._mu:
            return self._dirty.get(key)

    def get(self, key: str) -> Optional[bytes]:
        v = self._local(key)
        if v is not None:
            self._host_hits.add()
            return v
        self._host_misses.add()
        v = self._miss_fill(key)
        if v is not None:
            self._fill(key, v)
        return v

    def batch_get(self, keys: Sequence[str]) -> List[Optional[bytes]]:
        """Host hits served from RAM; ALL misses fetched as one striped
        fs batch (one node-grouped batch_read_files underneath)."""
        out: List[Optional[bytes]] = [None] * len(keys)
        missing: List[int] = []
        for i, key in enumerate(keys):
            v = self._local(key)
            if v is not None:
                out[i] = v
                self._host_hits.add()
            else:
                missing.append(i)
        if missing:
            self._host_misses.add(len(missing))
            got = self._miss_fill_batch([keys[i] for i in missing])
            for i, blob in zip(missing, got):
                out[i] = blob
                if blob is not None:
                    self._fill(keys[i], blob)
        return out

    # -- miss path (the serving fleet's interposition point) ----------------
    def _miss_fill(self, key: str) -> Optional[bytes]:
        """Resolve ONE host-tier miss from below. The base class goes
        straight to the fs tier; FleetKVCache (tpu3fs/serving/fleet.py)
        overrides this with single-flight -> peer host tier -> storage."""
        return self._fs.get(key)

    def _miss_fill_batch(self, keys: Sequence[str]) -> List[Optional[bytes]]:
        """Batch analogue of ``_miss_fill`` (same override point)."""
        return self._fs.batch_get(keys)

    def _fill(self, key: str, value) -> None:
        self._fill_bytes.add(len(value))
        self._evictions.add(self.tier.put(key, value))
        self._note_host()

    # -- writes -------------------------------------------------------------
    def put(self, key: str, value: bytes,
            write_through: Optional[bool] = None) -> None:
        """Visible to this client's readers immediately; durable in the fs
        tier synchronously (write_through) or via the background flusher.
        The dirty buffer blocks at dirty_max_bytes — bounded host memory
        under a stalled storage tier, like the loader's backpressure."""
        wt = self.write_through if write_through is None else write_through
        if wt:
            self._fs.put(key, value)
            self._evictions.add(self.tier.put(key, value))
            return
        if self.flush_poisoned:
            # the flusher burned its whole error budget: surface the
            # storage failure to the producer NOW instead of buffering
            # toward the dirty bound and stalling silently (write_through
            # still works — its errors surface synchronously anyway)
            raise FsError(Status(
                Code.KVCACHE_FLUSH_POISONED,
                f"write-back flusher failed {self._flush_fail_streak} "
                f"consecutive cycles (budget {self.flush_error_budget})"))
        with self._cond:
            while (not self._stop.is_set() and self._dirty
                   and self._dirty_bytes + len(value)
                   > self.dirty_max_bytes):
                self._cond.wait(0.5)
            old = self._dirty.pop(key, None)
            if old is not None:
                self._dirty_bytes -= len(old)
            self._dirty[key] = value
            self._dirty_bytes += len(value)
            self._dirty_gauge.set(self._dirty_bytes)
            self._cond.notify_all()
        self._evictions.add(self.tier.put(key, value))
        self._note_host()

    def batch_put(self, items, write_through: Optional[bool] = None) -> None:
        """Store many (key, value) entries in one drain: write-through
        rides ``KVCacheClient.batch_put`` (ONE batch_create + ONE striped
        batch write + ONE batch_close for the whole drain — never N serial
        create round trips); write-back lands everything in the dirty
        buffer and lets the flusher drain it batched the same way."""
        items = list(items)
        if not items:
            return
        wt = self.write_through if write_through is None else write_through
        if wt:
            batched = getattr(self._fs, "batch_put", None)
            if batched is not None and len(items) > 1:
                batched(items)
            else:
                for key, value in items:
                    self._fs.put(key, value)
            for key, value in items:
                self._evictions.add(self.tier.put(key, value))
            self._note_host()
            return
        for key, value in items:
            self.put(key, value, write_through=False)

    def peek(self, key: str) -> Optional[bytes]:
        """Local-only read (tier + dirty buffer): the serving host's
        peerRead answers from here — a peer miss must never recurse into
        THIS process's storage-fill path."""
        return self._local(key)

    def remove(self, key: str) -> bool:
        """Drops the local copies and the fs entry. Racing an in-flight
        flush of the same key can leave the fs entry behind (any cache
        remove races its writers); it then ages out by TTL GC."""
        self.tier.remove(key)
        with self._cond:
            old = self._dirty.pop(key, None)
            if old is not None:
                self._dirty_bytes -= len(old)
                self._dirty_gauge.set(self._dirty_bytes)
                self._cond.notify_all()
        return self._fs.remove(key)

    def invalidate(self, key: Optional[str] = None) -> None:
        """Drop local copies + the fs tier's cached inode state (the
        stale-block recovery path, blocks.py)."""
        if key is None:
            self.tier.clear()
        else:
            self.tier.remove(key)
        inval = getattr(self._fs, "invalidate", None)
        if inval is not None:
            inval(key)

    # -- presence -----------------------------------------------------------
    def contains(self, key: str) -> bool:
        return self._local(key) is not None or self._fs.contains(key)

    def batch_contains(self, keys: Sequence[str]) -> List[bool]:
        out = [self._local(k) is not None for k in keys]
        missing = [i for i, hit in enumerate(out) if not hit]
        if missing:
            got = self._fs.batch_contains([keys[i] for i in missing])
            for i, hit in zip(missing, got):
                out[i] = hit
        return out

    # -- arrays -------------------------------------------------------------
    def put_array(self, key: str, array,
                  write_through: Optional[bool] = None) -> None:
        self.put(key, encode_array(array), write_through)

    def get_array(self, key: str):
        raw = self.get(key)
        if raw is None:
            return None
        return decode_array(raw)

    # -- write-back machinery ----------------------------------------------
    def dirty_bytes(self) -> int:
        with self._mu:
            return self._dirty_bytes

    def _flush_loop(self) -> None:
        while not self._stop.is_set():
            with self._cond:
                while not self._dirty and not self._stop.is_set():
                    self._cond.wait(0.2)
                if self._stop.is_set():
                    return
                batch = list(self._dirty.items())[:self._flush_batch]
            self._flush_items(batch)

    @property
    def flush_poisoned(self) -> bool:
        """True once the flusher's consecutive-failure streak reached the
        budget; cleared by the next successful flush cycle."""
        return self._flush_fail_streak >= self.flush_error_budget

    def _retire(self, key, value) -> None:
        with self._cond:
            if self._dirty.get(key) is value:
                del self._dirty[key]
                self._dirty_bytes -= len(value)
                self._dirty_gauge.set(self._dirty_bytes)
                self._cond.notify_all()

    def _flush_items(self, batch) -> None:
        """Write a snapshot through the fs tier, then retire exactly the
        values that were flushed: the entry stays readable in the dirty
        buffer DURING the put (no visibility hole if the tier evicted
        it), and a concurrent overwrite (different value object under the
        same key) survives for the next cycle. The whole batch drains as
        ONE batched striped write (KVCacheClient.batch_put riding the
        pipelined write path) when the fs tier supports it; a failed
        batch falls back to per-key puts so one bad entry cannot wedge
        the rest. Every all-failed cycle burns one unit of the error
        budget (see flush_error_budget); any success resets it."""
        batch_put = getattr(self._fs, "batch_put", None)
        if batch_put is not None and len(batch) > 1:
            try:
                batch_put(batch)
                for key, value in batch:
                    self._flush_bytes.add(len(value))
                    self._retire(key, value)
                self._flush_fail_streak = 0
                return
            except FsError:
                pass  # per-key fallback isolates the failing entry
        flushed_any = False
        for key, value in batch:
            try:
                self._fs.put(key, value)
                self._flush_bytes.add(len(value))
            except FsError:
                self._flush_err.add()
                self._stop.wait(0.05)  # storage unhappy: back off, retry
                continue
            flushed_any = True
            self._retire(key, value)
        if flushed_any:
            self._flush_fail_streak = 0
        else:
            self._flush_fail_streak += 1
            if self.flush_poisoned:
                # poisoned: stop hammering a dead tier at full tilt; one
                # retry cycle per interval keeps probing for recovery
                self._stop.wait(0.2)

    def flush(self, timeout: float = 30.0) -> bool:
        """Block until the dirty buffer drains (True) or timeout."""
        import time as _time

        deadline = _time.monotonic() + timeout
        with self._cond:
            while self._dirty:
                left = deadline - _time.monotonic()
                if left <= 0:
                    return False
                self._cond.wait(min(0.2, left))
        return True

    def close(self, flush: bool = True) -> None:
        if flush:
            self.flush()
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        self._flusher.join(timeout=10)
