"""Pin leases: active decodes are never GC'd out from under themselves.

An inference session serving a long decode holds its prompt's prefix
blocks for seconds to minutes. TTL and capacity GC must not reclaim those
entries mid-decode — so a session PINS the keys it depends on. A pin is a
``kvcache.lease`` xattr on the entry (layout.encode_lease: expire
timestamp + owner), which makes it:

- durable and cross-process: any GC (in-process, admin CLI, a daemon on
  another machine) sees the lease on the stat() it already does — the
  check costs no extra metadata round trip;
- self-expiring: a crashed session's pins age out with the lease TTL, so
  abandoned leases can never wedge eviction permanently;
- re-entrant on content-addressed keys: two sessions sharing a prefix
  both pin the same entries; the later expiry wins (renewing extends,
  never shortens, another owner's protection).

``pin()`` returns a ``Lease`` handle; ``unpin()`` (or the context
manager) releases only pins this lease still owns — it never strips a
longer-lived lease another session stacked on the same block.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import List, Optional, Sequence

from tpu3fs.kvcache.layout import (
    LEASE_XATTR,
    decode_lease,
    encode_lease,
    shard_path,
)
from tpu3fs.monitor.recorder import ValueRecorder
from tpu3fs.qos.core import TrafficClass, tagged
from tpu3fs.utils.result import FsError


class Lease:
    """One session's pins: the keys it protects and their expiry."""

    def __init__(self, owner: str, keys: List[str], expire_ts: float):
        self.owner = owner
        self.keys = keys
        self.expire_ts = expire_ts

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        # the manager that minted this lease releases it
        self._manager.unpin(self)
        return False


class LeaseManager:
    """Pin/unpin entry leases for one cache root."""

    def __init__(self, meta, *, root: str = "/kvcache",
                 default_ttl_s: float = 300.0,
                 owner: Optional[str] = None):
        self._meta = meta
        self.root = root.rstrip("/") or "/kvcache"
        self.default_ttl_s = default_ttl_s
        self.owner = owner or f"kvlease-{uuid.uuid4().hex[:8]}"
        self._lock = threading.Lock()
        self._active = 0
        self._gauge = ValueRecorder("kvcache.leases")

    def _bump(self, delta: int) -> None:
        with self._lock:
            self._active += delta
            self._gauge.set(self._active)

    @property
    def active(self) -> int:
        with self._lock:
            return self._active

    def pin(self, keys: Sequence[str],
            ttl_s: Optional[float] = None) -> Lease:
        """Pin existing entries for ttl_s; missing keys are skipped (the
        caller's match_prefix already told it what exists). Pinning a key
        another session pinned EXTENDS the protection window when this
        lease outlives the old one, and leaves the longer lease alone
        otherwise."""
        ttl = self.default_ttl_s if ttl_s is None else ttl_s
        expire = time.time() + ttl
        pinned: List[str] = []
        with tagged(TrafficClass.KVCACHE):
            for key in keys:
                path = shard_path(self.root, key)
                try:
                    cur = self._lease_of(path)
                    if cur is not None and cur[0] > expire:
                        pinned.append(key)  # already better protected
                        continue
                    self._meta.set_xattr(
                        path, LEASE_XATTR, encode_lease(expire, self.owner))
                    pinned.append(key)
                except FsError:
                    continue  # missing entry: nothing to protect
        lease = Lease(self.owner, pinned, expire)
        lease._manager = self
        self._bump(len(pinned))
        return lease

    def renew(self, lease: Lease, ttl_s: Optional[float] = None) -> None:
        """Extend a live lease (long decodes renew well before expiry)."""
        ttl = self.default_ttl_s if ttl_s is None else ttl_s
        expire = time.time() + ttl
        with tagged(TrafficClass.KVCACHE):
            for key in lease.keys:
                try:
                    self._meta.set_xattr(
                        shard_path(self.root, key), LEASE_XATTR,
                        encode_lease(expire, self.owner))
                except FsError:
                    continue  # entry gone (expired lease + GC): skip
        lease.expire_ts = expire

    def unpin(self, lease: Lease) -> int:
        """Release a lease's pins; returns pins actually removed. Only
        strips the xattr while it still carries THIS lease's protection —
        a longer or foreign lease stacked on a shared block survives."""
        released = 0
        with tagged(TrafficClass.KVCACHE):
            for key in lease.keys:
                path = shard_path(self.root, key)
                try:
                    cur = self._lease_of(path)
                    if cur is None:
                        continue
                    expire, owner = cur
                    if owner == self.owner and expire <= lease.expire_ts:
                        self._meta.remove_xattr(path, LEASE_XATTR)
                        released += 1
                except FsError:
                    continue
        self._bump(-len(lease.keys))
        lease.keys = []
        return released

    def _lease_of(self, path: str):
        try:
            raw = self._meta.get_xattr(path, LEASE_XATTR)
        except FsError:
            return None
        return decode_lease(raw)
