"""Cluster-manager schema: nodes, targets, chains, routing info, lease.

Re-expresses src/fbs/mgmtd (RoutingInfo.h:11-41, MgmtdTypes.h,
MgmtdLeaseInfo.h:9-22): versioned routing snapshots of nodes + chain tables +
chains + targets, public/local target states from docs/design_notes.md
"Failure detection", and the primary-election lease record.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional


class NodeType(enum.IntEnum):
    MGMTD = 1
    META = 2
    STORAGE = 3
    CLIENT = 4
    FUSE = 5


class NodeStatus(enum.IntEnum):
    HEARTBEAT_CONNECTING = 0
    HEARTBEAT_CONNECTED = 1      # ref MgmtdTypes.h:30-36
    HEARTBEAT_FAILED = 2
    DISABLED = 3


class PublicTargetState(enum.IntEnum):
    """Read/write admission per design_notes table:
    serving R+W, syncing W-only, waiting/lastsrv/offline none."""

    SERVING = 1
    SYNCING = 2
    WAITING = 3
    LASTSRV = 4
    OFFLINE = 5

    @property
    def can_read(self) -> bool:
        return self == PublicTargetState.SERVING

    @property
    def can_write(self) -> bool:
        return self in (PublicTargetState.SERVING, PublicTargetState.SYNCING)


class LocalTargetState(enum.IntEnum):
    UPTODATE = 1
    ONLINE = 2
    OFFLINE = 3


@dataclass
class ChainTarget:
    """A target's position in a chain, with both state views."""

    target_id: int
    public_state: PublicTargetState = PublicTargetState.SERVING
    local_state: LocalTargetState = LocalTargetState.UPTODATE


@dataclass
class TargetInfo:
    target_id: int
    node_id: int = 0
    disk_index: int = 0
    chain_id: int = 0
    public_state: PublicTargetState = PublicTargetState.OFFLINE
    local_state: LocalTargetState = LocalTargetState.OFFLINE
    used_size: int = 0


@dataclass
class ChainInfo:
    chain_id: int
    chain_version: int = 1
    targets: List[ChainTarget] = field(default_factory=list)
    preferred_order: List[int] = field(default_factory=list)
    # EC chain-table type (ref deploy/data_placement data_placement.py:30
    # chain_table_type Literal["EC","CR"]): ec_k/ec_m nonzero makes this an
    # erasure-coded group — target at preferred_order position i holds shard
    # i of every stripe (i < ec_k data, else parity); (0, 0) = CRAQ chain
    ec_k: int = 0
    ec_m: int = 0

    @property
    def is_ec(self) -> bool:
        return self.ec_k > 0

    def shard_index(self, target_id: int) -> int:
        """Stable shard position of a target (chain_sm may reorder
        `targets`; `preferred_order` preserves the layout positions)."""
        return self.preferred_order.index(target_id)

    def target_of_shard(self, shard: int) -> Optional[ChainTarget]:
        if shard >= len(self.preferred_order):
            return None
        tid = self.preferred_order[shard]
        return next((t for t in self.targets if t.target_id == tid), None)

    def serving_targets(self) -> List[ChainTarget]:
        return [t for t in self.targets if t.public_state == PublicTargetState.SERVING]

    def head(self) -> Optional[ChainTarget]:
        serving = self.serving_targets()
        return serving[0] if serving else None

    def tail(self) -> Optional[ChainTarget]:
        serving = self.serving_targets()
        return serving[-1] if serving else None

    def writer_chain(self) -> List[ChainTarget]:
        """Targets that receive writes, in propagation order (serving+syncing)."""
        return [t for t in self.targets if t.public_state.can_write]


@dataclass
class ChainTable:
    table_id: int
    version: int = 1
    chain_ids: List[int] = field(default_factory=list)


@dataclass
class NodeInfo:
    node_id: int
    type: NodeType
    status: NodeStatus = NodeStatus.HEARTBEAT_CONNECTING
    host: str = ""
    port: int = 0
    last_heartbeat: float = 0.0
    heartbeat_version: int = 0
    config_version: int = 0
    tags: Dict[str, str] = field(default_factory=dict)


@dataclass
class ServingEndpoint:
    """One process's KVCache serving endpoint (tpu3fs/serving): where
    peers reach its peerRead service, published through RoutingInfo like
    chain tables so discovery is gossip-light — every routing refresh IS
    the peer directory. TTL-leased: an endpoint that stops re-registering
    is pruned by the mgmtd tick (a crashed serving process must fall out
    of peer selection even before breakers open)."""

    node_id: int
    host: str = ""
    port: int = 0
    registered_at: float = 0.0
    ttl_s: float = 30.0


@dataclass
class MetaPartition:
    """One metadata partition's assignment row (tpu3fs/metashard): the
    namespace is split into a FIXED number of partitions (directory-hash
    over the parent path for by-path ops; the partition id baked into the
    high bits of every inode id for by-inode ops) and mgmtd assigns each
    partition to exactly one live META node, publishing the table through
    RoutingInfo like chain tables. ``epoch`` bumps on every ownership
    change — a meta server fences ops against the epoch it loaded, so a
    reassigned partition's old owner answers META_WRONG_PARTITION instead
    of racing the new owner."""

    partition_id: int
    node_id: int = 0          # 0 = unassigned (no live meta node)
    epoch: int = 0
    # ops/s the owner reported for this partition on its last heartbeat
    # (admin_cli meta-partitions' load column; informational only)
    load: float = 0.0


@dataclass
class LeaseInfo:
    """Primary election record (ref MgmtdLeaseInfo.h:9-22); mutated only via
    KV compare-and-set inside a transaction (MgmtdStore::extendLease)."""

    primary_node_id: int = 0
    lease_start: float = 0.0
    lease_end: float = 0.0
    release_version: int = 0


@dataclass
class RoutingInfo:
    """Versioned cluster snapshot served to all services and clients
    (ref src/fbs/mgmtd/RoutingInfo.h:11-41)."""

    version: int = 0
    nodes: Dict[int, NodeInfo] = field(default_factory=dict)
    chain_tables: Dict[int, ChainTable] = field(default_factory=dict)
    chains: Dict[int, ChainInfo] = field(default_factory=dict)
    targets: Dict[int, TargetInfo] = field(default_factory=dict)
    # KVCache serving endpoints (tpu3fs/serving peer directory) — trailing
    # field on purpose: serde decoders default missing trailing fields, so
    # pre-serving peers interop (rpc/serde.py evolution rule)
    serving: Dict[int, ServingEndpoint] = field(default_factory=dict)
    # metadata partition table (tpu3fs/metashard) — also trailing: decoders
    # predating the metashard plane read an empty table and keep treating
    # the meta plane as a single unpartitioned process
    meta_partitions: Dict[int, MetaPartition] = field(default_factory=dict)

    def meta_owner(self, partition_id: int) -> Optional[NodeInfo]:
        """The NodeInfo currently owning one meta partition (None when
        the table is empty or the partition is unassigned)."""
        row = self.meta_partitions.get(partition_id)
        if row is None or not row.node_id:
            return None
        return self.nodes.get(row.node_id)

    def chain_of_target(self, target_id: int) -> Optional[ChainInfo]:
        info = self.targets.get(target_id)
        return self.chains.get(info.chain_id) if info else None

    def node_of_target(self, target_id: int) -> Optional[NodeInfo]:
        info = self.targets.get(target_id)
        return self.nodes.get(info.node_id) if info else None
