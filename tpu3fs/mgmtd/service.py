"""Cluster manager: lease-based primary election, heartbeats, chain updates,
versioned routing distribution, config distribution.

Re-expresses src/mgmtd: MgmtdState guarded state persisted through the KV
store (MgmtdStore.cc — "SING"/"CHIT"/"CHIF"/"TGIF"/"NODE" prefixes), lease
election by compare-and-set inside a transaction (MgmtdStore::extendLease,
store/MgmtdStore.h:19-46), versioned heartbeats with staleness rejection
(ops/HeartbeatOperation.cc:36-134), the background chain updater applying the
state machine (background/MgmtdChainsUpdater), and per-node-type config blobs
pushed via heartbeat responses (CoreServiceDef.h getConfig/hotUpdateConfig).

Only the primary mutates cluster state; every mutation re-validates the lease
inside the same KV transaction that writes, so a deposed primary's writes
fail atomically.
"""

from __future__ import annotations

import struct
import time
from dataclasses import dataclass, replace
from typing import Callable, Dict, List, Optional

from tpu3fs.kv.kv import IKVEngine, ITransaction, KeyPrefix, with_transaction
from tpu3fs.mgmtd.chain_sm import step_chain
from tpu3fs.mgmtd.types import (
    ChainInfo,
    ChainTable,
    ChainTarget,
    LeaseInfo,
    LocalTargetState,
    MetaPartition,
    NodeInfo,
    NodeStatus,
    NodeType,
    PublicTargetState,
    RoutingInfo,
    ServingEndpoint,
    TargetInfo,
)
from tpu3fs.rpc.serde import deserialize, serialize
from tpu3fs.utils.result import Code, FsError, Status

_LEASE_KEY = KeyPrefix.LEASE.value + b"primary"
_ROUTING_VER_KEY = b"RTVR"
_MIGRATION_SEQ_KEY = b"MGJC"


def _migration_key(job_id: int) -> bytes:
    return KeyPrefix.MIGRATION.value + struct.pack(">Q", job_id)


def _node_key(node_id: int) -> bytes:
    return KeyPrefix.NODE.value + struct.pack(">Q", node_id)


def _chain_key(chain_id: int) -> bytes:
    return KeyPrefix.CHAIN_INFO.value + struct.pack(">Q", chain_id)


def _table_key(table_id: int) -> bytes:
    return KeyPrefix.CHAIN_TABLE.value + struct.pack(">Q", table_id)


def _target_key(target_id: int) -> bytes:
    return KeyPrefix.TARGET_INFO.value + struct.pack(">Q", target_id)


def _config_key(node_type: NodeType) -> bytes:
    return KeyPrefix.CONFIG.value + struct.pack(">B", int(node_type))


def _serving_key(node_id: int) -> bytes:
    return KeyPrefix.SERVING.value + struct.pack(">Q", node_id)


def _meta_part_key(partition_id: int) -> bytes:
    # META_SERVER + "P": the persisted metadata partition table
    # (tpu3fs/metashard) — one row per partition, like chain rows
    return KeyPrefix.META_SERVER.value + b"P" + struct.pack(">H", partition_id)


@dataclass
class MgmtdConfig:
    lease_length_s: float = 60.0
    # T: silence after which a node is declared failed; services must
    # self-exit at T/2 without mgmtd contact (design_notes "Failure detection")
    heartbeat_timeout_s: float = 60.0
    new_chain_version_grace_s: float = 0.0
    # metadata partition count (tpu3fs/metashard): the table is created
    # lazily when the first META node connects; 0 = library default. The
    # count is FIXED once the table exists (partition math is baked into
    # issued inode ids), so changing this on a live cluster is ignored.
    meta_partitions: int = 0


@dataclass
class ConfigBlob:
    content: str = ""
    version: int = 0


@dataclass
class HeartbeatReply:
    routing_version: int
    config_version: int
    config_content: str = ""
    lease: Optional[LeaseInfo] = None


class Mgmtd:
    """One cluster-manager instance. Several may run; the lease picks one."""

    def __init__(
        self,
        node_id: int,
        engine: IKVEngine,
        config: Optional[MgmtdConfig] = None,
        clock: Callable[[], float] = time.time,
    ):
        self.node_id = node_id
        self._engine = engine
        self.config = config or MgmtdConfig()
        self._clock = clock
        # in-memory routing snapshot, rebuilt from KV (primary only serves it)
        self._routing = RoutingInfo()
        self._configs: Dict[NodeType, ConfigBlob] = {}
        # heartbeat-touched targets awaiting the TargetInfoPersister runner
        self._dirty_targets: set = set()
        # primacy edge detection for tick(): a standby reloads from KV on
        # promotion before running any background mutator
        self._was_primary = False
        # version-gated getRoutingInfo fast-path counter (lazy: most unit
        # tests never poll with a current version)
        self._not_modified_rec = None
        self._load()

    # -- persistence -------------------------------------------------------
    def _load(self) -> None:
        def op(txn: ITransaction):
            routing = RoutingInfo()
            ver = txn.get(_ROUTING_VER_KEY)
            routing.version = int(ver) if ver else 0
            for pair in txn.get_range(
                KeyPrefix.NODE.value, KeyPrefix.NODE.value + b"\xff" * 9,
                snapshot=True,
            ):
                info = deserialize(pair.value, NodeInfo)
                routing.nodes[info.node_id] = info
            for pair in txn.get_range(
                KeyPrefix.CHAIN_INFO.value, KeyPrefix.CHAIN_INFO.value + b"\xff" * 9,
                snapshot=True,
            ):
                info = deserialize(pair.value, ChainInfo)
                routing.chains[info.chain_id] = info
            for pair in txn.get_range(
                KeyPrefix.CHAIN_TABLE.value, KeyPrefix.CHAIN_TABLE.value + b"\xff" * 9,
                snapshot=True,
            ):
                tbl = deserialize(pair.value, ChainTable)
                routing.chain_tables[tbl.table_id] = tbl
            for pair in txn.get_range(
                KeyPrefix.TARGET_INFO.value, KeyPrefix.TARGET_INFO.value + b"\xff" * 9,
                snapshot=True,
            ):
                info = deserialize(pair.value, TargetInfo)
                routing.targets[info.target_id] = info
            for pair in txn.get_range(
                KeyPrefix.SERVING.value, KeyPrefix.SERVING.value + b"\xff" * 9,
                snapshot=True,
            ):
                ep = deserialize(pair.value, ServingEndpoint)
                routing.serving[ep.node_id] = ep
            for pair in txn.get_range(
                KeyPrefix.META_SERVER.value + b"P",
                KeyPrefix.META_SERVER.value + b"P" + b"\xff" * 3,
                snapshot=True,
            ):
                row = deserialize(pair.value, MetaPartition)
                routing.meta_partitions[row.partition_id] = row
            configs = {}
            for pair in txn.get_range(
                KeyPrefix.CONFIG.value, KeyPrefix.CONFIG.value + b"\xff" * 2,
                snapshot=True,
            ):
                nt = NodeType(pair.key[len(KeyPrefix.CONFIG.value)])
                configs[nt] = deserialize(pair.value, ConfigBlob)
            return routing, configs

        self._routing, self._configs = with_transaction(
            self._engine, op, read_only=True
        )

    def _bump_routing_in_txn(self, txn: ITransaction) -> int:
        """Bump the persisted routing version; the caller installs the
        returned value into the in-memory snapshot only AFTER the transaction
        commits (so deposed-primary/conflict aborts leave memory untouched)."""
        ver = txn.get(_ROUTING_VER_KEY)
        new = (int(ver) if ver else 0) + 1
        txn.set(_ROUTING_VER_KEY, str(new).encode())
        return new

    # -- lease election (ref MgmtdStore::extendLease) ------------------------
    def extend_lease(self, now: Optional[float] = None) -> LeaseInfo:
        """CAS on the lease record: acquire if free/expired, extend if held."""
        now = self._clock() if now is None else now

        def op(txn: ITransaction) -> LeaseInfo:
            raw = txn.get(_LEASE_KEY)
            lease = deserialize(raw, LeaseInfo) if raw else LeaseInfo()
            if lease.primary_node_id == self.node_id:
                lease.lease_end = now + self.config.lease_length_s
            elif lease.primary_node_id == 0 or now > lease.lease_end:
                lease = LeaseInfo(
                    primary_node_id=self.node_id,
                    lease_start=now,
                    lease_end=now + self.config.lease_length_s,
                    release_version=lease.release_version + 1,
                )
            txn.set(_LEASE_KEY, serialize(lease))
            return lease

        lease = with_transaction(self._engine, op)
        # primacy is CONFIRMED here (tests and apps may call extend_lease
        # outside tick); tick() reads the previous value before calling us
        # to detect the standby->primary edge
        self._was_primary = lease.primary_node_id == self.node_id
        return lease

    def _ensure_holder_in_txn(self, txn: ITransaction) -> None:
        """Reject when ANOTHER node holds the lease (expiry ignored):
        the guard for heartbeat/registration traffic. Accepting these on a
        node whose own lease merely expired is harmless — no other primary
        exists to diverge from, and the strict mutators still re-validate
        expiry — while rejecting them would break quiet clusters between
        lease extensions. The case that matters (a client pinned to a
        STANDBY while a live primary declares its nodes dead) is exactly
        `primary_node_id != self.node_id`, which this refuses."""
        raw = txn.get(_LEASE_KEY)
        lease = deserialize(raw, LeaseInfo) if raw else LeaseInfo()
        if lease.primary_node_id not in (0, self.node_id):
            raise FsError(Status(
                Code.MGMTD_NOT_PRIMARY,
                f"primary={lease.primary_node_id}"))

    def current_lease(self) -> LeaseInfo:
        def op(txn: ITransaction) -> LeaseInfo:
            raw = txn.get(_LEASE_KEY)
            return deserialize(raw, LeaseInfo) if raw else LeaseInfo()

        return with_transaction(self._engine, op, read_only=True)

    def is_primary(self, now: Optional[float] = None) -> bool:
        now = self._clock() if now is None else now
        lease = self.current_lease()
        return lease.primary_node_id == self.node_id and now <= lease.lease_end

    def _ensure_primary_in_txn(self, txn: ITransaction, now: float) -> None:
        """Re-validate the lease inside the mutating transaction, so writes of
        a deposed primary conflict-abort instead of landing."""
        raw = txn.get(_LEASE_KEY)
        lease = deserialize(raw, LeaseInfo) if raw else LeaseInfo()
        if lease.primary_node_id != self.node_id or now > lease.lease_end:
            raise FsError(
                Status(Code.MGMTD_NOT_PRIMARY, f"primary={lease.primary_node_id}")
            )

    # -- admin: bootstrap topology ------------------------------------------
    def create_target(
        self, target_id: int, node_id: int = 0, disk_index: int = 0
    ) -> None:
        info = TargetInfo(target_id, node_id=node_id, disk_index=disk_index)

        def op(txn: ITransaction) -> int:
            self._ensure_primary_in_txn(txn, self._clock())
            txn.set(_target_key(target_id), serialize(info))
            return self._bump_routing_in_txn(txn)

        ver = with_transaction(self._engine, op)
        self._routing.targets[target_id] = info
        self._routing.version = ver

    def upload_chain(self, chain_id: int, target_ids: List[int],
                     *, ec_k: int = 0, ec_m: int = 0,
                     wait_ready: bool = False) -> None:
        """Create a chain over existing targets. Default: optimistic
        SERVING/UPTODATE (single-process fabrics where targets exist by
        construction). wait_ready=True creates the chain NEWBORN — every
        target WAITING until its node heartbeats UPTODATE, when the
        NewBornChainsChecker promotes the whole chain to SERVING (ref
        src/mgmtd/background/MgmtdNewBornChainsChecker). With ec_k/ec_m
        the chain is an erasure-coded group (chain-table type "EC", ref
        data_placement.py:30): target i holds shard i."""
        if ec_k and len(target_ids) != ec_k + ec_m:
            raise FsError(Status(
                Code.INVALID_ARG,
                f"EC({ec_k},{ec_m}) needs {ec_k + ec_m} targets, "
                f"got {len(target_ids)}"))
        pub = (PublicTargetState.WAITING if wait_ready
               else PublicTargetState.SERVING)
        loc = (LocalTargetState.OFFLINE if wait_ready
               else LocalTargetState.UPTODATE)
        targets = [ChainTarget(t, pub, loc) for t in target_ids]
        chain = ChainInfo(chain_id, 1, targets, list(target_ids),
                          ec_k=ec_k, ec_m=ec_m)
        staged_infos = []
        for tid in target_ids:
            info = self._routing.targets.get(tid)
            info = replace(info) if info is not None else TargetInfo(tid)
            info.chain_id = chain_id
            info.public_state = pub
            info.local_state = loc
            staged_infos.append(info)

        def op(txn: ITransaction) -> int:
            self._ensure_primary_in_txn(txn, self._clock())
            txn.set(_chain_key(chain_id), serialize(chain))
            for info in staged_infos:
                txn.set(_target_key(info.target_id), serialize(info))
            return self._bump_routing_in_txn(txn)

        ver = with_transaction(self._engine, op)
        self._routing.chains[chain_id] = chain
        for info in staged_infos:
            self._routing.targets[info.target_id] = info
        self._routing.version = ver

    def upload_chain_table(self, table_id: int, chain_ids: List[int]) -> None:
        old = self._routing.chain_tables.get(table_id)
        tbl = ChainTable(table_id, (old.version + 1) if old else 1, list(chain_ids))

        def op(txn: ITransaction) -> int:
            self._ensure_primary_in_txn(txn, self._clock())
            txn.set(_table_key(table_id), serialize(tbl))
            return self._bump_routing_in_txn(txn)

        ver = with_transaction(self._engine, op)
        self._routing.chain_tables[table_id] = tbl
        self._routing.version = ver

    # -- live chain mutation (elasticity; ref src/mgmtd updateChain admin) ---
    def add_chain_target(self, chain_id: int, target_id: int, node_id: int,
                         *, disk_index: int = 0, replace_of: int = 0) -> None:
        """Join ``target_id`` (created on ``node_id``) to a LIVE chain.

        CR chains: the new member is APPENDED as WAITING/OFFLINE — the
        hosting node discovers it via routing, opens it ONLINE, and the
        chain state machine runs the ordinary WAITING→SYNCING→SERVING
        recovery ladder while every existing member keeps serving (the
        old member a migration job later drops stays readable the whole
        time).

        EC chains: members hold DIFFERENT shards, so a join must take
        over a specific shard position — ``replace_of`` names the member
        whose ``preferred_order`` slot the new target inherits; the old
        member leaves the chain atomically in the same version bump and
        the new shard is decode-rebuilt from the k+m-1 survivors
        (storage/ec_resync.py). Refused (MIGRATION_QUORUM) when any
        OTHER member is not SERVING — the swap may only spend the one
        redundancy unit the chain actually has spare.

        Idempotent: re-executing after a worker crash (the target is
        already a member) is a no-op."""
        chain = self._routing.chains.get(chain_id)
        if chain is None:
            raise FsError(Status(Code.MGMTD_CHAIN_NOT_FOUND, str(chain_id)))
        if any(t.target_id == target_id for t in chain.targets):
            return  # resumed worker re-executing a committed PREPARE
        from tpu3fs.mgmtd.types import ChainTarget

        new_member = ChainTarget(target_id, PublicTargetState.WAITING,
                                 LocalTargetState.OFFLINE)
        targets = [replace(t) for t in chain.targets]
        order = list(chain.preferred_order)
        dropped_info: Optional[TargetInfo] = None
        if chain.is_ec:
            if replace_of not in order:
                raise FsError(Status(
                    Code.INVALID_ARG,
                    f"EC join needs replace_of naming a member of chain "
                    f"{chain_id} (got {replace_of})"))
            others = [t for t in targets if t.target_id != replace_of]
            if any(t.public_state != PublicTargetState.SERVING
                   for t in others):
                raise FsError(Status(
                    Code.MIGRATION_QUORUM,
                    f"EC chain {chain_id} already degraded: swapping "
                    f"{replace_of} would spend a second redundancy unit"))
            order[order.index(replace_of)] = target_id
            targets = others + [new_member]
            old = self._routing.targets.get(replace_of)
            if old is not None:
                dropped_info = replace(old)
                # KEEP chain_id: the swapped-out member leaves the chain
                # but must survive the hosting node's retirement scan
                # (which reaps chain_id 0) until the migration worker
                # releases it at cutover — that window is the EC drain
                # DIRECT-COPY path (the worker reads the outgoing shard
                # target-addressed, 1/k the bytes of a decode rebuild)
                dropped_info.public_state = PublicTargetState.OFFLINE
        else:
            targets.append(new_member)
            order.append(target_id)
        new_chain = replace(chain, targets=targets, preferred_order=order,
                            chain_version=chain.chain_version + 1)
        info = TargetInfo(target_id, node_id=node_id, disk_index=disk_index,
                          chain_id=chain_id,
                          public_state=PublicTargetState.WAITING,
                          local_state=LocalTargetState.OFFLINE)

        def op(txn: ITransaction) -> int:
            self._ensure_primary_in_txn(txn, self._clock())
            txn.set(_chain_key(chain_id), serialize(new_chain))
            txn.set(_target_key(target_id), serialize(info))
            if dropped_info is not None:
                txn.set(_target_key(dropped_info.target_id),
                        serialize(dropped_info))
            return self._bump_routing_in_txn(txn)

        ver = with_transaction(self._engine, op)
        self._routing.chains[chain_id] = new_chain
        self._routing.targets[target_id] = info
        if dropped_info is not None:
            self._routing.targets[dropped_info.target_id] = dropped_info
        self._routing.version = ver

    def drop_chain_target(self, chain_id: int, target_id: int,
                          *, min_serving: int = 1) -> None:
        """Remove a member from a live chain (migration cutover / dead-
        member retirement). Refused (MIGRATION_QUORUM) when the chain
        would keep fewer than ``min_serving`` SERVING members — the
        caller passes the chain's nominal width so a cutover can never
        under-replicate, and ``1`` for emergency pruning. The detached
        target's info stays in routing with chain_id=0/OFFLINE so the
        hosting node's target scan retires (trash-routes) its data.

        Idempotent: dropping a non-member is a no-op."""
        chain = self._routing.chains.get(chain_id)
        if chain is None:
            raise FsError(Status(Code.MGMTD_CHAIN_NOT_FOUND, str(chain_id)))
        if all(t.target_id != target_id for t in chain.targets):
            # not a member: a resumed worker re-executing a committed
            # cutover (no-op), or the RELEASE of an EC swap's outgoing
            # member — detached from the chain at PREPARE but kept alive
            # in routing (chain_id intact) for the drain direct-copy
            # window; cutover detaches it to chain_id 0 / OFFLINE so the
            # hosting node's scan retires (trash-routes) it. No quorum
            # gate: the release changes no chain membership.
            info = self._routing.targets.get(target_id)
            if info is None or info.chain_id != chain_id:
                return
            released = replace(info)
            released.chain_id = 0
            released.public_state = PublicTargetState.OFFLINE

            def release_op(txn: ITransaction) -> int:
                self._ensure_primary_in_txn(txn, self._clock())
                txn.set(_target_key(target_id), serialize(released))
                return self._bump_routing_in_txn(txn)

            ver = with_transaction(self._engine, release_op)
            self._routing.targets[target_id] = released
            self._routing.version = ver
            return
        remaining = [replace(t) for t in chain.targets
                     if t.target_id != target_id]
        serving_after = sum(
            1 for t in remaining
            if t.public_state == PublicTargetState.SERVING)
        if serving_after < min_serving:
            raise FsError(Status(
                Code.MIGRATION_QUORUM,
                f"dropping {target_id} leaves chain {chain_id} with "
                f"{serving_after} serving < quorum {min_serving}"))
        order = [t for t in chain.preferred_order if t != target_id]
        new_chain = replace(chain, targets=remaining, preferred_order=order,
                            chain_version=chain.chain_version + 1)
        info = self._routing.targets.get(target_id)
        info = replace(info) if info is not None else TargetInfo(target_id)
        info.chain_id = 0
        info.public_state = PublicTargetState.OFFLINE

        def op(txn: ITransaction) -> int:
            self._ensure_primary_in_txn(txn, self._clock())
            txn.set(_chain_key(chain_id), serialize(new_chain))
            txn.set(_target_key(target_id), serialize(info))
            return self._bump_routing_in_txn(txn)

        ver = with_transaction(self._engine, op)
        self._routing.chains[chain_id] = new_chain
        self._routing.targets[target_id] = info
        self._routing.version = ver

    def set_node_tags(self, node_id: int, tags: Dict[str, str]) -> None:
        """Merge operator tags onto a node record (empty value deletes a
        key). ``draining=1`` is how an operator marks a node for the
        rebalance planner to empty; tags persist and ride routing so
        every planner invocation — any client, any time — sees them."""
        node = self._routing.nodes.get(node_id)
        if node is None:
            raise FsError(Status(Code.MGMTD_NODE_NOT_FOUND, str(node_id)))
        merged = dict(node.tags)
        for k, v in tags.items():
            if v == "":
                merged.pop(k, None)
            else:
                merged[k] = v
        staged = replace(node, tags=merged)

        def op(txn: ITransaction) -> int:
            self._ensure_primary_in_txn(txn, self._clock())
            txn.set(_node_key(node_id), serialize(staged))
            return self._bump_routing_in_txn(txn)

        ver = with_transaction(self._engine, op)
        self._routing.nodes[node_id] = staged
        self._routing.version = ver

    # -- migration job store (crash-safe; ref src/migration job service) -----
    # Jobs live ONLY in the KV — no in-memory cache — so a failed-over
    # primary serves them unchanged and every mutation is one atomic,
    # lease-validated transaction.

    def _next_target_id(self) -> int:
        return max(self._routing.targets, default=999) + 1

    def migration_submit(self, specs: List["MoveSpec"]) -> List[int]:
        """Persist one job per spec; allocates job ids (and fresh target
        ids for specs that left new_target=0). Refuses (MIGRATION_CONFLICT)
        when an ACTIVE job already reshapes one of the chains — a chain
        migrates one membership at a time, which is what keeps the
        quorum invariant local to a single job."""
        from tpu3fs.migration.types import MigrationJob

        now = self._clock()
        active_chains = {j.chain_id for j in self.migration_list()
                         if j.active}
        staged: List[MigrationJob] = []
        seen_chains = set()
        next_tid = self._next_target_id()
        for spec in specs:
            chain = self._routing.chains.get(spec.chain_id)
            if chain is None:
                raise FsError(Status(Code.MGMTD_CHAIN_NOT_FOUND,
                                     str(spec.chain_id)))
            if spec.chain_id in active_chains or spec.chain_id in seen_chains:
                raise FsError(Status(
                    Code.MIGRATION_CONFLICT,
                    f"chain {spec.chain_id} already has an active job"))
            seen_chains.add(spec.chain_id)
            new_target = spec.new_target
            if not new_target:
                new_target = next_tid
                next_tid += 1
            staged.append(MigrationJob(
                job_id=0, chain_id=spec.chain_id,
                out_target=spec.out_target, new_target=new_target,
                dst_node=spec.dst_node, is_ec=chain.is_ec,
                submitted_at=now, updated_at=now))

        def op(txn: ITransaction) -> List[int]:
            self._ensure_primary_in_txn(txn, now)
            raw = txn.get(_MIGRATION_SEQ_KEY)
            seq = int(raw) if raw else 0
            ids = []
            for job in staged:
                seq += 1
                job.job_id = seq
                txn.set(_migration_key(seq), serialize(job))
                ids.append(seq)
            txn.set(_MIGRATION_SEQ_KEY, str(seq).encode())
            return ids

        return with_transaction(self._engine, op)

    def migration_list(self) -> List["MigrationJob"]:
        from tpu3fs.migration.types import MigrationJob

        def op(txn: ITransaction) -> List[MigrationJob]:
            return [deserialize(pair.value, MigrationJob)
                    for pair in txn.get_range(
                        KeyPrefix.MIGRATION.value,
                        KeyPrefix.MIGRATION.value + b"\xff" * 9,
                        snapshot=True)]

        return with_transaction(self._engine, op, read_only=True)

    def migration_claim(self, worker: str, *, max_jobs: int = 4,
                        lease_s: float = 30.0) -> List["MigrationJob"]:
        """Hand up to ``max_jobs`` runnable jobs to ``worker`` (CAS in one
        txn). A job is claimable when active and unowned — or when its
        claim LAPSED (the owning worker died mid-plan; resume is just the
        next claim). Renewal is claiming a job you already own."""
        now = self._clock()

        def op(txn: ITransaction) -> List:
            from tpu3fs.migration.types import MigrationJob

            self._ensure_primary_in_txn(txn, now)
            out = []
            for pair in txn.get_range(
                    KeyPrefix.MIGRATION.value,
                    KeyPrefix.MIGRATION.value + b"\xff" * 9):
                job = deserialize(pair.value, MigrationJob)
                if not job.active:
                    continue
                if job.worker not in ("", worker) and now < job.claim_expire:
                    continue
                job.worker = worker
                job.claim_expire = now + lease_s
                job.updated_at = now
                txn.set(pair.key, serialize(job))
                out.append(job)
                if len(out) >= max_jobs:
                    break
            return out

        return with_transaction(self._engine, op)

    def migration_report(self, job_id: int, worker: str, *,
                         phase: Optional[int] = None,
                         copied_chunks: int = 0, copied_bytes: int = 0,
                         error: str = "",
                         lease_s: float = 30.0) -> "MigrationJob":
        """Persist a phase transition / progress heartbeat. Only the claim
        owner may report (MIGRATION_CONFLICT otherwise — a SIGKILLed
        worker that wakes up after its lease lapsed and was re-claimed
        cannot clobber the successor's progress). Phases only move
        FORWARD: an idempotent re-report of an already-passed phase is a
        no-op, which is what makes blind re-execution after a crash safe."""
        now = self._clock()

        def op(txn: ITransaction):
            from tpu3fs.migration.types import JobPhase, MigrationJob

            self._ensure_primary_in_txn(txn, now)
            raw = txn.get(_migration_key(job_id))
            if raw is None:
                raise FsError(Status(Code.MIGRATION_JOB_NOT_FOUND,
                                     str(job_id)))
            job = deserialize(raw, MigrationJob)
            if job.worker != worker and now < job.claim_expire:
                raise FsError(Status(
                    Code.MIGRATION_CONFLICT,
                    f"job {job_id} claimed by {job.worker!r}"))
            job.worker = worker
            job.claim_expire = now + lease_s
            if phase is not None and int(phase) > int(job.phase):
                job.phase = JobPhase(int(phase))
            job.copied_chunks += int(copied_chunks)
            job.copied_bytes += int(copied_bytes)
            if error:
                job.error = error
            job.updated_at = now
            txn.set(_migration_key(job_id), serialize(job))
            return job

        return with_transaction(self._engine, op)

    # -- registration & heartbeat -------------------------------------------
    def register_node(
        self, node_id: int, node_type: NodeType, host: str = "", port: int = 0
    ) -> None:
        def op(txn: ITransaction):
            self._ensure_holder_in_txn(txn)
            info = NodeInfo(
                node_id, node_type, NodeStatus.HEARTBEAT_CONNECTING, host, port
            )
            existing = txn.get(_node_key(node_id))
            if existing is not None:
                old = deserialize(existing, NodeInfo)
                info.heartbeat_version = old.heartbeat_version
            txn.set(_node_key(node_id), serialize(info))
            return info, self._bump_routing_in_txn(txn)

        info, ver = with_transaction(self._engine, op)
        self._routing.nodes[node_id] = info
        self._routing.version = ver

    # -- KVCache serving endpoints (tpu3fs/serving peer directory) ----------
    def serving_register(self, node_id: int, host: str, port: int,
                         ttl_s: float = 30.0,
                         now: Optional[float] = None) -> None:
        """Publish (or TTL-renew) a process's peerRead endpoint in routing.
        Persisted like node infos so a primary restart keeps the directory;
        the routing version bumps only when membership or placement
        actually changes — pure renewals stay version-silent so clients'
        known-version polls keep answering 'unchanged'."""
        now = self._clock() if now is None else now
        ep = ServingEndpoint(node_id=node_id, host=host, port=port,
                             registered_at=now, ttl_s=max(1.0, float(ttl_s)))
        old = self._routing.serving.get(node_id)
        renewal = (old is not None and old.host == host
                   and old.port == port)

        def op(txn: ITransaction):
            self._ensure_holder_in_txn(txn)
            txn.set(_serving_key(node_id), serialize(ep))
            if renewal:
                return self._routing.version
            return self._bump_routing_in_txn(txn)

        ver = with_transaction(self._engine, op)
        self._routing.serving[node_id] = ep
        self._routing.version = ver
        self._prune_serving(now)

    def serving_unregister(self, node_id: int) -> None:
        def op(txn: ITransaction):
            self._ensure_holder_in_txn(txn)
            txn.clear(_serving_key(node_id))
            if node_id in self._routing.serving:
                return self._bump_routing_in_txn(txn)
            return self._routing.version

        ver = with_transaction(self._engine, op)
        self._routing.serving.pop(node_id, None)
        self._routing.version = ver

    def _prune_serving(self, now: Optional[float] = None) -> List[int]:
        """Drop endpoints whose TTL lapsed (a crashed serving process
        stops renewing); runs on every register and every tick."""
        now = self._clock() if now is None else now
        expired = [ep.node_id for ep in self._routing.serving.values()
                   if now - ep.registered_at > ep.ttl_s]
        if not expired:
            return expired

        def op(txn: ITransaction):
            for node_id in expired:
                txn.clear(_serving_key(node_id))
            return self._bump_routing_in_txn(txn)

        ver = with_transaction(self._engine, op)
        for node_id in expired:
            self._routing.serving.pop(node_id, None)
        self._routing.version = ver
        return expired

    def heartbeat(
        self,
        node_id: int,
        hb_version: int,
        local_states: Optional[Dict[int, LocalTargetState]] = None,
        now: Optional[float] = None,
        meta_loads: Optional[Dict[int, float]] = None,
    ) -> HeartbeatReply:
        """Versioned heartbeat; stale versions rejected
        (ref HeartbeatOperation.cc:36-134)."""
        now = self._clock() if now is None else now

        def op(txn: ITransaction) -> NodeInfo:
            # the holder guard runs FIRST: a standby's stale snapshot must
            # answer MGMTD_NOT_PRIMARY (which the multi-address client
            # fails over on), never MGMTD_NODE_NOT_FOUND judged from a
            # lagging view — otherwise a client pinned to the standby
            # looks alive HERE while the primary (which never sees the
            # heartbeats) declares the node dead and rotates its targets
            self._ensure_holder_in_txn(txn)
            node = self._routing.nodes.get(node_id)
            if node is None:
                raise FsError(
                    Status(Code.MGMTD_NODE_NOT_FOUND, str(node_id)))
            if hb_version < node.heartbeat_version:
                raise FsError(
                    Status(
                        Code.MGMTD_STALE_HEARTBEAT,
                        f"{hb_version} < {node.heartbeat_version}",
                    )
                )
            node.heartbeat_version = hb_version
            node.last_heartbeat = now
            node.status = NodeStatus.HEARTBEAT_CONNECTED
            txn.set(_node_key(node_id), serialize(node))
            return node

        # the node the TRANSACTION validated, not a re-lookup: a racing
        # standby-tick _load() may swap self._routing in between
        node = with_transaction(self._engine, op)
        if local_states:
            for target_id, ls in local_states.items():
                info = self._routing.targets.get(target_id)
                if info is not None:
                    if (info.local_state != ls
                            or info.node_id != node_id):
                        self._dirty_targets.add(target_id)
                    info.local_state = ls
                    info.node_id = node_id
                chain = self._routing.chain_of_target(target_id)
                if chain is not None:
                    for t in chain.targets:
                        if t.target_id == target_id:
                            t.local_state = ls
        if meta_loads:
            # ephemeral per-partition op-rate gauge (metashard): published
            # on routing for the CLI/assigner, never persisted — a primary
            # restart starts the gauges at zero like heartbeats
            for pid, load in meta_loads.items():
                row = self._routing.meta_partitions.get(pid)
                if row is not None and row.node_id == node_id:
                    row.load = float(load)
        blob = self._configs.get(node.type, ConfigBlob())
        return HeartbeatReply(
            routing_version=self._routing.version,
            config_version=blob.version,
            config_content=blob.content,
            lease=self.current_lease(),
        )

    def check_heartbeats(self, now: Optional[float] = None) -> List[int]:
        """Declare silent nodes dead; their targets' local states go OFFLINE.
        Returns the node ids newly declared failed."""
        now = self._clock() if now is None else now
        dead = []
        for node in self._routing.nodes.values():
            if node.status == NodeStatus.HEARTBEAT_CONNECTED and (
                now - node.last_heartbeat > self.config.heartbeat_timeout_s
            ):
                node.status = NodeStatus.HEARTBEAT_FAILED
                dead.append(node.node_id)
        if not dead:
            return dead

        def op(txn: ITransaction) -> None:
            for node_id in dead:
                txn.set(_node_key(node_id), serialize(self._routing.nodes[node_id]))

        with_transaction(self._engine, op)
        dead_set = set(dead)
        for chain in self._routing.chains.values():
            for t in chain.targets:
                info = self._routing.targets.get(t.target_id)
                if info is not None and info.node_id in dead_set:
                    t.local_state = LocalTargetState.OFFLINE
                    info.local_state = LocalTargetState.OFFLINE
                    # every writer of local_state must mark the target
                    # dirty, or persist_target_infos never writes the
                    # OFFLINE state and a primary restart resurrects the
                    # dead node's last heartbeat as UPTODATE
                    self._dirty_targets.add(t.target_id)
        return dead

    # -- metadata partition assigner (tpu3fs/metashard) ----------------------
    def update_meta_partitions(self, now: Optional[float] = None) -> int:
        """Keep every metadata partition owned by an alive META node, like
        update_chains keeps chains serving (docs/metashard.md): the table
        is created lazily when the first META node connects; a dead
        owner's partitions move to the least-loaded survivors (epoch
        bump per move); a joining node pulls partitions until ownership
        counts are balanced within one. Retained assignments never churn.
        Persists changed rows + bumps the routing version in one
        lease-validated transaction. Returns the number of moved rows."""
        now = self._clock() if now is None else now
        alive = sorted(
            n.node_id for n in self._routing.nodes.values()
            if n.type == NodeType.META
            and n.status == NodeStatus.HEARTBEAT_CONNECTED)
        if not alive and not self._routing.meta_partitions:
            return 0
        if not self._routing.meta_partitions:
            # sharding is opt-in: no table unless the operator configured
            # a width (legacy meta servers keep the any-op-anywhere shape)
            nparts = self.config.meta_partitions
            if not nparts:
                return 0
            table = {pid: MetaPartition(partition_id=pid)
                     for pid in range(nparts)}
        else:
            # stage copies; memory is installed only after the txn commits
            table = {pid: replace(row)
                     for pid, row in self._routing.meta_partitions.items()}
        if not alive:
            # nobody left to own anything: keep the last assignment (the
            # client ladder fails over; survivors pick the table back up)
            return 0
        owned = {nid: 0 for nid in alive}
        for row in table.values():
            if row.node_id in owned:
                owned[row.node_id] += 1
        changed = []
        for pid in sorted(table):
            row = table[pid]
            if row.node_id in owned:
                continue  # owner alive: never churn a retained assignment
            nid = min(alive, key=lambda n: (owned[n], n))
            owned[nid] += 1
            row.node_id = nid
            row.epoch += 1
            row.load = 0.0
            changed.append(row)
        while True:  # join rebalance: drain the most-loaded one move at a time
            hi = max(alive, key=lambda n: (owned[n], -n))
            lo = min(alive, key=lambda n: (owned[n], n))
            if owned[hi] - owned[lo] <= 1:
                break
            pid = min(p for p, r in table.items() if r.node_id == hi)
            row = table[pid]
            row.node_id = lo
            row.epoch += 1
            row.load = 0.0
            owned[hi] -= 1
            owned[lo] += 1
            changed.append(row)
        if not changed:
            return 0

        def op(txn: ITransaction) -> int:
            self._ensure_primary_in_txn(txn, now)
            for row in changed:
                txn.set(_meta_part_key(row.partition_id), serialize(row))
            return self._bump_routing_in_txn(txn)

        ver = with_transaction(self._engine, op)
        self._routing.meta_partitions = table
        self._routing.version = ver
        return len(changed)

    # -- chain updater (ref MgmtdChainsUpdater) ------------------------------
    def update_chains(self, now: Optional[float] = None) -> int:
        """Run the state machine over every chain; persist & bump routing
        version if anything changed. Returns number of updated chains."""
        now = self._clock() if now is None else now
        # stage everything; nothing is installed in memory until the
        # lease-validated transaction commits
        new_chains = {}
        changed_chains = []
        staged_infos = {}
        for chain in self._routing.chains.values():
            new_chain, changed = step_chain(chain)
            new_chains[chain.chain_id] = new_chain
            if changed:
                changed_chains.append(new_chain)
            for t in new_chain.targets:
                info = self._routing.targets.get(t.target_id)
                if info is not None and info.public_state != t.public_state:
                    staged = replace(info)
                    staged.public_state = t.public_state
                    staged_infos[t.target_id] = staged
        if not changed_chains:
            # local-state refreshes only: no version bump, no persistence
            self._routing.chains.update(new_chains)
            return 0

        def op(txn: ITransaction) -> int:
            self._ensure_primary_in_txn(txn, now)
            for chain in changed_chains:
                txn.set(_chain_key(chain.chain_id), serialize(chain))
            for info in staged_infos.values():
                txn.set(_target_key(info.target_id), serialize(info))
            return self._bump_routing_in_txn(txn)

        ver = with_transaction(self._engine, op)
        self._routing.chains.update(new_chains)
        self._routing.targets.update(staged_infos)
        self._routing.version = ver
        return len(changed_chains)

    # -- routing distribution -----------------------------------------------
    def get_routing_info(self, known_version: int = -1) -> Optional[RoutingInfo]:
        """None when the caller is already up to date (version match) —
        the version-gated fast path: the RPC binding turns None into a
        tiny ``changed=False`` reply instead of re-serializing the full
        snapshot for every poller each TTL (docs/scale.md)."""
        if known_version == self._routing.version:
            rec = self._not_modified_rec
            if rec is None:
                from tpu3fs.monitor.recorder import CounterRecorder

                rec = CounterRecorder("mgmtd.routing_not_modified")
                self._not_modified_rec = rec
            rec.add(1)
            return None
        return self._routing

    # -- config distribution (ref SetConfig/GetConfig ops) -------------------
    def set_config(self, node_type: NodeType, content: str) -> int:
        old = self._configs.get(node_type, ConfigBlob())
        blob = ConfigBlob(content, old.version + 1)

        def op(txn: ITransaction) -> int:
            self._ensure_primary_in_txn(txn, self._clock())
            txn.set(_config_key(node_type), serialize(blob))
            return blob.version

        ver = with_transaction(self._engine, op)
        self._configs[node_type] = blob
        return ver

    def get_config(self, node_type: NodeType) -> ConfigBlob:
        return self._configs.get(node_type, ConfigBlob())

    # -- main periodic driver ------------------------------------------------
    def tick(self, now: Optional[float] = None) -> None:
        """One background round — the primary's runner set (ref
        src/mgmtd/background/): lease extension, heartbeat checking, chain
        updates, newborn-chain promotion, target-info persistence, metrics."""
        now = self._clock() if now is None else now
        was_primary = self._was_primary
        lease = self.extend_lease(now)  # updates _was_primary
        if lease.primary_node_id != self.node_id:
            # STANDBY: reload cluster state from the shared KV every tick.
            # Serving routing from (or, worse, later acting on) the
            # boot-time snapshot would hand out an empty/stale cluster —
            # and a freshly-promoted primary running check_heartbeats/
            # update_chains on stale state could clobber the real one.
            try:
                self._load()
            except FsError:
                pass  # KV hiccup: keep the last snapshot, retry next tick
            return
        if not was_primary:
            # primacy TRANSITION: act only on freshly-loaded state; a
            # failed load must NOT leave _was_primary set or the next
            # tick would mutate cluster state from the stale snapshot
            try:
                self._load()
            except FsError:
                self._was_primary = False
                return
            # HEARTBEAT GRACE: the loaded last_heartbeat stamps are from
            # the old primary's reign — up to a full residual lease old.
            # Judging them now would declare every surviving node dead in
            # one sweep. Promotion starts a fresh heartbeat epoch; nodes
            # get a full timeout to re-report before being judged.
            for node in self._routing.nodes.values():
                node.last_heartbeat = max(node.last_heartbeat, now)
        self.check_heartbeats(now)
        try:
            self._prune_serving(now)
        except FsError:
            pass  # deposed mid-tick: the new primary prunes
        try:
            self.update_meta_partitions(now)
        except FsError:
            pass  # deposed mid-tick: the new primary reassigns
        self.update_chains(now)
        self.check_newborn_chains()
        self.persist_target_infos()
        self.update_metrics()

    # -- background runners (ref src/mgmtd/background/) ----------------------
    def check_newborn_chains(self) -> int:
        """MgmtdNewBornChainsChecker analogue: a chain created with
        wait_ready=True holds every target WAITING until each target's
        node is heartbeat-connected and reports UPTODATE; only then does
        the whole chain flip to SERVING (one atomic version bump). The
        plain state machine cannot do this — WAITING stays WAITING without
        a serving source, which is exactly right for REPAIRS but would
        park a brand-new chain forever."""
        promoted = []
        staged_infos = {}
        for chain in self._routing.chains.values():
            targets = chain.targets
            if not targets or any(
                    t.public_state != PublicTargetState.WAITING
                    for t in targets):
                continue
            ready = True
            for t in targets:
                info = self._routing.targets.get(t.target_id)
                node = (self._routing.nodes.get(info.node_id)
                        if info is not None else None)
                if (info is None or node is None
                        or node.status != NodeStatus.HEARTBEAT_CONNECTED
                        or t.local_state != LocalTargetState.UPTODATE):
                    ready = False
                    break
            if not ready:
                continue
            new_targets = [replace(t, public_state=PublicTargetState.SERVING)
                           for t in targets]
            promoted.append(replace(
                chain, targets=new_targets,
                chain_version=chain.chain_version + 1))
            for t in new_targets:
                info = self._routing.targets.get(t.target_id)
                if info is not None:
                    staged = replace(info)
                    staged.public_state = PublicTargetState.SERVING
                    staged_infos[t.target_id] = staged
        if not promoted:
            return 0

        def op(txn: ITransaction) -> int:
            self._ensure_primary_in_txn(txn, self._clock())
            for chain in promoted:
                txn.set(_chain_key(chain.chain_id), serialize(chain))
            for info in staged_infos.values():
                txn.set(_target_key(info.target_id), serialize(info))
            return self._bump_routing_in_txn(txn)

        ver = with_transaction(self._engine, op)
        for chain in promoted:
            self._routing.chains[chain.chain_id] = chain
        self._routing.targets.update(staged_infos)
        self._routing.version = ver
        return len(promoted)

    def persist_target_infos(self) -> int:
        """MgmtdTargetInfoPersister analogue: heartbeat-reported LOCAL
        target states live in memory for speed; this runner batches the
        dirty ones into one transaction so a restarted primary reloads
        last-known states instead of assuming the world away (the loader
        half is _load(), which already reads them back)."""
        dirty = set(self._dirty_targets)
        if not dirty:
            return 0
        infos = [self._routing.targets[t] for t in dirty
                 if t in self._routing.targets]
        if not infos:
            self._dirty_targets -= dirty
            return 0

        def op(txn: ITransaction) -> int:
            self._ensure_primary_in_txn(txn, self._clock())
            for info in infos:
                txn.set(_target_key(info.target_id), serialize(info))
            return len(infos)

        try:
            n = with_transaction(self._engine, op)
        except FsError:
            # deposed / exhausted retries: keep the states dirty so a
            # future primacy (or the next tick) persists them
            return 0
        self._dirty_targets -= dirty
        return n

    def update_metrics(self) -> None:
        """MgmtdMetricsUpdater analogue: cluster-level gauges into the
        monitor pipeline (collector-queryable like every other recorder)."""
        rec = getattr(self, "_metrics_rec", None)
        if rec is None:
            from tpu3fs.monitor.recorder import ValueRecorder

            rec = {
                "nodes_connected": ValueRecorder("mgmtd.nodes_connected"),
                "chains_serving": ValueRecorder("mgmtd.chains_serving"),
                "chains_degraded": ValueRecorder("mgmtd.chains_degraded"),
                "routing_version": ValueRecorder("mgmtd.routing_version"),
            }
            self._metrics_rec = rec
        connected = sum(
            1 for n in self._routing.nodes.values()
            if n.status == NodeStatus.HEARTBEAT_CONNECTED)
        serving = degraded = 0
        for chain in self._routing.chains.values():
            if all(t.public_state == PublicTargetState.SERVING
                   for t in chain.targets):
                serving += 1
            else:
                degraded += 1
        rec["nodes_connected"].set(connected)
        rec["chains_serving"].set(serving)
        rec["chains_degraded"].set(degraded)
        rec["routing_version"].set(self._routing.version)
