"""The chain membership state machine.

Re-expresses the public-state transition semantics of
docs/design_notes.md "Failure detection" (table at lines ~211-230) and
src/mgmtd/service/updateChain.cc:25-140 — the same rules, written as a
pass over state groups:

- SERVING targets stay serving while alive; when ALL serving targets die,
  only the first becomes LASTSRV (the chain must wait for the head's data);
  later dead serving targets go OFFLINE.
- A LASTSRV target that comes back (and no serving exists) resumes SERVING —
  it is the single source of truth. If a serving target exists, LASTSRV
  demotes to OFFLINE.
- SYNCING finishes to SERVING when the service reports up-to-date; falls to
  WAITING if there is no serving source; OFFLINE if dead.
- WAITING/OFFLINE targets reporting ONLINE get promoted to SYNCING only when
  a serving source exists and no other target is already syncing (one
  recovery at a time per chain); otherwise alive targets wait. A target in
  WAITING reporting UPTODATE stays WAITING (same as the reference: a target
  may only claim up-to-date after sync-done, so services must report ONLINE
  when returning).
- New chain order groups SERVING, LASTSRV, SYNCING, WAITING, OFFLINE —
  i.e. dead targets rotate to the chain tail.
- The chain version bumps iff membership order or any public state changed.
"""

from __future__ import annotations

from dataclasses import replace
from typing import List, Tuple

from tpu3fs.mgmtd.types import ChainInfo, ChainTarget, LocalTargetState as LS, PublicTargetState as PS


def _alive(t: ChainTarget) -> bool:
    return t.local_state in (LS.UPTODATE, LS.ONLINE)


def generate_new_chain(targets: List[ChainTarget]) -> List[ChainTarget]:
    """One step of the state machine over a chain's targets (old order in,
    new order out)."""
    by_state = {s: [t for t in targets if t.public_state == s] for s in PS}
    out = {s: [] for s in PS}

    def put(t: ChainTarget, ps: PS):
        out[ps].append(replace(t, public_state=ps))

    for t in by_state[PS.SERVING]:
        if _alive(t):
            put(t, PS.SERVING)
        elif not out[PS.LASTSRV]:
            # all serving died: only the FIRST becomes lastsrv; the chain
            # must wait for it even if later replicas are complete
            put(t, PS.LASTSRV)
        else:
            put(t, PS.OFFLINE)

    for t in by_state[PS.LASTSRV]:
        if out[PS.SERVING]:
            put(t, PS.OFFLINE)
        elif _alive(t):
            put(t, PS.SERVING)
        else:
            put(t, PS.LASTSRV)

    for t in by_state[PS.SYNCING]:
        if t.local_state == LS.UPTODATE:
            put(t, PS.SERVING)
        elif t.local_state == LS.ONLINE:
            put(t, PS.SYNCING if out[PS.SERVING] else PS.WAITING)
        else:
            put(t, PS.OFFLINE)

    for group in (PS.WAITING, PS.OFFLINE):
        for t in by_state[group]:
            if out[PS.SERVING] and not out[PS.SYNCING] and t.local_state == LS.ONLINE:
                put(t, PS.SYNCING)  # start one recovery at a time
            elif _alive(t):
                put(t, PS.WAITING)
            else:
                put(t, PS.OFFLINE)

    # a lastsrv produced this round is void if any serving target remains
    if out[PS.SERVING] and out[PS.LASTSRV]:
        for t in out[PS.LASTSRV]:
            put(t, PS.OFFLINE)
        out[PS.LASTSRV] = []

    new_targets: List[ChainTarget] = []
    for s in (PS.SERVING, PS.LASTSRV, PS.SYNCING, PS.WAITING, PS.OFFLINE):
        new_targets.extend(out[s])
    assert len(new_targets) == len(targets)
    return new_targets


def step_chain(chain: ChainInfo) -> Tuple[ChainInfo, bool]:
    """Apply one state-machine step; bump chain_version iff anything changed."""
    new_targets = generate_new_chain(chain.targets)
    changed = [(t.target_id, t.public_state) for t in chain.targets] != [
        (t.target_id, t.public_state) for t in new_targets
    ]
    if not changed:
        # keep refreshed local states without a version bump
        chain = replace(chain, targets=new_targets)
        return chain, False
    return (
        replace(chain, targets=new_targets, chain_version=chain.chain_version + 1),
        True,
    )
