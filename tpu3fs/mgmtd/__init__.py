from tpu3fs.mgmtd.types import (  # noqa: F401
    ChainInfo,
    ChainTable,
    ChainTarget,
    LeaseInfo,
    LocalTargetState,
    NodeInfo,
    NodeStatus,
    NodeType,
    PublicTargetState,
    RoutingInfo,
    TargetInfo,
)
from tpu3fs.mgmtd.chain_sm import generate_new_chain  # noqa: F401
from tpu3fs.mgmtd.service import Mgmtd, MgmtdConfig  # noqa: F401
