"""Result/Status error model.

Re-expresses the reference's ``Result<T> = Expected<T, Status>`` and the
per-subsystem error taxonomy (ref: src/common/utils/Result.h,
src/common/utils/StatusCode.h) as a small Python type. Services return
``Result`` values instead of raising, so RPC layers can serialize failures and
clients can drive retry ladders off the code class.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Generic, Optional, TypeVar

T = TypeVar("T")


class Code(enum.IntEnum):
    """Error taxonomy, grouped by subsystem in disjoint ranges.

    Mirrors the reference's StatusCode/MetaCode/StorageCode/RPCCode split
    (src/common/utils/StatusCode.h); numbering is our own.
    """

    OK = 0

    # generic 1xx
    INVALID_ARG = 100
    NOT_IMPLEMENTED = 101
    TIMEOUT = 102
    CANCELLED = 103
    INTERNAL = 104
    FAULT_INJECTION = 105
    QUEUE_FULL = 106
    SHUTTING_DOWN = 107
    OVERLOADED = 108         # QoS shed: retryable, carries retry-after hint
    DEADLINE_EXCEEDED = 109  # the op's absolute deadline passed: work shed
    #                          at RPC admission / update-queue dequeue, or a
    #                          client ladder gave up (docs/robustness.md)

    # RPC 2xx
    RPC_CONNECT_FAILED = 200
    RPC_SEND_FAILED = 201
    RPC_TIMEOUT = 202
    RPC_BAD_REQUEST = 203
    RPC_METHOD_NOT_FOUND = 204
    RPC_SERVICE_NOT_FOUND = 205
    RPC_PEER_CLOSED = 206
    PEER_UNHEALTHY = 207     # circuit breaker open for this peer: the call
    #                          failed FAST without touching the wire — retry
    #                          after a routing refresh (docs/robustness.md)

    # KV / transaction 3xx
    KV_CONFLICT = 300
    KV_NOT_FOUND = 301
    KV_TXN_TOO_OLD = 302
    KV_MAYBE_COMMITTED = 303
    KV_RETRYABLE = 304
    KV_NOT_PRIMARY = 305       # replicated kvd: this node is not the leader

    # meta 4xx
    META_NOT_FOUND = 400
    META_EXISTS = 401
    META_NOT_DIRECTORY = 402
    META_IS_DIRECTORY = 403
    META_NOT_EMPTY = 404
    META_NO_PERMISSION = 405
    META_TOO_MANY_SYMLINKS = 406
    META_LOOP = 407          # rename would create a directory cycle
    META_BUSY = 408          # open write sessions exist
    META_NO_SESSION = 409
    META_BAD_LAYOUT = 410
    META_NAME_TOO_LONG = 411
    META_INVALID_PATH = 412
    META_NOT_FILE = 413
    META_NO_XATTR = 414      # ENODATA, distinct from a missing path
    META_WRONG_PARTITION = 415  # op routed to a meta server that does not
    #                          own the partition (stale table / mid-
    #                          reassignment): refresh routing and retry —
    #                          correctness is never at stake, the shared
    #                          KV serializes either way (docs/metashard.md)
    META_TXN_EXPIRED = 416   # two-phase prepare refused: the intent's
    #                          deadline passed (the resolver may already
    #                          be aborting it) or it was never written

    # storage 5xx (update-code taxonomy, ref StorageOperator.cc:401-434)
    CHUNK_NOT_FOUND = 500
    CHUNK_NOT_COMMIT = 501        # read saw an uncommitted head version
    CHUNK_STALE_UPDATE = 502      # update ver <= committed ver (duplicate)
    CHUNK_MISSING_UPDATE = 503    # update ver > committed+1 (gap)
    CHUNK_ADVANCE_UPDATE = 504    # retry raced ahead of a pending update
    CHUNK_COMMITTED_UPDATE = 505  # commit for an already-committed ver
    CHUNK_CHECKSUM_MISMATCH = 506
    NO_SPACE = 507
    TARGET_NOT_FOUND = 508
    TARGET_OFFLINE = 509
    CHAIN_VERSION_MISMATCH = 510
    CHAIN_NOT_FOUND = 511
    NOT_HEAD = 512                # client write sent to a non-head target
    NO_SUCCESSOR = 513
    SYNCING = 514                 # target still receiving full-chunk-replace
    ENGINE_ERROR = 515
    NONHEAD_WRITE_REJECTED = 516
    WRITE_FENCED = 517            # head's mgmtd lease-fence expired: no acks
    #                               until it re-establishes mgmtd contact —
    #                               retryable, routing refresh finds the
    #                               promoted successor (docs/scale.md)

    # mgmtd 6xx
    MGMTD_NOT_PRIMARY = 600
    MGMTD_LEASE_EXPIRED = 601
    MGMTD_STALE_HEARTBEAT = 602
    MGMTD_NODE_NOT_FOUND = 603
    MGMTD_CHAIN_NOT_FOUND = 604
    MGMTD_INVALID_TRANSITION = 605
    MGMTD_REGISTERED = 606

    # client 7xx
    CLIENT_RETRIES_EXHAUSTED = 700
    CLIENT_NO_CHANNEL = 701
    CLIENT_ROUTING_STALE = 702
    CLIENT_BUSY = 703        # bounded queue/limiter full (backpressure)

    # checkpoint subsystem 8xx (tpu3fs/ckpt)
    CKPT_BUSY = 800          # another save session holds this root
    CKPT_NOT_FOUND = 801     # no committed checkpoint at this step
    CKPT_CORRUPT = 802       # manifest/shard failed decode or CRC check

    # dataload subsystem 9xx (tpu3fs/dataload)
    DATALOAD_CORRUPT = 900   # record file header/index/record CRC mismatch
    DATALOAD_STATE_MISMATCH = 901  # resume state does not fit this dataset

    # kvcache subsystem 10xx (tpu3fs/kvcache)
    KVCACHE_STALE = 1000     # entry bytes fail the array-header magic —
    #                          a cached inode outlived its entry (GC'd);
    #                          invalidate and re-stat
    KVCACHE_CORRUPT = 1001   # array header malformed beyond staleness
    KVCACHE_FLUSH_POISONED = 1002  # write-back flusher exhausted its
    #                          consecutive-failure budget: producers must
    #                          stop buffering (tier.py error budget)

    # tenant subsystem 11xx (tpu3fs/tenant)
    TENANT_THROTTLED = 1100  # the op's TENANT exceeded its quota (bytes/s,
    #                          IOPS or kvcache resident budget): retryable,
    #                          carries a retry-after hint like OVERLOADED —
    #                          but it names WHO was over, not that the
    #                          server was full (docs/tenancy.md)

    # usrbio shared-memory data plane 12xx (tpu3fs/usrbio)
    USRBIO_RING_FULL = 1200       # SQ has `entries` unreaped ops in flight;
    #                               the client waits or falls back to sockets
    USRBIO_BAD_IOV = 1201         # SQE region escapes the registered iov /
    #                               token field overflow / unregistered iov id
    USRBIO_AGENT_GONE = 1202      # no completion within the ring deadline or
    #                               registration dropped: the serving process
    #                               is gone — re-handshake or use sockets
    USRBIO_TORN_RING = 1203       # ring header failed magic/version check:
    #                               the segment is torn or foreign — neither
    #                               side may trust its counters
    USRBIO_REPLY_OVERFLOW = 1204  # the reply did not fit the SQE's reply
    #                               region; retry with a larger region or
    #                               fall back to sockets
    USRBIO_UNSUPPORTED = 1205     # SQE names a (service, method) outside the
    #                               ring allowlist (usrbio/transport.py
    #                               RING_METHODS) — never dispatched

    # migration / elasticity subsystem 13xx (tpu3fs/migration, placement)
    MIGRATION_QUORUM = 1300       # chain mutation refused: it would drop the
    #                               chain below its serving write-quorum
    #                               mid-plan (docs/placement.md invariants)
    MIGRATION_CONFLICT = 1301     # an ACTIVE job already reshapes this
    #                               chain / the claim belongs to another
    #                               live worker
    MIGRATION_JOB_NOT_FOUND = 1302


#: Codes on which a client-side retry ladder may re-issue the request.
RETRYABLE_CODES = frozenset(
    {
        Code.TIMEOUT,
        Code.RPC_CONNECT_FAILED,
        Code.RPC_SEND_FAILED,
        Code.RPC_TIMEOUT,
        Code.RPC_PEER_CLOSED,
        Code.KV_CONFLICT,
        Code.KV_TXN_TOO_OLD,
        Code.KV_RETRYABLE,
        Code.KV_NOT_PRIMARY,
        Code.CHUNK_NOT_COMMIT,
        Code.CHAIN_VERSION_MISMATCH,
        Code.CHUNK_ADVANCE_UPDATE,
        Code.TARGET_OFFLINE,
        Code.SYNCING,
        Code.CLIENT_ROUTING_STALE,
        # metashard ownership fence: the op reached a non-owner; a routing
        # refresh re-routes it (MetaRpcClient refreshes before the retry)
        Code.META_WRONG_PARTITION,
        Code.QUEUE_FULL,
        # QoS load shed: the server is telling the client to come back
        # after the carried retry-after hint (qos.retry_after_ms_of)
        Code.OVERLOADED,
        # forwarding found no route to the successor after server-side
        # retries: routing is lagging (startup/failover) — clients should
        # back off and ladder, not fail the write
        Code.NO_SUCCESSOR,
        # the server shed work whose deadline had already passed; a caller
        # with budget left may re-issue (ladders check their own deadline
        # before each retry, so an expired caller stops immediately)
        Code.DEADLINE_EXCEEDED,
        # lease-fenced head: it cannot ack until it re-establishes mgmtd
        # contact; mgmtd is (or will be) promoting a successor — clients
        # refresh routing and the ladder lands on the new head
        Code.WRITE_FENCED,
        # breaker fail-fast: the peer is suspected sick — refresh routing
        # and retry (the half-open probe re-tests the peer independently)
        Code.PEER_UNHEALTHY,
        # tenant quota shed: the server is telling this TENANT to come
        # back after its bucket refills (retry-after hint, like
        # OVERLOADED; a well-behaved client ladder waits it out)
        Code.TENANT_THROTTLED,
    }
)


@dataclass(frozen=True)
class Status:
    code: Code
    message: str = ""

    def is_ok(self) -> bool:
        return self.code == Code.OK

    def retryable(self) -> bool:
        return self.code in RETRYABLE_CODES

    def __str__(self) -> str:  # pragma: no cover - cosmetic
        return f"{self.code.name}({int(self.code)}): {self.message}"


OK_STATUS = Status(Code.OK)


class FsError(Exception):
    """Exception carrying a Status, for code that prefers raising."""

    def __init__(self, status: Status):
        super().__init__(str(status))
        self.status = status

    @property
    def code(self) -> Code:
        return self.status.code


class Result(Generic[T]):
    """Either a value or a Status error. ``Result.ok(v)`` / ``Result.err(...)``."""

    __slots__ = ("_value", "_status")

    def __init__(self, value: Optional[T], status: Status):
        self._value = value
        self._status = status

    @classmethod
    def ok(cls, value: T = None) -> "Result[T]":
        return cls(value, OK_STATUS)

    @classmethod
    def err(cls, code: Code, message: str = "") -> "Result[T]":
        return cls(None, Status(code, message))

    @classmethod
    def from_status(cls, status: Status) -> "Result[T]":
        return cls(None, status)

    def is_ok(self) -> bool:
        return self._status.is_ok()

    @property
    def status(self) -> Status:
        return self._status

    @property
    def code(self) -> Code:
        return self._status.code

    @property
    def value(self) -> T:
        """The success value; raises FsError if this is an error result."""
        if not self.is_ok():
            raise FsError(self._status)
        return self._value

    def value_or(self, default: T) -> T:
        return self._value if self.is_ok() else default

    def __bool__(self) -> bool:
        return self.is_ok()

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        if self.is_ok():
            return f"Result.ok({self._value!r})"
        return f"Result.err({self._status})"


def make_error(code: Code, message: str = "") -> Result:
    return Result.err(code, message)


def err(code: Code, message: str = "") -> FsError:
    """Shorthand constructor for raising: ``raise err(Code.X, "...")``."""
    return FsError(Status(code, message))
