"""Size-classed reusable buffer pool for the transport receive path.

The registered-buffer-pool role of the reference (src/common/net/
RDMABuf.h:434 — a pool of pre-registered buffers RDMA operations land in;
BufferPool in net/Buffer.h): here the "registration" being amortized is
CPython allocation churn — every RPC frame used to allocate a fresh
bytearray. Buffers are leased with acquire() and either released back
(inline frames, whose fields are copied out during serde decode) or
detached (bulk frames, whose memoryview segments escape to the caller and
keep the buffer alive via the view; GC reclaims it).

Release discipline: releasing a buffer that still has exported memoryviews
would hand two frames the same memory — the caller must release ONLY when
no views escaped. The transport upholds this by releasing inline frames
after packet decode and never releasing bulk frames.
"""

from __future__ import annotations

import threading
from typing import Dict, List


def _alloc(n: int):
    """An UNINITIALIZED writable buffer of n bytes. numpy.empty skips the
    page-zeroing a fresh bytearray pays — receive buffers are filled by
    recv_into before any byte is read, so zeroing was pure memory traffic
    (measured ~13% of served-read client time at 256 KiB chunks)."""
    try:
        import numpy as np

        return np.empty(n, dtype=np.uint8)
    except ImportError:  # minimal envs: correctness over the zeroing cost
        return bytearray(n)


def _class_of(n: int) -> int:
    """Smallest power-of-two >= n (min 4 KiB) — the pooling size class."""
    size = 4096
    while size < n:
        size <<= 1
    return size


class BufferPool:
    """Bounded per-class freelists of reusable bytearrays."""

    def __init__(self, *, max_per_class: int = 32,
                 max_class_bytes: int = 8 << 20):
        self._free: Dict[int, List[bytearray]] = {}
        self._mu = threading.Lock()
        self._max_per_class = max_per_class
        # buffers above this size are allocated fresh and never pooled:
        # one 64 MiB frame must not pin 64 MiB of freelist forever
        self._max_class_bytes = max_class_bytes
        self.hits = 0
        self.misses = 0
        # lease accounting for the mem.bufpool_* gauges: acquires minus
        # releases. Detached bulk frames are never release()d by design
        # (their memoryviews own the buffer, GC reclaims), so outstanding
        # counts them until collected — a leak DETECTOR, not a leak.
        self.acquired = 0
        self.released = 0

    def acquire(self, n: int):
        """A writable buffer of len >= n (callers track their own exact
        length). May be a numpy uint8 array (uninitialized — see _alloc)
        or a bytearray; both support len/memoryview/recv_into."""
        cls = _class_of(n)
        if cls > self._max_class_bytes:
            with self._mu:
                self.misses += 1
                self.acquired += 1
            return _alloc(n)
        with self._mu:
            free = self._free.get(cls)
            self.acquired += 1
            if free:
                self.hits += 1
                return free.pop()
            self.misses += 1
        return _alloc(cls)

    def release(self, buf) -> None:
        """Return a lease. ONLY for buffers with no escaped memoryviews."""
        with self._mu:
            self.released += 1
        cls = len(buf)
        # non-class-sized buffers were allocated fresh (oversize path)
        if cls > self._max_class_bytes or cls & (cls - 1):
            return
        with self._mu:
            free = self._free.setdefault(cls, [])
            if len(free) < self._max_per_class:
                free.append(buf)

    def stats(self) -> dict:
        with self._mu:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "outstanding": self.acquired - self.released,
                "pooled_bytes": sum(
                    cls * len(v) for cls, v in self._free.items()),
            }


# shared process-wide pool for the RPC receive path
GLOBAL_POOL = BufferPool()
