from tpu3fs.utils.result import (  # noqa: F401
    Code,
    FsError,
    Result,
    Status,
    make_error,
)
from tpu3fs.utils.config import Config, ConfigItem  # noqa: F401
