"""Logging: XLOG-style leveled logging with an async rotating file writer.

Re-expresses src/common/logging (folly XLOG with custom file writers,
rotation, async queue): a single background writer thread drains a bounded
queue to the target file, rotating at max_bytes into ``.1 .. .N`` suffixes.
``xlog("DFATAL", ...)`` mirrors the reference's invariant style: it logs and
raises in tests (or aborts the process when TPU3FS_DFATAL_ABORT is set),
instead of silently continuing past a broken invariant.
"""

from __future__ import annotations

import os
import queue
import sys
import threading
import time
from typing import Optional

LEVELS = {"DBG": 0, "INFO": 1, "WARN": 2, "ERR": 3, "CRITICAL": 4, "DFATAL": 4}


class DFatalError(AssertionError):
    """Raised by xlog("DFATAL", ...) — a broken invariant."""


class AsyncFileWriter:
    """Bounded-queue async writer with size-based rotation
    (ref AsyncFileWriter + file rotation in src/common/logging)."""

    def __init__(self, path: str, *, max_bytes: int = 64 << 20,
                 max_files: int = 4, queue_size: int = 8192):
        self.path = path
        self.max_bytes = max_bytes
        self.max_files = max_files
        self._q: "queue.Queue[Optional[str]]" = queue.Queue(maxsize=queue_size)
        self.dropped = 0  # lines dropped when the queue is full
        self._f = open(path, "a", buffering=1)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="log-writer")
        self._thread.start()

    def write(self, line: str) -> None:
        try:
            self._q.put_nowait(line)
        except queue.Full:
            self.dropped += 1

    def _rotate(self) -> None:
        self._f.close()
        for i in range(self.max_files - 1, 0, -1):
            src = self.path if i == 1 else f"{self.path}.{i - 1}"
            dst = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, dst)
        self._f = open(self.path, "a", buffering=1)

    def _loop(self) -> None:
        while True:
            line = self._q.get()
            if line is None:
                return
            try:
                self._f.write(line + "\n")
                if self._f.tell() >= self.max_bytes:
                    self._rotate()
            except (OSError, ValueError):
                pass

    def flush(self) -> None:
        """Drain pending lines (best effort) and fsync."""
        deadline = time.time() + 2.0
        while not self._q.empty() and time.time() < deadline:
            time.sleep(0.005)
        try:
            self._f.flush()
        except (OSError, ValueError):
            pass

    def close(self) -> None:
        self.flush()
        self._q.put(None)
        self._thread.join(timeout=2.0)
        try:
            self._f.close()
        except OSError:
            pass


class _LogState:
    level = LEVELS["INFO"]
    writer: Optional[AsyncFileWriter] = None
    to_stderr = False
    lock = threading.Lock()


_state = _LogState()


def init_logging(path: Optional[str] = None, level: str = "INFO",
                 *, stderr: bool = False, max_bytes: int = 64 << 20,
                 max_files: int = 4) -> None:
    with _state.lock:
        _state.level = LEVELS.get(level.upper(), LEVELS["INFO"])
        _state.to_stderr = stderr
        if _state.writer is not None:
            _state.writer.close()
            _state.writer = None
        if path:
            _state.writer = AsyncFileWriter(path, max_bytes=max_bytes,
                                            max_files=max_files)


def shutdown_logging() -> None:
    with _state.lock:
        if _state.writer is not None:
            _state.writer.close()
            _state.writer = None


def xlog(level: str, fmt: str, *args) -> None:
    """XLOGF-style: xlog("INFO", "node %d up", 3). DFATAL logs then raises
    (ref XLOGF(DFATAL, ...) invariant checks)."""
    lvl = LEVELS.get(level.upper(), LEVELS["INFO"])
    msg = (fmt % args) if args else fmt
    if lvl >= _state.level:
        ts = time.strftime("%Y-%m-%dT%H:%M:%S")
        line = f"{ts} [{level.upper():5s}] {threading.current_thread().name}: {msg}"
        if _state.writer is not None:
            _state.writer.write(line)
        if _state.to_stderr or (_state.writer is None and lvl >= LEVELS["WARN"]):
            print(line, file=sys.stderr)
    if level.upper() == "DFATAL":
        if os.environ.get("TPU3FS_DFATAL_ABORT"):
            os.abort()
        raise DFatalError(msg)
