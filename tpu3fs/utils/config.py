"""Declarative config trees with validation and hot update.

Re-expresses the reference's ConfigBase (src/common/utils/ConfigBase.h:582):
declared items with defaults and checkers, TOML render/parse, dotted-path
overrides (``--config.a.b=v``), and hot updates that invoke registered
callbacks only for items flagged hot-updatable. mgmtd distributes rendered
config blobs per node type (src/fbs/core/service/CoreServiceDef.h:4-7); our
mgmtd does the same with these trees.

Usage::

    class StorageConfig(Config):
        io_depth = ConfigItem(32, hot=True, checker=lambda v: v > 0)
        class aio(Config):
            threads = ConfigItem(8)

Values live in each instance's ``__dict__`` (so plain attribute access reads
the configured value, shadowing the class-level declarations).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List

try:  # py311+: stdlib toml reader
    import tomllib
except ImportError:  # pragma: no cover
    try:  # py310: the tomli backport has the identical API
        import tomli as tomllib
    except ImportError:
        tomllib = None


class ConfigItem:
    def __init__(
        self,
        default: Any,
        *,
        hot: bool = False,
        checker: Callable[[Any], bool] | None = None,
        doc: str = "",
    ):
        self.default = default
        self.hot = hot
        self.checker = checker
        self.doc = doc


class Config:
    """A config node: items + nested sections, with hot-update semantics."""

    def __init__(self, **overrides: Any):
        self._items: Dict[str, ConfigItem] = {}
        self._sections: Dict[str, "Config"] = {}
        self._callbacks: List[Callable[["Config"], None]] = []
        self._lock = threading.RLock()
        for name in dir(type(self)):
            if name.startswith("_"):
                continue
            decl = getattr(type(self), name)
            if isinstance(decl, ConfigItem):
                self._items[name] = decl
                # instance attribute shadows the class-level declaration
                setattr(self, name, decl.default)
            elif isinstance(decl, type) and issubclass(decl, Config):
                sec = decl()
                self._sections[name] = sec
                setattr(self, name, sec)
        for key, val in overrides.items():
            self.set(key, val)

    # -- access ------------------------------------------------------------
    def get(self, dotted: str) -> Any:
        node: Any = self
        for part in dotted.split("."):
            node = getattr(node, part)
        return node

    def _resolve(self, dotted: str):
        """-> (owning node, leaf name, ConfigItem); raises KeyError."""
        parts = dotted.split(".")
        node = self
        for part in parts[:-1]:
            if part not in node._sections:
                raise KeyError(f"unknown config section: {dotted}")
            node = node._sections[part]
        leaf = parts[-1]
        if leaf not in node._items:
            raise KeyError(f"unknown config item: {dotted}")
        return node, leaf, node._items[leaf]

    @staticmethod
    def _coerce_and_check(item: ConfigItem, dotted: str, value: Any) -> Any:
        # coerce to the default's type first, so checkers see typed values
        # (flag/TOML inputs arrive as strings)
        if item.default is not None and value is not None:
            want = type(item.default)
            if not isinstance(value, want):
                if want is bool and isinstance(value, str):
                    value = value.lower() in ("1", "true", "yes")
                else:
                    value = want(value)
        if item.checker is not None and not item.checker(value):
            raise ValueError(f"config check failed for {dotted}={value!r}")
        return value

    def set(self, dotted: str, value: Any, *, hot_only: bool = False) -> None:
        node, leaf, item = self._resolve(dotted)
        if hot_only and not item.hot:
            raise ValueError(f"config item not hot-updatable: {dotted}")
        value = self._coerce_and_check(item, dotted, value)
        with node._lock:
            setattr(node, leaf, value)

    # -- hot update --------------------------------------------------------
    def add_callback(self, fn: Callable[["Config"], None]) -> None:
        """Callback invoked when a hot update touches this node's subtree."""
        self._callbacks.append(fn)

    def hot_update(self, updates: Dict[str, Any]) -> None:
        """Apply dotted-path updates; every path must be hot-updatable.

        Validation happens before any value changes, so a failed update leaves
        the tree untouched (ref ConfigBase.h guard semantics). Callbacks fire
        on every node along the path of each changed item (leaf-most first),
        plus the root, each at most once.
        """
        staged = []
        notify: List[Config] = []
        for dotted, value in updates.items():
            node, leaf, item = self._resolve(dotted)
            if not item.hot:
                raise ValueError(f"config item not hot-updatable: {dotted}")
            value = self._coerce_and_check(item, dotted, value)
            staged.append((node, leaf, value))
            # nodes along the path, leaf-most first
            path_nodes = [self]
            cur = self
            for part in dotted.split(".")[:-1]:
                cur = cur._sections[part]
                path_nodes.append(cur)
            for n in reversed(path_nodes):
                if n not in notify:
                    notify.append(n)
        for node, leaf, value in staged:
            with node._lock:
                setattr(node, leaf, value)
        for n in notify:
            for fn in n._callbacks:
                fn(n)

    # -- render / parse ----------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {name: getattr(self, name) for name in self._items}
        for name, sec in self._sections.items():
            out[name] = sec.to_dict()
        return out

    def render_toml(self, _prefix: str = "") -> str:
        lines = []
        for name in sorted(self._items):
            lines.append(f"{name} = {_toml_value(getattr(self, name))}")
        for name in sorted(self._sections):
            sec = self._sections[name]
            path = f"{_prefix}{name}"
            lines.append("")
            lines.append(f"[{path}]")
            lines.append(sec.render_toml(path + "."))
        return "\n".join(lines).strip() + "\n"

    def load_dict(self, data: Dict[str, Any]) -> None:
        for key, val in data.items():
            if isinstance(val, dict) and key in self._sections:
                self._sections[key].load_dict(val)
            else:
                self.set(key, val)

    def load_toml(self, text: str) -> None:
        if tomllib is None:  # pragma: no cover
            raise NotImplementedError("tomllib unavailable")
        self.load_dict(tomllib.loads(text))

    def apply_flag_overrides(self, argv: List[str]) -> List[str]:
        """Consume ``--config.a.b=v`` style flags; returns unconsumed argv."""
        rest = []
        for arg in argv:
            if arg.startswith("--config.") and "=" in arg:
                dotted, value = arg[len("--config."):].split("=", 1)
                self.set(dotted, value)
            else:
                rest.append(arg)
        return rest


def _toml_value(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return repr(v)
    if isinstance(v, str):
        return '"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"'
    if isinstance(v, (list, tuple)):
        return "[" + ", ".join(_toml_value(x) for x in v) + "]"
    raise TypeError(f"unsupported config value type: {type(v)}")
