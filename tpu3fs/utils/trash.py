"""Trash: delayed deletion with timestamped trash directories + cleaner.

Re-expresses the reference's two-piece trash machinery:
- hf3fs_utils/trash.py:11-18 — user-facing `rm` moves files into per-user
  trash directories whose names encode creation time and keep-duration
  (`{name}-{create}-{keep}`), so deletion is undoable until expiry;
- src/client/trash_cleaner/src/main.rs (Trash::clean :137) — a standalone
  cleaner scans trash directories and permanently removes entries whose
  keep-time has elapsed.

Both run against the MetaStore API only (rename + remove), exactly like the
reference drives them through the mounted filesystem.
"""

from __future__ import annotations

import re
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

from tpu3fs.meta.store import MetaStore, ROOT_USER, User
from tpu3fs.utils.result import Code, FsError

TRASH_ROOT = "/trash"

_NAME_RE = re.compile(r"^(?P<orig>.+)-(?P<create>\d+)-(?P<keep>\d+)$")


def trash_entry_name(orig_name: str, create_ts: float, keep_s: int) -> str:
    """`{name}-{create}-{keep}` naming (ref hf3fs_utils/trash.py:11-18)."""
    return f"{orig_name}-{int(create_ts)}-{int(keep_s)}"


def parse_trash_entry(name: str) -> Optional[tuple]:
    """Returns (orig_name, create_ts, keep_s) or None if not a trash name."""
    m = _NAME_RE.match(name)
    if m is None:
        return None
    return m.group("orig"), int(m.group("create")), int(m.group("keep"))


@dataclass
class TrashEntry:
    path: str
    orig_name: str
    create_ts: int
    keep_s: int

    @property
    def expire_ts(self) -> int:
        return self.create_ts + self.keep_s


def user_trash_dir(user: User) -> str:
    return f"{TRASH_ROOT}/{user.uid}"


def move_to_trash(
    meta: MetaStore,
    path: str,
    user: User = ROOT_USER,
    *,
    keep_s: int = 3 * 86400,
    clock: Callable[[], float] = time.time,
) -> str:
    """Move `path` into the caller's trash dir; returns the trash path."""
    now = clock()
    tdir = user_trash_dir(user)
    # the shared /trash root must be root-owned and world-writable, or the
    # first user to trash something would own it 0o755 and lock everyone
    # else out of creating their own per-user trash dir
    try:
        meta.mkdirs(TRASH_ROOT, user=ROOT_USER, perm=0o777)
    except FsError as e:
        if e.code != Code.META_EXISTS:
            raise
    try:
        meta.mkdirs(tdir, user=user)
    except FsError as e:
        if e.code != Code.META_EXISTS:
            raise
    name = path.rstrip("/").rsplit("/", 1)[-1]
    # rename overwrites an existing destination, which would permanently
    # destroy a same-named entry trashed in the same second — uniquify first
    base = name
    for n in range(1_000_000):
        dest = f"{tdir}/{trash_entry_name(base, now, keep_s)}"
        try:
            meta.stat(dest, user=user, follow=False)
        except FsError as e:
            if e.code == Code.META_NOT_FOUND:
                break
            raise
        base = f"{name}.{n + 1}"
    meta.rename(path, dest, user=user)
    return dest


def list_trash(meta: MetaStore, user: User = ROOT_USER) -> List[TrashEntry]:
    tdir = user_trash_dir(user)
    try:
        ents = meta.list_dir(tdir, user=user)
    except FsError as e:
        if e.code == Code.META_NOT_FOUND:
            return []
        raise
    out = []
    for ent in ents:
        parsed = parse_trash_entry(ent.name)
        if parsed is None:
            continue
        orig, create_ts, keep_s = parsed
        out.append(TrashEntry(f"{tdir}/{ent.name}", orig, create_ts, keep_s))
    return out


def restore_from_trash(
    meta: MetaStore, trash_path: str, dest: str, user: User = ROOT_USER
) -> None:
    meta.rename(trash_path, dest, user=user)


class TrashCleaner:
    """Scans every user's trash dir, purging expired entries
    (ref src/client/trash_cleaner/src/main.rs Trash::clean)."""

    def __init__(self, meta: MetaStore, *, clock: Callable[[], float] = time.time):
        self._meta = meta
        self._clock = clock

    def clean_once(self) -> int:
        now = self._clock()
        removed = 0
        try:
            user_dirs = self._meta.list_dir(TRASH_ROOT)
        except FsError as e:
            if e.code == Code.META_NOT_FOUND:
                return 0
            raise
        for udir in user_dirs:
            base = f"{TRASH_ROOT}/{udir.name}"
            for ent in self._meta.list_dir(base):
                parsed = parse_trash_entry(ent.name)
                if parsed is None:
                    continue
                _, create_ts, keep_s = parsed
                if create_ts + keep_s <= now:
                    self._meta.remove(f"{base}/{ent.name}", recursive=True)
                    removed += 1
        return removed
