"""Request-scoped fault injection (ref: src/common/utils/FaultInjection.h:15-29).

``with fault_injection(prob, times):`` arms injection for the current context;
``inject("point-name")`` then raises FsError(FAULT_INJECTION) with probability
``prob`` for at most ``times`` firings. Server code threads the armed state
through request debug flags, mirroring FAULT_INJECTION_POINT usage in
StorageOperator.cc:103-105.
"""

from __future__ import annotations

import contextlib
import contextvars
import random
from dataclasses import dataclass, field
from typing import List, Optional

from tpu3fs.utils.result import Code, FsError, Status


@dataclass
class _Injection:
    prob: float
    times: int
    only_points: Optional[List[str]] = None
    fired: int = field(default=0)

    def should_fire(self, point: str) -> bool:
        if self.times >= 0 and self.fired >= self.times:
            return False
        if self.only_points is not None and point not in self.only_points:
            return False
        if random.random() >= self.prob:
            return False
        self.fired += 1
        return True


_current: contextvars.ContextVar[Optional[_Injection]] = contextvars.ContextVar(
    "tpu3fs_fault_injection", default=None
)


@contextlib.contextmanager
def fault_injection(prob: float, times: int = -1, only_points: Optional[List[str]] = None):
    """Arm fault injection in this context. times<0 means unlimited."""
    token = _current.set(_Injection(prob, times, only_points))
    try:
        yield
    finally:
        _current.reset(token)


def current_injection() -> Optional[_Injection]:
    return _current.get()


def inject(point: str) -> None:
    """Raise FsError(FAULT_INJECTION) if an armed injection fires for point."""
    inj = _current.get()
    if inj is not None and inj.should_fire(point):
        raise FsError(Status(Code.FAULT_INJECTION, f"injected at {point}"))


def inject_result(point: str) -> Optional[Status]:
    """Non-raising form: returns an error Status when the injection fires."""
    inj = _current.get()
    if inj is not None and inj.should_fire(point):
        return Status(Code.FAULT_INJECTION, f"injected at {point}")
    return None
