"""Fault injection: request-scoped contexts + the hot-configurable
cluster fault plane.

Two layers share one set of injection points (``inject("point")`` calls
sprinkled through the storage/rpc stack):

1. REQUEST-SCOPED contexts (ref src/common/utils/FaultInjection.h:15-29):
   ``with fault_injection(prob, times):`` arms injection for the current
   context; ``inject("point")`` raises FsError(FAULT_INJECTION) with
   probability ``prob`` for at most ``times`` firings. Deterministic when
   constructed with ``seed=`` (chaos drives and tests reproduce runs).

2. THE CLUSTER FAULT PLANE: a process-global rule table configured from a
   ``FaultPlaneConfig`` spec string that rides the EXISTING mgmtd config
   push (``[faults] spec=...`` hot-updates every service binary live, no
   restart — ``admin_cli fault`` is the operator surface). Rules fire at
   the transports' send/dispatch boundaries and at the storage engine
   points, and support three kinds:

   - ``error``: raise FsError(FAULT_INJECTION) (a flaky peer);
   - ``delay_ms``: sleep ``arg`` milliseconds (a gray straggler);
   - ``drop``: raise ConnectionError (the transport tears the
     connection down, like a half-dead NIC).

   Spec grammar — entries separated by ``;``, fields by ``,``::

       point=storage.read,kind=delay_ms,arg=100,prob=1.0,node=11;
       point=rpc.dispatch,kind=error,prob=0.05,times=50

   ``point`` is a PREFIX match on the fired point name; ``node`` (0 =
   any) scopes a rule to one node id so a single type-wide config push
   can make exactly one replica sick. All randomness comes from ONE
   ``random.Random(seed)`` so a chaos run replays bit-identically.
"""

from __future__ import annotations

import contextlib
import contextvars
import random
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional

from tpu3fs.utils.config import Config, ConfigItem
from tpu3fs.utils.result import Code, FsError, Status


@dataclass
class _Injection:
    prob: float
    times: int
    only_points: Optional[List[str]] = None
    fired: int = field(default=0)
    # explicit RNG so chaos drives/tests are reproducible (seeded) while
    # legacy callers keep the old unseeded behavior (fresh Random())
    rng: random.Random = field(default_factory=random.Random)

    def should_fire(self, point: str) -> bool:
        if self.times >= 0 and self.fired >= self.times:
            return False
        if self.only_points is not None and point not in self.only_points:
            return False
        if self.rng.random() >= self.prob:
            return False
        self.fired += 1
        return True


_current: contextvars.ContextVar[Optional[_Injection]] = contextvars.ContextVar(
    "tpu3fs_fault_injection", default=None
)


@contextlib.contextmanager
def fault_injection(prob: float, times: int = -1,
                    only_points: Optional[List[str]] = None,
                    seed: Optional[int] = None):
    """Arm fault injection in this context. times<0 means unlimited;
    seed!=None makes the firing sequence reproducible."""
    rng = random.Random(seed) if seed is not None else random.Random()
    token = _current.set(_Injection(prob, times, only_points, rng=rng))
    try:
        yield
    finally:
        _current.reset(token)


def current_injection() -> Optional[_Injection]:
    return _current.get()


# -- the cluster fault plane --------------------------------------------------


@dataclass
class FaultRule:
    point: str                 # prefix match on the fired point name
    kind: str = "error"        # error | delay_ms | drop
    arg: float = 0.0           # delay_ms: milliseconds to sleep
    prob: float = 1.0
    times: int = -1            # max firings; <0 = unlimited
    node: int = 0              # 0 = any node; else only that node id
    fired: int = 0

    _KINDS = ("error", "delay_ms", "drop")


def parse_spec(spec: str) -> List[FaultRule]:
    """Parse a fault-plane spec string; malformed entries raise ValueError
    (a config push must reject bad specs atomically, ConfigBase rules)."""
    rules: List[FaultRule] = []
    for entry in (spec or "").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        fields = {}
        for part in entry.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"fault spec field without '=': {part!r}")
            k, v = part.split("=", 1)
            fields[k.strip()] = v.strip()
        if "point" not in fields:
            raise ValueError(f"fault spec entry without point=: {entry!r}")
        kind = fields.get("kind", "error")
        if kind not in FaultRule._KINDS:
            raise ValueError(f"unknown fault kind {kind!r} "
                             f"(want one of {FaultRule._KINDS})")
        try:
            rule = FaultRule(
                point=fields["point"],
                kind=kind,
                arg=float(fields.get("arg", 0.0)),
                prob=float(fields.get("prob", 1.0)),
                times=int(fields.get("times", -1)),
                node=int(fields.get("node", 0)),
            )
        except ValueError as e:
            raise ValueError(f"fault spec entry {entry!r}: {e}")
        if not 0.0 <= rule.prob <= 1.0:
            raise ValueError(f"fault prob out of range: {rule.prob}")
        rules.append(rule)
    return rules


def _check_spec(spec: str) -> bool:
    """ConfigItem checker: parseable spec (or empty)."""
    try:
        parse_spec(spec)
        return True
    except ValueError:
        return False


class FaultPlaneConfig(Config):
    """The hot-updatable fault-plane section every service binary carries
    (``[faults]`` in the pushed TOML). An empty spec = no faults."""

    spec = ConfigItem("", hot=True, checker=_check_spec,
                      doc="semicolon-separated fault rules; see "
                          "docs/robustness.md")
    seed = ConfigItem(0, hot=True,
                      doc="RNG seed for probabilistic rules (reproducible "
                          "chaos)")


class FaultPlane:
    """Process-global fault rule table. ``fire(point, node=...)`` is the
    one hook the transports and engine points call — a couple of loads
    when no rules are configured."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rules: List[FaultRule] = []
        self._rng = random.Random(0)
        self._fired_total = 0
        # lazy per-(kind, rule point) faults.fired counters — tagged by
        # the RULE's point prefix (bounded cardinality: one per
        # configured rule), so a soak can assert its schedule actually
        # fired instead of a typo'd spec injecting nothing, silently
        self._recs: dict = {}

    def configure(self, spec: str, seed: int = 0) -> None:
        """Install a new rule set (atomic: a bad spec raises and leaves
        the previous rules live). Reconfiguring resets firing counts and
        reseeds the RNG so a replayed run fires identically."""
        rules = parse_spec(spec)
        with self._lock:
            self._rules = rules
            self._rng = random.Random(seed)

    def clear(self) -> None:
        with self._lock:
            self._rules = []

    @property
    def active(self) -> bool:
        return bool(self._rules)

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [dict(point=r.point, kind=r.kind, arg=r.arg,
                         prob=r.prob, times=r.times, node=r.node,
                         fired=r.fired)
                    for r in self._rules]

    @property
    def fired_total(self) -> int:
        return self._fired_total

    def fire(self, point: str, node: int = 0) -> None:
        """Evaluate the rules for one injection point. May sleep (delay),
        raise FsError(FAULT_INJECTION) (error) or raise ConnectionError
        (drop — the transports' connection-error handling tears the
        stream down)."""
        if not self._rules:
            return
        delay_ms = 0.0
        boom: Optional[BaseException] = None
        with self._lock:
            for r in self._rules:
                if not point.startswith(r.point):
                    continue
                if r.node and node and r.node != node:
                    continue
                if r.node and not node:
                    continue  # node-scoped rule, unscoped fire point
                if r.times >= 0 and r.fired >= r.times:
                    continue
                if r.prob < 1.0 and self._rng.random() >= r.prob:
                    continue
                r.fired += 1
                self._fired_total += 1
                self._count_fired(r)
                if r.kind == "delay_ms":
                    delay_ms += r.arg
                elif r.kind == "drop":
                    boom = ConnectionError(
                        f"fault plane drop at {point}")
                else:
                    boom = FsError(Status(
                        Code.FAULT_INJECTION,
                        f"fault plane injected at {point}"))
                if boom is not None:
                    break
        if delay_ms > 0:
            time.sleep(delay_ms / 1000.0)
        if boom is not None:
            raise boom

    def _count_fired(self, rule: FaultRule) -> None:
        rec = self._recs.get((rule.kind, rule.point))
        if rec is None:
            from tpu3fs.monitor.recorder import CounterRecorder

            rec = CounterRecorder("faults.fired",
                                  tags={"kind": rule.kind,
                                        "point": rule.point})
            self._recs[(rule.kind, rule.point)] = rec
        rec.add()


_PLANE = FaultPlane()


def plane() -> FaultPlane:
    return _PLANE


def apply_plane_config(cfg: FaultPlaneConfig,
                       target: Optional[FaultPlane] = None) -> None:
    """Bind a FaultPlaneConfig section to a plane and follow its hot
    updates (the service binaries call this once at boot)."""
    pl = target if target is not None else _PLANE

    def _apply(_node=None):
        try:
            pl.configure(cfg.spec, int(cfg.seed))
        except ValueError:
            pass  # checker already rejected; belt and braces

    _apply()
    cfg.add_callback(_apply)


# -- the shared injection hook ------------------------------------------------

def inject(point: str, node: int = 0) -> None:
    """Raise FsError(FAULT_INJECTION) if an armed request-scoped injection
    fires for point, then evaluate the cluster fault plane (which may
    also sleep or drop). ``node`` scopes plane rules to one node id."""
    inj = _current.get()
    if inj is not None and inj.should_fire(point):
        raise FsError(Status(Code.FAULT_INJECTION, f"injected at {point}"))
    _PLANE.fire(point, node)


def inject_result(point: str, node: int = 0) -> Optional[Status]:
    """Non-raising form: returns an error Status when an injection fires
    (plane delays still sleep in place; drops surface as a Status too)."""
    inj = _current.get()
    if inj is not None and inj.should_fire(point):
        return Status(Code.FAULT_INJECTION, f"injected at {point}")
    try:
        _PLANE.fire(point, node)
    except FsError as e:
        return e.status
    except ConnectionError as e:
        return Status(Code.FAULT_INJECTION, str(e))
    return None
