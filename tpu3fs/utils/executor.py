"""Bounded executors, concurrency limiters and periodic runners.

The thread-shaped re-design of the reference's coroutine toolkit
(src/common/utils/CoroutinesPool.h — one bounded queue + N consumers per
pool; src/common/utils/BackgroundRunner.h — named periodic tasks with
jittered intervals; folly Semaphore throttles). Consumers: the storage
client's per-node batch fan-out (WorkerPool), the service apps'
spawn_periodic background tasks (PeriodicRunner via app/application.py),
and the USRBIO agent's host-wide IO throttle (ConcurrencyLimiter).

CPython threads carry the GIL, but every pool consumer here spends its
time in blocking IO (sockets, engine syscalls, KV fsync) where the GIL is
released — the same reason the per-target UpdateWorker queues scale.
"""

from __future__ import annotations

import contextvars
import random
import threading
import time
from typing import Any, Callable, List, Optional

from tpu3fs.utils.result import Code, FsError, Status


class Future:
    """Minimal completion cell: set_result/set_exception once, get() waits."""

    __slots__ = ("_event", "_value", "_exc")

    def __init__(self):
        self._event = threading.Event()
        self._value: Any = None
        self._exc: Optional[BaseException] = None

    def set_result(self, value: Any) -> None:
        self._value = value
        self._event.set()

    def set_exception(self, exc: BaseException) -> None:
        self._exc = exc
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def get(self, timeout: Optional[float] = None) -> Any:
        if not self._event.wait(timeout):
            raise FsError(Status(Code.RPC_TIMEOUT, "future timeout"))
        if self._exc is not None:
            raise self._exc
        return self._value


class WorkerPool:
    """N workers draining one bounded FIFO (ref CoroutinesPool.h:24-56).

    submit() applies backpressure: when the queue is full it BLOCKS (the
    reference's bounded channel semantics) unless block=False, which
    raises instead — callers on a latency budget pick their poison.

    Each task runs inside a ``contextvars.copy_context()`` snapshot taken
    at submit time, so context-scoped request state — the QoS ``tagged()``
    traffic class and armed ``fault_injection`` — follows work into the
    pool instead of silently resetting: fanned-out IO stays classified
    and armed fault points keep firing (the reference's coroutine pools
    get this for free from coroutine-local state).
    """

    def __init__(self, name: str, num_workers: int = 4,
                 queue_cap: int = 256):
        assert num_workers >= 1 and queue_cap >= 1
        self.name = name
        self._cap = queue_cap
        self._queue: List = []
        self._mu = threading.Lock()
        self._not_empty = threading.Condition(self._mu)
        self._not_full = threading.Condition(self._mu)
        self._running = True
        self._workers = [
            threading.Thread(target=self._run, daemon=True,
                             name=f"{name}-{i}")
            for i in range(num_workers)
        ]
        for w in self._workers:
            w.start()

    def submit(self, fn: Callable, *args, block: bool = True,
               timeout: Optional[float] = None) -> Future:
        fut = Future()
        ctx = contextvars.copy_context()
        with self._mu:
            if not self._running:
                raise FsError(Status(Code.SHUTTING_DOWN, self.name))
            if len(self._queue) >= self._cap:
                if not block:
                    raise FsError(Status(
                        Code.CLIENT_BUSY,
                        f"{self.name} queue full ({self._cap})"))
                deadline = None if timeout is None else (
                    time.monotonic() + timeout)
                while len(self._queue) >= self._cap and self._running:
                    left = None if deadline is None else (
                        deadline - time.monotonic())
                    if left is not None and left <= 0:
                        raise FsError(Status(
                            Code.CLIENT_BUSY,
                            f"{self.name} backpressure timeout"))
                    self._not_full.wait(left)
                if not self._running:
                    raise FsError(Status(Code.SHUTTING_DOWN, self.name))
            self._queue.append((ctx, fn, args, fut))
            self._not_empty.notify()
        return fut

    def map(self, fn: Callable, items) -> List[Any]:
        """Submit fn(item) for every item; wait for all; first error wins
        (after every task finished, so partial work is never abandoned
        mid-flight)."""
        futs = [self.submit(fn, item) for item in items]
        out, first_exc = [], None
        for f in futs:
            try:
                out.append(f.get())
            except BaseException as e:  # noqa: BLE001 — re-raised below
                if first_exc is None:
                    first_exc = e
                out.append(None)
        if first_exc is not None:
            raise first_exc
        return out

    @property
    def queue_depth(self) -> int:
        with self._mu:
            return len(self._queue)

    def _run(self) -> None:
        while True:
            with self._mu:
                while self._running and not self._queue:
                    self._not_empty.wait()
                if not self._running and not self._queue:
                    return
                ctx, fn, args, fut = self._queue.pop(0)
                self._not_full.notify()
            try:
                fut.set_result(ctx.run(fn, *args))
            except BaseException as e:  # noqa: BLE001 — delivered via Future
                fut.set_exception(e)

    def shutdown(self, wait: bool = True) -> None:
        with self._mu:
            self._running = False
            self._not_empty.notify_all()
            self._not_full.notify_all()
        if wait:
            for w in self._workers:
                w.join(timeout=10)


class ConcurrencyLimiter:
    """Counted gate over an arbitrary section (the folly::Semaphore role
    in the reference's read/write paths): at most `limit` holders; excess
    callers block (bounded) or fail fast."""

    def __init__(self, name: str, limit: int):
        self.name = name
        self._sem = threading.BoundedSemaphore(limit)

    def __enter__(self):
        self._sem.acquire()
        return self

    def __exit__(self, *exc):
        self._sem.release()
        return False

    def try_acquire(self, timeout: float = 0.0) -> bool:
        return self._sem.acquire(timeout=timeout)

    def release(self) -> None:
        self._sem.release()


class PeriodicRunner:
    """Named background task on a jittered interval (ref
    BackgroundRunner.h / the mgmtd background runners): start() spawns the
    loop, stop() joins it; errors are swallowed per tick (a failing
    background task must not die silently forever — it logs and retries
    next tick). interval_s may be a float or a zero-arg callable so
    hot-updatable config intervals re-read every tick (the service apps
    pass `lambda: config.get(...)`)."""

    def __init__(self, name: str, interval_s, fn: Callable[[], Any],
                 *, jitter: float = 0.1):
        self.name = name
        self.interval_s = interval_s
        self.fn = fn
        self.jitter = jitter
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        assert self._thread is None, f"{self.name} already started"
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"runner-{self.name}")
        self._thread.start()

    def _loop(self) -> None:
        from tpu3fs.utils.logging import xlog

        while not self._stop.is_set():
            # the interval callable is inside the try too: a transient
            # hot-config error must not silently kill the runner thread
            # (a dead mgmtd-tick runner would stop lease extension)
            try:
                base = (self.interval_s() if callable(self.interval_s)
                        else self.interval_s)
                delay = base * (
                    1.0 + random.uniform(-self.jitter, self.jitter))
                if self._stop.wait(max(0.0, delay)):
                    return
                self.fn()
            except Exception as e:  # noqa: BLE001 — retried next tick
                xlog("WARNING", "periodic %s failed: %r", self.name, e)
                if self._stop.wait(1.0):
                    return

    def request_stop(self) -> None:
        """Signal without joining (app shutdown paths that must not block)."""
        self._stop.set()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
