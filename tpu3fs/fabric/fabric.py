"""Single-process multi-node cluster for tests and benches.

Clone of the reference's test::UnitTestFabric (tests/lib/UnitTestFabric.h:169):
boots a real Mgmtd, N real StorageService nodes, the MetaStore and real
clients in one process, parameterized like SystemSetupConfig
(UnitTestFabric.h:86-135 — chunk size, num_chains/num_replicas/
num_storage_nodes). Node "RPC" is direct dispatch through a messenger that
honors kill/restart, so fail-stop and recovery paths run exactly as they
would over sockets (the RPC layer drops in the same messenger signature).

A controllable clock drives heartbeat timeouts deterministically.
"""

from __future__ import annotations

import itertools
import tempfile
from dataclasses import dataclass
from typing import Dict, List, Optional

from tpu3fs.client.file_io import FileIoClient
from tpu3fs.client.storage_client import StorageClient
from tpu3fs.kv import MemKVEngine
from tpu3fs.meta.store import ChainAllocator, MetaStore
from tpu3fs.mgmtd.service import Mgmtd, MgmtdConfig
from tpu3fs.mgmtd.types import LocalTargetState, NodeType, PublicTargetState
from tpu3fs.storage.craq import StorageService
from tpu3fs.storage.resync import ResyncWorker
from tpu3fs.storage.target import StorageTarget
from tpu3fs.utils.result import Code, FsError, Status


def _freeze_routing(live):
    """Shallow-freeze a RoutingInfo: copy the container dicts (and the
    version) so later chain/target/node INSTALLS are invisible, while
    still sharing the current member objects. mgmtd replaces chain and
    target records wholesale on every mutation (mgmtd/service.py uses
    dataclasses.replace before installing), so sharing is safe."""
    from dataclasses import replace as _replace

    return _replace(
        live,
        nodes=dict(live.nodes),
        chain_tables=dict(live.chain_tables),
        chains=dict(live.chains),
        targets=dict(live.targets),
        serving=dict(live.serving),
        meta_partitions=dict(live.meta_partitions),
    )


class FabricClock:
    def __init__(self, t: float = 10_000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@dataclass
class SystemSetupConfig:
    num_storage_nodes: int = 3
    num_chains: int = 2
    num_replicas: int = 2
    chunk_size: int = 1 << 16
    engine: str = "mem"
    # base directory for disk-backed engines (None = system tempdir);
    # benches point this at /dev/shm so the numbers measure the framework,
    # not the host disk's writeback throttle
    engine_dir: Optional[str] = None
    heartbeat_timeout_s: float = 60.0
    # EC(k, m) chain tables instead of CR replication: each chain gets
    # k+m targets (on distinct nodes when possible) holding one stripe
    # shard each; num_replicas is ignored for EC chains
    ec_k: int = 0
    ec_m: int = 0
    # "ici" + a mesh: CR chains replicate staged batches via the
    # chain_write_step collective (storage/ici_chain.py) instead of the
    # per-hop messenger — the intra-pod serving mode. Requires every
    # chain's targets on one node (pass num_storage_nodes=1) and the
    # mesh's ``chain`` axis equal to num_replicas.
    chain_transport: str = "messenger"
    mesh: object = None
    # a qos.QosConfig: every storage node gets a QosManager over it
    # (admission + weighted-fair update scheduling + shed recorders);
    # None = legacy unscheduled behavior
    qos: object = None
    # arm the mgmtd lease fence on every storage service (docs/scale.md):
    # T/2 of mgmtd silence closes the node's client-write ack path and
    # demotes its targets to ONLINE. Off by default — most unit tests
    # drive heartbeats explicitly and predate the fencing contract.
    fencing: bool = False


class _Node:
    def __init__(self, node_id: int, service: StorageService):
        self.node_id = node_id
        self.service = service
        self.alive = True
        self.hb_version = 0
        # routing snapshot frozen at partition start: a node cut off from
        # mgmtd must keep acting on the LAST routing it saw (the live
        # RoutingInfo is a shared in-process object — without freezing,
        # a partitioned head would instantly "learn" about its own
        # replacement, which no real partitioned process could)
        self.frozen_routing = None


class Fabric:
    MGMTD_NODE_ID = 1
    # direct-dispatch marker: chain forwards through `send` stay inside
    # this process, so CRAQ hands successors its owned staged buffers +
    # checksums (trusted forward) instead of re-shipping/re-verifying
    in_process = True
    FIRST_STORAGE_NODE_ID = 10
    FIRST_TARGET_ID = 1000
    FIRST_CHAIN_ID = 900_000

    def __init__(self, cfg: Optional[SystemSetupConfig] = None):
        self.cfg = cfg or SystemSetupConfig()
        self.clock = FabricClock()
        self.kv = MemKVEngine()
        self.mgmtd = Mgmtd(
            self.MGMTD_NODE_ID,
            self.kv,
            MgmtdConfig(heartbeat_timeout_s=self.cfg.heartbeat_timeout_s),
            clock=self.clock,
        )
        self.mgmtd.extend_lease()
        self.nodes: Dict[int, _Node] = {}
        self.chain_ids: List[int] = []
        self._engine_dirs: List[str] = []
        # symmetric blocked (src, dst) node-id pairs — the chaos
        # ``partition`` event's wire cut (mgmtd is node MGMTD_NODE_ID)
        self._blocked: set = set()
        self._boot_topology()
        self.meta = MetaStore(
            self.kv,
            ChainAllocator(1, self.chain_ids),
            file_length_hook=self._file_length,
            truncate_hook=self._truncate_chunks,
            space_hook=self._cluster_space,
            default_chunk_size=self.cfg.chunk_size,
        )
        self._client_seq = itertools.count(1)

    # -- topology -----------------------------------------------------------
    def _boot_topology(self) -> None:
        cfg = self.cfg
        for i in range(cfg.num_storage_nodes):
            node_id = self.FIRST_STORAGE_NODE_ID + i
            service = StorageService(
                node_id, self.node_routing(node_id), self.send_from(node_id)
            )
            if cfg.fencing:
                service.enable_fencing(
                    self.clock, cfg.heartbeat_timeout_s / 2.0)
            if cfg.qos is not None:
                from tpu3fs.qos.manager import QosManager

                service.set_qos(QosManager(
                    cfg.qos, tags={"node": str(node_id)}))
            self.nodes[node_id] = _Node(node_id, service)
            self.mgmtd.register_node(node_id, NodeType.STORAGE)
        # chains: targets assigned round-robin over nodes (a chain's replicas
        # land on distinct nodes)
        tid = self.FIRST_TARGET_ID
        node_ids = sorted(self.nodes)
        node_cursor = 0
        is_ec = cfg.ec_k > 0
        width = (cfg.ec_k + cfg.ec_m) if is_ec else cfg.num_replicas
        # EC targets hold one shard of each stripe: engine chunk size is the
        # shard size, not the stripe size
        if is_ec:
            from tpu3fs.ops.stripe import shard_size_of

            target_chunk_size = shard_size_of(cfg.chunk_size, cfg.ec_k)
        else:
            target_chunk_size = cfg.chunk_size
        for c in range(cfg.num_chains):
            chain_id = self.FIRST_CHAIN_ID + c + 1
            target_ids = []
            for _ in range(width):
                node_id = node_ids[node_cursor % len(node_ids)]
                node_cursor += 1
                self.mgmtd.create_target(tid, node_id=node_id)
                tpath = None
                if cfg.engine != "mem" and cfg.engine_dir:
                    tpath = tempfile.mkdtemp(
                        prefix=f"t{tid}-", dir=cfg.engine_dir)
                    self._engine_dirs.append(tpath)
                target = StorageTarget(
                    tid, chain_id, engine=cfg.engine,
                    path=tpath,
                    chunk_size=target_chunk_size,
                )
                self.nodes[node_id].service.add_target(target)
                target_ids.append(tid)
                tid += 1
            self.mgmtd.upload_chain(
                chain_id, target_ids, ec_k=cfg.ec_k, ec_m=cfg.ec_m)
            self.chain_ids.append(chain_id)
        self.mgmtd.upload_chain_table(1, self.chain_ids)
        self.heartbeat_all()
        if cfg.chain_transport == "ici":
            from tpu3fs.storage.ici_chain import IciChainReplicator

            assert cfg.mesh is not None, "ici transport needs a mesh"
            for node in self.nodes.values():
                node.service.set_ici_replicator(
                    IciChainReplicator(cfg.mesh))

    # -- plumbing -----------------------------------------------------------
    def close(self) -> None:
        """Release disk-backed engine state (benches create fabrics on
        tmpfs via engine_dir — without cleanup /dev/shm fills up)."""
        import shutil

        for node in self.nodes.values():
            for target in node.service.targets():
                try:
                    target.engine.close()
                except Exception:
                    pass
        for d in self._engine_dirs:
            shutil.rmtree(d, ignore_errors=True)
        self._engine_dirs.clear()

    def routing(self):
        return self.mgmtd.get_routing_info()

    def node_routing(self, node_id: int):
        """Routing provider bound to one storage node: identical to the
        live view until a partition cuts the node off from mgmtd, then
        frozen at the snapshot taken when the partition began."""
        def provider():
            node = self.nodes.get(node_id)
            if node is not None and node.frozen_routing is not None \
                    and not self.can_reach(node_id, self.MGMTD_NODE_ID):
                return node.frozen_routing
            return self.mgmtd.get_routing_info()

        return provider

    # -- partitions (chaos ``partition`` events; docs/scale.md) --------------
    def set_partition(self, side_a: List[int], side_b: List[int]) -> None:
        """Cut every link between the two node sets (symmetric; node ids,
        MGMTD_NODE_ID stands for mgmtd). Nodes losing mgmtd reachability
        freeze their routing view at the current snapshot."""
        overlap = set(side_a) & set(side_b)
        if overlap:
            raise ValueError(f"partition sides overlap: {sorted(overlap)}")
        for a in side_a:
            for b in side_b:
                self._blocked.add((a, b))
                self._blocked.add((b, a))
        live = self.mgmtd.get_routing_info()
        for node in self.nodes.values():
            if node.frozen_routing is None \
                    and not self.can_reach(node.node_id, self.MGMTD_NODE_ID):
                node.frozen_routing = _freeze_routing(live)

    def heal_partitions(self) -> None:
        self._blocked.clear()
        for node in self.nodes.values():
            node.frozen_routing = None

    def can_reach(self, src: int, dst: int) -> bool:
        return (src, dst) not in self._blocked

    def send_from(self, src_id: int):
        """Messenger bound to a source node, so chain forwards respect
        partitions (the plain ``send`` has no source and models client
        traffic, which partitions never cut)."""
        def _send(node_id: int, method: str, payload):
            if self._blocked and not self.can_reach(src_id, node_id):
                raise FsError(Status(
                    Code.RPC_CONNECT_FAILED,
                    f"partitioned: {src_id} -/-> {node_id}"))
            return self.send(node_id, method, payload)

        return _send

    def send(self, node_id: int, method: str, payload):
        """Direct-dispatch messenger with fail-stop semantics."""
        node = self.nodes.get(node_id)
        if node is None or not node.alive:
            raise FsError(Status(Code.RPC_CONNECT_FAILED, f"node {node_id} down"))
        # cluster fault plane: the in-fabric analogue of the transports'
        # send/dispatch boundaries, so chaos schedules with rpc.* rules
        # (chaos/schedule.py) exercise transport faults in-process too;
        # drop rules surface as the torn-connection error the retry
        # ladders know
        from tpu3fs.utils.fault_injection import plane as _fault_plane

        pl = _fault_plane()
        if pl.active:
            try:
                pl.fire(f"rpc.send.Fabric.{method}", node=node_id)
                pl.fire(f"rpc.dispatch.Fabric.{method}", node=node_id)
            except ConnectionError as e:
                raise FsError(Status(Code.RPC_PEER_CLOSED,
                                     f"node {node_id}: {e}"))
        svc = node.service
        if method == "write":
            return svc.write(payload)
        if method == "write_shard":
            return svc.write_shard(payload)
        if method == "update":
            return svc.update(payload)
        if method == "read_rebuild":
            return svc.read_rebuild(payload)
        if method == "batch_read_rebuild":
            return svc.batch_read_rebuild(payload)
        if method == "read":
            return svc.read(payload)
        if method == "batch_read":
            return svc.batch_read(payload)
        if method == "batch_write":
            return svc.batch_write(payload)
        if method == "batch_update":
            return svc.batch_update(payload)
        if method == "stat_chunks":
            return svc.stat_chunks(*payload)
        if method == "batch_write_shard":
            return svc.batch_write_shard(payload)
        if method == "chain_encode":
            return svc.chain_encode(payload)
        if method == "dump_chunkmeta":
            return svc.dump_chunkmeta(payload)
        if method == "dump_pending_chunkmeta":
            return svc.dump_pending_chunkmeta(payload)
        if method == "sync_done":
            return svc.sync_done(payload)
        if method == "remove_chunk":
            return svc.remove_chunk(*payload)
        if method == "remove_file_chunks":
            return svc.remove_file_chunks(*payload)
        if method == "query_last_chunk":
            return svc.query_last_chunk(*payload)
        if method == "truncate_file_chunks":
            return svc.truncate_file_chunks(*payload)
        if method == "space_info":
            return svc.space_info()
        raise FsError(Status(Code.RPC_METHOD_NOT_FOUND, method))

    # -- clients ------------------------------------------------------------
    def storage_client(self, **kw) -> StorageClient:
        return StorageClient(
            f"client-{next(self._client_seq)}", self.routing, self.send, **kw
        )

    def file_client(self, **kw) -> FileIoClient:
        return FileIoClient(self.storage_client(**kw))

    def _file_length(self, inode) -> int:
        return self.file_client().file_length(inode)

    def _truncate_chunks(self, inode, length: int) -> None:
        self.file_client().truncate_chunks(inode, length)

    def _cluster_space(self):
        si = self.storage_client().space_info()
        return si.capacity, si.used

    # -- elasticity (cluster reshaping; docs/placement.md) -------------------
    def add_storage_node(self, node_id: Optional[int] = None) -> int:
        """Join an empty storage node to the live cluster (the in-process
        analogue of booting another storage_main): registered, heartbeat-
        connected, zero targets — exactly what the rebalance planner
        treats as a JOIN delta."""
        if node_id is None:
            node_id = max(self.nodes) + 1
        service = StorageService(
            node_id, self.node_routing(node_id), self.send_from(node_id))
        if self.cfg.fencing:
            service.enable_fencing(
                self.clock, self.cfg.heartbeat_timeout_s / 2.0)
        if self.cfg.qos is not None:
            from tpu3fs.qos.manager import QosManager

            service.set_qos(QosManager(
                self.cfg.qos, tags={"node": str(node_id)}))
        self.nodes[node_id] = _Node(node_id, service)
        self.mgmtd.register_node(node_id, NodeType.STORAGE)
        self.heartbeat_all()
        return node_id

    def open_assigned_targets(self) -> int:
        """The in-process mirror of storage_main.scan_targets: open any
        routing-assigned target a live node does not serve yet (migration
        PREPARE assigns them). Fresh targets on a chain past v1 report
        ONLINE and ride the WAITING→SYNCING recovery ladder."""
        routing = self.routing()
        is_ec = self.cfg.ec_k > 0
        if is_ec:
            from tpu3fs.ops.stripe import shard_size_of

            chunk_size = shard_size_of(self.cfg.chunk_size, self.cfg.ec_k)
        else:
            chunk_size = self.cfg.chunk_size
        opened = 0
        for info in routing.targets.values():
            node = self.nodes.get(info.node_id)
            if node is None or not node.alive or not info.chain_id:
                continue
            if node.service.target(info.target_id) is not None:
                continue
            tpath = None
            if self.cfg.engine != "mem" and self.cfg.engine_dir:
                tpath = tempfile.mkdtemp(
                    prefix=f"t{info.target_id}-", dir=self.cfg.engine_dir)
                self._engine_dirs.append(tpath)
            target = StorageTarget(
                info.target_id, info.chain_id, engine=self.cfg.engine,
                path=tpath, chunk_size=chunk_size)
            chain = routing.chains.get(info.chain_id)
            if chain is not None and chain.chain_version > 1:
                target.local_state = LocalTargetState.ONLINE
            node.service.add_target(target)
            opened += 1
        return opened

    def retire_unassigned_targets(self) -> int:
        """The in-process mirror of storage_main's retirement pass: drop
        local targets routing no longer assigns here (migration cutover
        detached them — chain_id 0)."""
        retired = 0
        routing = self.routing()
        for node in self.nodes.values():
            if not node.alive:
                continue
            for target in node.service.targets():
                info = routing.targets.get(target.target_id)
                if info is None or info.chain_id == 0 \
                        or info.node_id != node.node_id:
                    dropped = node.service.drop_target(target.target_id)
                    if dropped is not None:
                        try:
                            dropped.engine.close()
                        except Exception:
                            pass
                        retired += 1
        return retired

    def elastic_tick(self, *, resync: bool = True) -> None:
        """One full elasticity round: open new assignments, heartbeat,
        run the chain updater, run resync/rebuild workers, retire
        detached targets — what the live cluster's loops do continuously.
        ``resync=False`` leaves the copying entirely to a migration
        worker (tests proving the worker moves the bytes)."""
        from tpu3fs.storage.ec_resync import EcResyncWorker

        self.open_assigned_targets()
        self.tick()
        if resync:
            for node in self.nodes.values():
                if node.alive:
                    ResyncWorker(node.service, self.send).run_once()
                    EcResyncWorker(node.service, self.send).run_once()
        self.tick()
        self.retire_unassigned_targets()

    # -- cluster life -------------------------------------------------------
    def heartbeat_all(self) -> None:
        now = self.clock()
        for node in self.nodes.values():
            if not node.alive:
                continue
            if self._blocked \
                    and not self.can_reach(node.node_id, self.MGMTD_NODE_ID):
                # partitioned from mgmtd: the heartbeat never lands, and
                # the node judges its own lease fence on local time
                node.service.fence_tick()
                continue
            node.hb_version += 1
            states = {
                t.target_id: t.local_state for t in node.service.targets()
            }
            self.mgmtd.heartbeat(node.node_id, node.hb_version, states)
            node.service.note_mgmtd_contact(now)
            node.service.fence_tick()

    def tick(self, *, heartbeat: bool = True) -> None:
        if heartbeat:
            self.heartbeat_all()
        self.mgmtd.tick()

    def kill_node(self, node_id: int) -> None:
        node = self.nodes[node_id]
        node.alive = False
        node.service.stopped = True
        node.service.stop_workers()

    def fail_node(self, node_id: int) -> None:
        """Kill + advance time past the heartbeat timeout + chain update."""
        self.kill_node(node_id)
        self.clock.advance(self.cfg.heartbeat_timeout_s + 1)
        self.heartbeat_all()
        self.mgmtd.tick()

    def restart_node(self, node_id: int) -> None:
        """Bring a node back following the recovery protocol: its targets
        report ONLINE (not up-to-date) and go through WAITING->SYNCING
        (design_notes "Data recovery" step 1)."""
        node = self.nodes[node_id]
        node.alive = True
        node.service.stopped = False
        for target in node.service.targets():
            public = self.routing().targets.get(target.target_id)
            if public is not None and public.public_state in (
                PublicTargetState.OFFLINE,
                PublicTargetState.WAITING,
                PublicTargetState.LASTSRV,
            ):
                target.local_state = LocalTargetState.ONLINE
            # else keep UPTODATE (e.g. clean restart before mgmtd noticed)
        self.heartbeat_all()
        self.mgmtd.tick()

    def resync_all(self, rounds: int = 4, *, mesh=None) -> int:
        """Run resync workers on all live nodes until chains converge.
        CR chains use full-chunk-replace copying; EC chains rebuild the
        recovering shard on device (optionally over a mesh collective)."""
        from tpu3fs.storage.ec_resync import EcResyncWorker

        moved = 0
        for _ in range(rounds):
            for node in self.nodes.values():
                if node.alive:
                    moved += ResyncWorker(node.service, self.send).run_once()
                    moved += EcResyncWorker(
                        node.service, self.send, mesh=mesh).run_once()
            self.tick()
            if all(
                t.public_state == PublicTargetState.SERVING
                for chain in self.routing().chains.values()
                for t in chain.targets
            ):
                break
        return moved

    # -- GC (driving MetaStore's queue against storage; ref GcManager) -------
    def run_gc(self) -> int:
        from tpu3fs.qos.core import TrafficClass, tagged

        removed = 0
        fio = self.file_client()
        # chunk removals are GC-class traffic: scheduled behind foreground
        # IO by the storage-side WFQ (tpu3fs/qos)
        with tagged(TrafficClass.GC):
            for inode in self.meta.gc_scan():
                if self.meta.has_sessions(inode.id):
                    continue  # still write-open somewhere
                fio.remove_chunks(inode)
                self.meta.gc_finish(inode.id)
                removed += 1
        return removed
