from tpu3fs.fabric.fabric import Fabric, SystemSetupConfig  # noqa: F401
