"""Replicated kvd: fault tolerance for the FoundationDB role.

The reference inherits replicated, failover-capable transactions from
FoundationDB (/root/reference/src/fdb/FDBTransaction.h,
HybridKvEngine.h:12-22). Round 3 shipped a single-process kvd with a WAL —
a single point of failure under the lease election, routing, and all
metadata. This module adds the missing property: a kvd GROUP of N peers
with one elected leader, where a transaction is acknowledged only after
its resolved write set is durable on a MAJORITY, and any future leader
provably holds every acknowledged transaction.

The protocol is Raft's core (terms, log-completeness voting, quorum
commit, a no-op barrier entry per new term), deliberately without
membership changes:

- LOG: entries (term, index, payload) where payload is the serialized
  resolved write set (kv.service.WalRecord — versionstamps already
  expanded), appended to a per-node log file BEFORE acking the leader.
- COMMIT PATH (leader, fully serialized): conflict-check + apply on the
  leader engine -> append entry -> replicate -> wait majority -> ack the
  client. If quorum cannot be reached the leader steps down and REBUILDS
  its engine from the durable prefix, so the un-replicated application is
  discarded and the client (never acked) retries on the next leader.
  Serializing snapshot() behind the same lock means no client can observe
  engine state that is not yet quorum-durable.
- ELECTION: a candidate wins only if its (last_term, last_index) is >= the
  voter's for a majority — the standard argument makes every acknowledged
  entry present in the winner's log. The winner replays its log into a
  fresh engine, appends a no-op entry of its own term, and serves only
  after that barrier replicates (the figure-8 guard).
- SNAPSHOT/COMPACTION: when the log exceeds a threshold, the leader dumps
  the applied engine state, persists it, and truncates the log prefix;
  followers too far behind receive installSnapshot. Mirrors the kvd WAL's
  snapshot compaction from round 3.

Followers reject client ops with KV_NOT_PRIMARY + a leader hint; the
client (kv/remote.py ReplicatedRemoteKVEngine) re-resolves and retries,
and with_transaction treats it as one more retriable code.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from tpu3fs.kv.mem import MemKVEngine
from tpu3fs.utils.logging import xlog
from tpu3fs.kv.service import (
    CommitReq,
    CommitRsp,
    EmptyMsg,
    GetReq,
    KvService,
    RangePair,
    RangeReq,
    ReleaseReq,
    SnapshotReq,
    SnapshotRsp,
    WalRecord,
    WriteEntry,
    RangeEntry,
)
from tpu3fs.rpc.net import RpcClient, RpcServer, ServiceDef
from tpu3fs.rpc.serde import deserialize, serialize
from tpu3fs.utils.result import Code, FsError, Status

KV_REPL_SERVICE_ID = 6

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"


# -- wire schemas ------------------------------------------------------------

@dataclass
class LogEntry:
    term: int = 0
    index: int = 0
    payload: bytes = b""     # serialized WalRecord; b"" = no-op barrier
    # non-empty = membership entry: JSON {node_id: [host, port], ...}.
    # Activated at APPEND time on every replica (Raft's single-server
    # change, dissertation §4.2.2): because each reconfig adds OR removes
    # at most one node, any majority of the old config overlaps any
    # majority of the new one, so two leaders can never be elected for
    # the same term across the boundary — no joint consensus needed.
    config: str = ""


@dataclass
class AppendReq:
    term: int = 0
    leader_id: int = 0
    prev_index: int = 0
    prev_term: int = 0
    entries: List[LogEntry] = field(default_factory=list)
    commit_index: int = 0


@dataclass
class AppendRsp:
    term: int = 0
    ok: bool = False
    match_index: int = 0


@dataclass
class VoteReq:
    term: int = 0
    candidate_id: int = 0
    last_log_index: int = 0
    last_log_term: int = 0


@dataclass
class VoteRsp:
    term: int = 0
    granted: bool = False


@dataclass
class SnapInstallReq:
    term: int = 0
    leader_id: int = 0
    last_index: int = 0
    last_term: int = 0
    engine_version: int = 0
    pairs: List[RangePair] = field(default_factory=list)
    # membership active at the snapshot point (config entries may have
    # been compacted out of the log)
    peers_json: str = ""


@dataclass
class SnapInstallRsp:
    term: int = 0
    ok: bool = False


@dataclass
class ReconfigReq:
    peers_json: str = ""     # the COMPLETE new map {node_id: [host, port]}


@dataclass
class ReconfigRsp:
    ok: bool = False
    term: int = 0
    index: int = 0
    message: str = ""


@dataclass
class StatusReq:
    pass


@dataclass
class StatusRsp:
    node_id: int = 0
    role: str = ""
    term: int = 0
    leader_id: int = 0
    last_index: int = 0
    commit_index: int = 0
    engine_version: int = 0
    peers_json: str = ""


class ReplicatedKvService:
    """One member of a kvd replication group."""

    def __init__(
        self,
        node_id: int,
        peers: Dict[int, Tuple[str, int]],
        *,
        data_dir: Optional[str] = None,
        election_timeout_s: Tuple[float, float] = (0.8, 1.6),
        heartbeat_s: float = 0.25,
        compact_entries: int = 100_000,
        fsync: bool = False,
        rpc_client: Optional[RpcClient] = None,
    ):
        self.node_id = node_id
        self.peers = dict(peers)          # node_id -> (host, port), incl. self
        self._others = [p for p in peers if p != node_id]
        self._quorum = len(peers) // 2 + 1
        self._dir = data_dir
        self._fsync = fsync
        self._election_window = election_timeout_s
        self._heartbeat_s = heartbeat_s
        self._compact_entries = compact_entries
        # short transport deadlines: a dead peer must not stall the
        # commit path or the election loop for the default 30s
        self._rpc = rpc_client or RpcClient(
            connect_timeout=max(heartbeat_s, 0.2),
            call_timeout=max(heartbeat_s * 8, 2.0))

        self._mu = threading.RLock()
        self.role = FOLLOWER
        self.term = 0
        self.voted_for = 0
        self.leader_id = 0
        self.commit_index = 0
        self.last_applied = 0
        self._match: Dict[int, int] = {}
        self._next: Dict[int, int] = {}
        self._last_leader_contact = time.monotonic()
        self._stopped = False

        # log[i] holds the entry at index snap_last_index + 1 + i
        self.log: List[LogEntry] = []
        self.snap_last_index = 0
        self.snap_last_term = 0
        self._snap_pairs: List[Tuple[bytes, bytes]] = []
        self._snap_engine_version = 0
        self._snap_peers_json = ""   # membership at the snapshot point
        self._log_f = None

        # serializes the full commit round (apply -> replicate -> ack) AND
        # snapshot(): nothing observable escapes before quorum durability
        self._commit_lock = threading.Lock()

        self.engine = MemKVEngine()
        # the client-facing read front (pins/floor) over the shared engine;
        # no WAL — the replicated log IS the durability story
        self.kv = KvService(self.engine)

        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            self._load_durable()
            self._log_f = open(self._log_path(), "ab")
        with self._mu:
            # a recovered log/snapshot may carry a NEWER membership than
            # the bootstrap map this process was started with
            self._active_config_rescan()
        self._rebuild_engine(upto=self.snap_last_index)
        self.last_applied = self.snap_last_index

        self._ticker = threading.Thread(
            target=self._tick_loop, daemon=True,
            name=f"kvd-repl-{node_id}")
        self._ticker.start()

    # -- membership ----------------------------------------------------------
    @staticmethod
    def _peers_to_json(peers: Dict[int, Tuple[str, int]]) -> str:
        return json.dumps({str(n): list(a) for n, a in sorted(peers.items())})

    @staticmethod
    def _peers_from_json(blob: str) -> Dict[int, Tuple[str, int]]:
        return {int(n): (a[0], int(a[1]))
                for n, a in json.loads(blob).items()}

    def _adopt_config(self, peers: Dict[int, Tuple[str, int]]) -> None:
        """Caller holds _mu. Switch to `peers` (append-time activation):
        quorum and replication targets change NOW; removed peers drop out
        of _match/_next, added ones start from scratch (snapshot/backoff
        brings them up)."""
        if peers == self.peers:
            return
        self.peers = dict(peers)
        self._others = [p for p in peers if p != self.node_id]
        self._quorum = len(peers) // 2 + 1
        for gone in [p for p in self._match if p not in peers]:
            self._match.pop(gone, None)
            self._next.pop(gone, None)
        if self.role == LEADER:
            for p in self._others:
                self._match.setdefault(p, 0)
                self._next.setdefault(p, self._last_index() + 1)

    def _active_config_rescan(self) -> None:
        """Caller holds _mu. Recompute the active config after a log
        truncation or durable load: the LAST surviving config entry wins;
        with none, the snapshot's; with neither, the bootstrap map."""
        chosen: Optional[Dict[int, Tuple[str, int]]] = None
        for e in reversed(self.log):
            if e.config:
                chosen = self._peers_from_json(e.config)
                break
        if chosen is None and self._snap_peers_json:
            chosen = self._peers_from_json(self._snap_peers_json)
        if chosen is not None:
            self._adopt_config(chosen)

    # -- durable state -------------------------------------------------------
    def _state_path(self) -> str:
        return os.path.join(self._dir, "raft_state.json")

    def _log_path(self) -> str:
        return os.path.join(self._dir, "repl.log")

    def _snap_path(self) -> str:
        return os.path.join(self._dir, "repl.snap")

    def _persist_state(self) -> None:
        if not self._dir:
            return
        tmp = self._state_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"term": self.term, "voted_for": self.voted_for}, f)
            if self._fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, self._state_path())

    def _append_durable(self, entries: List[LogEntry]) -> None:
        if self._log_f is None:
            return
        buf = b"".join(
            len(raw).to_bytes(4, "big") + raw
            for raw in (serialize(e) for e in entries))
        self._log_f.write(buf)
        self._log_f.flush()
        if self._fsync:
            os.fsync(self._log_f.fileno())

    def _rewrite_log(self) -> None:
        """Persist the current in-memory log tail (after truncation or
        compaction) atomically."""
        if not self._dir:
            return
        tmp = self._log_path() + ".tmp"
        with open(tmp, "wb") as f:
            for e in self.log:
                raw = serialize(e)
                f.write(len(raw).to_bytes(4, "big") + raw)
            f.flush()
            if self._fsync:
                os.fsync(f.fileno())
        if self._log_f is not None:
            self._log_f.close()
        os.replace(tmp, self._log_path())
        self._log_f = open(self._log_path(), "ab")

    def _persist_snapshot(self) -> None:
        if not self._dir:
            return
        tmp = self._snap_path() + ".tmp"
        with open(tmp, "wb") as f:
            head = json.dumps({
                "last_index": self.snap_last_index,
                "last_term": self.snap_last_term,
                "engine_version": self._snap_engine_version,
                "peers": self._snap_peers_json,
            }).encode()
            f.write(len(head).to_bytes(4, "big") + head)
            for k, v in self._snap_pairs:
                f.write(len(k).to_bytes(4, "big") + k)
                f.write(len(v).to_bytes(4, "big") + v)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snap_path())

    def _load_durable(self) -> None:
        try:
            with open(self._state_path()) as f:
                st = json.load(f)
            self.term = int(st.get("term", 0))
            self.voted_for = int(st.get("voted_for", 0))
        except (OSError, ValueError):
            pass
        try:
            with open(self._snap_path(), "rb") as f:
                raw = f.read()
            n = int.from_bytes(raw[:4], "big")
            head = json.loads(raw[4:4 + n])
            self.snap_last_index = int(head["last_index"])
            self.snap_last_term = int(head["last_term"])
            self._snap_engine_version = int(head["engine_version"])
            self._snap_peers_json = str(head.get("peers", ""))
            pos = 4 + n
            pairs = []
            while pos + 4 <= len(raw):
                kl = int.from_bytes(raw[pos:pos + 4], "big")
                k = raw[pos + 4:pos + 4 + kl]
                pos += 4 + kl
                vl = int.from_bytes(raw[pos:pos + 4], "big")
                v = raw[pos + 4:pos + 4 + vl]
                pos += 4 + vl
                pairs.append((k, v))
            self._snap_pairs = pairs
        except (OSError, ValueError, KeyError):
            pass
        try:
            with open(self._log_path(), "rb") as f:
                raw = f.read()
            pos = 0
            while pos + 4 <= len(raw):
                n = int.from_bytes(raw[pos:pos + 4], "big")
                if pos + 4 + n > len(raw):
                    break  # torn tail (never acked)
                try:
                    e = deserialize(raw[pos + 4:pos + 4 + n], LogEntry)
                except Exception:
                    break
                if e.index == self.snap_last_index + len(self.log) + 1:
                    self.log.append(e)
                pos += 4 + n
        except OSError:
            pass

    # -- log helpers ---------------------------------------------------------
    def _last_index(self) -> int:
        return self.snap_last_index + len(self.log)

    def _term_at(self, index: int) -> int:
        if index == self.snap_last_index:
            return self.snap_last_term
        off = index - self.snap_last_index - 1
        if 0 <= off < len(self.log):
            return self.log[off].term
        return -1

    def _entry_at(self, index: int) -> Optional[LogEntry]:
        off = index - self.snap_last_index - 1
        if 0 <= off < len(self.log):
            return self.log[off]
        return None

    # -- engine application --------------------------------------------------
    def _apply_record(self, payload: bytes) -> None:
        if not payload:
            return  # no-op barrier
        rec = deserialize(payload, WalRecord)
        writes = {w.key: (None if w.tombstone else w.value)
                  for w in rec.writes}
        clears = [(r.begin, r.end) for r in rec.clear_ranges]
        self.engine.commit_external(
            self.engine.version, [], [], writes, clears, [])
        if rec.version > self.engine.version:
            self.engine.restore_version_floor(rec.version)

    def _rebuild_engine(self, upto: int) -> None:
        """Fresh engine = snapshot + log entries (snap_last, upto]."""
        self.engine = MemKVEngine()
        if self._snap_pairs:
            self.engine.commit_external(
                0, [], [], {k: v for k, v in self._snap_pairs}, [], [])
            self.engine.restore_version_floor(self._snap_engine_version)
        for idx in range(self.snap_last_index + 1, upto + 1):
            e = self._entry_at(idx)
            if e is not None:
                self._apply_record(e.payload)
        self.kv = KvService(self.engine)
        self.last_applied = upto

    def _advance_applied(self) -> None:
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            e = self._entry_at(self.last_applied)
            if e is not None:
                self._apply_record(e.payload)

    # -- role transitions ----------------------------------------------------
    def _become_follower(self, term: int, leader_id: int = 0) -> None:
        self.role = FOLLOWER
        if term > self.term:
            self.term = term
            self.voted_for = 0
            self._persist_state()
        if leader_id:
            self.leader_id = leader_id
        self._last_leader_contact = time.monotonic()

    def _become_leader(self) -> None:
        self.role = LEADER
        self.leader_id = self.node_id
        last = self._last_index()
        self._match = {p: 0 for p in self._others}
        self._next = {p: last + 1 for p in self._others}
        # no-op barrier of our own term: once it commits, every prior
        # entry in this log is committed too (the figure-8 guard), and the
        # engine rebuilt below is known quorum-durable. Client ops are
        # REJECTED until the barrier commits (_require_leader): otherwise a
        # read could observe an inherited entry that a future leader
        # (elected without it) is still allowed to discard.
        barrier = LogEntry(term=self.term, index=last + 1, payload=b"")
        self.log.append(barrier)
        self._append_durable([barrier])
        self._barrier_index = barrier.index
        if len(self.peers) == 1:
            self.commit_index = barrier.index  # quorum of one
        self._rebuild_engine(upto=self._last_index())

    # -- background: election timer + heartbeats -----------------------------
    def _tick_loop(self) -> None:
        timeout = random.uniform(*self._election_window)
        while not self._stopped:
            time.sleep(self._heartbeat_s / 2)
            with self._mu:
                if self._stopped:
                    return
                role = self.role
                silent = time.monotonic() - self._last_leader_contact
            if role == LEADER:
                self._broadcast_heartbeat()
            elif silent > timeout:
                timeout = random.uniform(*self._election_window)
                self._run_election()

    def _run_election(self) -> None:
        with self._mu:
            if self._stopped:
                return  # a zombie candidate must not bump/persist terms
            self.role = CANDIDATE
            self.term += 1
            self.voted_for = self.node_id
            self._persist_state()
            term = self.term
            req = VoteReq(
                term=term,
                candidate_id=self.node_id,
                last_log_index=self._last_index(),
                last_log_term=self._term_at(self._last_index()),
            )
            self._last_leader_contact = time.monotonic()
        votes = 1
        for peer in self._others:
            try:
                rsp = self._rpc.call(
                    self.peers[peer], KV_REPL_SERVICE_ID, 2, req, VoteRsp)
            except FsError:
                continue
            with self._mu:
                if rsp.term > self.term:
                    self._become_follower(rsp.term)
                    return
            if rsp.granted:
                votes += 1
        with self._mu:
            if self.role != CANDIDATE or self.term != term:
                return
            if votes >= self._quorum:
                self._become_leader()
            else:
                self.role = FOLLOWER
        if self.role == LEADER:
            self._broadcast_heartbeat()

    def _broadcast_heartbeat(self) -> None:
        if self._stopped:
            return
        for peer in self._others:
            self._replicate_to(peer)
        self._advance_commit_from_matches()

    def _advance_commit_from_matches(self) -> None:
        """Leader: commit = the highest index stored on a majority, but
        only once an entry of OUR term reaches it (Raft's commit rule) —
        this is what lets the election barrier commit without client
        traffic."""
        with self._mu:
            if self.role != LEADER:
                return
            stored = sorted(
                [self._last_index()] + list(self._match.values()),
                reverse=True)
            candidate = stored[self._quorum - 1]
            if (candidate > self.commit_index
                    and self._term_at(candidate) == self.term):
                self.commit_index = candidate
                self._advance_applied()

    # -- replication ---------------------------------------------------------
    def _replicate_to(self, peer: int) -> bool:
        """Bring one follower up to date; True when it matches our log."""
        for _ in range(4):  # back off through log mismatches
            with self._mu:
                if self.role != LEADER or self._stopped:
                    return False
                nxt = self._next.get(peer, self._last_index() + 1)
                if nxt <= self.snap_last_index:
                    return self._install_snapshot_on(peer)
                prev = nxt - 1
                req = AppendReq(
                    term=self.term,
                    leader_id=self.node_id,
                    prev_index=prev,
                    prev_term=self._term_at(prev),
                    entries=[self._entry_at(i)
                             for i in range(nxt, self._last_index() + 1)],
                    commit_index=self.commit_index,
                )
            try:
                rsp = self._rpc.call(
                    self.peers[peer], KV_REPL_SERVICE_ID, 1, req, AppendRsp)
            except FsError:
                return False
            with self._mu:
                if rsp.term > self.term:
                    self._become_follower(rsp.term)
                    return False
                if rsp.ok:
                    # max(): a late heartbeat reply must not regress match
                    self._match[peer] = max(self._match.get(peer, 0),
                                            rsp.match_index)
                    self._next[peer] = self._match[peer] + 1
                    return True
                # consistency miss: back off (follower told us how far back)
                self._next[peer] = max(
                    1, min(rsp.match_index + 1, self._next.get(peer, 1) - 1))
        return False

    def _install_snapshot_on(self, peer: int) -> bool:
        # caller holds _mu
        req = SnapInstallReq(
            term=self.term,
            leader_id=self.node_id,
            last_index=self.snap_last_index,
            last_term=self.snap_last_term,
            engine_version=self._snap_engine_version,
            pairs=[RangePair(k, v) for k, v in self._snap_pairs],
            peers_json=(self._snap_peers_json
                        or self._peers_to_json(self.peers)),
        )
        addr = self.peers[peer]
        self._mu.release()
        try:
            rsp = self._rpc.call(
                addr, KV_REPL_SERVICE_ID, 3, req, SnapInstallRsp)
        except FsError:
            return False
        finally:
            self._mu.acquire()
        if rsp.term > self.term:
            self._become_follower(rsp.term)
            return False
        if rsp.ok:
            self._match[peer] = req.last_index
            self._next[peer] = req.last_index + 1
        return rsp.ok

    def _replicate_quorum(self) -> bool:
        """Push the current log to followers; True once a majority
        (including self) stores the last index."""
        target = self._last_index()
        acked = 1
        for peer in self._others:
            if self._replicate_to(peer):
                with self._mu:
                    if self._match.get(peer, 0) >= target:
                        acked += 1
            if acked >= self._quorum:
                break
        if acked >= self._quorum:
            with self._mu:
                if self.role == LEADER and self.term == self._term_at(target):
                    self.commit_index = max(self.commit_index, target)
                    self._advance_applied()
            return True
        return False

    def _maybe_compact(self) -> None:
        with self._mu:
            self._compact_locked()

    def _compact_locked(self) -> None:
        """Caller holds _mu. Snapshot applied state + truncate the log
        prefix; runs on leaders AND followers (a follower that never lags
        would otherwise grow its log forever)."""
        if self._stopped:
            return  # never rewrite files a successor may own
        if len(self.log) <= self._compact_entries:
            return
        keep_from = self.last_applied  # snapshot covers exactly this state
        if keep_from <= self.snap_last_index or keep_from > self.commit_index:
            return
        # membership at the snapshot point: the last config entry at or
        # below keep_from (those entries are about to be truncated away)
        for e in self.log:
            if e.index > keep_from:
                break
            if e.config:
                self._snap_peers_json = e.config
        self._snap_pairs = self.engine.dump_at(self.engine.version)
        self._snap_engine_version = self.engine.version
        self.snap_last_term = self._term_at(keep_from)
        self.log = self.log[keep_from - self.snap_last_index:]
        self.snap_last_index = keep_from
        self._persist_snapshot()
        self._rewrite_log()

    # -- client-facing KV API (leader only) ----------------------------------
    def _require_leader(self) -> None:
        with self._mu:
            if self.role != LEADER:
                raise FsError(Status(
                    Code.KV_NOT_PRIMARY,
                    f"not primary; leader={self.leader_id}"))
            if self.commit_index < getattr(self, "_barrier_index", 0):
                # elected but the term barrier has not replicated yet:
                # nothing this engine shows is known quorum-durable
                raise FsError(Status(
                    Code.KV_NOT_PRIMARY,
                    f"not primary (barrier pending); "
                    f"leader={self.leader_id}"))

    def snapshot(self, req: SnapshotReq) -> SnapshotRsp:
        self._require_leader()
        # serialized behind in-flight commits: the version handed out is
        # quorum-durable (see module docstring)
        with self._commit_lock:
            return self.kv.snapshot(req)

    def get(self, req: GetReq):
        self._require_leader()
        return self.kv.get(req)

    def get_range(self, req: RangeReq):
        self._require_leader()
        return self.kv.get_range(req)

    def release(self, req: ReleaseReq) -> EmptyMsg:
        self._require_leader()
        return self.kv.release(req)

    def commit(self, req: CommitReq) -> CommitRsp:
        self._require_leader()
        writes = {w.key: (None if w.tombstone else w.value)
                  for w in req.writes}
        clears = [(r.begin, r.end) for r in req.clear_ranges]
        stamps = [(s.prefix, s.suffix, s.value) for s in req.versionstamped]
        with self._commit_lock:
            self._require_leader()
            self.kv._check_version(req.read_version)
            version = self.engine.commit_external(
                req.read_version,
                list(req.read_keys),
                [(r.begin, r.end) for r in req.read_ranges],
                writes,
                clears,
                stamps,
            )
            if not (writes or clears or stamps):
                return CommitRsp(version=version)  # read-only: no log entry
            if stamps:
                import struct as _struct

                for order, (prefix, suffix, value) in enumerate(stamps):
                    stamp = _struct.pack(">QH", version, order)
                    writes[prefix + stamp + suffix] = value
            rec = WalRecord(
                version=version,
                writes=[WriteEntry(k, v if v is not None else b"", v is None)
                        for k, v in writes.items()],
                clear_ranges=[RangeEntry(b, e) for b, e in clears],
            )
            with self._mu:
                if self.role != LEADER:
                    # deposed between the engine apply and the log append
                    # (a higher-term leader contacted us): the local apply
                    # is discarded, nothing was appended anywhere — the
                    # retry is unambiguous
                    self._rebuild_engine(upto=min(self.commit_index,
                                                  self._last_index()))
                    raise FsError(Status(
                        Code.KV_NOT_PRIMARY,
                        f"deposed mid-commit; leader={self.leader_id}"))
                entry = LogEntry(term=self.term,
                                 index=self._last_index() + 1,
                                 payload=serialize(rec))
                self.log.append(entry)
                self._append_durable([entry])
                # the engine ALREADY applied this record (commit_external
                # above): mark it applied now or _advance_applied would
                # re-apply it after quorum, double-bumping the version
                self.last_applied = max(self.last_applied, entry.index)
            if not self._replicate_quorum():
                # the entry IS durably in our log: if this node is later
                # re-elected (it may have the longest log) the entry
                # commits after all — a genuinely ambiguous outcome. Hide
                # the apply locally and say MAYBE_COMMITTED, mirroring
                # FDB's commit_unknown_result.
                with self._mu:
                    self.role = FOLLOWER
                    self._rebuild_engine(upto=min(self.commit_index,
                                                  self._last_index()))
                raise FsError(Status(
                    Code.KV_MAYBE_COMMITTED,
                    "lost quorum mid-commit; outcome unknown"))
            self._maybe_compact()
        return CommitRsp(version=version)

    # -- replication RPC handlers (peer-facing) ------------------------------
    def append_entries(self, req: AppendReq) -> AppendRsp:
        with self._mu:
            if self._stopped:
                return AppendRsp(term=self.term, ok=False,
                                 match_index=self._last_index())
            # note: appends from leaders OUTSIDE our (possibly stale)
            # config are ACCEPTED — a lagging member must be able to learn
            # the very config entries that make the sender legitimate, and
            # the log-consistency check below protects correctness either
            # way. Removed-node containment lives in request_vote's leader
            # stickiness, not here.
            if req.term < self.term:
                return AppendRsp(term=self.term, ok=False,
                                 match_index=self._last_index())
            self._become_follower(req.term, req.leader_id)
            # consistency check at prev (indices covered by our snapshot
            # are trusted: snapshots only contain committed state)
            if req.prev_index > self._last_index() or (
                    req.prev_index > self.snap_last_index
                    and self._term_at(req.prev_index) != req.prev_term):
                # tell the leader how far back we actually are
                return AppendRsp(
                    term=self.term, ok=False,
                    match_index=min(self._last_index(),
                                    max(req.prev_index - 1, 0)))
            new_durable: List[LogEntry] = []
            truncated = False
            for e in req.entries:
                if e.index <= self.snap_last_index:
                    continue  # covered by our snapshot
                have = self._entry_at(e.index)
                if have is not None and have.term == e.term:
                    continue
                if have is not None:
                    # conflicting suffix: drop it (it was never committed)
                    self.log = self.log[: e.index - self.snap_last_index - 1]
                    truncated = True
                if e.index == self._last_index() + 1:
                    self.log.append(e)
                    new_durable.append(e)
            if truncated:
                self._rewrite_log()
                if self.last_applied > self._last_index():
                    # rebuild below the truncation point
                    self._rebuild_engine(
                        upto=min(self.commit_index, self._last_index()))
            elif new_durable:
                self._append_durable(new_durable)
            if truncated or any(e.config for e in new_durable):
                # membership activates at APPEND time (and a truncation
                # may have rolled a config entry back out)
                self._active_config_rescan()
            if req.commit_index > self.commit_index:
                self.commit_index = min(req.commit_index, self._last_index())
                self._advance_applied()
                self._compact_locked()
            return AppendRsp(term=self.term, ok=True,
                             match_index=self._last_index())

    def request_vote(self, req: VoteReq) -> VoteRsp:
        with self._mu:
            if self._stopped:
                return VoteRsp(term=self.term, granted=False)
            if (time.monotonic() - self._last_leader_contact
                    < self._election_window[0]):
                # leader stickiness (Raft dissertation §4.2.3): while we
                # hear a current leader, campaigns are refused WITHOUT
                # adopting the candidate's term — this is what contains a
                # REMOVED node (its config no longer includes it, but it
                # keeps timing out and campaigning at ever-higher terms)
                # without blocking a lagging member's catch-up
                return VoteRsp(term=self.term, granted=False)
            if req.term < self.term:
                return VoteRsp(term=self.term, granted=False)
            if req.term > self.term:
                self._become_follower(req.term)
            up_to_date = (
                req.last_log_term > self._term_at(self._last_index())
                or (req.last_log_term == self._term_at(self._last_index())
                    and req.last_log_index >= self._last_index()))
            if up_to_date and self.voted_for in (0, req.candidate_id):
                self.voted_for = req.candidate_id
                self._persist_state()
                self._last_leader_contact = time.monotonic()
                return VoteRsp(term=self.term, granted=True)
            return VoteRsp(term=self.term, granted=False)

    def install_snapshot(self, req: SnapInstallReq) -> SnapInstallRsp:
        with self._mu:
            if self._stopped:
                return SnapInstallRsp(term=self.term, ok=False)
            if req.term < self.term:
                return SnapInstallRsp(term=self.term, ok=False)
            self._become_follower(req.term, req.leader_id)
            self._snap_pairs = [(p.key, p.value) for p in req.pairs]
            self._snap_engine_version = req.engine_version
            self.snap_last_index = req.last_index
            self.snap_last_term = req.last_term
            self._snap_peers_json = req.peers_json
            self.log = [e for e in self.log if e.index > req.last_index]
            # a snapshot replaces everything up to last_index
            if self.log and self.log[0].index != req.last_index + 1:
                self.log = []
            self._persist_snapshot()
            self._rewrite_log()
            self.commit_index = max(self.commit_index, req.last_index)
            self._rebuild_engine(upto=self.commit_index)
            self._active_config_rescan()
            return SnapInstallRsp(term=self.term, ok=True)

    def reconfig(self, req: ReconfigReq) -> ReconfigRsp:
        """Online membership change (the role FDB's reconfigurable cluster
        plays for the reference, src/fdb/HybridKvEngine.h:12-22): append a
        config entry carrying the COMPLETE new peer map and replicate it
        under the NEW quorum. One node added or removed per call (the
        single-server rule that makes append-time activation safe); the
        current leader cannot remove itself. A freshly added node is
        started empty with the new map as its bootstrap config and catches
        up via snapshot/log backoff."""
        self._require_leader()
        try:
            new_peers = self._peers_from_json(req.peers_json)
        except (ValueError, KeyError, TypeError) as e:
            return ReconfigRsp(ok=False, message=f"bad peer map: {e!r}")
        with self._commit_lock:
            with self._mu:
                if self.role != LEADER:
                    return ReconfigRsp(
                        ok=False, term=self.term,
                        message=f"not leader; leader={self.leader_id}")
                if not new_peers:
                    return ReconfigRsp(ok=False, message="empty peer map")
                if self.node_id not in new_peers:
                    return ReconfigRsp(
                        ok=False,
                        message="leader cannot remove itself; move "
                                "leadership first")
                # ONE changed node per entry — added, removed, OR an
                # existing member's address rewrite all count (the
                # quorum-overlap argument needs every other member's
                # identity AND address unchanged)
                delta = set(new_peers) ^ set(self.peers)
                delta |= {n for n in set(new_peers) & set(self.peers)
                          if new_peers[n] != self.peers[n]}
                if len(delta) > 1:
                    return ReconfigRsp(
                        ok=False,
                        message=f"one node per change (delta={sorted(delta)}"
                                "); reconfig repeatedly for more")
                entry = LogEntry(term=self.term,
                                 index=self._last_index() + 1,
                                 config=self._peers_to_json(new_peers))
                self.log.append(entry)
                self._append_durable([entry])
                self._adopt_config(new_peers)  # append-time activation
                self.last_applied = max(self.last_applied, entry.index)
                term, index = self.term, entry.index
            if not self._replicate_quorum():
                # the entry is durably in our log; like a client commit
                # that lost quorum mid-round the outcome is ambiguous —
                # step down and report it
                with self._mu:
                    self.role = FOLLOWER
                return ReconfigRsp(
                    ok=False, term=term, index=index,
                    message="lost quorum mid-reconfig; outcome unknown")
        return ReconfigRsp(ok=True, term=term, index=index)

    def status(self, req: StatusReq) -> StatusRsp:
        with self._mu:
            return StatusRsp(
                node_id=self.node_id,
                role=self.role,
                term=self.term,
                leader_id=self.leader_id,
                last_index=self._last_index(),
                commit_index=self.commit_index,
                engine_version=self.engine.version,
                peers_json=self._peers_to_json(self.peers),
            )

    def stop(self) -> None:
        with self._mu:
            self._stopped = True
            self.role = FOLLOWER
        # QUIESCE before releasing the data dir: join the ticker and take
        # the lock once more so any in-flight RPC handler finishes. An
        # in-process restart (tests) constructs a NEW service over the
        # SAME files with a different lock — a zombie writer thread from
        # this instance racing the successor's reads/writes corrupts the
        # log (impossible with real process kills, very possible with
        # thread-level ones).
        if self._ticker is not None \
                and self._ticker is not threading.current_thread():
            deadline = time.monotonic() + 30
            while self._ticker.is_alive() and time.monotonic() < deadline:
                self._ticker.join(timeout=1)
            if self._ticker.is_alive():
                # loud, not silent: the quiesce invariant is broken and a
                # successor over this data dir would race a zombie writer
                xlog("WARN",
                     f"kvd {self.node_id}: ticker still alive after "
                     "stop() quiesce window")
        # drain any in-flight client commit (it holds _commit_lock across
        # replication): its post-quorum compact is also _stopped-guarded
        with self._commit_lock:
            pass
        with self._mu:
            if self._log_f is not None:
                self._log_f.close()
                self._log_f = None


def bind_repl_service(server: RpcServer, svc: ReplicatedKvService) -> None:
    s = ServiceDef(KV_REPL_SERVICE_ID, "KvRepl")
    s.method(1, "appendEntries", AppendReq, AppendRsp, svc.append_entries)
    s.method(2, "requestVote", VoteReq, VoteRsp, svc.request_vote)
    s.method(3, "installSnapshot", SnapInstallReq, SnapInstallRsp,
             svc.install_snapshot)
    s.method(4, "status", StatusReq, StatusRsp, svc.status)
    s.method(5, "reconfig", ReconfigReq, ReconfigRsp, svc.reconfig)
    server.add_service(s)


def bind_replicated_kv(server: RpcServer, svc: ReplicatedKvService) -> None:
    """Expose the client-facing KV schema (same ids as the plain kvd) plus
    the replication service on one server."""
    from tpu3fs.kv.service import KV_SERVICE_ID, GetRsp, RangeRsp

    s = ServiceDef(KV_SERVICE_ID, "Kv")
    s.method(1, "snapshot", SnapshotReq, SnapshotRsp, svc.snapshot)
    s.method(2, "get", GetReq, GetRsp, svc.get)
    s.method(3, "getRange", RangeReq, RangeRsp, svc.get_range)
    s.method(4, "commit", CommitReq, CommitRsp, svc.commit)
    s.method(5, "release", ReleaseReq, EmptyMsg, svc.release)
    server.add_service(s)
    bind_repl_service(server, svc)
