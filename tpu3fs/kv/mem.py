"""In-memory MVCC KV engine emulating FoundationDB transaction semantics.

Mirrors the reference's mem KV (src/common/kv/mem/{MemKV,MemKVEngine,
MemTransaction}.h): snapshot reads at the transaction's read version,
read-your-writes, half-open range scans, clear ranges, versionstamped keys,
and optimistic read/write conflict detection at commit — the full contract the
meta service depends on, so the meta suite runs unchanged against mem or a
real FDB-like engine.
"""

from __future__ import annotations

import bisect
import struct
import threading
from typing import Dict, List, Optional, Tuple

from tpu3fs.kv.kv import IKVEngine, ITransaction, KVPair
from tpu3fs.utils.result import Code, FsError, Status


class MemKVEngine(IKVEngine):
    def __init__(self):
        self._lock = threading.RLock()
        self._version = 0
        # MVCC store: key -> [(version, value-or-None)], append-ordered
        self._data: Dict[bytes, List[Tuple[int, Optional[bytes]]]] = {}
        self._sorted_keys: List[bytes] = []
        # commit log for conflict detection: (version, point_keys, ranges)
        self._commits: List[Tuple[int, List[bytes], List[Tuple[bytes, bytes]]]] = []
        # read versions of live transactions: lower-bounds pruning
        self._active: Dict[int, int] = {}
        self._commits_since_prune = 0

    # -- engine API --------------------------------------------------------
    def transaction(self) -> "MemTransaction":
        with self._lock:
            txn = MemTransaction(self, self._version)
            self._active[id(txn)] = self._version
            return txn

    def _finish_txn(self, txn: "MemTransaction") -> None:
        with self._lock:
            self._active.pop(id(txn), None)

    def _maybe_prune(self) -> None:
        """Drop commit-log entries and MVCC history no live transaction can
        see — long-running services (mgmtd lease/heartbeat loops) would
        otherwise grow without bound. Caller holds the lock."""
        self._commits_since_prune += 1
        if self._commits_since_prune < 256:
            return
        self._commits_since_prune = 0
        floor = min(self._active.values(), default=self._version)
        # conflict checks only scan commits with ver > a live read_version
        self._commits = [c for c in self._commits if c[0] > floor]
        dead_keys = []
        for key, history in self._data.items():
            # keep the newest entry at-or-below the floor + all newer entries
            cut = 0
            for i, (ver, _val) in enumerate(history):
                if ver <= floor:
                    cut = i
            if cut:
                del history[:cut]
            if len(history) == 1 and history[0][1] is None and history[0][0] <= floor:
                dead_keys.append(key)  # fully-pruned tombstone
        for key in dead_keys:
            del self._data[key]
            idx = bisect.bisect_left(self._sorted_keys, key)
            if idx < len(self._sorted_keys) and self._sorted_keys[idx] == key:
                del self._sorted_keys[idx]

    @property
    def version(self) -> int:
        return self._version

    def dump_at(self, version: int) -> List[Tuple[bytes, bytes]]:
        """All live (key, value) pairs at a snapshot version — feeds the
        network KV service's WAL compaction (replay = snapshot + tail)."""
        with self._lock:
            out = []
            for key in list(self._sorted_keys):
                val = self._resolve(key, version)
                if val is not None:
                    out.append((key, val))
            return out

    def restore_version_floor(self, version: int) -> None:
        """Fast-forward the version counter (never backwards): a restarted
        service replaying a compacted WAL must not reissue version numbers
        (versionstamped keys depend on monotonicity across restarts)."""
        with self._lock:
            self._version = max(self._version, version)

    # -- external transaction surface (shared by MemTransaction and the
    # network KV service: one conflict-check + atomic-apply path) ----------
    def pin_version(self, token, version: int) -> None:
        """Hold MVCC history >= version alive (remote snapshot in use)."""
        with self._lock:
            self._active[token] = version

    def unpin_version(self, token) -> None:
        with self._lock:
            self._active.pop(token, None)

    def read_at(self, key: bytes, version: int) -> Optional[bytes]:
        """Point read at an MVCC snapshot version."""
        with self._lock:
            return self._resolve(key, version)

    def range_at(
        self, begin: bytes, end: bytes, version: int
    ) -> List[Tuple[bytes, bytes]]:
        """[begin, end) live pairs at a snapshot version (unlimited)."""
        with self._lock:
            out = []
            for key in self._range_keys(begin, end):
                val = self._resolve(key, version)
                if val is not None:
                    out.append((key, val))
            return out

    def commit_external(
        self,
        read_version: int,
        read_keys: List[bytes],
        read_ranges: List[Tuple[bytes, bytes]],
        writes: Dict[bytes, Optional[bytes]],
        clear_ranges: List[Tuple[bytes, bytes]],
        versionstamped: List[Tuple[bytes, bytes, bytes]],
    ) -> int:
        """Validate the read set against commits after read_version and, if
        clean, apply the write set atomically. Returns the commit version;
        raises FsError(KV_CONFLICT) otherwise."""
        with self._lock:
            if self._check_conflicts(read_version, read_keys, read_ranges):
                raise FsError(Status(Code.KV_CONFLICT, "read-write conflict"))
            if not writes and not clear_ranges and not versionstamped:
                return self._version
            self._version += 1
            version = self._version
            all_writes = dict(writes)
            for order, (prefix, suffix, value) in enumerate(versionstamped):
                stamp = struct.pack(">QH", version, order)
                all_writes[prefix + stamp + suffix] = value
            self._apply(version, all_writes, clear_ranges)
            self._commits.append(
                (version, list(all_writes.keys()), list(clear_ranges))
            )
            self._maybe_prune()
            return version

    # -- internals used by MemTransaction ----------------------------------
    def _resolve(self, key: bytes, version: int) -> Optional[bytes]:
        history = self._data.get(key)
        if not history:
            return None
        for ver, val in reversed(history):
            if ver <= version:
                return val
        return None

    def _range_keys(self, begin: bytes, end: bytes) -> List[bytes]:
        lo = bisect.bisect_left(self._sorted_keys, begin)
        hi = bisect.bisect_left(self._sorted_keys, end)
        return self._sorted_keys[lo:hi]

    def _apply(self, version: int, writes: Dict[bytes, Optional[bytes]],
               clear_ranges: List[Tuple[bytes, bytes]]) -> None:
        for begin, end in clear_ranges:
            for key in self._range_keys(begin, end):
                self._data.setdefault(key, []).append((version, None))
        for key, value in writes.items():
            history = self._data.get(key)
            if history is None:
                self._data[key] = [(version, value)]
                bisect.insort(self._sorted_keys, key)
            else:
                history.append((version, value))
        # keys cleared by ranges might be new tombstones for unseen keys: not
        # needed — clearing nonexistent keys is a no-op.

    def _check_conflicts(
        self,
        read_version: int,
        read_keys: List[bytes],
        read_ranges: List[Tuple[bytes, bytes]],
    ) -> bool:
        point_set = set(read_keys)
        for ver, keys, ranges in reversed(self._commits):
            if ver <= read_version:
                break
            for k in keys:
                if k in point_set:
                    return True
                for begin, end in read_ranges:
                    if begin <= k < end:
                        return True
            for begin, end in ranges:
                for rk in read_keys:
                    if begin <= rk < end:
                        return True
                for rb, re_ in read_ranges:
                    if rb < end and begin < re_:
                        return True
        return False


class MemTransaction(ITransaction):
    def __init__(self, engine: MemKVEngine, read_version: int):
        self._engine = engine
        self._read_version = read_version
        self._writes: Dict[bytes, Optional[bytes]] = {}
        self._clear_ranges: List[Tuple[bytes, bytes]] = []
        self._read_keys: List[bytes] = []
        self._read_ranges: List[Tuple[bytes, bytes]] = []
        self._versionstamped: List[Tuple[bytes, bytes, bytes]] = []
        self._committed_version: Optional[int] = None
        self._done = False

    # -- reads -------------------------------------------------------------
    def _local_lookup(self, key: bytes):
        """-> (found_locally, value) honoring writes and clear ranges."""
        if key in self._writes:
            return True, self._writes[key]
        for begin, end in self._clear_ranges:
            if begin <= key < end:
                return True, None
        return False, None

    def get(self, key: bytes) -> Optional[bytes]:
        found, val = self._local_lookup(key)
        if found:
            return val
        self._read_keys.append(key)
        with self._engine._lock:
            return self._engine._resolve(key, self._read_version)

    def snapshot_get(self, key: bytes) -> Optional[bytes]:
        found, val = self._local_lookup(key)
        if found:
            return val
        with self._engine._lock:
            return self._engine._resolve(key, self._read_version)

    def get_range(
        self,
        begin: bytes,
        end: bytes,
        *,
        limit: int = 0,
        reverse: bool = False,
        snapshot: bool = False,
    ) -> List[KVPair]:
        if not snapshot:
            self._read_ranges.append((begin, end))
        with self._engine._lock:
            keys = self._engine._range_keys(begin, end)
            merged: Dict[bytes, Optional[bytes]] = {}
            for key in keys:
                merged[key] = self._engine._resolve(key, self._read_version)
        # overlay local effects
        for rb, re_ in self._clear_ranges:
            for key in list(merged):
                if rb <= key < re_:
                    merged[key] = None
        for key, val in self._writes.items():
            if begin <= key < end:
                merged[key] = val
        items = sorted(
            (k for k, v in merged.items() if v is not None), reverse=reverse
        )
        if limit:
            items = items[:limit]
        return [KVPair(k, merged[k]) for k in items]

    def add_read_conflict(self, key: bytes) -> None:
        self._read_keys.append(key)

    # -- writes ------------------------------------------------------------
    def set(self, key: bytes, value: bytes) -> None:
        assert not self._done
        self._writes[key] = bytes(value)

    def set_versionstamped_key(self, prefix: bytes, suffix: bytes, value: bytes) -> None:
        assert not self._done
        self._versionstamped.append((bytes(prefix), bytes(suffix), bytes(value)))

    def clear(self, key: bytes) -> None:
        assert not self._done
        self._writes[key] = None

    def clear_range(self, begin: bytes, end: bytes) -> None:
        assert not self._done
        # drop overlapping buffered writes, then record the range
        for key in [k for k in self._writes if begin <= k < end]:
            del self._writes[key]
        self._clear_ranges.append((begin, end))

    # -- commit ------------------------------------------------------------
    def commit(self) -> None:
        assert not self._done
        self._done = True
        eng = self._engine
        with eng._lock:
            eng._active.pop(id(self), None)
            self._committed_version = eng.commit_external(
                self._read_version,
                self._read_keys,
                self._read_ranges,
                self._writes,
                self._clear_ranges,
                self._versionstamped,
            )

    def cancel(self) -> None:
        self._done = True
        self._engine._finish_txn(self)

    @property
    def committed_version(self) -> Optional[int]:
        return self._committed_version
