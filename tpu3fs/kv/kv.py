"""Transactional KV abstraction + retry driver.

Re-expresses the reference's IKVEngine/ITransaction interfaces and the
transaction-with-retry loop every metadata/mgmtd operation runs inside
(src/common/kv/IKVEngine.h, ITransaction.h, WithTransaction.h:34-46). The
in-memory engine (kv/mem.py) emulates FoundationDB semantics — snapshot
isolation, read-set conflict detection, versionstamps — faithfully enough
that the meta test suite runs identically against it, which is the
reference's own trick (tests/common/kv/mem vs tests/common/kv/fdb).

Key prefixes mirror src/common/kv/KeyPrefix-def.h:6-23.
"""

from __future__ import annotations

import abc
import enum
import random
import time
from dataclasses import dataclass
from typing import Callable, List, Optional, TypeVar

from tpu3fs.utils.result import Code, FsError

T = TypeVar("T")


class KeyPrefix(bytes, enum.Enum):
    """4-byte key namespaces (ref KeyPrefix-def.h)."""

    INODE = b"INOD"          # inode id -> inode
    DIR_ENTRY = b"DENT"      # (parent, name) -> dirent
    META_SERVER = b"META"    # meta server heartbeat map (Distributor)
    USER = b"USER"           # user/token records
    NODE = b"NODE"           # mgmtd node infos
    LEASE = b"SING"          # mgmtd primary lease ("single" record)
    CHAIN_INFO = b"CHIT"     # chain infos
    CHAIN_TABLE = b"CHIF"    # chain tables
    INODE_SESSION = b"INOS"  # write-open file sessions
    IDEMPOTENT = b"IDEM"     # cached op results for client retries
    CONFIG = b"CONF"         # per-node-type config blobs
    TARGET_INFO = b"TGIF"    # target infos
    MIGRATION = b"MGJB"      # migration job records (+ b"MGJC" id counter)
    SERVING = b"SRVE"        # KVCache serving endpoints (peer directory)


def make_key(prefix: KeyPrefix, *parts: bytes) -> bytes:
    return prefix.value + b"".join(parts)


@dataclass
class KVPair:
    key: bytes
    value: bytes


class ITransaction(abc.ABC):
    """One transaction: snapshot reads + buffered writes + conflict commit."""

    @abc.abstractmethod
    def get(self, key: bytes) -> Optional[bytes]:
        """Read with conflict tracking."""

    @abc.abstractmethod
    def snapshot_get(self, key: bytes) -> Optional[bytes]:
        """Read WITHOUT adding to the conflict read-set."""

    @abc.abstractmethod
    def get_range(
        self,
        begin: bytes,
        end: bytes,
        *,
        limit: int = 0,
        reverse: bool = False,
        snapshot: bool = False,
    ) -> List[KVPair]:
        """Half-open [begin, end) ordered scan; limit 0 = unlimited."""

    @abc.abstractmethod
    def set(self, key: bytes, value: bytes) -> None: ...

    @abc.abstractmethod
    def set_versionstamped_key(self, prefix: bytes, suffix: bytes, value: bytes) -> None:
        """Write to prefix + 10-byte commit versionstamp + suffix."""

    @abc.abstractmethod
    def clear(self, key: bytes) -> None: ...

    @abc.abstractmethod
    def clear_range(self, begin: bytes, end: bytes) -> None: ...

    @abc.abstractmethod
    def add_read_conflict(self, key: bytes) -> None:
        """Manually add a key to the read conflict set."""

    @abc.abstractmethod
    def commit(self) -> None:
        """Raises FsError(KV_CONFLICT / KV_TXN_TOO_OLD) on failure."""

    @abc.abstractmethod
    def cancel(self) -> None: ...

    @property
    @abc.abstractmethod
    def committed_version(self) -> Optional[int]: ...


class IKVEngine(abc.ABC):
    @abc.abstractmethod
    def transaction(self) -> ITransaction: ...


@dataclass
class RetryConfig:
    """Backoff ladder for transaction retries (ref FDBRetryStrategy)."""

    max_retries: int = 10
    backoff_base_s: float = 0.001
    backoff_max_s: float = 0.1


def with_transaction(
    engine: IKVEngine,
    fn: Callable[[ITransaction], T],
    retry: Optional[RetryConfig] = None,
    *,
    read_only: bool = False,
) -> T:
    """Run fn inside a transaction, committing and retrying on conflicts.

    fn may be re-executed; it must be idempotent up to its KV effects (the
    same contract as the reference's WithTransaction::run retry loop).

    Traced ops get a ``meta.txn`` stage span covering the whole retry
    ladder — the "where did the meta op's time go" stage of the
    distributed trace (tpu3fs/analytics/spans.py).
    """
    from tpu3fs.analytics import spans as _spans

    _tctx = _spans.current_trace()
    if _tctx is not None:
        with _spans.span("kv.with_transaction", "txn"):
            return _with_transaction_untraced(engine, fn, retry,
                                              read_only=read_only)
    return _with_transaction_untraced(engine, fn, retry,
                                      read_only=read_only)


def _with_transaction_untraced(
    engine: IKVEngine,
    fn: Callable[[ITransaction], T],
    retry: Optional[RetryConfig] = None,
    *,
    read_only: bool = False,
) -> T:
    retry = retry or RetryConfig()
    attempt = 0
    while True:
        txn = engine.transaction()
        try:
            result = fn(txn)
            if read_only:
                txn.cancel()
            else:
                txn.commit()
            return result
        except FsError as e:
            txn.cancel()
            # KV_NOT_PRIMARY: kvd failover mid-transaction — restart on the
            # new leader. KV_MAYBE_COMMITTED mirrors FDB's
            # commit_unknown_result, which its default retry loop DOES
            # retry; the meta layer's Idempotent records / existence checks
            # carry the same at-least-once burden as in the reference.
            if e.code not in (Code.KV_CONFLICT, Code.KV_TXN_TOO_OLD,
                              Code.KV_RETRYABLE, Code.KV_NOT_PRIMARY,
                              Code.KV_MAYBE_COMMITTED):
                raise
            attempt += 1
            if attempt > retry.max_retries:
                raise
            delay = min(retry.backoff_max_s, retry.backoff_base_s * (2 ** attempt))
            time.sleep(delay * (0.5 + random.random() / 2))
