"""Network KV service: the shared transactional store for meta/mgmtd.

Plays the role FoundationDB plays in the reference (src/fdb/FDBTransaction.h,
HybridKvEngine selecting mem vs fdb): meta servers are stateless and mgmtd
elects its primary by CAS, which only works if every server sees ONE
transactional KV. This service exposes the MVCC engine (kv/mem.py) over RPC
with FDB's client model: the client takes a snapshot version, reads at that
version, buffers writes locally, and submits one atomic commit carrying its
read set — the server validates conflicts and applies (optimistic
concurrency, same retry loop as local transactions).

Durability: an optional write-ahead log records every applied commit; on
restart the service replays it into a fresh engine (the reference gets this
from FDB itself). The WAL is BOUNDED: when it outgrows
max(compact_min_bytes, 4x the last snapshot), it is rewritten as one
snapshot record (the full live dump at the current version) — replay is
then snapshot + tail, and sustained commit load cannot grow the log or the
restart time without bound. The snapshot record carries the commit version
so versionstamp monotonicity survives restarts.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from tpu3fs.kv.mem import MemKVEngine
from tpu3fs.rpc.net import RpcServer, ServiceDef
from tpu3fs.rpc.serde import deserialize, serialize
from tpu3fs.utils.result import Code, FsError, Status

KV_SERVICE_ID = 5

_SNAPSHOT_TTL_S = 60.0


# -- wire schemas ------------------------------------------------------------

@dataclass
class SnapshotReq:
    client_id: str = ""


@dataclass
class SnapshotRsp:
    version: int = 0


@dataclass
class GetReq:
    key: bytes = b""
    version: int = 0


@dataclass
class GetRsp:
    found: bool = False
    value: bytes = b""


@dataclass
class RangeReq:
    begin: bytes = b""
    end: bytes = b""
    version: int = 0
    limit: int = 0
    reverse: bool = False


@dataclass
class RangePair:
    key: bytes = b""
    value: bytes = b""


@dataclass
class RangeRsp:
    pairs: List[RangePair] = field(default_factory=list)


@dataclass
class WriteEntry:
    key: bytes = b""
    value: bytes = b""
    tombstone: bool = False


@dataclass
class RangeEntry:
    begin: bytes = b""
    end: bytes = b""


@dataclass
class StampEntry:
    prefix: bytes = b""
    suffix: bytes = b""
    value: bytes = b""


@dataclass
class CommitReq:
    read_version: int = 0
    read_keys: List[bytes] = field(default_factory=list)
    read_ranges: List[RangeEntry] = field(default_factory=list)
    writes: List[WriteEntry] = field(default_factory=list)
    clear_ranges: List[RangeEntry] = field(default_factory=list)
    versionstamped: List[StampEntry] = field(default_factory=list)


@dataclass
class CommitRsp:
    version: int = 0


@dataclass
class ReleaseReq:
    version: int = 0


@dataclass
class EmptyMsg:
    pass


# -- WAL record --------------------------------------------------------------

@dataclass
class WalRecord:
    version: int = 0
    writes: List[WriteEntry] = field(default_factory=list)
    clear_ranges: List[RangeEntry] = field(default_factory=list)
    # true on the snapshot record a compaction writes: `writes` is the FULL
    # live dump at `version`, and replay fast-forwards the engine version
    snapshot: bool = False


class KvService:
    """Server half: MVCC engine + remote-snapshot pinning + WAL."""

    def __init__(self, engine: Optional[MemKVEngine] = None, *,
                 wal_path: Optional[str] = None,
                 snapshot_ttl_s: float = _SNAPSHOT_TTL_S,
                 compact_min_bytes: int = 4 << 20,
                 fsync: bool = False):
        # NOTE: set_snapshot_ttl supports hot config updates
        self.engine = engine or MemKVEngine()
        self._ttl = snapshot_ttl_s
        self._lock = threading.Lock()
        self._pins: Dict[int, Tuple[int, float]] = {}  # token -> (ver, dl)
        self._next_token = 1
        self._wal_path = wal_path
        self._wal = None
        self._fsync = fsync
        self._compact_min_bytes = compact_min_bytes
        self._wal_bytes = 0
        self._snap_bytes = 0
        # serializes commit_external + WAL append so file order == version
        # order (RpcServer dispatches concurrently)
        self._commit_lock = threading.Lock()
        if wal_path:
            valid = self._replay_wal(wal_path)
            # truncate any torn tail record BEFORE reopening for append, or
            # post-restart commits land after the garbage and are lost on
            # the next replay
            if (valid is not None and os.path.exists(wal_path)
                    and valid < os.path.getsize(wal_path)):
                with open(wal_path, "r+b") as f:
                    f.truncate(valid)
            self._wal = open(wal_path, "ab")
            self._wal_bytes = os.path.getsize(wal_path)
            self._snap_bytes = self._wal_bytes
        # snapshots below the floor may reference pruned MVCC history:
        # reject them with KV_TXN_TOO_OLD instead of silently misreading
        self._floor = self.engine.version

    # -- WAL ----------------------------------------------------------------
    def _replay_wal(self, path: str):
        """Replay; returns the byte length of the valid prefix (for
        truncating a torn tail) or None if the file doesn't exist."""
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            raw = f.read()
        pos = 0
        while pos + 4 <= len(raw):
            n = int.from_bytes(raw[pos:pos + 4], "big")
            if pos + 4 + n > len(raw):
                break  # torn tail record (write was never acked)
            try:
                rec = deserialize(raw[pos + 4:pos + 4 + n], WalRecord)
            except Exception:
                break  # corrupt tail
            writes = {
                w.key: (None if w.tombstone else w.value) for w in rec.writes
            }
            clears = [(r.begin, r.end) for r in rec.clear_ranges]
            self.engine.commit_external(
                self.engine.version, [], [], writes, clears, [])
            if rec.snapshot:
                # versionstamped keys must stay monotonic across restarts
                self.engine.restore_version_floor(rec.version)
            pos += 4 + n
        return pos

    def _wal_append(self, version: int,
                    writes: Dict[bytes, Optional[bytes]],
                    clears: List[Tuple[bytes, bytes]]) -> None:
        if self._wal is None:
            return
        rec = WalRecord(
            version=version,
            writes=[WriteEntry(k, v if v is not None else b"", v is None)
                    for k, v in writes.items()],
            clear_ranges=[RangeEntry(b, e) for b, e in clears],
        )
        raw = serialize(rec)
        self._wal.write(len(raw).to_bytes(4, "big") + raw)
        self._wal.flush()
        if self._fsync:
            os.fsync(self._wal.fileno())
        self._wal_bytes += 4 + len(raw)

    def _maybe_compact(self) -> None:
        """Caller holds _commit_lock. Rewrite the WAL as ONE snapshot
        record when it outgrows max(compact_min_bytes, 4x last snapshot):
        replay becomes snapshot + tail, and sustained commits cannot grow
        the log without bound (the role RocksDB compaction / FDB's own
        storage plays in the reference)."""
        if self._wal is None:
            return
        if self._wal_bytes <= max(self._compact_min_bytes,
                                  4 * self._snap_bytes):
            return
        version = self.engine.version
        pairs = self.engine.dump_at(version)
        rec = WalRecord(
            version=version,
            writes=[WriteEntry(k, v, False) for k, v in pairs],
            snapshot=True,
        )
        raw = serialize(rec)
        tmp = self._wal_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(len(raw).to_bytes(4, "big") + raw)
            f.flush()
            os.fsync(f.fileno())
        self._wal.close()
        os.replace(tmp, self._wal_path)   # atomic swap: old WAL or new, never half
        self._wal = open(self._wal_path, "ab")
        self._wal_bytes = os.path.getsize(self._wal_path)
        self._snap_bytes = self._wal_bytes

    # -- snapshot pinning ----------------------------------------------------
    def _sweep_pins(self, now: float) -> None:
        dead = [t for t, (_, dl) in self._pins.items() if dl < now]
        for t in dead:
            del self._pins[t]
            self.engine.unpin_version(("kvd", t))
        # raise the floor whenever no pin holds an older version — versions
        # below it may lose MVCC history to pruning, so reads/commits at
        # them must fail with KV_TXN_TOO_OLD rather than silently misread
        live = [v for v, _ in self._pins.values()]
        self._floor = max(self._floor,
                          min(live) if live else self.engine.version)

    def _check_version(self, version: int) -> None:
        # _floor is only raised by _sweep_pins under _lock; read it under the
        # same lock so a concurrent sweep orders strictly before or after
        with self._lock:
            floor = self._floor
        if version < floor:
            raise FsError(Status(
                Code.KV_TXN_TOO_OLD,
                f"snapshot {version} expired (floor {floor})"))

    # -- ops ------------------------------------------------------------------
    def snapshot(self, req: SnapshotReq) -> SnapshotRsp:
        now = time.monotonic()
        with self._lock:
            self._sweep_pins(now)
            token = self._next_token
            self._next_token += 1
            version = self.engine.version
            self._pins[token] = (version, now + self._ttl)
            self.engine.pin_version(("kvd", token), version)
        return SnapshotRsp(version=version)

    def get(self, req: GetReq) -> GetRsp:
        self._check_version(req.version)
        val = self.engine.read_at(req.key, req.version)
        # re-check AFTER the read: if a concurrent sweep raised the floor
        # past our version, a commit may have pruned the MVCC history this
        # read resolved against — fail loudly rather than return a silent
        # misread (sweep raises the floor before any prune can run, so a
        # read that passes the post-check saw intact history)
        self._check_version(req.version)
        return GetRsp(found=val is not None, value=val or b"")

    def get_range(self, req: RangeReq) -> RangeRsp:
        self._check_version(req.version)
        pairs = self.engine.range_at(req.begin, req.end, req.version)
        self._check_version(req.version)  # see get(): post-read floor check
        if req.reverse:
            pairs = list(reversed(pairs))
        if req.limit:
            pairs = pairs[:req.limit]
        return RangeRsp(pairs=[RangePair(k, v) for k, v in pairs])

    def commit(self, req: CommitReq) -> CommitRsp:
        writes = {
            w.key: (None if w.tombstone else w.value) for w in req.writes
        }
        clears = [(r.begin, r.end) for r in req.clear_ranges]
        stamps = [(s.prefix, s.suffix, s.value) for s in req.versionstamped]
        with self._commit_lock:
            # floor check must happen INSIDE _commit_lock: MVCC history is
            # only pruned by commit_external (serialized on this lock), so a
            # sweep that expires this txn's pin either raised the floor
            # before this check (we reject) or the commit-log entries the
            # conflict check needs are still intact (we commit safely) — no
            # window where a stale txn commits against pruned history
            self._check_version(req.read_version)
            version = self.engine.commit_external(
                req.read_version,
                list(req.read_keys),
                [(r.begin, r.end) for r in req.read_ranges],
                writes,
                clears,
                stamps,
            )
            if writes or clears or stamps:
                # WAL carries the fully-resolved write set (stamped keys
                # included), appended in commit-version order under the lock
                if stamps:
                    import struct as _struct

                    for order, (prefix, suffix, value) in enumerate(stamps):
                        stamp = _struct.pack(">QH", version, order)
                        writes[prefix + stamp + suffix] = value
                self._wal_append(version, writes, clears)
                self._maybe_compact()
        return CommitRsp(version=version)

    def release(self, req: ReleaseReq) -> EmptyMsg:
        # pins are keyed by token server-side; version-based release is a
        # best-effort early unpin of the oldest matching pin
        with self._lock:
            for t, (ver, _) in list(self._pins.items()):
                if ver == req.version:
                    del self._pins[t]
                    self.engine.unpin_version(("kvd", t))
                    break
        return EmptyMsg()

    def set_snapshot_ttl(self, ttl_s: float) -> None:
        self._ttl = float(ttl_s)

    def close(self) -> None:
        if self._wal is not None:
            self._wal.close()
            self._wal = None


def bind_kv_service(server: RpcServer, svc: KvService) -> ServiceDef:
    s = ServiceDef(KV_SERVICE_ID, "Kv")
    s.method(1, "snapshot", SnapshotReq, SnapshotRsp, svc.snapshot)
    s.method(2, "get", GetReq, GetRsp, svc.get)
    s.method(3, "getRange", RangeReq, RangeRsp, svc.get_range)
    s.method(4, "commit", CommitReq, CommitRsp, svc.commit)
    s.method(5, "release", ReleaseReq, EmptyMsg, svc.release)
    server.add_service(s)
    return s
