from tpu3fs.kv.kv import IKVEngine, ITransaction, KeyPrefix, with_transaction  # noqa: F401
from tpu3fs.kv.mem import MemKVEngine  # noqa: F401
