"""RemoteKVEngine: IKVEngine client for the network KV service.

The FDB-client model (ref src/fdb/FDBTransaction.h semantics over our own
service instead of the FDB C library): a transaction takes a server snapshot
version, reads at that version over RPC, buffers writes/clears locally with
read-your-writes overlay, and submits ONE atomic commit RPC carrying the
read set — the server validates and applies. Conflicts surface as
FsError(KV_CONFLICT) so the standard with_transaction retry loop drives
retries identically to the in-memory engine; the meta/mgmtd suites run
unchanged on top.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from tpu3fs.kv.kv import IKVEngine, ITransaction, KVPair
from tpu3fs.kv.service import (
    KV_SERVICE_ID,
    CommitReq,
    CommitRsp,
    EmptyMsg,
    GetReq,
    GetRsp,
    RangeEntry,
    RangeReq,
    RangeRsp,
    ReleaseReq,
    SnapshotReq,
    SnapshotRsp,
    StampEntry,
    WriteEntry,
)
from tpu3fs.rpc.net import RpcClient
from tpu3fs.utils.result import Code, FsError, Status


def engine_from_flag(kv_flag: str):
    """'host:port' -> RemoteKVEngine; 'h1:p1,h2:p2,...' (or explicit
    'id=h:p,...') -> ReplicatedRemoteKVEngine over the kvd group; empty ->
    local MemKVEngine (dev)."""
    if not kv_flag:
        from tpu3fs.kv.mem import MemKVEngine

        return MemKVEngine()
    if "," in kv_flag or "=" in kv_flag:
        peers = {}
        for i, part in enumerate(kv_flag.split(",")):
            part = part.strip()
            if not part:
                continue
            if "=" in part:
                nid, addr = part.split("=", 1)
            else:
                nid, addr = str(i + 1), part
            host, port = addr.rsplit(":", 1)
            peers[int(nid)] = (host, int(port))
        return ReplicatedRemoteKVEngine(peers)
    host, port = kv_flag.rsplit(":", 1)
    return RemoteKVEngine((host, int(port)))


class RemoteKVEngine(IKVEngine):
    def __init__(self, addr: Tuple[str, int],
                 client: Optional[RpcClient] = None,
                 client_id: str = ""):
        self._addr = (addr[0], int(addr[1]))
        self._client = client or RpcClient()
        self._client_id = client_id

    def _call(self, method_id: int, req, rsp_type):
        return self._client.call(
            self._addr, KV_SERVICE_ID, method_id, req, rsp_type
        )

    def transaction(self) -> "RemoteTransaction":
        rsp = self._call(1, SnapshotReq(self._client_id), SnapshotRsp)
        return RemoteTransaction(self, rsp.version)

    def close(self) -> None:
        self._client.close()


class RemoteTransaction(ITransaction):
    """Local write buffer + RPC snapshot reads + single commit RPC."""

    def __init__(self, engine: RemoteKVEngine, read_version: int):
        self._engine = engine
        self._read_version = read_version
        self._writes: Dict[bytes, Optional[bytes]] = {}
        self._clear_ranges: List[Tuple[bytes, bytes]] = []
        self._read_keys: List[bytes] = []
        self._read_ranges: List[Tuple[bytes, bytes]] = []
        self._versionstamped: List[Tuple[bytes, bytes, bytes]] = []
        self._committed_version: Optional[int] = None
        self._done = False

    # -- reads (read-your-writes overlay, same rules as MemTransaction) -----
    def _local_lookup(self, key: bytes):
        if key in self._writes:
            return True, self._writes[key]
        for begin, end in self._clear_ranges:
            if begin <= key < end:
                return True, None
        return False, None

    def _remote_get(self, key: bytes) -> Optional[bytes]:
        rsp = self._engine._call(
            2, GetReq(bytes(key), self._read_version), GetRsp
        )
        return rsp.value if rsp.found else None

    def get(self, key: bytes) -> Optional[bytes]:
        found, val = self._local_lookup(key)
        if found:
            return val
        self._read_keys.append(bytes(key))
        return self._remote_get(key)

    def snapshot_get(self, key: bytes) -> Optional[bytes]:
        found, val = self._local_lookup(key)
        if found:
            return val
        return self._remote_get(key)

    def get_range(
        self,
        begin: bytes,
        end: bytes,
        *,
        limit: int = 0,
        reverse: bool = False,
        snapshot: bool = False,
    ) -> List[KVPair]:
        begin, end = bytes(begin), bytes(end)
        if not snapshot:
            self._read_ranges.append((begin, end))
        # push limit/reverse to the server only when no buffered local edits
        # could change which keys survive the overlay; otherwise fetch the
        # full range and trim after merging
        clean = not self._writes and not self._clear_ranges
        rsp = self._engine._call(
            3,
            RangeReq(begin, end, self._read_version,
                     limit if clean else 0, reverse if clean else False),
            RangeRsp,
        )
        merged: Dict[bytes, Optional[bytes]] = {
            p.key: p.value for p in rsp.pairs
        }
        for rb, re_ in self._clear_ranges:
            for key in list(merged):
                if rb <= key < re_:
                    merged[key] = None
        for key, val in self._writes.items():
            if begin <= key < end:
                merged[key] = val
        items = sorted(
            (k for k, v in merged.items() if v is not None), reverse=reverse
        )
        if limit:
            items = items[:limit]
        return [KVPair(k, merged[k]) for k in items]

    def add_read_conflict(self, key: bytes) -> None:
        self._read_keys.append(bytes(key))

    # -- writes --------------------------------------------------------------
    def set(self, key: bytes, value: bytes) -> None:
        assert not self._done
        self._writes[bytes(key)] = bytes(value)

    def set_versionstamped_key(self, prefix: bytes, suffix: bytes,
                               value: bytes) -> None:
        assert not self._done
        self._versionstamped.append(
            (bytes(prefix), bytes(suffix), bytes(value)))

    def clear(self, key: bytes) -> None:
        assert not self._done
        self._writes[bytes(key)] = None

    def clear_range(self, begin: bytes, end: bytes) -> None:
        assert not self._done
        begin, end = bytes(begin), bytes(end)
        for key in [k for k in self._writes if begin <= k < end]:
            del self._writes[key]
        self._clear_ranges.append((begin, end))

    # -- commit ---------------------------------------------------------------
    def commit(self) -> None:
        assert not self._done
        self._done = True
        req = CommitReq(
            read_version=self._read_version,
            read_keys=list(self._read_keys),
            read_ranges=[RangeEntry(b, e) for b, e in self._read_ranges],
            writes=[
                WriteEntry(k, v if v is not None else b"", v is None)
                for k, v in self._writes.items()
            ],
            clear_ranges=[RangeEntry(b, e) for b, e in self._clear_ranges],
            versionstamped=[
                StampEntry(p, s, v) for p, s, v in self._versionstamped
            ],
        )
        try:
            rsp = self._engine._call(4, req, CommitRsp)
            self._committed_version = rsp.version
        finally:
            self._release()  # on conflict too: free the snapshot pin now

    def cancel(self) -> None:
        if not self._done:
            self._done = True
            self._release()

    def _release(self) -> None:
        try:
            self._engine._call(5, ReleaseReq(self._read_version), EmptyMsg)
        except FsError:
            pass  # pin expires by TTL server-side

    @property
    def committed_version(self) -> Optional[int]:
        return self._committed_version


class ReplicatedRemoteKVEngine(RemoteKVEngine):
    """Client for a replicated kvd group (kv/replica.py): tracks the
    leader, follows KV_NOT_PRIMARY hints, and retries across peers through
    elections.

    Failing over MID-transaction is safe by construction: any version a
    client ever observed is quorum-durable, every new leader's engine is
    rebuilt to at least that version, and its read floor starts AT its
    rebuilt version — so a re-routed read either resolves identical state
    (same log prefix => same bytes) or fails loudly with KV_TXN_TOO_OLD
    and the with_transaction loop restarts the transaction."""

    # generous by design: a leader election under heavy host load can take
    # well past 15s (observed in CI-like runs with parallel suites), and
    # exhausting the window surfaces RPC_CONNECT_FAILED to callers whose
    # transaction would have succeeded one election later. FDB clients
    # effectively retry until the transaction timeout; 45s approximates
    # that while still failing a genuinely dead cluster promptly.
    RETRY_WINDOW_S = 45.0

    def __init__(self, peers, client: Optional[RpcClient] = None,
                 client_id: str = ""):
        peers = {int(i): (h, int(p)) for i, (h, p) in dict(peers).items()}
        super().__init__(next(iter(peers.values())), client, client_id)
        self._peers = peers
        self._order = sorted(peers)
        self._leader: Optional[int] = None

    _COMMIT_METHOD = 4

    def _call(self, method_id: int, req, rsp_type):
        import time as _time

        deadline = _time.monotonic() + self.RETRY_WINDOW_S
        last: Optional[FsError] = None
        cursor = 0
        while _time.monotonic() < deadline:
            nid = (self._leader if self._leader in self._peers
                   else self._order[cursor % len(self._order)])
            try:
                return self._client.call(
                    self._peers[nid], KV_SERVICE_ID, method_id, req, rsp_type)
            except FsError as e:
                last = e
                ambiguous_commit = (
                    method_id == self._COMMIT_METHOD
                    and e.code in (Code.RPC_TIMEOUT, Code.RPC_PEER_CLOSED,
                                   Code.TIMEOUT))
                if ambiguous_commit:
                    # the commit REACHED the server and its fate is
                    # unknown (it may yet replicate): blind transport
                    # retry could apply the write set twice. Surface
                    # FDB's commit_unknown_result; with_transaction
                    # restarts the whole transaction.
                    raise FsError(Status(
                        Code.KV_MAYBE_COMMITTED,
                        f"commit outcome unknown: {e.status.message}"))
                if e.code == Code.KV_NOT_PRIMARY:
                    # pre-apply rejection (or a barrier-pending leader):
                    # always safe to re-send
                    hint = _leader_hint(e.status.message)
                    if hint in self._peers and hint != nid:
                        self._leader = hint
                        continue
                    self._leader = None
                    cursor += 1
                    _time.sleep(0.1)  # election likely in progress
                elif e.code in (Code.RPC_CONNECT_FAILED, Code.RPC_SEND_FAILED,
                                Code.RPC_TIMEOUT, Code.RPC_PEER_CLOSED,
                                Code.TIMEOUT):
                    # request provably not processed (connect/send), or a
                    # non-commit op (reads are idempotent): safe to retry
                    self._leader = None
                    cursor += 1
                    _time.sleep(0.05)
                else:
                    raise  # conflicts/too-old etc. belong to the caller
        raise last or FsError(Status(Code.RPC_CONNECT_FAILED,
                                     "no kvd peer reachable"))


def _leader_hint(message: str) -> Optional[int]:
    # "not primary; leader=3" -> 3 (0 = unknown)
    marker = "leader="
    pos = message.find(marker)
    if pos < 0:
        return None
    digits = ""
    for ch in message[pos + len(marker):]:
        if ch.isdigit():
            digits += ch
        else:
            break
    nid = int(digits) if digits else 0
    return nid or None
