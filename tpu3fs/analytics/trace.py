"""Structured trace log: stream typed event records to columnar files.

Re-expresses src/analytics — SerdeObjectWriter.h (any serde struct stream →
Parquet), SerdeObjectReader.h:2-53 (read back), StructuredTraceLog.h:18-40
(rotating trace sink plugged into the storage write path at
src/storage/service/StorageOperator.h:36). The reference rides Arrow/Parquet;
this build writes Parquet when pyarrow is importable and otherwise a
self-contained columnar NPZ container (schema JSON + one numpy array per
column) that needs nothing beyond numpy to read back. Dataclass events are
flattened (nested fields joined with '.') so every column is a flat scalar
array — the same property the serde→Arrow bridge guarantees.
"""

from __future__ import annotations

import dataclasses
import enum
import functools
import json
import os
import threading
import time
import typing
from typing import Any, Dict, List, Optional, Sequence, Tuple, Type

import numpy as np

try:  # pragma: no cover - exercised only when pyarrow is installed
    import pyarrow as _pa
    import pyarrow.parquet as _pq
except ImportError:
    _pa = None
    _pq = None


# -- row flattening ----------------------------------------------------------

def _flatten(obj: Any, prefix: str = "") -> Dict[str, Any]:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out: Dict[str, Any] = {}
        for f in dataclasses.fields(obj):
            out.update(_flatten(getattr(obj, f.name),
                                f"{prefix}{f.name}."))
        return out
    if isinstance(obj, dict):
        out = {}
        for k, v in obj.items():
            out.update(_flatten(v, f"{prefix}{k}."))
        return out
    if isinstance(obj, enum.Enum):
        obj = obj.value
    return {prefix[:-1]: obj}


def _rows_of(events: Sequence[Any]) -> List[Dict[str, Any]]:
    return [_flatten(e) if not isinstance(e, dict) else dict(e)
            for e in events]


# -- columnar write/read -----------------------------------------------------

def _ordered_keys(rows: List[Dict[str, Any]]) -> List[str]:
    """First-seen column order, union over all rows."""
    keys: List[str] = []
    for row in rows:
        for k in row:
            if k not in keys:
                keys.append(k)
    return keys


def _columns(rows: List[Dict[str, Any]]) -> "Tuple[Dict[str, np.ndarray], List[str]]":
    keys = _ordered_keys(rows)
    bytes_cols: List[str] = []
    cols: Dict[str, np.ndarray] = {}
    for k in keys:
        vals = [row.get(k) for row in rows]
        sample = next((v for v in vals if v is not None), 0)
        if isinstance(sample, bool):
            cols[k] = np.array([bool(v) for v in vals], dtype=np.bool_)
        elif isinstance(sample, int):
            cols[k] = np.array([int(v or 0) for v in vals], dtype=np.int64)
        elif isinstance(sample, float):
            cols[k] = np.array(
                [float(v) if v is not None else np.nan for v in vals],
                dtype=np.float64,
            )
        elif isinstance(sample, bytes):
            # hex-encoded; recorded in the schema so reads decode to bytes
            cols[k] = np.array([v.hex() if v else "" for v in vals])
            bytes_cols.append(k)
        else:
            cols[k] = np.array(["" if v is None else str(v) for v in vals])
    return cols, bytes_cols


def write_records(path_base: str, rows: Sequence[Dict[str, Any]]) -> str:
    """Write rows columnar; returns the actual path (.parquet or .npz)."""
    rows = list(rows)
    if _pq is not None:
        # normalize: from_pylist takes its schema from the first row, so a
        # key appearing later would silently drop its whole column
        keys = _ordered_keys(rows)
        norm = [{k: row.get(k) for k in keys} for row in rows]
        path = path_base + ".parquet"
        _pq.write_table(_pa.Table.from_pylist(norm), path)
        return path
    path = path_base + ".npz"
    cols, bytes_cols = _columns(rows)
    meta = json.dumps({"n": len(rows), "columns": list(cols),
                       "bytes_columns": bytes_cols})
    np.savez_compressed(path, __schema__=np.array(meta), **cols)
    # np.savez appends .npz only when missing; path already carries it
    return path


def read_records(path: str) -> List[Dict[str, Any]]:
    """Read rows back (either backend) as list-of-dicts."""
    if path.endswith(".parquet"):  # pragma: no cover - needs pyarrow
        if _pq is None:
            raise RuntimeError("pyarrow is required to read parquet traces")
        return _pq.read_table(path).to_pylist()
    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(str(z["__schema__"]))
        cols = {k: z[k] for k in meta["columns"]}
    bytes_cols = set(meta.get("bytes_columns", []))
    out = []
    for i in range(meta["n"]):
        row = {}
        for k, arr in cols.items():
            v = arr[i]
            if k in bytes_cols:
                row[k] = bytes.fromhex(str(v))
            else:
                row[k] = str(v) if arr.dtype.kind == "U" else v.item()
        out.append(row)
    return out


# -- serde object stream -----------------------------------------------------

class SerdeObjectWriter:
    """Buffered writer of one dataclass type to a columnar file
    (ref analytics::SerdeObjectWriter — one parquet row group per flush)."""

    def __init__(self, path_base: str, *, flush_rows: int = 4096):
        self._path_base = path_base
        self._flush_rows = flush_rows
        self._rows: List[Dict[str, Any]] = []
        self._part = 0
        self._lock = threading.Lock()
        self.paths: List[str] = []

    def write(self, event: Any) -> None:
        with self._lock:
            self._rows.append(_flatten(event))
            if len(self._rows) >= self._flush_rows:
                self._flush_locked()

    def write_row(self, row: Dict[str, Any]) -> None:
        """Append an already-flat row (no reflection walk — the span
        sink's hot path: SpanEvent is flat, its __dict__ IS the row)."""
        with self._lock:
            self._rows.append(row)
            if len(self._rows) >= self._flush_rows:
                self._flush_locked()

    def _flush_locked(self) -> None:
        if not self._rows:
            return
        path = write_records(f"{self._path_base}.{self._part:05d}",
                             self._rows)
        self.paths.append(path)
        self._part += 1
        self._rows = []

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def close(self) -> None:
        self.flush()


@functools.lru_cache(maxsize=None)
def _resolved_hints(cls: Type) -> Dict[str, Any]:
    """Field annotations may be strings under `from __future__ import
    annotations` — resolve once per class, not per row."""
    try:
        return typing.get_type_hints(cls)
    except Exception:
        return {}


class SerdeObjectReader:
    """Read a columnar stream back into dataclass instances
    (ref analytics::SerdeObjectReader). Nested dataclasses are rebuilt from
    the dotted column names."""

    def __init__(self, cls: Type):
        self._cls = cls

    def _build(self, cls: Type, row: Dict[str, Any], prefix: str) -> Any:
        hints = _resolved_hints(cls)
        kwargs = {}
        for f in dataclasses.fields(cls):
            key = f"{prefix}{f.name}"
            ftype = hints.get(f.name, f.type)
            if dataclasses.is_dataclass(ftype) and isinstance(ftype, type):
                kwargs[f.name] = self._build(ftype, row, key + ".")
            elif key in row:
                v = row[key]
                if isinstance(ftype, type) and issubclass(ftype, enum.Enum):
                    v = ftype(v)
                kwargs[f.name] = v
        return cls(**kwargs)

    def read(self, paths: Sequence[str]) -> List[Any]:
        out = []
        for path in paths:
            for row in read_records(path):
                out.append(self._build(self._cls, row, ""))
        return out


class StructuredTraceLog:
    """Rotating trace sink for hot paths (ref StructuredTraceLog.h:18-40):
    append() is lock-cheap; rows land in rotated columnar parts under dir."""

    def __init__(self, name: str, directory: str, *,
                 flush_rows: int = 4096, enabled: bool = True):
        self.name = name
        self.enabled = enabled
        os.makedirs(directory, exist_ok=True)
        stamp = time.strftime("%Y%m%d-%H%M%S")
        self._writer = SerdeObjectWriter(
            os.path.join(directory, f"{name}-{stamp}"),
            flush_rows=flush_rows,
        )

    def append(self, event: Any) -> None:
        if self.enabled:
            self._writer.write(event)

    def append_row(self, row: Dict[str, Any]) -> None:
        if self.enabled:
            self._writer.write_row(row)

    def flush(self) -> None:
        self._writer.flush()

    @property
    def paths(self) -> List[str]:
        return self._writer.paths
