"""Trace assembler: join per-process span files into per-trace trees.

Each traced process streams ``spans.SpanEvent`` rows into its own
columnar file set (``spans-<stamp>.*`` parts under that process's trace
dir). This module loads any number of those file sets, groups rows by
trace id, rebuilds the span tree from parent ids (which cross process
boundaries: a server op parents to the client's rpc span carried on the
envelope), and derives the two operator views:

- ``format_trace``: one trace as an indented tree with per-span wall
  times and a STAGE COVERAGE line — the fraction of the root
  (client-observed) latency that attributed stage spans account for.
  Coverage sums additive stages only: container stages (``collect``,
  ``forward``) hold their callee's whole pipeline and would double
  count.
- ``top_traces`` / ``stage_percentiles``: slowest ops and per-stage
  p50/p90/p99 across every loaded trace — the trace-top view.
"""

from __future__ import annotations

import glob
import os
from typing import Dict, Iterable, List, Optional, Sequence

from tpu3fs.analytics.trace import read_records

# stages whose duration CONTAINS downstream work (excluded from the
# additive coverage sum; see module doc)
CONTAINER_STAGES = frozenset({"collect", "forward"})


def span_files(paths: Iterable[str]) -> List[str]:
    """Expand files/dirs into the span part files they hold (a dir is
    scanned recursively — one trace root can hold every node's subdir)."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for pat in ("spans-*.npz", "spans-*.parquet"):
                out.extend(glob.glob(os.path.join(p, "**", pat),
                                     recursive=True))
        elif os.path.exists(p):
            out.append(p)
    return sorted(set(out))


def load_spans(paths: Iterable[str]) -> List[dict]:
    rows: List[dict] = []
    for path in span_files(paths):
        rows.extend(read_records(path))
    return rows


class TraceTree:
    """One assembled trace: spans indexed by id, children by parent."""

    def __init__(self, trace_id: str, rows: List[dict]):
        self.trace_id = trace_id
        self.rows = rows
        self.by_id: Dict[str, dict] = {r["span_id"]: r for r in rows}
        self.children: Dict[str, List[dict]] = {}
        self.roots: List[dict] = []
        for r in rows:
            parent = r.get("parent_id") or ""
            if parent and parent in self.by_id:
                self.children.setdefault(parent, []).append(r)
            else:
                self.roots.append(r)
        for kids in self.children.values():
            kids.sort(key=lambda r: (r.get("ts", 0.0),
                                     -r.get("dur_us", 0.0)))
        self.roots.sort(key=lambda r: -r.get("dur_us", 0.0))

    @property
    def root(self) -> Optional[dict]:
        return self.roots[0] if self.roots else None

    def stage_rows(self) -> List[dict]:
        return [r for r in self.rows if r.get("stage")]

    def coverage(self) -> float:
        """Fraction of the root (client-observed) wall during which at
        least one ATTRIBUTED stage was active: the interval UNION of
        additive stage spans clipped to the root window, over the root
        duration. Union, not sum — pipelined fan-outs run stages
        concurrently, and a plain sum would exceed 100% without meaning
        the breakdown explains the latency. Cross-process span clocks
        are wall time on (assumed loosely synced) hosts; sub-ms skew
        only blurs the interval edges."""
        root = self.root
        if root is None or not root.get("dur_us"):
            return 0.0
        r0 = root.get("ts", 0.0)
        r1 = r0 + root["dur_us"] / 1e6
        ivals = []
        for r in self.stage_rows():
            if r["stage"] in CONTAINER_STAGES:
                continue
            a = max(r0, r.get("ts", 0.0))
            b = min(r1, r.get("ts", 0.0) + r.get("dur_us", 0.0) / 1e6)
            if b > a:
                ivals.append((a, b))
        ivals.sort()
        covered = 0.0
        cur_a = cur_b = None
        for a, b in ivals:
            if cur_b is None or a > cur_b:
                if cur_b is not None:
                    covered += cur_b - cur_a
                cur_a, cur_b = a, b
            else:
                cur_b = max(cur_b, b)
        if cur_b is not None:
            covered += cur_b - cur_a
        return covered / (r1 - r0)

    def services(self) -> List[str]:
        return sorted({f"{r.get('service', '')}:{r.get('node', 0)}"
                       for r in self.rows})

    def tenants(self) -> List[str]:
        """Tenant tags this trace's op spans carry (tpu3fs/tenant):
        empty for pre-tenancy span files."""
        return sorted({r.get("tenant", "") for r in self.rows
                       if r.get("tenant")})


def assemble_traces(rows: Sequence[dict]) -> Dict[str, TraceTree]:
    groups: Dict[str, List[dict]] = {}
    for r in rows:
        tid = r.get("trace_id")
        if tid:
            groups.setdefault(tid, []).append(r)
    return {tid: TraceTree(tid, trows) for tid, trows in groups.items()}


# -- flight-recorder dumps (monitor/flight.py black boxes) --------------------


def flight_files(paths: Iterable[str]) -> List[str]:
    """Expand files/dirs into flight dump files (recursive)."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(glob.glob(os.path.join(p, "**", "flight-*.jsonl"),
                                 recursive=True))
        elif os.path.exists(p):
            out.append(p)
    return sorted(set(out))


def load_flight(paths: Iterable[str]) -> List[dict]:
    """Load N processes' flight dumps into one ts-sorted timeline. Each
    row keeps its dump's identity (``_service``/``_node``/``_dump``
    from the file's leading meta row), so a merged view still attributes
    every event to its black box."""
    import json

    rows: List[dict] = []
    for path in flight_files(paths):
        meta = {"service": "?", "node": 0}
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if row.get("kind") == "meta":
                    meta = row
                row.setdefault("_service", meta.get("service", "?"))
                row.setdefault("_node", meta.get("node", 0))
                row["_dump"] = os.path.basename(path)
                rows.append(row)
    rows.sort(key=lambda r: r.get("ts", 0.0))
    return rows


def format_flight(rows: Sequence[dict], *, spans: int = 3,
                  events: int = 40) -> str:
    """Merged black-box view: the dump inventory, the event timeline
    (alerts, config pushes, dump reasons), and the slowest cross-process
    span trees rebuilt from the dumps' span rows through the PR 8 trace
    machinery (trace ids join across processes)."""
    if not rows:
        return "no flight dumps found"
    lines: List[str] = []
    metas = [r for r in rows if r.get("kind") == "meta"]
    lines.append(f"flight view: {len(metas)} dump(s), {len(rows)} rows")
    for m in metas:
        lines.append(
            f"  {m.get('_dump')}: {m.get('service')}:{m.get('node')} "
            f"pid {m.get('pid')} reason={m.get('reason')!r} "
            f"events={m.get('events')}")
    timeline = [r for r in rows
                if r.get("kind") in ("alert", "config")]
    if timeline:
        lines.append("timeline (alerts + config pushes):")
        for r in timeline[-events:]:
            who = f"{r.get('_service')}:{r.get('_node')}"
            if r.get("kind") == "alert":
                lines.append(
                    f"  {r.get('ts', 0.0):.3f} [{who}] ALERT "
                    f"{r.get('rule')} -> {r.get('transition')} "
                    f"({r.get('message', '')})")
            else:
                ok = "applied" if r.get("ok") else "REJECTED"
                lines.append(
                    f"  {r.get('ts', 0.0):.3f} [{who}] CONFIG {ok} "
                    f"(source={r.get('source')}"
                    + (f", v{r['version']}" if "version" in r else "")
                    + ")")
    span_rows = [r for r in rows if r.get("kind") == "span"]
    if span_rows:
        trees = assemble_traces(span_rows)
        ranked = top_traces(trees, spans)
        lines.append(f"slow-op traces ({len(trees)} in the dumps, "
                     f"slowest {len(ranked)}):")
        for tree in ranked:
            lines.append(format_trace(tree))
    return "\n".join(lines)


def _fmt_row(r: dict) -> str:
    name = r.get("op", "?")
    if r.get("stage"):
        name = f"{name}/{r['stage']}"
    where = f"{r.get('service', '?')}:{r.get('node', 0)}"
    extra = ""
    if r.get("nbytes"):
        extra += f" {r['nbytes']}B"
    if r.get("code"):
        extra += f" code={r['code']}"
    if r.get("slow"):
        extra += " SLOW"
    return f"{name:<34s} {r.get('dur_us', 0.0) / 1e3:9.3f} ms" \
           f"  [{where}]{extra}"


def format_trace(tree: TraceTree) -> str:
    """Indented tree + coverage summary for one trace."""
    lines = [f"trace {tree.trace_id}  "
             f"({len(tree.rows)} spans, {len(tree.services())} processes: "
             f"{', '.join(tree.services())})"]

    def walk(r: dict, depth: int) -> None:
        lines.append("  " * depth + _fmt_row(r))
        for kid in tree.children.get(r["span_id"], []):
            walk(kid, depth + 1)

    for root in tree.roots:
        walk(root, 1)
    root = tree.root
    if root is not None:
        stages = {r["stage"] for r in tree.stage_rows()}
        lines.append(
            f"  stages: {len(stages)} distinct "
            f"({', '.join(sorted(stages))})")
        lines.append(
            f"  stage coverage: {tree.coverage() * 100.0:.1f}% of "
            f"{root.get('dur_us', 0.0) / 1e3:.3f} ms client-observed")
    return "\n".join(lines)


def _pct(sorted_vals: List[float], p: float) -> float:
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(p * len(sorted_vals)))]


def stage_percentiles(rows: Sequence[dict]) -> Dict[str, dict]:
    """stage -> {count, p50, p90, p99, total_ms} over every stage span."""
    groups: Dict[str, List[float]] = {}
    for r in rows:
        if r.get("stage"):
            groups.setdefault(r["stage"], []).append(r.get("dur_us", 0.0))
    out: Dict[str, dict] = {}
    for stage, durs in groups.items():
        durs.sort()
        out[stage] = {
            "count": len(durs),
            "p50_us": _pct(durs, 0.5),
            "p90_us": _pct(durs, 0.9),
            "p99_us": _pct(durs, 0.99),
            "total_ms": sum(durs) / 1e3,
        }
    return out


def top_traces(trees: Dict[str, TraceTree], n: int = 10) -> List[TraceTree]:
    """Slowest traces by root duration (rootless fragments sort last)."""
    def key(t: TraceTree) -> float:
        root = t.root
        return -(root.get("dur_us", 0.0) if root else 0.0)

    return sorted(trees.values(), key=key)[:max(1, n)]


def tenant_percentiles(rows: Sequence[dict]) -> Dict[str, dict]:
    """tenant -> {count, p50, p90, p99, total_ms, bytes} over every
    tenant-tagged OP span: the "who is hurting whom" rollup of trace-top
    (tpu3fs/tenant). Untagged (pre-tenancy / internal) spans group under
    '-'. Only op spans count — stage spans would double-bill an op's
    wall to its owner."""
    groups: Dict[str, List[float]] = {}
    nbytes: Dict[str, int] = {}
    for r in rows:
        if r.get("stage"):
            continue
        tenant = r.get("tenant") or "-"
        groups.setdefault(tenant, []).append(r.get("dur_us", 0.0))
        nbytes[tenant] = nbytes.get(tenant, 0) + int(r.get("nbytes", 0))
    out: Dict[str, dict] = {}
    for tenant, durs in groups.items():
        durs.sort()
        out[tenant] = {
            "count": len(durs),
            "p50_us": _pct(durs, 0.5),
            "p90_us": _pct(durs, 0.9),
            "p99_us": _pct(durs, 0.99),
            "total_ms": sum(durs) / 1e3,
            "bytes": nbytes.get(tenant, 0),
        }
    return out


def format_top(trees: Dict[str, TraceTree], rows: Sequence[dict],
               n: int = 10, by_tenant: bool = False) -> str:
    lines = [f"{len(trees)} traces, {len(rows)} spans; slowest {n}:"]
    for t in top_traces(trees, n):
        root = t.root
        if root is None:
            continue
        slow = " SLOW" if any(r.get("slow") for r in t.rows) else ""
        tenants = t.tenants()
        who = f"  [{','.join(tenants)}]" if tenants else ""
        lines.append(
            f"  {t.trace_id}  {root.get('op', '?'):<24s} "
            f"{root.get('dur_us', 0.0) / 1e3:9.3f} ms  "
            f"cov {t.coverage() * 100.0:5.1f}%  "
            f"{len(t.services())} procs{slow}{who}")
    if by_tenant:
        tp = tenant_percentiles(rows)
        if tp:
            lines.append(f"  {'tenant':<18s} {'ops':>6s} {'p50ms':>9s} "
                         f"{'p90ms':>9s} {'p99ms':>9s} {'MiB':>9s}")
            for tenant in sorted(tp):
                s = tp[tenant]
                lines.append(
                    f"  {tenant:<18s} {s['count']:>6d} "
                    f"{s['p50_us'] / 1e3:>9.3f} "
                    f"{s['p90_us'] / 1e3:>9.3f} "
                    f"{s['p99_us'] / 1e3:>9.3f} "
                    f"{s['bytes'] / (1 << 20):>9.2f}")
    pcts = stage_percentiles(rows)
    if pcts:
        lines.append(f"  {'stage':<18s} {'count':>6s} {'p50ms':>9s} "
                     f"{'p90ms':>9s} {'p99ms':>9s} {'total_ms':>9s}")
        for stage in sorted(pcts):
            s = pcts[stage]
            lines.append(
                f"  {stage:<18s} {s['count']:>6d} "
                f"{s['p50_us'] / 1e3:>9.3f} {s['p90_us'] / 1e3:>9.3f} "
                f"{s['p99_us'] / 1e3:>9.3f} {s['total_ms']:>9.3f}")
    return "\n".join(lines)
