from tpu3fs.analytics.trace import (  # noqa: F401
    SerdeObjectReader,
    SerdeObjectWriter,
    StructuredTraceLog,
    read_records,
    write_records,
)
