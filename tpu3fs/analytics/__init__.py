from tpu3fs.analytics.trace import (  # noqa: F401
    SerdeObjectReader,
    SerdeObjectWriter,
    StructuredTraceLog,
    read_records,
    write_records,
)
from tpu3fs.analytics.spans import (  # noqa: F401
    SpanEvent,
    TraceConfig,
    TraceContext,
    current_trace,
    root_span,
    tracer,
)
