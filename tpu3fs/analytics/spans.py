"""Distributed request tracing: span-stamped RPCs + stage-level timings.

Re-expresses the reference's three-way instrumentation (monitor latency
families on every op, a StructuredTraceLog plugged into the storage write
path, per-request identity threaded through the stack) as ONE substrate:
a ``TraceContext`` (trace id, current span id, sampled + slow bits) rides
the RPC envelope's ``message`` field on requests — a field every decoder,
old or new, python or native, already parses and ignores on requests, so
the encoding is version-tolerant in both directions — and propagates
in-process through a ``contextvars.ContextVar`` (the same machinery that
carries the QoS traffic class through WorkerPool fan-outs, chain-forward
helper threads and the fabric's direct dispatch).

Each layer emits typed ``SpanEvent`` rows — op spans (an RPC dispatch, a
client batch op) and stage spans (admission wait, update-queue wait,
engine stage, chain forward, commit, meta txn, client issue/collect) —
into the context's process-local accumulator. At op end ONE decision
flushes or drops the whole accumulation:

- HEAD SAMPLING: the root creator samples deterministically from the
  trace id (``sampled_of``), downstream hops honor the bit — a trace is
  captured everywhere or nowhere;
- SLOW-OP CAPTURE: an op whose wall time exceeds ``slow_op_ms`` flushes
  UNCONDITIONALLY, sampling rate 0 included — the ops an operator most
  needs are never the ones sampling dropped;
- FORCED capture: the wire slow bit (set via ``start_trace(force=True)``)
  makes every hop flush, for targeted debugging.

Flushed spans stream through ``analytics.trace.StructuredTraceLog`` —
the same columnar sink the storage event trace uses — one file set per
process; ``analytics.assemble`` joins the files of N processes back into
per-trace trees. Overhead discipline: with no tracer configured the only
cost on any hot path is one ContextVar read returning None.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import threading
import time
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from tpu3fs.utils.config import Config, ConfigItem

# -- the wire + file schema ---------------------------------------------------

WIRE_VERSION = "t1"

# wire flag bits (TraceContext.flags on the envelope)
FLAG_SAMPLED = 1
FLAG_SLOW = 2      # forced capture: every hop flushes


@dataclass
class SpanEvent:
    """One span row (columnar via analytics.trace; schema in
    docs/observability.md). Op spans have stage == ""; stage spans carry
    the stage name and parent to their op span."""

    trace_id: str = ""
    span_id: str = ""
    parent_id: str = ""
    service: str = ""      # emitting process role (storage/meta/client/...)
    node: int = 0          # emitting node id (0 = client-side)
    op: str = ""           # operation name (client.batch_write, rpc.server...)
    stage: str = ""        # "" for op spans; stage name for stage spans
    ts: float = 0.0        # wall-clock start (time.time; cross-process join)
    dur_us: float = 0.0
    code: int = 0          # status code (0 = OK)
    nbytes: int = 0
    tclass: str = ""       # QoS traffic class, when tagged
    tenant: str = ""       # owning tenant (op spans; tpu3fs/tenant)
    sampled: bool = False
    slow: bool = False     # flushed by the slow-op/forced path


class TraceContext:
    """Per-request trace identity + the process-local span accumulator.

    ``span_id`` is the CURRENT span: events emitted under this context
    parent to it. ``child()`` derives a nested context (new span id, same
    trace, same accumulator) for a sub-operation whose own events should
    parent to the sub-op span — the RPC client span does this so server
    spans nest under the wire hop.
    """

    __slots__ = ("trace_id", "span_id", "parent_id", "sampled", "slow",
                 "events")

    def __init__(self, trace_id: str, span_id: str, parent_id: str = "",
                 sampled: bool = False, slow: bool = False,
                 events: Optional[list] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.sampled = sampled
        self.slow = slow
        # list.append is GIL-atomic: overlap-forward helper threads and
        # worker threads may append concurrently with the op thread
        self.events: List[SpanEvent] = events if events is not None else []

    def child(self) -> "TraceContext":
        """Nested context for a sub-op in THIS process (shared
        accumulator: one flush decision covers the whole op)."""
        return TraceContext(self.trace_id, _new_id(), self.span_id,
                            self.sampled, self.slow, self.events)

    # -- envelope carriage -------------------------------------------------
    def to_wire(self) -> str:
        flags = (FLAG_SAMPLED if self.sampled else 0) \
            | (FLAG_SLOW if self.slow else 0)
        return f"{WIRE_VERSION}.{self.trace_id}.{self.span_id}.{flags:x}"


def decode_wire(message: str) -> Optional[TraceContext]:
    """Parse a TraceContext off a request envelope; None for absent,
    malformed or future-versioned encodings (old servers that never call
    this simply ignore the field — interop is free in both directions).
    Fields beyond the fourth are ignored: a newer peer may append."""
    if not message or not message.startswith(WIRE_VERSION + "."):
        return None
    parts = message.split(".")
    if len(parts) < 4:
        return None
    trace_id, span_id = parts[1], parts[2]
    if not trace_id or not span_id:
        return None
    try:
        flags = int(parts[3], 16)
    except ValueError:
        return None
    # fresh accumulator: this process flushes its own spans
    return TraceContext(trace_id, span_id,
                        sampled=bool(flags & FLAG_SAMPLED),
                        slow=bool(flags & FLAG_SLOW))


def _new_id() -> str:
    return os.urandom(8).hex()


def sampled_of(trace_id: str, rate: float) -> bool:
    """Deterministic head-sampling decision: a pure function of
    (trace id, rate), so any process given the same id and rate agrees —
    the property the sampling-determinism test pins."""
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    try:
        v = int(trace_id[:8], 16)
    except ValueError:
        return False
    return (v / float(0xFFFFFFFF)) < rate


# -- config -------------------------------------------------------------------


class TraceConfig(Config):
    """Hot-updatable tracing knobs, one section per service binary
    (config pushes through mgmtd retune sampling live — no restart)."""

    enabled = ConfigItem(True, hot=True)
    # head-sampling probability for ops with no inbound context
    sample_rate = ConfigItem(0.0, hot=True,
                             checker=lambda v: 0.0 <= v <= 1.0)
    # ops slower than this flush unconditionally (sampling=0 included);
    # <= 0 disables slow-op capture
    slow_op_ms = ConfigItem(200.0, hot=True)
    # span sink directory; "" = tracing off for this process
    dir = ConfigItem("")
    flush_rows = ConfigItem(512, hot=True, checker=lambda v: v >= 1)


# -- the per-process tracer ---------------------------------------------------


class Tracer:
    """Process-global tracing state: identity tags, sampling knobs, the
    columnar sink. ``configure()`` is idempotent and hot-callable."""

    def __init__(self):
        self.enabled = False
        self.service = "proc"
        self.node = 0
        self.sample_rate = 0.0
        self.slow_op_us = 200_000.0
        self._log = None
        self._log_dir = None
        self._lock = threading.Lock()
        # slow-op hooks (the flight recorder's black-box feed): called
        # with the op's accumulated events whenever an op crosses the
        # slow threshold, independent of the sampling decision
        self._slow_hooks: List = []

    def configure(self, *, service: Optional[str] = None,
                  node: Optional[int] = None,
                  directory: Optional[str] = None,
                  sample_rate: Optional[float] = None,
                  slow_op_ms: Optional[float] = None,
                  enabled: Optional[bool] = None,
                  flush_rows: int = 512) -> "Tracer":
        with self._lock:
            if service is not None:
                self.service = service
            if node is not None:
                self.node = node
            if sample_rate is not None:
                self.sample_rate = float(sample_rate)
            if slow_op_ms is not None:
                self.slow_op_us = (float(slow_op_ms) * 1e3
                                   if slow_op_ms and slow_op_ms > 0
                                   else float("inf"))
            if directory is not None and directory != self._log_dir:
                from tpu3fs.analytics.trace import StructuredTraceLog

                self._log = StructuredTraceLog("spans", directory,
                                               flush_rows=flush_rows)
                self._log_dir = directory
            if enabled is not None:
                self.enabled = bool(enabled) and self._log is not None
            elif self._log is not None:
                self.enabled = True
        return self

    def apply_config(self, cfg: TraceConfig, *, service: str,
                     node: int) -> None:
        """Bind a TraceConfig section (and follow its hot updates)."""
        def _apply(_node=None):
            self.configure(
                service=service, node=node,
                directory=(cfg.dir or None),
                sample_rate=cfg.sample_rate, slow_op_ms=cfg.slow_op_ms,
                enabled=bool(cfg.enabled) and bool(cfg.dir),
                flush_rows=int(cfg.flush_rows))

        _apply()
        cfg.add_callback(_apply)

    def add_slow_hook(self, fn) -> None:
        """Register fn(events) to run on every slow-op flush (idempotent
        for the same callable — N apps in one process hook once)."""
        if fn not in self._slow_hooks:
            self._slow_hooks.append(fn)

    def flush(self) -> None:
        log = self._log
        if log is not None:
            log.flush()

    @property
    def span_paths(self) -> List[str]:
        log = self._log
        if log is None:
            return []
        return list(log.paths)

    # -- emission ----------------------------------------------------------
    def start_trace(self, force: bool = False) -> Optional[TraceContext]:
        """Head decision for an op with no inbound context. Returns None
        when tracing is off for this process (the zero-overhead path)."""
        if not self.enabled:
            return None
        tid = _new_id()
        return TraceContext(tid, _new_id(),
                            sampled=sampled_of(tid, self.sample_rate),
                            slow=force)

    def _flush_events(self, events: Sequence[SpanEvent],
                      slow: bool) -> None:
        log = self._log
        if log is None:
            return
        for ev in events:
            if slow:
                ev.slow = True
            # SpanEvent is flat: its __dict__ IS the columnar row (skips
            # the per-event reflection walk on the flush path)
            log.append_row(dict(ev.__dict__))

    def end_op(self, ctx: TraceContext, op: str, ts: float, dur_s: float,
               *, code: int = 0, nbytes: int = 0,
               tclass: str = "", tenant: str = "") -> None:
        """Append the op span for a NESTED op (the flush decision belongs
        to whichever op owns the accumulator — the process root). An
        empty tenant resolves from the ambient scope, so every op span
        carries its owner without each call site threading it."""
        if not tenant:
            from tpu3fs.tenant.identity import current_tenant

            tenant = current_tenant() or ""
        ctx.events.append(SpanEvent(
            trace_id=ctx.trace_id, span_id=ctx.span_id,
            parent_id=ctx.parent_id, service=self.service, node=self.node,
            op=op, stage="", ts=ts, dur_us=dur_s * 1e6, code=code,
            nbytes=nbytes, tclass=tclass, tenant=tenant,
            sampled=ctx.sampled))

    def finish_op(self, ctx: TraceContext, op: str, ts: float,
                  dur_s: float, *, code: int = 0, nbytes: int = 0,
                  tclass: str = "", tenant: str = "") -> None:
        """Emit the op span and make the flush-or-drop decision for every
        event the op accumulated in this process."""
        self.end_op(ctx, op, ts, dur_s, code=code, nbytes=nbytes,
                    tclass=tclass, tenant=tenant)
        is_slow = ctx.slow or dur_s * 1e6 >= self.slow_op_us
        if is_slow and self._slow_hooks:
            for hook in self._slow_hooks:
                try:
                    hook(list(ctx.events))
                except Exception:
                    pass  # a black-box feed must never fail the op
        if ctx.sampled or is_slow:
            self._flush_events(ctx.events, is_slow and not ctx.sampled)
        ctx.events.clear()


_TRACER = Tracer()


def tracer() -> Tracer:
    return _TRACER


# -- context propagation ------------------------------------------------------

_trace_var: contextvars.ContextVar[Optional[TraceContext]] = \
    contextvars.ContextVar("tpu3fs_trace_ctx", default=None)

# the update worker's coalesced round may serve SEVERAL traces in one
# engine crossing; stage spans fan out to all of them (each op genuinely
# experienced the full round's stage wall time)
_round_var: contextvars.ContextVar[Optional[Tuple[TraceContext, ...]]] = \
    contextvars.ContextVar("tpu3fs_trace_round", default=None)


def current_trace() -> Optional[TraceContext]:
    return _trace_var.get()


@contextlib.contextmanager
def trace_scope(ctx: Optional[TraceContext]):
    token = _trace_var.set(ctx)
    try:
        yield ctx
    finally:
        _trace_var.reset(token)


@contextlib.contextmanager
def round_scope(ctxs: Sequence[TraceContext]):
    """Scope of one coalesced update round: stage spans address every
    member trace; downstream RPCs (chain forward) propagate the first."""
    ctxs = tuple(ctxs)
    tok_r = _round_var.set(ctxs if ctxs else None)
    tok_t = _trace_var.set(ctxs[0] if ctxs else None)
    try:
        yield
    finally:
        _round_var.reset(tok_r)
        _trace_var.reset(tok_t)


def round_traces() -> Tuple[TraceContext, ...]:
    """Traces the current update round serves: the round scope's set, or
    the single current context, or ()."""
    ctxs = _round_var.get()
    if ctxs is not None:
        return ctxs
    ctx = _trace_var.get()
    return (ctx,) if ctx is not None else ()


# -- emission helpers ---------------------------------------------------------


def add_span(ctx: Optional[TraceContext], op: str, stage: str, ts: float,
             dur_s: float, *, code: int = 0, nbytes: int = 0) -> None:
    """Append one already-measured stage span to a context (no-op on
    None): the storage pipeline measures its stage/forward/commit walls
    anyway — tracing reuses those numbers instead of re-clocking."""
    if ctx is None:
        return
    t = _TRACER
    ctx.events.append(SpanEvent(
        trace_id=ctx.trace_id, span_id=_new_id(), parent_id=ctx.span_id,
        service=t.service, node=t.node, op=op, stage=stage, ts=ts,
        dur_us=dur_s * 1e6, code=code, nbytes=nbytes,
        sampled=ctx.sampled))


def add_span_multi(ctxs: Sequence[TraceContext], op: str, stage: str,
                   ts: float, dur_s: float, *, code: int = 0,
                   nbytes: int = 0) -> None:
    for ctx in ctxs:
        add_span(ctx, op, stage, ts, dur_s, code=code, nbytes=nbytes)


@contextlib.contextmanager
def span(op: str, stage: str, *, nbytes: int = 0):
    """Clock a block as a stage span under the current context (no-op —
    not even a clock read — when untraced)."""
    ctx = _trace_var.get()
    if ctx is None:
        yield None
        return
    ts = time.time()
    t0 = time.perf_counter()
    try:
        yield ctx
    finally:
        add_span(ctx, op, stage, ts, time.perf_counter() - t0,
                 nbytes=nbytes)


@contextlib.contextmanager
def root_span(op: str, *, nbytes: int = 0, force: bool = False):
    """Client-side op boundary: joins the current trace when one is
    active (nested client ops emit a plain span), otherwise head-starts a
    trace — sampling decision, envelope stamping downstream, flush-or-
    drop at exit (incl. slow-op capture). Yields the context or None."""
    outer = _trace_var.get()
    if outer is not None:
        with span(op, "", nbytes=nbytes):
            yield outer
        return
    ctx = _TRACER.start_trace(force=force)
    if ctx is None:
        yield None
        return
    ts = time.time()
    t0 = time.perf_counter()
    token = _trace_var.set(ctx)
    code = 0
    try:
        yield ctx
    except BaseException:
        code = -1
        raise
    finally:
        _trace_var.reset(token)
        _TRACER.finish_op(ctx, op, ts, time.perf_counter() - t0,
                          code=code, nbytes=nbytes)
