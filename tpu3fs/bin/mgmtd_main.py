"""mgmtd service binary (ref src/mgmtd/mgmtd.cpp).

One-phase boot (mgmtd cannot fetch config from itself); holds the cluster KV
store, serves heartbeat/routing/admin RPCs and runs the background updaters:
lease extension, heartbeat checking, chain updating (ref
src/mgmtd/background/{MgmtdLeaseExtender,MgmtdHeartbeatChecker,
MgmtdChainsUpdater}).
"""

from __future__ import annotations

import sys
from typing import List, Optional

from tpu3fs.app.application import OnePhaseApplication
from tpu3fs.kv.mem import MemKVEngine
from tpu3fs.mgmtd.service import Mgmtd, MgmtdConfig
from tpu3fs.mgmtd.types import NodeType
from tpu3fs.rpc.net import RpcServer
from tpu3fs.rpc.services import bind_mgmtd_admin, bind_mgmtd_service
from tpu3fs.analytics.spans import TraceConfig
from tpu3fs.monitor.flight import FlightConfig
from tpu3fs.utils.config import Config, ConfigItem
from tpu3fs.qos.core import QosConfig
from tpu3fs.utils.fault_injection import FaultPlaneConfig
from tpu3fs.tenant.quota import TenantConfig


class MgmtdAppConfig(Config):
    # QoS admission limits for the mgmtd RPC dispatch (tpu3fs/qos)
    qos = QosConfig
    # cluster fault plane (utils/fault_injection.py): hot-pushed
    # fault rules for chaos drives / gray-failure testing
    faults = FaultPlaneConfig
    # multi-tenant quota table (tpu3fs/tenant): per-tenant
    # WFQ weights + token-bucket limits, hot-pushed via mgmtd
    tenants = TenantConfig
    # observability: distributed tracing + monitor sample push
    # (tpu3fs/analytics/spans.py; both hot-configured)
    trace = TraceConfig
    # flight recorder (monitor/flight.py): bounded in-process black box
    # dumped on SLO breach / fatal signal / admin_cli flight-dump
    flight = FlightConfig
    collector = ConfigItem("", hot=True)   # host:port; "" = off
    monitor_push_period_s = ConfigItem(5.0, hot=True)
    lease_length_s = ConfigItem(60.0, hot=True)
    heartbeat_timeout_s = ConfigItem(60.0, hot=True)
    tick_interval_s = ConfigItem(5.0, hot=True)
    # metadata partition count (metashard/partition.py); 0 = no partition
    # table — legacy any-op-anywhere meta servers. Cold: the table is
    # created lazily on the first META heartbeat once a width is set,
    # and the width is persisted with it.
    meta_partitions = ConfigItem(0)


class MgmtdApp(OnePhaseApplication):
    node_type = NodeType.MGMTD

    def __init__(self, argv: Optional[List[str]] = None, *, engine=None,
                 clock=None):
        super().__init__(argv)
        # --kv host:port = shared network KV (lease CAS across mgmtds)
        self.engine = engine or self._make_engine()
        self._clock_override = clock
        self.mgmtd: Optional[Mgmtd] = None

    def _make_engine(self):
        from tpu3fs.kv.remote import engine_from_flag

        return engine_from_flag(self.flag("kv", ""))

    def default_config(self) -> Config:
        return MgmtdAppConfig()

    def build_services(self, server: RpcServer) -> None:
        import time as _time

        cfg = MgmtdConfig(
            lease_length_s=self.config.get("lease_length_s"),
            heartbeat_timeout_s=self.config.get("heartbeat_timeout_s"),
            meta_partitions=int(self.config.get("meta_partitions")),
        )
        self.mgmtd = Mgmtd(self.info.node_id or 1, self.engine, cfg,
                           clock=self._clock_override or _time.time)

        # HOT-configurable failure detection: a hotUpdateConfig push of
        # lease_length_s / heartbeat_timeout_s retunes the LIVE Mgmtd
        # (check cadence is already hot via the callable tick interval) —
        # an operator can shorten the gray-node declaration window
        # without restarting the cluster manager
        def _sync_mgmtd_config(_node=None) -> None:
            self.mgmtd.config.lease_length_s = float(
                self.config.get("lease_length_s"))
            self.mgmtd.config.heartbeat_timeout_s = float(
                self.config.get("heartbeat_timeout_s"))

        self.config.add_callback(_sync_mgmtd_config)
        svc = bind_mgmtd_service(server, self.mgmtd)
        bind_mgmtd_admin(svc, self.mgmtd)

    def before_start(self) -> None:
        self.mgmtd.extend_lease()
        # hot-updatable cadence: the callable interval re-reads config
        # every tick (utils.executor.PeriodicRunner)
        self.spawn_periodic(
            "mgmtd-tick",
            lambda: self.config.get("tick_interval_s"),
            self.mgmtd.tick,
        )


def main(argv: Optional[List[str]] = None) -> int:
    MgmtdApp(argv if argv is not None else sys.argv[1:]).run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
