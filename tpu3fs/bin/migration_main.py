"""migration worker binary (ref src/migration/main.cpp — the job-service
process).

Two-phase boot like every service: registers with mgmtd (CLIENT node
type — the worker serves no data, it IS a client of the data plane),
then loops claiming migration jobs from the mgmtd KV and executing them
(tpu3fs/migration/service.py MigrationWorker). Stateless by design: all
durable job state lives in mgmtd, so N workers share the queue and a
SIGKILLed worker's jobs are re-claimed after its lease lapses — by its
own restart or by any surviving peer.
"""

from __future__ import annotations

import sys
from typing import List, Optional

from tpu3fs.analytics.spans import TraceConfig
from tpu3fs.app.application import TwoPhaseApplication
from tpu3fs.mgmtd.types import NodeType
from tpu3fs.monitor.flight import FlightConfig
from tpu3fs.qos.core import QosConfig
from tpu3fs.rpc.net import RpcServer
from tpu3fs.tenant.quota import TenantConfig
from tpu3fs.utils.config import Config, ConfigItem
from tpu3fs.utils.fault_injection import FaultPlaneConfig
from tpu3fs.utils.logging import xlog


class MigrationAppConfig(Config):
    poll_interval_s = ConfigItem(0.5, hot=True)
    batch_chunks = ConfigItem(64, hot=True)
    claim_lease_s = ConfigItem(15.0, hot=True)
    max_jobs = ConfigItem(4, hot=True)
    # auto re-plan: when every job settled but draining/dead nodes still
    # host chains (multi-failure chains take one wave per member), the
    # worker submits the next wave itself — drains converge unattended
    auto_replan = ConfigItem(True, hot=True)
    qos = QosConfig
    faults = FaultPlaneConfig
    tenants = TenantConfig
    trace = TraceConfig
    flight = FlightConfig
    collector = ConfigItem("", hot=True)
    monitor_push_period_s = ConfigItem(5.0, hot=True)


class MigrationApp(TwoPhaseApplication):
    node_type = NodeType.CLIENT

    def __init__(self, argv: Optional[List[str]] = None):
        super().__init__(argv)
        self.worker = None

    def default_config(self) -> Config:
        return MigrationAppConfig()

    def build_services(self, server: RpcServer) -> None:
        pass  # core service only: the worker exposes no data plane

    def before_start(self) -> None:
        from tpu3fs.client.storage_client import StorageClient
        from tpu3fs.migration.service import MigrationWorker
        from tpu3fs.rpc.services import RpcMessenger

        # refresh_routing (not routing): chain mutations the worker itself
        # issues must be visible on its next poll — the bound method gives
        # StorageClient the TTL-invalidation hook and every _routing()
        # call re-polls mgmtd once the worker invalidates
        messenger = RpcMessenger(self.mgmtd_client.refresh_routing)
        client = StorageClient(
            f"migration-worker-{self.info.node_id}",
            self.mgmtd_client.refresh_routing, messenger)
        self.worker = MigrationWorker(
            self.mgmtd_client, client,
            worker_id=f"mig-{self.info.node_id}",
            batch_chunks=self.config.get("batch_chunks"),
            lease_s=self.config.get("claim_lease_s"),
            max_jobs=self.config.get("max_jobs"),
            auto_replan=self.config.get("auto_replan"))
        self.spawn(self._work_loop, "migration-work")

    def _work_loop(self) -> None:
        while not self._stop.wait(self.config.get("poll_interval_s")):
            try:
                self.worker._lease_s = self.config.get("claim_lease_s")
                self.worker._batch = self.config.get("batch_chunks")
                self.worker._max_jobs = self.config.get("max_jobs")
                self.worker._auto_replan = self.config.get("auto_replan")
                advanced = self.worker.run_once()
                if advanced:
                    xlog("INFO", "migration worker advanced %d job(s)",
                         advanced)
            except Exception as e:  # a bad round must not kill the loop
                xlog("ERR", "migration round failed: %r", e)


def main(argv: Optional[List[str]] = None) -> int:
    MigrationApp(argv if argv is not None else sys.argv[1:]).run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
