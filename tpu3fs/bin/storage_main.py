"""storage service binary (ref src/storage/storage.cpp:5-8 —
TwoPhaseApplication<StorageServer>).

Two-phase boot: launcher fetches the STORAGE config template from mgmtd and
registers the node; beforeStart opens every target assigned to this node in
routing (ref StorageTargets.create opening every target at
StorageServer::beforeStart) and keeps discovering new assignments on routing
refresh. Heartbeats carry per-target local states up; a resync loop pushes
recovery transfers when this node heads a chain with a syncing successor
(ref src/storage/sync/ResyncWorker).
"""

from __future__ import annotations

import os
import sys
from typing import Dict, List, Optional

from tpu3fs.analytics.spans import TraceConfig
from tpu3fs.monitor.flight import FlightConfig
from tpu3fs.app.application import TwoPhaseApplication
from tpu3fs.mgmtd.types import LocalTargetState, NodeType
from tpu3fs.qos.core import QosConfig
from tpu3fs.utils.fault_injection import FaultPlaneConfig
from tpu3fs.tenant.quota import TenantConfig
from tpu3fs.rpc.net import RpcServer
from tpu3fs.rpc.services import RpcMessenger, bind_storage_service
from tpu3fs.storage.craq import StorageService
from tpu3fs.storage.ec_resync import EcResyncWorker
from tpu3fs.storage.resync import ResyncWorker
from tpu3fs.storage.target import StorageTarget
from tpu3fs.storage.workers import (
    AllocateWorker,
    CheckWorker,
    DumpWorker,
    PunchHoleWorker,
)
from tpu3fs.utils.config import Config, ConfigItem
from tpu3fs.utils.logging import xlog


class StorageAppConfig(Config):
    # "auto" = the native C++ engine when its .so builds (the flagship
    # serving configuration, round-3 verdict ask #8), mem otherwise;
    # explicit "native" refuses to start without the library
    engine = ConfigItem("auto")         # auto | mem | native
    data_dir = ConfigItem("")           # required for engine=native/auto
    chunk_size = ConfigItem(1 << 20)
    resync_interval_s = ConfigItem(5.0, hot=True)
    target_scan_interval_s = ConfigItem(5.0, hot=True)
    # maintenance workers (ref src/storage/worker/)
    check_interval_s = ConfigItem(3.0, hot=True)
    punch_hole_interval_s = ConfigItem(10.0, hot=True)
    dump_interval_s = ConfigItem(0.0, hot=True)   # 0 = disabled
    dump_dir = ConfigItem("")                     # default <data_dir>/dumps
    reject_create_threshold = ConfigItem(0.98, hot=True)
    emergency_recycling_ratio = ConfigItem(0.95, hot=True)
    trace_dir = ConfigItem("")  # write-path structured trace; "" = off
    # QoS: per-class admission/scheduling limits (tpu3fs/qos) — every
    # item hot-updates via mgmtd config push without restart
    qos = QosConfig
    # cluster fault plane (utils/fault_injection.py): hot-pushed
    # fault rules for chaos drives / gray-failure testing
    faults = FaultPlaneConfig
    # multi-tenant quota table (tpu3fs/tenant): per-tenant
    # WFQ weights + token-bucket limits, hot-pushed via mgmtd
    tenants = TenantConfig
    # distributed request tracing (tpu3fs/analytics/spans.py) + monitor
    # sample push to monitor_collector — both hot-configured
    trace = TraceConfig
    # flight recorder (monitor/flight.py): bounded in-process black box
    # dumped on SLO breach / fatal signal / admin_cli flight-dump
    flight = FlightConfig
    collector = ConfigItem("", hot=True)          # host:port; "" = off
    monitor_push_period_s = ConfigItem(5.0, hot=True)
    # USRBIO shared-memory data plane (tpu3fs/usrbio): co-located clients
    # register shm rings through the Usrbio control service and the data
    # path rides them instead of sockets. 0 disables hosting entirely.
    usrbio = ConfigItem(1)
    usrbio_reap_interval_s = ConfigItem(60.0, hot=True)
    usrbio_iov_max_age_s = ConfigItem(3600.0, hot=True)
    # elasticity: close + trash-route local targets whose routing
    # assignment was taken away by a migration cutover (docs/placement.md)
    retire_targets = ConfigItem(1, hot=True)


class StorageApp(TwoPhaseApplication):
    node_type = NodeType.STORAGE

    def __init__(self, argv: Optional[List[str]] = None):
        super().__init__(argv)
        self.service: Optional[StorageService] = None
        self._trace = None
        self._usrbio_host = None

    def default_config(self) -> Config:
        return StorageAppConfig()

    def _qos_exempt_services(self) -> set:
        # storage methods are admission-checked inside StorageService via
        # the shared controller (read gates, write entry, WFQ shedding) —
        # RPC-level charging on top would double-count each op
        from tpu3fs.rpc.services import STORAGE_SERVICE_ID

        return {STORAGE_SERVICE_ID}

    def build_services(self, server: RpcServer) -> None:
        messenger = RpcMessenger(lambda: self.mgmtd_client.routing())
        self.service = StorageService(
            self.info.node_id, lambda: self.mgmtd_client.routing(), messenger
        )
        from tpu3fs.qos.manager import QosManager

        self.service.set_qos(QosManager(
            self.config.qos, tags={"node": str(self.info.node_id)},
            admission=self.admission))
        trace_dir = self.config.get("trace_dir")
        if trace_dir:
            from tpu3fs.analytics.trace import StructuredTraceLog

            self._trace = StructuredTraceLog("storage-event", trace_dir)
            self.service.set_trace_log(self._trace)
        bind_storage_service(server, self.service)
        # USRBIO shm data plane: co-located clients register rings via
        # the control service; their RPCs then dispatch through the SAME
        # admission entry as socket frames (tpu3fs/usrbio/server.py)
        if self.config.get("usrbio"):
            from tpu3fs.usrbio.server import (
                UsrbioRpcHost,
                bind_usrbio_service,
            )

            self._usrbio_host = UsrbioRpcHost(server)
            bind_usrbio_service(server, self._usrbio_host)

    def after_stop(self) -> None:
        if self._usrbio_host is not None:
            self._usrbio_host.stop()
        if self._trace is not None:
            # the writer buffers flush_rows rows; a restart must not lose
            # the tail of the trace
            self._trace.flush()

    # -- target discovery ---------------------------------------------------
    def _target_path(self, target_id: int, disk_index: int) -> Optional[str]:
        base = self.config.get("data_dir")
        if not base:
            return None
        path = os.path.join(base, f"disk{disk_index}", f"target{target_id}")
        os.makedirs(path, exist_ok=True)
        return path

    def retire_targets(self, routing) -> int:
        """Close + trash-route local targets routing no longer assigns
        here (a migration cutover detached them: chain_id 0, or the
        membership moved to another node). The DATA is not destroyed —
        a disk-backed target directory is renamed into
        ``<data_dir>/trash/`` with a timestamp so an operator can still
        recover from a mistaken plan; mem engines just release."""
        import time as _time

        retired = 0
        for target in self.service.targets():
            info = routing.targets.get(target.target_id)
            if info is None:
                continue  # unknown to routing: never reap on ignorance
            if info.chain_id and info.node_id == self.info.node_id:
                continue
            dropped = self.service.drop_target(target.target_id)
            if dropped is None:
                continue
            try:
                dropped.engine.close()
            except Exception:
                pass
            path = self._target_path(target.target_id, info.disk_index) \
                if self.config.get("data_dir") else None
            if path and os.path.isdir(path):
                trash = os.path.join(self.config.get("data_dir"), "trash")
                os.makedirs(trash, exist_ok=True)
                dst = os.path.join(
                    trash, f"target{target.target_id}-{int(_time.time())}")
                try:
                    os.rename(path, dst)
                except OSError:
                    pass
            retired += 1
            xlog("INFO", "node %d retired target %d (trash-routed)",
                 self.info.node_id, target.target_id)
        if retired:
            from tpu3fs.migration.service import record_retired_target

            record_retired_target(retired)
        return retired

    def scan_targets(self) -> int:
        """Open targets routing assigns to this node (ref StorageTargets
        create/load at startup + admin create-target afterwards); retire
        the ones routing took away (migration cutover)."""
        routing = self.mgmtd_client.refresh_routing()
        if self.config.get("retire_targets"):
            self.retire_targets(routing)
        added = 0
        for info in routing.targets.values():
            if info.node_id != self.info.node_id:
                continue
            if self.service.target(info.target_id) is not None:
                continue
            if not info.chain_id:
                continue  # not part of a chain yet
            target = StorageTarget(
                info.target_id,
                info.chain_id,
                engine=self.config.get("engine"),
                path=self._target_path(info.target_id, info.disk_index),
                chunk_size=self.config.get("chunk_size"),
            )
            # a target opened on a fresh/possibly stale disk is not
            # automatically up to date: if its chain already bumped past v1,
            # report ONLINE and let the resync protocol promote it
            chain = routing.chains.get(info.chain_id)
            if chain is not None and chain.chain_version > 1:
                target.local_state = LocalTargetState.ONLINE
            self.service.add_target(target)
            added += 1
            xlog("INFO", "node %d opened target %d (chain %d, %s)",
                 self.info.node_id, info.target_id, info.chain_id,
                 self.config.get("engine"))
        # refresh the native read fast path every scan (no-op on the
        # python transport): registry entries track target/routing state
        # with at most one scan interval of lag
        try:
            from tpu3fs.storage.native_fastpath import sync_read_fastpath

            sync_read_fastpath(self.server, self.service)
        except Exception:
            pass
        return added

    def local_target_states(self) -> Dict[int, LocalTargetState]:
        return {t.target_id: t.local_state for t in self.service.targets()}

    def before_start(self) -> None:
        self.scan_targets()
        self.spawn(self._target_scan_loop, "target-scan")
        self.spawn(self._resync_loop, "resync")
        self.spawn(self._check_loop, "check-disk")
        self.spawn(self._punch_hole_loop, "punch-hole")
        # always spawned so dump_interval_s can be hot-enabled from 0
        self.spawn(self._dump_loop, "dump-chunkmeta")
        if self._usrbio_host is not None:
            self.spawn(self._usrbio_reap_loop, "usrbio-reap")

    def _usrbio_reap_loop(self) -> None:
        while not self._stop.wait(
                self.config.get("usrbio_reap_interval_s")):
            try:
                self._usrbio_host.reap_pass(
                    iov_max_age_s=self.config.get("usrbio_iov_max_age_s"))
            except Exception:
                pass

    def _target_scan_loop(self) -> None:
        while not self._stop.wait(self.config.get("target_scan_interval_s")):
            try:
                if self.scan_targets():
                    self.heartbeat_once()
            except Exception:
                pass

    def _resync_loop(self) -> None:
        worker = None
        ec_worker = None
        while not self._stop.wait(self.config.get("resync_interval_s")):
            try:
                if worker is None:
                    messenger = RpcMessenger(
                        lambda: self.mgmtd_client.routing())
                    worker = ResyncWorker(self.service, messenger)
                    # EC chains rebuild + heal (healthy-chain roll-forward
                    # of interrupted two-phase commits) on the same cadence
                    ec_worker = EcResyncWorker(self.service, messenger)
                worker.run_once()
                ec_worker.run_once()
            except Exception:
                pass

    def _check_loop(self) -> None:
        worker = CheckWorker(
            self.service,
            reject_create_threshold=self.config.get("reject_create_threshold"),
            emergency_recycling_ratio=self.config.get(
                "emergency_recycling_ratio"),
            # a freshly offlined disk must reach mgmtd now, not at the next
            # periodic heartbeat (ref CheckWorker triggerHeartbeat)
            on_offline=lambda t: self.heartbeat_once(),
        )
        allocator = AllocateWorker(self.service)
        while not self._stop.wait(self.config.get("check_interval_s")):
            try:
                worker.reject_create_threshold = self.config.get(
                    "reject_create_threshold")
                worker.emergency_recycling_ratio = self.config.get(
                    "emergency_recycling_ratio")
                worker.run_once()
                allocator.run_once()
            except Exception:
                pass

    def _punch_hole_loop(self) -> None:
        worker = PunchHoleWorker(self.service)
        while not self._stop.wait(self.config.get("punch_hole_interval_s")):
            try:
                worker.run_once()
            except Exception:
                pass

    def _dump_loop(self) -> None:
        dump_dir = self.config.get("dump_dir") or os.path.join(
            self.config.get("data_dir") or ".", "dumps")
        worker = DumpWorker(self.service, dump_dir, self.info.node_id)
        while True:
            interval = self.config.get("dump_interval_s")
            # 0 = disabled: poll for a hot re-enable without busy-looping
            if self._stop.wait(interval if interval > 0 else 1.0):
                return
            if interval <= 0:
                continue
            try:
                worker.run_once()
            except Exception:
                pass


def main(argv: Optional[List[str]] = None) -> int:
    StorageApp(argv if argv is not None else sys.argv[1:]).run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
