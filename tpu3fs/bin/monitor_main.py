"""monitor_collector service binary (ref src/monitor_collector/
monitor_collector.cpp): receives Sample batches from all services and
batch-commits them to the analytics sink (JSONL here; the reference writes
ClickHouse/TaosDB, MonitorCollectorService.h:24-31)."""

from __future__ import annotations

import sys
from typing import List, Optional

from tpu3fs.app.application import OnePhaseApplication
from tpu3fs.mgmtd.types import NodeType
from tpu3fs.monitor.collector import CollectorService, bind_collector_service
from tpu3fs.monitor.recorder import JsonlSink, SqliteSink
from tpu3fs.rpc.net import RpcServer
from tpu3fs.analytics.spans import TraceConfig
from tpu3fs.utils.config import Config, ConfigItem
from tpu3fs.qos.core import QosConfig
from tpu3fs.utils.fault_injection import FaultPlaneConfig
from tpu3fs.tenant.quota import TenantConfig


class MonitorAppConfig(Config):
    # QoS admission limits for the collector RPC dispatch (tpu3fs/qos)
    qos = QosConfig
    # cluster fault plane (utils/fault_injection.py): hot-pushed
    # fault rules for chaos drives / gray-failure testing
    faults = FaultPlaneConfig
    # multi-tenant quota table (tpu3fs/tenant): per-tenant
    # WFQ weights + token-bucket limits, hot-pushed via mgmtd
    tenants = TenantConfig
    # observability: distributed tracing + monitor sample push
    # (tpu3fs/analytics/spans.py; both hot-configured)
    trace = TraceConfig
    collector = ConfigItem("", hot=True)   # host:port; "" = off
    monitor_push_period_s = ConfigItem(5.0, hot=True)
    out_path = ConfigItem("monitor_samples.jsonl")


class MonitorApp(OnePhaseApplication):
    node_type = NodeType.CLIENT  # monitor nodes are not in the data plane

    def __init__(self, argv: Optional[List[str]] = None, *, sink=None):
        super().__init__(argv)
        self._sink = sink
        self.collector: Optional[CollectorService] = None

    def default_config(self) -> Config:
        return MonitorAppConfig()

    def build_services(self, server: RpcServer) -> None:
        out = self.config.get("out_path")
        if self._sink is not None:
            sink = self._sink
        elif self.flag("sink", "sqlite" if out.endswith(".db")
                       else "jsonl") == "sqlite":
            # queryable store (the ClickHouse stand-in): admin_cli
            # query-metrics reads it over the collector RPC
            sink = SqliteSink(out)
        else:
            sink = JsonlSink(out)
        self.collector = CollectorService(sink)
        bind_collector_service(server, self.collector)

    def after_stop(self) -> None:
        if self.collector is not None:
            self.collector.flush()


def main(argv: Optional[List[str]] = None) -> int:
    MonitorApp(argv if argv is not None else sys.argv[1:]).run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
