"""monitor_collector service binary (ref src/monitor_collector/
monitor_collector.cpp): receives Sample batches from all services and
batch-commits them to the analytics sink (JSONL here; the reference writes
ClickHouse/TaosDB, MonitorCollectorService.h:24-31).

Beyond ingest, this binary runs the cluster's JUDGMENT layer:

- a ``WindowedAggregator`` rolls every series up into ring-retained
  windows (rate/last/p50/p90/p99 via ``aggQuery``);
- an ``SloEngine`` evaluates hot-pushed ``[slo]`` rules on a period and
  answers the single cluster verdict (``sloStatus`` / ``admin_cli
  health``); a firing rule bumps the flight-dump epoch every pusher
  sees on its next Ack;
- a retention pass keeps the raw-sample sink bounded (rows beyond the
  horizon are dropped once rolled up), with ``monitor.retained_bytes``
  / ``monitor.ingest_rate`` / ``monitor.agg_*`` self-gauges published
  through the same MemoryMonitor path as every other binary's gauges.

The collector boots one-phase (it cannot fetch config from mgmtd), so
``[slo]`` hot-pushes arrive via the core ``hotUpdateConfig`` RPC —
``admin_cli slo set --collector host:port --spec ...``.
"""

from __future__ import annotations

import sys
import time
from typing import List, Optional

from tpu3fs.app.application import OnePhaseApplication
from tpu3fs.mgmtd.types import NodeType
from tpu3fs.monitor.agg import WindowedAggregator
from tpu3fs.monitor.collector import (
    CollectorService,
    LocalCollectorSink,
    bind_collector_service,
)
from tpu3fs.monitor.flight import FlightConfig
from tpu3fs.monitor.recorder import JsonlSink, Monitor, SqliteSink
from tpu3fs.monitor.slo import SloConfig, SloEngine, apply_slo_config
from tpu3fs.rpc.net import RpcServer
from tpu3fs.analytics.spans import TraceConfig
from tpu3fs.utils.config import Config, ConfigItem
from tpu3fs.qos.core import QosConfig
from tpu3fs.utils.fault_injection import FaultPlaneConfig
from tpu3fs.tenant.quota import TenantConfig


class MonitorAppConfig(Config):
    # QoS admission limits for the collector RPC dispatch (tpu3fs/qos)
    qos = QosConfig
    # cluster fault plane (utils/fault_injection.py): hot-pushed
    # fault rules for chaos drives / gray-failure testing
    faults = FaultPlaneConfig
    # multi-tenant quota table (tpu3fs/tenant): per-tenant
    # WFQ weights + token-bucket limits, hot-pushed via mgmtd
    tenants = TenantConfig
    # observability: distributed tracing + monitor sample push
    # (tpu3fs/analytics/spans.py; both hot-configured)
    trace = TraceConfig
    # SLO rule engine over the windowed aggregates (monitor/slo.py;
    # hot via core hotUpdateConfig — admin_cli slo set)
    slo = SloConfig
    # flight recorder (monitor/flight.py): the collector keeps its own
    # black box too (alert transitions, its self-gauges)
    flight = FlightConfig
    collector = ConfigItem("", hot=True)   # host:port; "" = off
    monitor_push_period_s = ConfigItem(5.0, hot=True)
    out_path = ConfigItem("monitor_samples.jsonl")
    # windowed-aggregation geometry (bounded memory by construction)
    agg_bucket_s = ConfigItem(2.0, checker=lambda v: v > 0)
    agg_slots = ConfigItem(150, checker=lambda v: v >= 2)
    agg_max_series = ConfigItem(8192, hot=True, checker=lambda v: v >= 1)
    # raw-row retention (SqliteSink.compact): rows beyond the horizon
    # are dropped once rolled up; 0 disables an axis
    retain_s = ConfigItem(900.0, hot=True)
    retain_max_bytes = ConfigItem(256 << 20, hot=True)
    compact_interval_s = ConfigItem(30.0, hot=True,
                                    checker=lambda v: v > 0)


class MonitorApp(OnePhaseApplication):
    node_type = NodeType.CLIENT  # monitor nodes are not in the data plane

    def __init__(self, argv: Optional[List[str]] = None, *, sink=None):
        super().__init__(argv)
        self._sink = sink
        self.collector: Optional[CollectorService] = None
        self.aggregator: Optional[WindowedAggregator] = None
        self.slo_engine: Optional[SloEngine] = None

    def default_config(self) -> Config:
        return MonitorAppConfig()

    def build_services(self, server: RpcServer) -> None:
        out = self.config.get("out_path")
        if self._sink is not None:
            sink = self._sink
        elif self.flag("sink", "sqlite" if out.endswith(".db")
                       else "jsonl") == "sqlite":
            # queryable store (the ClickHouse stand-in): admin_cli
            # query-metrics reads it over the collector RPC
            sink = SqliteSink(out)
        else:
            sink = JsonlSink(out)
        self.aggregator = WindowedAggregator(
            bucket_s=float(self.config.get("agg_bucket_s")),
            slots=int(self.config.get("agg_slots")),
            max_series=int(self.config.get("agg_max_series")))
        self.slo_engine = SloEngine(self.aggregator)
        apply_slo_config(self.config.slo, self.slo_engine)
        # a firing rule also snapshots THIS process's black box (remote
        # binaries dump via the Ack dump-epoch on their next push)
        self.slo_engine.add_firing_callback(self._dump_local_flight)
        self.collector = CollectorService(
            sink, aggregator=self.aggregator, slo=self.slo_engine)
        bind_collector_service(server, self.collector)
        # the collector drinks its own telemetry (slo.* transitions,
        # monitor.* gauges) straight into its store — zero RPCs
        Monitor.default().add_sink(LocalCollectorSink(self.collector))

    @staticmethod
    def _dump_local_flight(_state) -> None:
        from tpu3fs.monitor.flight import flight

        flight().dump(reason=f"slo breach: {_state.rule}")

    def before_start(self) -> None:
        self.spawn_periodic(
            "slo-eval",
            lambda: float(self.config.get("slo.eval_period_s")),
            self._slo_tick)
        self.spawn_periodic(
            "sink-compact",
            lambda: float(self.config.get("compact_interval_s")),
            self._compact_tick)

    def _slo_tick(self) -> None:
        if self.slo_engine is not None and self.config.get("slo.enabled"):
            self.slo_engine.evaluate()

    def _compact_tick(self) -> None:
        sink = self.collector._sink if self.collector else None
        if sink is not None and hasattr(sink, "compact"):
            sink.compact(float(self.config.get("retain_s")),
                         int(self.config.get("retain_max_bytes")))

    def _start_memory_monitor(self, interval_s: float = 30.0) -> None:
        super()._start_memory_monitor(interval_s)
        # collector self-observability: the judgment layer must be
        # bounded-memory BY CONSTRUCTION, and these gauges prove it live
        sink = self.collector._sink if self.collector else None
        if sink is not None and hasattr(sink, "db_bytes"):
            self.memory_monitor.add_source(
                "monitor.retained_bytes", sink.db_bytes)
        if self.aggregator is not None:
            agg = self.aggregator
            self.memory_monitor.add_source(
                "monitor.agg_series", lambda: agg.stats()["series"])
            self.memory_monitor.add_source(
                "monitor.agg_bytes", lambda: agg.stats()["bytes"])
        if self.collector is not None:
            svc = self.collector
            last = {"t": time.time(), "n": svc.ingested}

            def ingest_rate() -> float:
                now = time.time()
                n = svc.ingested
                dt = max(now - last["t"], 1e-9)
                rate = (n - last["n"]) / dt
                last["t"], last["n"] = now, n
                return rate

            self.memory_monitor.add_source(
                "monitor.ingest_rate", ingest_rate)

    def after_stop(self) -> None:
        if self.collector is not None:
            self.collector.flush()


def main(argv: Optional[List[str]] = None) -> int:
    MonitorApp(argv if argv is not None else sys.argv[1:]).run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
