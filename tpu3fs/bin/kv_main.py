"""kv service binary: the shared transactional KV store.

Plays the role FoundationDB plays in the reference deployment (meta +
mgmtd persist through one transactional KV; src/fdb/). Serves the Kv RPC
service (snapshot/get/getRange/commit/release) over the MVCC engine with an
optional write-ahead log for restart durability:

  python -m tpu3fs.bin.kv_main --port 9500 [--wal /data/kv.wal] [--rpc native]
"""

from __future__ import annotations

import sys
from typing import List, Optional

from tpu3fs.app.application import OnePhaseApplication
from tpu3fs.kv.service import KvService, bind_kv_service
from tpu3fs.mgmtd.types import NodeType
from tpu3fs.rpc.net import RpcServer
from tpu3fs.analytics.spans import TraceConfig
from tpu3fs.monitor.flight import FlightConfig
from tpu3fs.utils.config import Config, ConfigItem
from tpu3fs.qos.core import QosConfig
from tpu3fs.utils.fault_injection import FaultPlaneConfig
from tpu3fs.tenant.quota import TenantConfig


class KvAppConfig(Config):
    # QoS admission limits for the KV RPC dispatch (tpu3fs/qos)
    qos = QosConfig
    # cluster fault plane (utils/fault_injection.py): hot-pushed
    # fault rules for chaos drives / gray-failure testing
    faults = FaultPlaneConfig
    # multi-tenant quota table (tpu3fs/tenant): per-tenant
    # WFQ weights + token-bucket limits, hot-pushed via mgmtd
    tenants = TenantConfig
    # observability: distributed tracing + monitor sample push
    # (tpu3fs/analytics/spans.py; both hot-configured)
    trace = TraceConfig
    # flight recorder (monitor/flight.py): bounded in-process black box
    # dumped on SLO breach / fatal signal / admin_cli flight-dump
    flight = FlightConfig
    collector = ConfigItem("", hot=True)   # host:port; "" = off
    monitor_push_period_s = ConfigItem(5.0, hot=True)
    snapshot_ttl_s = ConfigItem(60.0, hot=True)


class KvApp(OnePhaseApplication):
    node_type = NodeType.CLIENT  # not part of the storage data plane

    def __init__(self, argv: Optional[List[str]] = None):
        super().__init__(argv)
        self.service: Optional[KvService] = None

    def default_config(self) -> Config:
        return KvAppConfig()

    def build_services(self, server: RpcServer) -> None:
        peers_flag = self.flag("peers", "")
        if peers_flag:
            # replicated kvd group member (kv/replica.py):
            #   --node-id 1 --peers 1=h:p,2=h:p,3=h:p --data-dir /data/kvd1
            from tpu3fs.kv.replica import (
                ReplicatedKvService,
                bind_replicated_kv,
            )

            peers = {}
            for part in peers_flag.split(","):
                nid, addr = part.strip().split("=", 1)
                host, port = addr.rsplit(":", 1)
                peers[int(nid)] = (host, int(port))
            self.service = ReplicatedKvService(
                int(self.flag("node_id", 0) or 0),
                peers,
                data_dir=self.flag("data_dir", "") or None,
                fsync=bool(int(self.flag("fsync", 0) or 0)),
            )
            bind_replicated_kv(server, self.service)
            return
        wal = self.flag("wal", "") or None
        self.service = KvService(
            wal_path=wal,
            snapshot_ttl_s=self.config.get("snapshot_ttl_s"),
            compact_min_bytes=int(
                self.flag("compact_min_bytes", 4 << 20) or (4 << 20)),
            fsync=bool(int(self.flag("fsync", 0) or 0)),
        )
        bind_kv_service(server, self.service)
        self.config.add_callback(
            lambda cfg: self.service.set_snapshot_ttl(
                cfg.get("snapshot_ttl_s")))

    def after_stop(self) -> None:
        if self.service is None:
            return
        if hasattr(self.service, "stop"):
            self.service.stop()       # replicated group member
        else:
            self.service.close()      # plain kvd


def main(argv: Optional[List[str]] = None) -> int:
    KvApp(argv if argv is not None else sys.argv[1:]).run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
