"""Service binaries (ref the four deployed mains: src/mgmtd/mgmtd.cpp,
src/meta/meta.cpp, src/storage/storage.cpp, src/monitor_collector/
monitor_collector.cpp). Each module exposes ``main(argv)`` and a
``*App`` class usable in-process by tests and by the cluster runner."""
