"""kvcache_gc: standalone KV-cache garbage-collection daemon.

The inference-side twin of ckpt_gc (bin/ckpt_gc_main.py): connects to a
live cluster like admin_cli (``--connect HOST:PORT``) and periodically
runs the two KVCacheGC passes over a cache root —

- TTL pass: cursor-scanned shard sweeps removing entries older than
  ``--ttl`` (never more than ``--max-shards`` leaf dirs per tick, so the
  sweep can never monopolize the metadata service);
- CAPACITY pass: oldest-touched LRU eviction down to a bytes budget —

both lease-respecting (an inference session's pinned prefix blocks are
never evicted mid-decode, kvcache/leases.py).

MULTI-TENANT (tpu3fs/tenant, docs/tenancy.md): with ``--per-tenant``,
first-level subdirectories of the root whose names are valid tenant ids
are treated as per-tenant stores (the ``KVCacheClient(root=f"{root}/
{tenant}")`` layout). Each tick then runs a capacity pass PER TENANT
with that tenant's ``kvcache_bytes`` quota as the budget (falling back
to ``--capacity-bytes`` when the quota table has no row), and publishes
the measured per-tenant resident bytes to the tenant registry
(``tenant.kvcache_bytes`` gauge) — the authoritative figure behind the
writer-side resident-budget gate (kvcache/cache.py).

HOT CONFIG: each tick the daemon re-fetches the STORAGE config template
from mgmtd and re-applies its ``[tenants] spec`` to the local registry,
so a single ``admin_cli tenant-quota set`` push retunes the eviction
budgets of the running daemon — no restart, the same config plane every
service binary follows.

    python -m tpu3fs.bin.kvcache_gc_main --connect HOST:PORT \
        [--root /kvcache] [--ttl 3600] [--capacity-bytes 0] \
        [--max-shards 64] [--per-tenant] [--interval 60] [--once]

Tests drive run_loop() directly against an in-process Fabric.
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import Dict, List, Optional

from tpu3fs.kvcache.cache import KVCacheGC
from tpu3fs.tenant.identity import valid_tenant
from tpu3fs.tenant.quota import registry
from tpu3fs.utils.result import FsError


def _refresh_quota_table(fabric, *, out=sys.stdout) -> None:
    """Pull the storage config template's [tenants] spec into the local
    registry (best-effort: a cluster without a pushed table keeps the
    daemon's current — default-permissive — state)."""
    try:
        from tpu3fs.mgmtd.types import NodeType
        from tpu3fs.utils.config import tomllib

        blob = fabric.mgmtd.get_config(NodeType.STORAGE)
        if blob is None or not blob.content or tomllib is None:
            return
        data = tomllib.loads(blob.content)
        sec = data.get("tenants")
        if isinstance(sec, dict) and "spec" in sec:
            registry().configure(
                str(sec.get("spec", "")),
                enabled=bool(sec.get("enabled", True)),
                retry_after_ms=int(sec.get("shed_retry_after_ms", 50)))
    except (FsError, ValueError, AttributeError) as e:
        print(f"kvcache-gc: config refresh skipped ({e!r})", file=out)


def tenant_roots(meta, root: str) -> Dict[str, str]:
    """First-level subdirs of `root` whose names are valid tenant ids ->
    their paths (the per-tenant store layout); {} when none."""
    out: Dict[str, str] = {}
    try:
        for e in meta.list_dir(root):
            if valid_tenant(e.name):
                out[e.name] = f"{root.rstrip('/')}/{e.name}"
    except FsError:
        pass
    return out


def build_gc(meta, root: str, args: argparse.Namespace) -> KVCacheGC:
    return KVCacheGC(
        meta,
        root=root,
        ttl_s=args.ttl,
        max_shards=args.max_shards,
        capacity_bytes=args.capacity_bytes or None,
        client_id="kvcache-gc",
    )


def run_once(fabric, args: argparse.Namespace, *,
             gcs: Dict[str, KVCacheGC], out=sys.stdout) -> Dict[str, int]:
    """One tick: quota refresh, TTL + capacity passes (global or
    per-tenant), resident-gauge publish. Returns counters."""
    meta = fabric.meta
    stats = {"removed_ttl": 0, "removed_capacity": 0, "tenants": 0}
    _refresh_quota_table(fabric, out=out)
    roots: Dict[str, str] = {}
    if args.per_tenant:
        roots = tenant_roots(meta, args.root)
    if not roots:
        roots = {"": args.root}
    for tenant, root in sorted(roots.items()):
        gc = gcs.get(root)
        if gc is None:
            gc = gcs[root] = build_gc(meta, root, args)
        stats["removed_ttl"] += gc.run_once()
        budget = args.capacity_bytes or None
        if tenant:
            stats["tenants"] += 1
            quota_budget = registry().kvcache_budget(tenant)
            if quota_budget > 0:
                budget = quota_budget
        if budget:
            stats["removed_capacity"] += gc.capacity_pass(
                capacity_bytes=budget)
        if tenant:
            # authoritative resident figure AFTER eviction: one scan,
            # published to the registry gauge the writer-side budget
            # gate consults (kvcache/cache.py _check_resident_budget)
            resident = sum(length for _, length, _, _
                           in gc.scan_entries())
            registry().set_kvcache_resident(tenant, resident)
            print(f"kvcache-gc: tenant={tenant} resident={resident} "
                  f"budget={budget or 0}", file=out)
    return stats


def run_loop(fabric, args: argparse.Namespace, *, out=sys.stdout) -> int:
    """Sweep until stopped (or once); returns total entries removed."""
    gcs: Dict[str, KVCacheGC] = {}
    total = 0
    while True:
        stats = run_once(fabric, args, gcs=gcs, out=out)
        total += stats["removed_ttl"] + stats["removed_capacity"]
        print(f"kvcache-gc: root={args.root} "
              f"ttl_removed={stats['removed_ttl']} "
              f"capacity_removed={stats['removed_capacity']} "
              f"tenants={stats['tenants']}", file=out)
        if args.once:
            return total
        time.sleep(args.interval)


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(prog="kvcache_gc", description=__doc__)
    p.add_argument("--connect", metavar="HOST:PORT",
                   help="mgmtd address of a live cluster")
    p.add_argument("--token", default="", help="bearer token (auth mode)")
    p.add_argument("--root", default="/kvcache")
    p.add_argument("--ttl", type=float, default=3600.0,
                   help="seconds since last touch before an entry is "
                        "TTL-evictable")
    p.add_argument("--capacity-bytes", type=int, default=0,
                   help="global bytes budget for the capacity pass "
                        "(0 = TTL only; per-tenant quotas override)")
    p.add_argument("--max-shards", type=int, default=64,
                   help="leaf dirs visited per TTL tick")
    p.add_argument("--per-tenant", action="store_true",
                   help="treat <root>/<tenant> subdirs as per-tenant "
                        "stores budgeted by their kvcache_bytes quota")
    p.add_argument("--interval", type=float, default=60.0)
    p.add_argument("--once", action="store_true")
    return p.parse_args(argv)


def main(argv: Optional[List[str]] = None) -> int:  # pragma: no cover
    args = parse_args(argv if argv is not None else sys.argv[1:])
    if not args.connect:
        print("kvcache_gc: --connect HOST:PORT is required",
              file=sys.stderr)
        return 2
    from tpu3fs.cli import RpcFabricView

    host, port_s = args.connect.rsplit(":", 1)
    fabric = RpcFabricView((host, int(port_s)), token=args.token,
                           client_id="kvcache-gc")
    run_loop(fabric, args)
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
