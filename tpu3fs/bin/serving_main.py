"""serving service binary: one fleet KVCache serving process.

A serving node is an inference host's cache-side process: it owns a
``FleetKVCache`` (host tier over the kvcache store, miss path =
single-flight -> peer fill -> claimed storage fill, tpu3fs/serving/) and
exposes the Serving RPC table (peerRead/fillClaim/fillRelease/
servingStats/servingLoad) so OTHER serving nodes can fill their misses
from this node's host tier — the fleet serves itself before touching
storage (docs/serving.md).

Two-phase boot like every service binary: launcher fetches the CLIENT
config template from mgmtd and registers the node; beforeStart registers
this node's serving endpoint in the mgmtd serving directory (a TTL
lease, renewed at ttl/3 like a heartbeat) so peers discover it through
RoutingInfo.serving exactly like chain tables. Co-located peers ride
USRBIO shm rings (the binary hosts the Usrbio control service; peerRead
is ring-dispatchable, usrbio/transport.py RING_METHODS).

    python -m tpu3fs.bin.serving_main --node-id 61 --mgmtd HOST:PORT \
        [--port 0] [--straggle-ms 0] [--tenant t0] [--config.root=/kvcache]
"""

from __future__ import annotations

import sys
import time
import uuid
from typing import List, Optional

from tpu3fs.analytics.spans import TraceConfig
from tpu3fs.app.application import TwoPhaseApplication
from tpu3fs.mgmtd.types import NodeType
from tpu3fs.monitor.flight import FlightConfig
from tpu3fs.qos.core import QosConfig
from tpu3fs.rpc.net import RpcClient, RpcServer
from tpu3fs.rpc.services import MetaRpcClient, RpcMessenger
from tpu3fs.tenant.quota import TenantConfig
from tpu3fs.utils.config import Config, ConfigItem
from tpu3fs.utils.fault_injection import FaultPlaneConfig
from tpu3fs.utils.logging import xlog
from tpu3fs.utils.result import FsError


class ServingAppConfig(Config):
    root = ConfigItem("/kvcache")        # kvcache store root
    # host tier (TieredKVCache): the RAM this process serves from
    capacity_bytes = ConfigItem(256 << 20)
    dirty_max_bytes = ConfigItem(64 << 20)
    write_through = ConfigItem(1)        # serving fills must be peer-readable
    # cached-inode fast path: REQUIRED for serve-through (peek miss ->
    # get_cached with zero meta round trips); entries, not bytes
    inode_cache = ConfigItem(4096)
    touch_coalesce_s = ConfigItem(30.0, hot=True)
    # fleet fill ladder (serving/fleet.py)
    claim_ttl_ms = ConfigItem(2000, hot=True)
    claim_poll_ms = ConfigItem(20.0, hot=True)
    claim_polls = ConfigItem(3, hot=True)
    singleflight_timeout_s = ConfigItem(30.0, hot=True)
    peer_est_bytes = ConfigItem(1 << 20)
    # peer transport: prefer shm rings to co-located peers
    peer_usrbio = ConfigItem(1)
    peer_ring_entries = ConfigItem(64)
    peer_iov_bytes = ConfigItem(8 << 20)
    # serving-directory lease (mgmtd _prune_serving expires silent nodes)
    serving_ttl_s = ConfigItem(30.0, hot=True)
    # QoS / tenants / faults / tracing / flight: the standard config
    # plane every service binary carries (hot via mgmtd config push)
    qos = QosConfig
    tenants = TenantConfig
    faults = FaultPlaneConfig
    trace = TraceConfig
    flight = FlightConfig
    collector = ConfigItem("", hot=True)
    monitor_push_period_s = ConfigItem(5.0, hot=True)
    # USRBIO hosting (this binary's OWN ring server, for peers' rings)
    usrbio = ConfigItem(1)
    usrbio_reap_interval_s = ConfigItem(60.0, hot=True)
    usrbio_iov_max_age_s = ConfigItem(3600.0, hot=True)


class ServingApp(TwoPhaseApplication):
    node_type = NodeType.CLIENT

    def __init__(self, argv: Optional[List[str]] = None):
        super().__init__(argv)
        self.fleet = None
        self.host = None
        self._usrbio_host = None

    def default_config(self) -> Config:
        return ServingAppConfig()

    # -- wiring --------------------------------------------------------------
    def _meta_addrs(self):
        """META node addresses from routing; the cluster may still be
        assembling, so wait for at least one (the launcher retried its
        config fetch the same way)."""
        deadline = time.time() + float(self.flag("launcher_timeout", "30"))
        while True:
            routing = self.mgmtd_client.refresh_routing()
            addrs = [(n.host, n.port) for n in routing.nodes.values()
                     if n.type == NodeType.META and n.host]
            if addrs:
                return addrs
            if time.time() >= deadline:
                raise SystemExit(
                    "serving_main: no META nodes in routing "
                    "(is the cluster up?)")
            time.sleep(0.5)

    def build_services(self, server: RpcServer) -> None:
        from tpu3fs.client.file_io import FileIoClient
        from tpu3fs.client.storage_client import StorageClient
        from tpu3fs.kvcache.cache import KVCacheClient
        from tpu3fs.serving.fleet import FleetKVCache
        from tpu3fs.serving.service import (
            ServingHost,
            ServingPeerClient,
            bind_serving_service,
        )

        node_id = self.info.node_id
        routing = self.mgmtd_client.refresh_routing
        messenger = RpcMessenger(lambda: self.mgmtd_client.routing())
        meta = MetaRpcClient(self._meta_addrs(),
                             client_id=f"serving-{node_id}",
                             token=self.flag("token"))
        # storage clients need UNIQUE wire ids (cli.py RpcFabricView: the
        # exactly-once channel table is keyed by client id)
        storage = StorageClient(
            f"serving-{node_id}-{uuid.uuid4().hex[:8]}", routing, messenger)
        kv = KVCacheClient(
            meta, FileIoClient(storage),
            root=self.config.get("root"),
            client_id=f"serving-{node_id}",
            inode_cache=int(self.config.get("inode_cache")),
            touch_coalesce_s=float(self.config.get("touch_coalesce_s")),
            tenant=self.flag("tenant"),
        )
        peers = ServingPeerClient(
            RpcClient(),
            usrbio=bool(self.config.get("peer_usrbio")),
            entries=int(self.config.get("peer_ring_entries")),
            iov_bytes=int(self.config.get("peer_iov_bytes")),
        )
        # the directory reads routing on EVERY pick: hand it the cached
        # snapshot (kept fresh by the app's routing-poll loop), not the
        # per-call mgmtd RPC — membership is eventually consistent anyway
        self.fleet = FleetKVCache(
            kv, node_id=node_id, routing=self.mgmtd_client.routing,
            peer_client=peers,
            claim_ttl_ms=int(self.config.get("claim_ttl_ms")),
            claim_poll_ms=float(self.config.get("claim_poll_ms")),
            claim_polls=int(self.config.get("claim_polls")),
            singleflight_timeout_s=float(
                self.config.get("singleflight_timeout_s")),
            peer_est_bytes=int(self.config.get("peer_est_bytes")),
            capacity_bytes=int(self.config.get("capacity_bytes")),
            dirty_max_bytes=int(self.config.get("dirty_max_bytes")),
            write_through=bool(self.config.get("write_through")),
        )
        # ONE claim table per process: the host answers remote fillClaim
        # against the same table the local fill ladder claims from
        self.host = ServingHost(
            self.fleet, node_id, claims=self.fleet.claims,
            straggle_ms=float(self.flag("straggle_ms", "0") or 0),
        )
        bind_serving_service(server, self.host)
        if self.config.get("usrbio"):
            from tpu3fs.usrbio.server import (
                UsrbioRpcHost,
                bind_usrbio_service,
            )

            self._usrbio_host = UsrbioRpcHost(server)
            bind_usrbio_service(server, self._usrbio_host)

    # -- serving-directory lease ---------------------------------------------
    def _serving_register_once(self) -> bool:
        try:
            self.mgmtd_client.serving_register(
                self.info.node_id, self.info.hostname, self.info.port,
                ttl_s=float(self.config.get("serving_ttl_s")))
            return True
        except FsError as e:
            xlog("WARN", "serving %d register failed: %r",
                 self.info.node_id, e)
            return False

    def _serving_renew_loop(self) -> None:
        # renew at ttl/3 so two missed renewals still beat expiry
        while not self._stop.wait(
                max(1.0, float(self.config.get("serving_ttl_s")) / 3.0)):
            self._serving_register_once()

    def before_start(self) -> None:
        # self.info.port is final here (init_server bound the socket)
        self._serving_register_once()
        self.spawn(self._serving_renew_loop, "serving-renew")
        if self._usrbio_host is not None:
            self.spawn(self._usrbio_reap_loop, "usrbio-reap")

    def _usrbio_reap_loop(self) -> None:
        while not self._stop.wait(
                self.config.get("usrbio_reap_interval_s")):
            try:
                self._usrbio_host.reap_pass(
                    iov_max_age_s=self.config.get("usrbio_iov_max_age_s"))
            except Exception:
                pass

    def after_stop(self) -> None:
        try:
            self.mgmtd_client.serving_unregister(self.info.node_id)
        except Exception:
            pass  # TTL expiry prunes the directory entry
        if self._usrbio_host is not None:
            self._usrbio_host.stop()
        if self.fleet is not None:
            try:
                self.fleet.close()
            except Exception as e:
                xlog("WARN", "serving %d close: %r", self.info.node_id, e)


def main(argv: Optional[List[str]] = None) -> int:
    ServingApp(argv if argv is not None else sys.argv[1:]).run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
