"""ckpt_gc: standalone checkpoint-retention daemon.

The training-side twin of the trash cleaner (src/client/trash_cleaner):
connects to a live cluster like admin_cli (--connect HOST:PORT), then
periodically runs the retention sweep over one checkpoint root —
keep-last-N / keep-every-K eviction through the trash subsystem plus
stale ``.tmp`` reaping — under the ``ckpt`` QoS class so sweeps schedule
behind foreground IO.

With ``--archive-after N`` each tick ALSO auto-archives: committed steps
older than the newest N re-encode onto an erasure-coded layout
(CheckpointGC.archive_pass) — cold checkpoints stop paying replication's
capacity overhead without an operator ever issuing explicit archive
calls. The EC chains come from the cluster's routing table (filtered by
``--archive-ec-k/-m`` when given); already-EC steps are skipped, so the
sweep is idempotent.

    python -m tpu3fs.bin.ckpt_gc_main --connect HOST:PORT \
        [--root /ckpt] [--keep-last 3] [--keep-every 0] \
        [--trash-keep 86400] [--interval 300] [--once] \
        [--archive-after N] [--archive-ec-k K] [--archive-ec-m M] \
        [--archive-chunk-size BYTES]

Tests drive run_loop() directly against an in-process Fabric.
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import List, Optional

from tpu3fs.ckpt.retention import CheckpointGC, RetentionPolicy


def build_gc(fabric, args: argparse.Namespace) -> CheckpointGC:
    return CheckpointGC(
        fabric.meta,
        fabric.file_client(),
        root=args.root,
        policy=RetentionPolicy(keep_last=args.keep_last,
                               keep_every=args.keep_every),
        trash_keep_s=args.trash_keep,
        tmp_ttl_s=args.tmp_ttl,
    )


def ec_archive_layout(fabric, args: argparse.Namespace):
    """EC layout for auto-archival, from the live routing table: every
    SERVING EC chain (optionally filtered to EC(k, m)). None when the
    cluster has no matching EC chains — archival is then skipped, not an
    error, so one daemon config works across clusters."""
    from tpu3fs.meta.types import Layout

    routing = fabric.routing()
    chains = []
    for c in routing.chains.values():
        if not c.is_ec:
            continue
        if args.archive_ec_k and c.ec_k != args.archive_ec_k:
            continue
        if args.archive_ec_m and c.ec_m != args.archive_ec_m:
            continue
        chains.append(c.chain_id)
    if not chains:
        return None
    return Layout(table_id=1, chains=sorted(chains),
                  chunk_size=args.archive_chunk_size, seed=1)


def run_loop(fabric, args: argparse.Namespace, *, out=sys.stdout) -> int:
    """Sweep until stopped (or once); returns total steps evicted."""
    gc = build_gc(fabric, args)
    total = 0
    while True:
        removed = gc.run_once()
        total += removed
        archived = 0
        if args.archive_after > 0:
            layout = ec_archive_layout(fabric, args)
            if layout is None:
                print("ckpt-gc: no EC chains in routing; archive pass "
                      "skipped", file=out)
            else:
                archived = gc.archive_pass(
                    layout, keep_replicated=args.archive_after)
        print(f"ckpt-gc: root={gc.root} evicted={removed} "
              f"archived={archived} steps_left={len(gc.steps())}",
              file=out)
        if args.once:
            return total
        time.sleep(args.interval)


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(prog="ckpt_gc", description=__doc__)
    p.add_argument("--connect", metavar="HOST:PORT",
                   help="mgmtd address of a live cluster")
    p.add_argument("--token", default="", help="bearer token (auth mode)")
    p.add_argument("--root", default="/ckpt")
    p.add_argument("--keep-last", type=int, default=3)
    p.add_argument("--keep-every", type=int, default=0)
    p.add_argument("--trash-keep", type=int, default=86400,
                   help="seconds an evicted step stays recoverable")
    p.add_argument("--tmp-ttl", type=float, default=3600.0,
                   help="age before a crashed save's .tmp dir is reaped")
    p.add_argument("--interval", type=float, default=300.0)
    p.add_argument("--once", action="store_true")
    p.add_argument("--archive-after", type=int, default=0,
                   help="auto-archive steps older than the newest N onto "
                        "EC chains each tick (0 = off)")
    p.add_argument("--archive-ec-k", type=int, default=0,
                   help="only use EC chains with this k (0 = any)")
    p.add_argument("--archive-ec-m", type=int, default=0,
                   help="only use EC chains with this m (0 = any)")
    p.add_argument("--archive-chunk-size", type=int, default=1 << 20)
    return p.parse_args(argv)


def main(argv: Optional[List[str]] = None) -> int:  # pragma: no cover
    args = parse_args(argv if argv is not None else sys.argv[1:])
    if not args.connect:
        print("ckpt_gc: --connect HOST:PORT is required", file=sys.stderr)
        return 2
    from tpu3fs.cli import RpcFabricView

    host, port_s = args.connect.rsplit(":", 1)
    fabric = RpcFabricView((host, int(port_s)), token=args.token,
                           client_id="ckpt-gc")
    run_loop(fabric, args)
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
