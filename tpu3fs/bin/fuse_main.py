"""FUSE daemon binary (ref src/fuse/hf3fs_fuse.cpp + FuseClients.h:179-239).

Two-phase boot as a FUSE node: builds the mgmtd/meta/storage client stack
(the reference's FuseClients singleton), a USRBIO agent for 3fs-virt ring
registration, then mounts FuseOps at --mountpoint through libfuse.
"""

from __future__ import annotations

import sys
from typing import List, Optional

from tpu3fs.app.application import TwoPhaseApplication
from tpu3fs.client.file_io import FileIoClient
from tpu3fs.client.storage_client import StorageClient
from tpu3fs.fuse.mount import FuseMount
from tpu3fs.fuse.ops import FuseOps
from tpu3fs.mgmtd.types import NodeType
from tpu3fs.rpc.net import RpcServer
from tpu3fs.rpc.services import MetaRpcClient, RpcMessenger
from tpu3fs.usrbio.agent import UsrbioAgent
from tpu3fs.analytics.spans import TraceConfig
from tpu3fs.monitor.flight import FlightConfig
from tpu3fs.utils.config import Config, ConfigItem
from tpu3fs.utils.logging import xlog


class FuseAppConfig(Config):
    # observability: distributed tracing + monitor sample push
    # (tpu3fs/analytics/spans.py; both hot-configured)
    trace = TraceConfig
    # flight recorder (monitor/flight.py): bounded in-process black box
    # dumped on SLO breach / fatal signal / admin_cli flight-dump
    flight = FlightConfig
    collector = ConfigItem("", hot=True)   # host:port; "" = off
    monitor_push_period_s = ConfigItem(5.0, hot=True)
    mountpoint = ConfigItem("")
    fsname = ConfigItem("tpu3fs")
    # shared mounts want allow_other, but non-root mounts need
    # user_allow_other in /etc/fuse.conf — so it must be switchable
    allow_other = ConfigItem(False)


class FuseApp(TwoPhaseApplication):
    node_type = NodeType.FUSE

    def __init__(self, argv: Optional[List[str]] = None):
        super().__init__(argv)
        self.fuse: Optional[FuseMount] = None
        self.ops: Optional[FuseOps] = None

    def default_config(self) -> Config:
        return FuseAppConfig()

    def build_services(self, server: RpcServer) -> None:
        routing = self.mgmtd_client.refresh_routing()
        meta_addrs = [
            (n.host, n.port) for n in routing.nodes.values()
            if n.type == NodeType.META and n.port
        ]
        if not meta_addrs:
            raise SystemExit("no meta servers in routing info")
        meta = MetaRpcClient(meta_addrs,
                             client_id=f"fuse-{self.info.node_id}")
        # prefetch on: the mount is this client's single mutation path
        # (its own writes/truncates invalidate), and FUSE readers are the
        # sequential-scan workload readahead exists for
        fio = FileIoClient(StorageClient(
            f"fuse-{self.info.node_id}",
            lambda: self.mgmtd_client.routing(),
            RpcMessenger(lambda: self.mgmtd_client.routing()),
        ), prefetch=True)
        agent = UsrbioAgent(meta, fio, client_id=f"fuse-{self.info.node_id}")
        self.ops = FuseOps(meta, fio, agent)

    def before_start(self) -> None:
        mountpoint = self.flag("mountpoint") or self.config.get("mountpoint")
        if not mountpoint:
            raise SystemExit("--mountpoint is required")
        self.fuse = FuseMount(self.ops, mountpoint,
                              fsname=self.config.get("fsname"),
                              allow_other=self.config.get("allow_other"))
        self.fuse.mount()
        if not self.fuse.wait_mounted():
            raise SystemExit(f"mount at {mountpoint} failed "
                             f"(exit {self.fuse.exit_code})")
        xlog("INFO", "fuse mounted at %s", mountpoint)

    def after_stop(self) -> None:
        if self.fuse is not None:
            self.fuse.unmount()


def main(argv: Optional[List[str]] = None) -> int:
    FuseApp(argv if argv is not None else sys.argv[1:]).run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
