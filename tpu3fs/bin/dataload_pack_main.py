"""dataload_pack: pack local sample files into a tpu3fs record file.

The FFRecord-style ingest tool (the reference ships a companion packer
for exactly this): each input file becomes one record of a packed
record file (tpu3fs/dataload/recordio.py) — fixed header, per-record
offset index + CRC32C, atomic ``.tmp`` → rename commit — written into a
live cluster through the striped client write path.

    python -m tpu3fs.bin.dataload_pack_main --connect HOST:PORT \
        --out /data/train.rec SAMPLE_FILE... [--from-dir DIR]

    python -m tpu3fs.bin.dataload_pack_main --connect HOST:PORT \
        --inspect /data/train.rec

Tests drive run() directly against an in-process Fabric.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from tpu3fs.utils.result import Code, FsError


def _inputs(args: argparse.Namespace) -> List[str]:
    paths = list(args.files)
    if args.from_dir:
        paths.extend(
            os.path.join(args.from_dir, name)
            for name in sorted(os.listdir(args.from_dir))
            if os.path.isfile(os.path.join(args.from_dir, name)))
    return paths


def run(fabric, args: argparse.Namespace, *, out=sys.stdout) -> int:
    """Pack (or inspect) against any fabric-shaped object; returns an
    exit code."""
    from tpu3fs.dataload.recordio import RecordFile, RecordFileWriter

    fio = fabric.file_client()
    if args.inspect:
        rf = RecordFile.open(fabric.meta, fio, args.inspect)
        for k, v in rf.summary().items():
            print(f"{k}: {v}", file=out)
        return 0

    paths = _inputs(args)
    if not paths:
        print("dataload_pack: no input files", file=sys.stderr)
        return 2
    parent = args.out.rsplit("/", 1)[0]
    if parent:
        try:
            fabric.meta.mkdirs(parent, recursive=True)
        except FsError as e:
            if e.code != Code.META_EXISTS:
                raise
    writer = RecordFileWriter(fabric.meta, fio, args.out,
                              num_records=len(paths))
    total = 0
    try:
        for p in paths:
            with open(p, "rb") as f:
                payload = f.read()
            writer.append(payload)
            total += len(payload)
    except BaseException:
        writer.abort()
        raise
    rf = writer.commit()
    print(f"packed {rf.num_records} records, {total} payload bytes "
          f"-> {args.out}", file=out)
    return 0


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(prog="dataload_pack", description=__doc__)
    p.add_argument("--connect", metavar="HOST:PORT",
                   help="mgmtd address of a live cluster")
    p.add_argument("--token", default="", help="bearer token (auth mode)")
    p.add_argument("--out", default="",
                   help="destination record file path in the FS")
    p.add_argument("--from-dir", default="",
                   help="pack every regular file under DIR (sorted)")
    p.add_argument("--inspect", default="",
                   help="print a packed file's summary instead of packing")
    p.add_argument("files", nargs="*", help="local sample files to pack")
    args = p.parse_args(argv)
    if not args.inspect and not args.out:
        p.error("--out (or --inspect) is required")
    return args


def main(argv: Optional[List[str]] = None) -> int:  # pragma: no cover
    args = parse_args(argv if argv is not None else sys.argv[1:])
    if not args.connect:
        print("dataload_pack: --connect HOST:PORT is required",
              file=sys.stderr)
        return 2
    from tpu3fs.cli import RpcFabricView

    host, port_s = args.connect.rsplit(":", 1)
    fabric = RpcFabricView((host, int(port_s)), token=args.token,
                           client_id="dataload-pack")
    return run(fabric, args)


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
