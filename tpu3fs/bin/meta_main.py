"""meta service binary (ref src/meta/meta.cpp).

Two-phase boot; serves the MetaSerde ops over a transactional KV engine.
File-length-on-close and truncate go through a storage client over the RPC
messenger (ref src/meta/components/FileHelper.cc queryLastChunk); a GC loop
drains the deferred-removal queue against storage (ref GcManager background
scans). The chain allocator follows the chain table published in routing.
"""

from __future__ import annotations

import sys
from typing import List, Optional

from tpu3fs.app.application import TwoPhaseApplication
from tpu3fs.client.file_io import FileIoClient
from tpu3fs.client.storage_client import StorageClient
from tpu3fs.kv.mem import MemKVEngine
from tpu3fs.meta.store import ChainAllocator, MetaStore
from tpu3fs.mgmtd.types import NodeType
from tpu3fs.rpc.net import RpcServer
from tpu3fs.rpc.services import RpcMessenger, bind_meta_service
from tpu3fs.analytics.spans import TraceConfig
from tpu3fs.monitor.flight import FlightConfig
from tpu3fs.utils.config import Config, ConfigItem
from tpu3fs.qos.core import QosConfig
from tpu3fs.utils.fault_injection import FaultPlaneConfig
from tpu3fs.tenant.quota import TenantConfig


class MetaAppConfig(Config):
    # QoS admission limits for the meta RPC dispatch (tpu3fs/qos)
    qos = QosConfig
    # cluster fault plane (utils/fault_injection.py): hot-pushed
    # fault rules for chaos drives / gray-failure testing
    faults = FaultPlaneConfig
    # multi-tenant quota table (tpu3fs/tenant): per-tenant
    # WFQ weights + token-bucket limits, hot-pushed via mgmtd
    tenants = TenantConfig
    # observability: distributed tracing + monitor sample push
    # (tpu3fs/analytics/spans.py; both hot-configured)
    trace = TraceConfig
    # flight recorder (monitor/flight.py): bounded in-process black box
    # dumped on SLO breach / fatal signal / admin_cli flight-dump
    flight = FlightConfig
    collector = ConfigItem("", hot=True)   # host:port; "" = off
    monitor_push_period_s = ConfigItem(5.0, hot=True)
    chunk_size = ConfigItem(1 << 20)
    stripe = ConfigItem(1)
    gc_interval_s = ConfigItem(10.0, hot=True)
    chain_table_id = ConfigItem(1)
    # two-phase crash-resolver cadence (tpu3fs/metashard): each server
    # converges dangling rename/hardlink intents on its OWNED partitions
    resolve_interval_s = ConfigItem(2.0, hot=True)


class MetaApp(TwoPhaseApplication):
    node_type = NodeType.META

    def __init__(self, argv: Optional[List[str]] = None, *, engine=None):
        super().__init__(argv)
        # --kv host:port points at the shared network KV service (the
        # FoundationDB role; tpu3fs/bin/kv_main.py) so multiple meta servers
        # share one namespace; without it this instance owns a private MemKV
        # (single-node/dev mode)
        self.engine = engine or self._make_engine()
        self.meta: Optional[MetaStore] = None
        self._fio: Optional[FileIoClient] = None
        self._peer_rpc = None
        self._nparts = 0

    def _make_engine(self):
        from tpu3fs.kv.remote import engine_from_flag

        return engine_from_flag(self.flag("kv", ""))

    def default_config(self) -> Config:
        return MetaAppConfig()

    def _file_client(self) -> FileIoClient:
        if self._fio is None:
            messenger = RpcMessenger(lambda: self.mgmtd_client.routing())
            sc = StorageClient(
                f"meta-{self.info.node_id}",
                lambda: self.mgmtd_client.routing(),
                messenger,
            )
            self._fio = FileIoClient(sc)
        return self._fio

    def _cluster_space(self):
        si = self._file_client().storage.space_info()
        return si.capacity, si.used

    def _owned_partitions(self):
        """The set of partition ids assigned to THIS node by mgmtd, or
        None while the table is unpublished (own everything — single-node
        boot before the assigner's first tick)."""
        try:
            ri = self.mgmtd_client.routing()
        except Exception:
            return None
        if not ri.meta_partitions:
            return None
        return {pid for pid, row in ri.meta_partitions.items()
                if row.node_id == self.info.node_id}

    def _peer_client(self):
        """MetaRpcClient over the cluster's META nodes, routed by the
        partition table — carries two-phase participant RPCs
        (renamePrepare/renameFinish) to peer owners."""
        from tpu3fs.rpc.net import RpcClient
        from tpu3fs.rpc.services import MetaRpcClient

        ri = self.mgmtd_client.routing()
        addrs = [(n.host, n.port) for n in ri.nodes.values()
                 if n.type == NodeType.META and n.host]
        if self._peer_rpc is None:
            self._peer_rpc = RpcClient()
        return MetaRpcClient(
            addrs or [(self.info.hostname, self.info.port)],
            self._peer_rpc, client_id=f"meta-{self.info.node_id}",
            token=self.flag("token", ""), mgmtd=self.mgmtd_client,
            nparts=self._nparts)

    def build_services(self, server: RpcServer) -> None:
        routing = self.mgmtd_client.refresh_routing()
        table_id = self.config.get("chain_table_id")
        table = routing.chain_tables.get(table_id)
        chains = table.chain_ids if table else [1]
        hooks = dict(
            file_length_hook=lambda ino: self._file_client().file_length(ino),
            truncate_hook=lambda ino, ln: self._file_client().truncate_chunks(ino, ln),
            space_hook=self._cluster_space,
            default_chunk_size=self.config.get("chunk_size"),
            default_stripe=self.config.get("stripe"),
        )
        # --meta-partitions N: serve the sharded store (tpu3fs/metashard).
        # Unset = the published table's width when mgmtd has one (a sharded
        # fleet restart), else the legacy single-partition MetaStore —
        # sharding is opt-in, so multi-meta deployments without the flag
        # keep the any-op-anywhere shape. 0 = legacy explicitly.
        flag = self.flag("meta_partitions", "")
        self._peer_rpc = None
        nparts = int(flag) if flag else len(routing.meta_partitions)
        if nparts <= 0:
            self.meta = MetaStore(
                self.engine, ChainAllocator(table_id, chains), **hooks)
        else:
            from tpu3fs.metashard import ShardedMetaStore

            self._nparts = nparts

            def peer_prepare(pid, intent, dst_path):
                owned = self._owned_partitions()
                if owned is None or pid in owned:
                    # participant partition is local: apply in-process
                    from tpu3fs.meta.store import ROOT_USER

                    self.meta.twophase_prepare(intent, dst_path, ROOT_USER)
                else:
                    self._peer_client().rename_prepare(pid, intent, dst_path)

            def peer_finish(pid, txn_id):
                owned = self._owned_partitions()
                if owned is None or pid in owned:
                    self.meta.twophase_finish(txn_id)
                else:
                    self._peer_client().rename_finish(pid, txn_id)

            self.meta = ShardedMetaStore(
                self.engine, ChainAllocator(table_id, chains),
                nparts=self._nparts, owner_view=self._owned_partitions,
                peer_prepare=peer_prepare, peer_finish=peer_finish,
                **hooks)
        # --auth 1: enforce bearer-token authentication via the UserStore
        # in the shared KV (ref src/core/user; tokens resolved server-side)
        user_store = None
        if self.flag("auth", "") in ("1", "true", "yes"):
            from tpu3fs.core.user import UserStore

            user_store = UserStore(self.engine)
        bind_meta_service(server, self.meta, user_store=user_store,
                          tenant_mode=self.flag("tenant_mode", "enforce"))

    def meta_partition_loads(self):
        snap = getattr(self.meta, "snapshot_loads", None)
        if snap is None:
            return {}
        return {pid: float(n) for pid, n in snap().items()}

    def before_start(self) -> None:
        self.spawn(self._gc_loop, "meta-gc")
        if hasattr(self.meta, "resolve_intents"):
            self.spawn(self._resolver_loop, "meta-twophase-resolver")

    def _resolver_loop(self) -> None:
        """Converge dangling two-phase intents on OWNED partitions — a
        reassigned partition's new owner rolls a dead coordinator's
        in-flight renames forward/back (docs/metashard.md crash matrix)."""
        while not self._stop.wait(self.config.get("resolve_interval_s")):
            try:
                self.meta.resolve_intents(pids=self._owned_partitions())
            except Exception:
                pass

    def run_gc(self) -> int:
        from tpu3fs.qos.core import TrafficClass, tagged

        removed = 0
        fio = self._file_client()
        # chunk removals are GC-class traffic: the storage-side QoS
        # scheduler keeps them behind foreground IO (tpu3fs/qos)
        with tagged(TrafficClass.GC):
            for inode in self.meta.gc_scan():
                if self.meta.has_sessions(inode.id):
                    continue
                fio.remove_chunks(inode)
                self.meta.gc_finish(inode.id)
                removed += 1
        return removed

    def _gc_loop(self) -> None:
        while not self._stop.wait(self.config.get("gc_interval_s")):
            try:
                self.run_gc()
            except Exception:
                pass


def main(argv: Optional[List[str]] = None) -> int:
    MetaApp(argv if argv is not None else sys.argv[1:]).run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
