"""Static tenant-quota enforcement classification of every RPC method.

Tenant quotas (tenant/quota.py) are charged at admission — but WHICH
admission, and on WHAT axis, is a static property of each method, so it
lives in one table that ``tools/check_rpc_registry.py`` enforces against
every bound service method (check 6, the idempotency-table pattern): a
new method without a classification fails CI, and a data-plane method
(one whose untagged QoS classification is foreground read/write) can
never silently classify EXEMPT and dodge quota enforcement.

Classification values:

- ``bytes``: charged ops + payload bytes against the tenant's
  iops/bytes_per_s buckets. Storage data-plane methods enforce INSIDE
  the service (craq read/write admission, where the true payload sizes
  are known and the in-process fabric path is covered); everything else
  enforces at RPC dispatch using the frame size.
- ``iops``: charged ops only (metadata ops: the payload is not the
  resource being protected).
- ``exempt``: control-plane traffic (heartbeats, routing, config,
  cluster internals). Never quota-charged — throttling a heartbeat
  under a tenant's quota would convert one tenant's flood into a
  cluster-membership incident. Exempt methods still RESOLVE a tenant
  (identity.resolved_tenant) so spans and recorders stay attributed.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

BYTES = "bytes"
IOPS = "iops"
EXEMPT = "exempt"

#: (service name, method name) -> classification. check_rpc_registry
#: verifies this table covers every bound method and carries no stale
#: rows, so it IS the registry.
ENFORCEMENT: Dict[Tuple[str, str], str] = {
    # -- StorageSerde (enforced in-service: craq._admit_read/_admit_write
    #    charge the tenant buckets with true payload sizes) --------------
    ("StorageSerde", "write"): BYTES,
    ("StorageSerde", "update"): BYTES,       # chain-internal: head charged
    ("StorageSerde", "read"): BYTES,
    ("StorageSerde", "dumpChunkMeta"): EXEMPT,
    ("StorageSerde", "syncDone"): EXEMPT,
    ("StorageSerde", "removeChunk"): IOPS,
    ("StorageSerde", "removeFileChunks"): IOPS,
    ("StorageSerde", "queryLastChunk"): IOPS,
    ("StorageSerde", "truncateChunks"): IOPS,
    ("StorageSerde", "spaceInfo"): EXEMPT,
    ("StorageSerde", "batchRead"): BYTES,
    ("StorageSerde", "batchWrite"): BYTES,
    ("StorageSerde", "writeShard"): BYTES,
    ("StorageSerde", "batchWriteShard"): BYTES,
    ("StorageSerde", "batchUpdate"): BYTES,  # chain-internal: head charged
    ("StorageSerde", "statChunks"): IOPS,
    ("StorageSerde", "pruneClientChannels"): EXEMPT,
    ("StorageSerde", "offlineTarget"): EXEMPT,
    # EC recovery reads go through the byte-charging read gate, which
    # skips tenant buckets for background classes (system work)
    ("StorageSerde", "readRebuild"): BYTES,
    ("StorageSerde", "dumpPendingChunkMeta"): EXEMPT,
    ("StorageSerde", "batchReadRebuild"): BYTES,
    # chain-encode: the head hop charges the whole batch; chain-internal
    # hops pass free like update/batchUpdate (charged at entry)
    ("StorageSerde", "chainEncodeWrite"): BYTES,
    # -- MetaSerde (enforced at RPC dispatch: iops buckets) ---------------
    ("MetaSerde", "statFs"): IOPS,
    ("MetaSerde", "stat"): IOPS,
    ("MetaSerde", "create"): IOPS,
    ("MetaSerde", "mkdirs"): IOPS,
    ("MetaSerde", "symlink"): IOPS,
    ("MetaSerde", "hardLink"): IOPS,
    ("MetaSerde", "remove"): IOPS,
    ("MetaSerde", "open"): IOPS,
    ("MetaSerde", "sync"): IOPS,
    ("MetaSerde", "close"): IOPS,
    ("MetaSerde", "rename"): IOPS,
    ("MetaSerde", "list"): IOPS,
    ("MetaSerde", "truncate"): IOPS,
    ("MetaSerde", "getRealPath"): IOPS,
    ("MetaSerde", "setAttr"): IOPS,
    ("MetaSerde", "pruneSession"): EXEMPT,
    ("MetaSerde", "batchStat"): IOPS,
    ("MetaSerde", "authenticate"): EXEMPT,   # the op that NAMES a tenant
    ("MetaSerde", "setXattr"): IOPS,
    ("MetaSerde", "getXattr"): IOPS,
    ("MetaSerde", "listXattrs"): IOPS,
    ("MetaSerde", "removeXattr"): IOPS,
    ("MetaSerde", "batchClose"): IOPS,
    ("MetaSerde", "batchSetAttr"): IOPS,
    ("MetaSerde", "batchCreate"): IOPS,
    ("MetaSerde", "batchMkdirs"): IOPS,
    # two-phase participant plane (tpu3fs/metashard): server-to-server
    # internals riding the coordinator's already-charged op — like chain
    # forwarding, charging them again would double-bill the rename
    ("MetaSerde", "renamePrepare"): EXEMPT,
    ("MetaSerde", "renameFinish"): EXEMPT,
    ("MetaSerde", "renameResolve"): EXEMPT,
    # -- Usrbio ring registration: control plane (the data plane rides
    #    StorageSerde methods, which keep their bytes/iops classification
    #    and are charged at ring dequeue through dispatch_packet) --------
    ("Usrbio", "usrbioHandshake"): EXEMPT,
    ("Usrbio", "usrbioRegister"): EXEMPT,
    ("Usrbio", "usrbioDeregister"): EXEMPT,
    # -- Mgmtd / Core / Kv / internals: control plane ---------------------
    ("Mgmtd", "heartbeat"): EXEMPT,
    ("Mgmtd", "getRoutingInfo"): EXEMPT,
    ("Mgmtd", "registerNode"): EXEMPT,
    ("Mgmtd", "createTarget"): EXEMPT,
    ("Mgmtd", "uploadChain"): EXEMPT,
    ("Mgmtd", "uploadChainTable"): EXEMPT,
    ("Mgmtd", "setConfig"): EXEMPT,
    ("Mgmtd", "getConfig"): EXEMPT,
    ("Mgmtd", "tick"): EXEMPT,
    # elasticity / migration control plane: operator + worker traffic;
    # the DATA the workers move is charged/classified where it flows
    # (StorageSerde methods under the migration/ec_rebuild classes,
    # which are BACKGROUND — system work, never tenant-charged)
    ("Mgmtd", "addChainTarget"): EXEMPT,
    ("Mgmtd", "dropChainTarget"): EXEMPT,
    ("Mgmtd", "setNodeTags"): EXEMPT,
    ("Mgmtd", "migrationSubmit"): EXEMPT,
    ("Mgmtd", "migrationList"): EXEMPT,
    ("Mgmtd", "migrationClaim"): EXEMPT,
    ("Mgmtd", "migrationReport"): EXEMPT,
    ("Mgmtd", "servingRegister"): EXEMPT,
    ("Mgmtd", "servingUnregister"): EXEMPT,
    ("Core", "echo"): EXEMPT,
    ("Core", "renderConfig"): EXEMPT,
    ("Core", "hotUpdateConfig"): EXEMPT,
    ("Core", "shutdown"): EXEMPT,
    ("Core", "getConfig"): EXEMPT,
    ("Core", "getLastConfigUpdateRecord"): EXEMPT,
    ("Core", "flightDump"): EXEMPT,
    ("Kv", "snapshot"): EXEMPT,
    ("Kv", "get"): EXEMPT,
    ("Kv", "getRange"): EXEMPT,
    ("Kv", "commit"): EXEMPT,
    ("Kv", "release"): EXEMPT,
    ("KvRepl", "appendEntries"): EXEMPT,
    ("KvRepl", "requestVote"): EXEMPT,
    ("KvRepl", "installSnapshot"): EXEMPT,
    ("KvRepl", "status"): EXEMPT,
    ("KvRepl", "reconfig"): EXEMPT,
    ("MonitorCollector", "write"): EXEMPT,   # every binary's own push loop
    ("MonitorCollector", "query"): EXEMPT,
    ("MonitorCollector", "aggQuery"): EXEMPT,   # operator/SLO surface
    ("MonitorCollector", "sloStatus"): EXEMPT,
    # -- SimpleExample ----------------------------------------------------
    ("SimpleExample", "write"): BYTES,
    ("SimpleExample", "read"): BYTES,
    # -- Serving (fleet KVCache peer-fill, tpu3fs/serving) ----------------
    # peerRead dispatch charges IOPS only: the REQUESTER charges the
    # peer-filled payload bytes against its own tenant with the true
    # size (FleetKVCache._admit_peer_bytes, ops+bytes+resident gate), so
    # every byte is charged exactly once and a peer fill can never
    # launder a tenant's bytes through another process's quota.
    ("Serving", "peerRead"): IOPS,
    ("Serving", "fillClaim"): EXEMPT,       # fill-intent lease, tiny frames
    ("Serving", "fillRelease"): EXEMPT,
    ("Serving", "servingStats"): EXEMPT,
    # bench/driver workload surface: the cache ops it runs charge
    # through the normal kvcache client paths underneath
    ("Serving", "servingLoad"): EXEMPT,
}


def enforcement_of(service: str, method: str) -> Optional[str]:
    """Classification for one bound method, or None when unclassified
    (which the static registry check turns into a CI failure)."""
    return ENFORCEMENT.get((service, method))


def quota_enforced(service: str, method: str) -> bool:
    return ENFORCEMENT.get((service, method)) in (BYTES, IOPS)
