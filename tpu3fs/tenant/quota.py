"""Distributed tenant quotas: a hot-configurable table of per-tenant
token-bucket limits, enforced at admission.

The table is ONE spec string riding the existing mgmtd config machinery
(``[tenants] spec=...`` — the fault-plane pattern), so a single config
push retunes every node's quota enforcement live, no restart. Each node
enforces its own buckets: for N storage nodes a tenant's cluster-wide
throughput caps at ~N x its per-node rate, exactly like the reference's
per-node admission — the operator sets per-node rates, the placement
layer spreads tenants, and the monitor's per-tenant recorders
(``tenant.*``) verify the aggregate.

Spec grammar — entries separated by ``;``, fields by ``,``::

    tenant=default,weight=1;
    tenant=alice,weight=4,bytes_per_s=8388608,iops=500,kvcache_bytes=1073741824

- ``weight``: the tenant's share inside its traffic class's nested WFQ
  lane (qos/scheduler.py) — two ``fg`` tenants split the class's
  capacity weight:weight instead of FIFO luck;
- ``bytes_per_s`` / ``iops``: token-bucket rates (0 = unlimited; burst =
  ``burst_s`` seconds of rate). Sheds answer the retryable
  ``Code.TENANT_THROTTLED`` with a retry-after hint the client ladders
  honor (client/storage_client.py);
- ``kvcache_bytes``: resident-bytes budget for the inference KV-cache
  tier — writers shed once their tenant's measured resident gauge
  exceeds it, and the kvcache GC daemon's capacity pass evicts back
  under it (bin/kvcache_gc_main.py);
- ``tenant=default`` overrides the limits applied to every tenant
  WITHOUT an explicit row (including untenanted legacy traffic).

Background classes (resync/EC-rebuild/migration/GC/ckpt) are exempt from
tenant buckets: recovery is the system's own work, already metered by
its class limits — throttling it under a client's quota would turn one
tenant's flood into everyone's durability problem.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from tpu3fs.tenant.identity import DEFAULT_TENANT, valid_tenant
from tpu3fs.utils.config import Config, ConfigItem


@dataclass
class TenantQuota:
    """One tenant's limits; 0 anywhere = unlimited on that axis."""

    weight: int = 1          # nested-WFQ share inside the traffic class
    bytes_per_s: float = 0.0
    iops: float = 0.0
    kvcache_bytes: int = 0   # resident-bytes budget (kvcache tier)
    burst_s: float = 1.0     # bucket depth, seconds of rate


def parse_spec(spec: str) -> Dict[str, TenantQuota]:
    """Parse a quota-table spec; malformed entries raise ValueError (a
    config push must reject bad specs atomically, ConfigBase rules)."""
    out: Dict[str, TenantQuota] = {}
    for entry in (spec or "").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        fields: Dict[str, str] = {}
        for part in entry.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"tenant spec field without '=': {part!r}")
            k, v = part.split("=", 1)
            fields[k.strip()] = v.strip()
        name = fields.pop("tenant", "")
        if not valid_tenant(name):
            raise ValueError(f"tenant spec entry with bad tenant=: {entry!r}")
        try:
            q = TenantQuota(
                weight=int(fields.pop("weight", 1)),
                bytes_per_s=float(fields.pop("bytes_per_s", 0.0)),
                iops=float(fields.pop("iops", 0.0)),
                kvcache_bytes=int(fields.pop("kvcache_bytes", 0)),
                burst_s=float(fields.pop("burst_s", 1.0)),
            )
        except ValueError as e:
            raise ValueError(f"tenant spec entry {entry!r}: {e}")
        if fields:
            raise ValueError(
                f"tenant spec entry {entry!r}: unknown fields "
                f"{sorted(fields)}")
        if q.weight < 1 or q.bytes_per_s < 0 or q.iops < 0 \
                or q.kvcache_bytes < 0 or q.burst_s <= 0:
            raise ValueError(f"tenant spec entry {entry!r}: out of range")
        if name in out:
            raise ValueError(f"tenant {name!r} listed twice")
        out[name] = q
    return out


def _check_spec(spec: str) -> bool:
    try:
        parse_spec(spec)
        return True
    except ValueError:
        return False


class TenantConfig(Config):
    """The hot-updatable ``[tenants]`` section every service binary
    carries. An empty spec = no quotas (weights default to 1, buckets
    unlimited) — tenancy still ATTRIBUTES (recorders, spans, nested WFQ
    lanes) even before an operator configures enforcement."""

    enabled = ConfigItem(True, hot=True)
    spec = ConfigItem("", hot=True, checker=_check_spec,
                      doc="semicolon-separated tenant quota rows; see "
                          "docs/tenancy.md")
    shed_retry_after_ms = ConfigItem(50, hot=True, checker=lambda v: v >= 1)


class _Bucket:
    """Minimal token bucket (qos.core.TokenBucket shape, kept local so
    the tenant plane has no import cycle with qos). rate <= 0 =
    unlimited; try_acquire returns 0.0 or the refill horizon seconds."""

    __slots__ = ("_lock", "_rate", "_burst", "_tokens", "_last")

    def __init__(self, rate: float, burst: float):
        self._lock = threading.Lock()
        self._rate = float(rate)
        self._burst = max(1.0, float(burst))
        self._tokens = self._burst
        self._last = time.monotonic()

    def configure(self, rate: float, burst: float) -> None:
        with self._lock:
            self._refill()
            was_unlimited = self._rate <= 0
            self._rate = float(rate)
            self._burst = max(1.0, float(burst))
            if was_unlimited:
                # the unlimited period kept the bucket conceptually full:
                # a freshly-introduced rate starts from its whole burst
                self._tokens = self._burst
            self._tokens = min(self._tokens, self._burst)

    def _refill(self) -> None:
        now = time.monotonic()
        if self._rate > 0:
            self._tokens = min(
                self._burst, self._tokens + (now - self._last) * self._rate)
        else:
            # an unlimited bucket stays FULL: when a config push later
            # introduces a rate, the tenant starts with its whole burst
            # instead of whatever residue the unlimited period left
            self._tokens = self._burst
        self._last = now

    def try_acquire(self, cost: float) -> float:
        if self._rate <= 0:
            return 0.0
        with self._lock:
            self._refill()
            if self._tokens >= cost:
                self._tokens -= cost
                return 0.0
            return (cost - self._tokens) / self._rate


class TenantRegistry:
    """Process-global tenant state: the quota table, per-tenant buckets,
    kvcache resident gauges and the ``tenant.*`` recorders.

    One registry per process (``registry()``), bound to the binary's
    ``[tenants]`` config section by ``apply_tenant_config`` so hot pushes
    reconfigure buckets in place (in-flight references stay valid, the
    AdmissionController.reload discipline)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.enabled = True
        self.retry_after_ms = 50
        self._table: Dict[str, TenantQuota] = {}
        self._default = TenantQuota()
        # (tenant, axis) -> bucket; axis in {"bytes", "iops"}
        self._buckets: Dict[Tuple[str, str], _Bucket] = {}
        # tenant -> measured kvcache resident bytes (set by the GC
        # daemon's scans / charged incrementally by writers)
        self._kv_resident: Dict[str, float] = {}
        # recorder caches (lazy per tenant; see _recs)
        self._rec_admitted: Dict[str, object] = {}
        self._rec_bytes: Dict[str, object] = {}
        self._rec_shed: Dict[Tuple[str, str], object] = {}
        self._rec_wait: Dict[str, object] = {}
        self._rec_kv: Dict[str, object] = {}
        # process-lifetime totals (tests/drives; monitor counters reset
        # every collection window, these never do)
        self._totals: Dict[str, Dict[str, float]] = {}
        # reload hooks: fired after every configure() so mirrors of the
        # quota table (the native transport's C-side tenant gate,
        # rpc/native_net.py) re-sync on hot pushes — the same discipline
        # as AdmissionController.add_reload_hook
        self._reload_hooks: list = []

    def add_reload_hook(self, fn) -> None:
        self._reload_hooks.append(fn)
        try:
            fn(self)
        except Exception:
            pass

    def table_snapshot(self) -> Dict[str, TenantQuota]:
        """The configured rows (copy) — mirrors push exactly what an
        operator configured, never the lazily-minted per-tenant state."""
        with self._lock:
            return dict(self._table)

    # -- configuration ----------------------------------------------------
    def configure(self, spec: str, *, enabled: bool = True,
                  retry_after_ms: int = 50) -> None:
        """Install a quota table (atomic: a bad spec raises and leaves
        the previous table live). Existing buckets are reconfigured in
        place; tenants dropped from the table fall back to default."""
        table = parse_spec(spec)
        with self._lock:
            self._table = table
            self._default = table.get(DEFAULT_TENANT, TenantQuota())
            self.enabled = bool(enabled)
            self.retry_after_ms = int(retry_after_ms)
            for (tenant, axis), b in self._buckets.items():
                q = table.get(tenant, self._default)
                rate = q.bytes_per_s if axis == "bytes" else q.iops
                b.configure(rate, max(1.0, rate * q.burst_s))
        for fn in list(self._reload_hooks):
            try:
                fn(self)
            except Exception:
                pass

    def clear(self) -> None:
        """Tests/drives: back to the permissive boot state."""
        self.configure("")

    def quota(self, tenant: str) -> TenantQuota:
        with self._lock:
            return self._table.get(tenant, self._default)

    def weight(self, tenant: str) -> int:
        return max(1, int(self.quota(tenant).weight))

    def kvcache_budget(self, tenant: str) -> int:
        return int(self.quota(tenant).kvcache_bytes)

    # -- recorders --------------------------------------------------------
    # ONE declaration site per tenant.* name (recorder-registry rule);
    # instances are minted lazily per tenant and held strongly here.
    def _admitted_rec(self, tenant: str):
        rec = self._rec_admitted.get(tenant)
        if rec is None:
            from tpu3fs.monitor.recorder import CounterRecorder

            tags = {"tenant": tenant}
            rec = CounterRecorder("tenant.admitted", tags)
            self._rec_admitted[tenant] = rec
        return rec

    def _bytes_rec(self, tenant: str):
        rec = self._rec_bytes.get(tenant)
        if rec is None:
            from tpu3fs.monitor.recorder import CounterRecorder

            tags = {"tenant": tenant}
            rec = CounterRecorder("tenant.bytes", tags)
            self._rec_bytes[tenant] = rec
        return rec

    def _shed_rec(self, tenant: str, kind: str):
        rec = self._rec_shed.get((tenant, kind))
        if rec is None:
            from tpu3fs.monitor.recorder import CounterRecorder

            tags = {"tenant": tenant, "kind": kind}
            rec = CounterRecorder("tenant.shed", tags)
            self._rec_shed[(tenant, kind)] = rec
        return rec

    def _wait_rec(self, tenant: str):
        rec = self._rec_wait.get(tenant)
        if rec is None:
            from tpu3fs.monitor.recorder import DistributionRecorder

            tags = {"tenant": tenant}
            rec = DistributionRecorder("tenant.queue_wait_us", tags)
            self._rec_wait[tenant] = rec
        return rec

    def _kv_rec(self, tenant: str):
        rec = self._rec_kv.get(tenant)
        if rec is None:
            from tpu3fs.monitor.recorder import ValueRecorder

            tags = {"tenant": tenant}
            rec = ValueRecorder("tenant.kvcache_bytes", tags)
            self._rec_kv[tenant] = rec
        return rec

    def _count(self, tenant: str, key: str, n: float = 1.0) -> None:
        with self._lock:
            t = self._totals.setdefault(tenant, {})
            t[key] = t.get(key, 0.0) + n

    # -- accounting (AdmissionController hook) ----------------------------
    def account_admit(self, tenant: str) -> None:
        """Per-tenant attribution of a CLASS-admission admit (called by
        qos.core.AdmissionController so `tenant.admitted` mirrors
        `qos.admitted` with a tenant tag)."""
        self._admitted_rec(tenant).add()
        self._count(tenant, "admitted")

    def account_shed(self, tenant: str) -> None:
        """Class-level shed attributed to its tenant (kind=class: the op
        was shed by its CLASS's limits, not the tenant's own quota)."""
        self._shed_rec(tenant, "class").add()
        self._count(tenant, "shed_class")

    def record_queue_wait(self, tenant: str, wait_s: float) -> None:
        self._wait_rec(tenant).record(wait_s * 1e6)

    # -- quota enforcement ------------------------------------------------
    def _bucket(self, tenant: str, axis: str) -> _Bucket:
        key = (tenant, axis)
        b = self._buckets.get(key)
        if b is None:
            with self._lock:
                b = self._buckets.get(key)
                if b is None:
                    q = self._table.get(tenant, self._default)
                    rate = q.bytes_per_s if axis == "bytes" else q.iops
                    b = _Bucket(rate, max(1.0, rate * q.burst_s))
                    self._buckets[key] = b
        return b

    def try_admit(self, tenant: str, *, ops: float = 1.0, nbytes: int = 0,
                  kv_charge: bool = False) -> Optional[int]:
        """Charge one op (or batch) against the tenant's quota buckets.
        -> None when admitted, else the retry-after hint (ms) for the
        TENANT_THROTTLED reply. Order: iops, then bytes, then the kvcache
        resident gate (cheapest refusal first); an iops take that then
        sheds on bytes is deliberately not refunded — the partial charge
        biases AGAINST a tenant already over one axis."""
        if not self.enabled:
            return None
        base = self.retry_after_ms
        wait = self._bucket(tenant, "iops").try_acquire(max(1.0, ops))
        if wait > 0.0:
            self._shed_rec(tenant, "iops").add(int(max(1, ops)))
            self._count(tenant, "shed_iops", max(1, ops))
            return max(base, int(wait * 1000) + 1)
        if nbytes > 0:
            wait = self._bucket(tenant, "bytes").try_acquire(float(nbytes))
            if wait > 0.0:
                self._shed_rec(tenant, "bytes").add(int(max(1, ops)))
                self._count(tenant, "shed_bytes", max(1, ops))
                return max(base, int(wait * 1000) + 1)
            self._bytes_rec(tenant).add(nbytes)
            self._count(tenant, "bytes", nbytes)
        if kv_charge:
            budget = self.kvcache_budget(tenant)
            if budget > 0 and self._kv_resident.get(tenant, 0.0) > budget:
                self._shed_rec(tenant, "kvcache").add(int(max(1, ops)))
                self._count(tenant, "shed_kvcache", max(1, ops))
                return base
        return None

    def shed_kvcache(self, tenant: str, n: int = 1) -> None:
        """Count a writer-side kvcache-budget shed (kvcache/cache.py)."""
        self._shed_rec(tenant, "kvcache").add(n)
        self._count(tenant, "shed_kvcache", n)

    # -- kvcache resident gauge -------------------------------------------
    def charge_kvcache(self, tenant: str, delta: int) -> None:
        """Incremental resident-bytes estimate from the writer's side
        (authoritative numbers come from set_kvcache_resident scans)."""
        with self._lock:
            v = max(0.0, self._kv_resident.get(tenant, 0.0) + delta)
            self._kv_resident[tenant] = v
        self._kv_rec(tenant).set(v)

    def set_kvcache_resident(self, tenant: str, nbytes: int) -> None:
        """Authoritative per-tenant resident bytes from a GC scan."""
        with self._lock:
            self._kv_resident[tenant] = float(max(0, nbytes))
        self._kv_rec(tenant).set(float(max(0, nbytes)))

    def kvcache_resident(self, tenant: str) -> int:
        with self._lock:
            return int(self._kv_resident.get(tenant, 0.0))

    def kvcache_over(self, tenant: str) -> bool:
        budget = self.kvcache_budget(tenant)
        return budget > 0 and self.kvcache_resident(tenant) > budget

    # -- views ------------------------------------------------------------
    def totals(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {t: dict(v) for t, v in self._totals.items()}

    def snapshot(self) -> Dict[str, dict]:
        """Per-tenant quota + live totals for the admin CLI."""
        with self._lock:
            names = set(self._table) | set(self._totals) \
                | {t for t, _ in self._buckets} | {DEFAULT_TENANT}
            out: Dict[str, dict] = {}
            for name in sorted(names):
                q = self._table.get(name, self._default)
                tot = self._totals.get(name, {})
                out[name] = {
                    "weight": q.weight,
                    "bytes_per_s": q.bytes_per_s,
                    "iops": q.iops,
                    "kvcache_bytes": q.kvcache_bytes,
                    "explicit": name in self._table,
                    "kv_resident": int(self._kv_resident.get(name, 0.0)),
                    "admitted": int(tot.get("admitted", 0)),
                    "bytes": int(tot.get("bytes", 0)),
                    "shed": int(tot.get("shed_iops", 0)
                                + tot.get("shed_bytes", 0)
                                + tot.get("shed_kvcache", 0)),
                    "shed_class": int(tot.get("shed_class", 0)),
                }
            return out

    def shed_total(self, tenant: str) -> int:
        """Quota sheds (all axes) for one tenant, process lifetime."""
        with self._lock:
            t = self._totals.get(tenant, {})
            return int(t.get("shed_iops", 0) + t.get("shed_bytes", 0)
                       + t.get("shed_kvcache", 0))


_REGISTRY = TenantRegistry()


def registry() -> TenantRegistry:
    return _REGISTRY


def apply_tenant_config(cfg: TenantConfig,
                        target: Optional[TenantRegistry] = None) -> None:
    """Bind a ``[tenants]`` config section to a registry and follow its
    hot updates (service binaries call this once at boot)."""
    reg = target if target is not None else _REGISTRY

    def _apply(_node=None):
        try:
            reg.configure(cfg.spec, enabled=bool(cfg.enabled),
                          retry_after_ms=int(cfg.shed_retry_after_ms))
        except ValueError:
            pass  # checker already rejected; belt and braces

    _apply()
    cfg.add_callback(_apply)
