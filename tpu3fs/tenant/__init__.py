"""Multi-tenant fairness: tenant identity on every RPC, nested
per-tenant weighted-fair queuing inside each traffic class, distributed
token-bucket quotas, and per-tenant attribution (docs/tenancy.md).

- ``identity``: the ContextVar + envelope carriage of the tenant id;
- ``quota``: the hot-configurable quota table + per-tenant buckets,
  enforced at admission with the retryable ``Code.TENANT_THROTTLED``;
- ``enforcement``: the static per-method enforcement classification
  checked by tools/check_rpc_registry.py (check 6).
"""

from tpu3fs.tenant.identity import (  # noqa: F401
    DEFAULT_TENANT,
    current_tenant,
    decode_tenant,
    resolved_tenant,
    tenant_scope,
    valid_tenant,
)
from tpu3fs.tenant.quota import (  # noqa: F401
    TenantConfig,
    TenantQuota,
    TenantRegistry,
    apply_tenant_config,
    registry,
)
