"""Tenant identity: who owns a request, end to end.

The north star is "heavy traffic from millions of users"; QoS (tpu3fs/qos)
made traffic fair across CLASSES, but one greedy client inside ``fg``
could still starve its peers. This module gives every operation an OWNER
— a compact tenant id — the way the reference attributes work per user
(token-authenticated UserStore identities, per-user metric tags via
``monitor::instanceTagSet``), carried on the same two channels the QoS
class, the trace context and the deadline already ride:

1. IN-PROCESS: a ``contextvars.ContextVar`` (``tenant_scope`` /
   ``current_tenant``). The same machinery that carries the traffic
   class means the tenant follows fanned-out IO for free: WorkerPool
   tasks run inside ``contextvars.copy_context()`` snapshots,
   ``_OverlapForward`` helper threads snapshot their spawning context,
   the prefetcher deliberately DETACHES, and the update worker captures
   the submitter's tenant per job (storage/update_worker.py).
2. ON THE WIRE: a ``u1.<tenant>`` token appended to the request
   envelope's ``message`` field, composing with the trace (``t1.*``) and
   deadline (``d1.*``) tokens — the field every decoder, old or new,
   python or native, already parses and ignores on requests, so the
   encoding is version-tolerant in BOTH directions exactly like
   TraceContext: an old server keeps its trace + deadline and ignores
   the tenant; a new server parses all three.

Wire forms (dot-separated tokens; append order trace, deadline, tenant)::

    u1.<tenant>                              bare tenant
    d1.<micros-hex>.u1.<tenant>              deadline + tenant
    t1.<tid>.<sid>.<flags>.u1.<tenant>       trace + tenant
    t1.<tid>.<sid>.<flags>.d1.<hex>.u1.<tenant>   all three

Tenant names are restricted to ``[a-z0-9_-]`` (1..64 chars): no dots, so
a name can never be confused with a token boundary. An absent/invalid
tenant resolves to ``DEFAULT_TENANT`` ("default") — every dispatch path
resolves SOME tenant (tools/check_rpc_registry.py check 6), so quota
enforcement and per-tenant recorders never see an unowned op.
"""

from __future__ import annotations

import contextlib
import contextvars
import re
from typing import Optional

#: wire token introducing the tenant field (the tenant name follows)
WIRE_TOKEN = "u1"

#: the owner of untenanted traffic (legacy clients, internal daemons)
DEFAULT_TENANT = "default"

_NAME_RE = re.compile(r"^[a-z0-9_-]{1,64}$")

_tenant_var: contextvars.ContextVar[Optional[str]] = \
    contextvars.ContextVar("tpu3fs_tenant", default=None)


def valid_tenant(name: str) -> bool:
    """True iff `name` is a legal tenant id (wire-safe: no dots)."""
    return bool(name) and _NAME_RE.match(name) is not None


# -- context propagation ------------------------------------------------------

def current_tenant() -> Optional[str]:
    """The ambient tenant id, or None when untenanted."""
    return _tenant_var.get()


def resolved_tenant() -> str:
    """The ambient tenant, defaulted: every caller gets an owner."""
    t = _tenant_var.get()
    return t if t else DEFAULT_TENANT


@contextlib.contextmanager
def tenant_scope(tenant: Optional[str]):
    """Arm a tenant id for the block (None/"" = no-op passthrough).
    Unlike deadlines there is no tightening rule: the INNERMOST explicit
    scope wins — a service re-issuing IO on behalf of a client keeps the
    client's tenant simply by not re-scoping. Invalid names raise."""
    if not tenant:
        yield None
        return
    if not valid_tenant(tenant):
        raise ValueError(f"invalid tenant id: {tenant!r}")
    token = _tenant_var.set(tenant)
    try:
        yield tenant
    finally:
        _tenant_var.reset(token)


# -- envelope carriage --------------------------------------------------------

def append_wire(message: str, tenant: Optional[str]) -> str:
    """Append the tenant token to an (optionally empty) envelope message
    already carrying trace and/or deadline tokens. Invalid names are
    dropped rather than corrupting the envelope (belt and braces — the
    scope constructor already refuses them)."""
    if not tenant or not valid_tenant(tenant):
        return message or ""
    tok = f"{WIRE_TOKEN}.{tenant}"
    return f"{message}.{tok}" if message else tok


def decode_tenant(message: str) -> Optional[str]:
    """Parse the tenant off a request envelope message; None for absent,
    malformed or future encodings. Tokens are positional — the scan
    starts after the 4 trace fields when the message is traced, then
    steps over 2-field tokens (``d1``, unknown future tokens) until it
    finds ``u1`` — so a trace/span id that happens to spell 'u1' can
    never be misread as a tenant introducer."""
    if not message or WIRE_TOKEN not in message:
        return None
    parts = message.split(".")
    idx = 4 if parts[0] == "t1" else 0
    while idx + 1 < len(parts):
        if parts[idx] == WIRE_TOKEN:
            name = parts[idx + 1]
            return name if valid_tenant(name) else None
        # any other token (d1 deadline, future extensions) is 2 fields
        idx += 2
    return None
