"""UserStore: token-authenticated users + TTL ACL cache.

Re-expresses the reference's user subsystem (src/core/user/UserStore.cc,
UserToken.cc; cache src/meta/components/AclCache.h): user records live in
the shared transactional KV under the USER prefix, each with a bearer token;
services resolve request tokens to (uid, gid, groups, admin) server-side so
clients cannot claim arbitrary identities. The meta service authenticates
every op through an AclCache — a TTL map over the store so the hot path does
not pay one KV read per request (the reference's AclCache plays the same
role over FDB).
"""

from __future__ import annotations

import secrets
import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from tpu3fs.kv.kv import IKVEngine, ITransaction, KeyPrefix, with_transaction
from tpu3fs.meta.store import User
from tpu3fs.rpc.serde import deserialize, serialize
from tpu3fs.utils.result import Code, FsError
from tpu3fs.utils.result import err as _err


def _user_key(uid: int) -> bytes:
    return KeyPrefix.USER.value + b"U" + struct.pack(">Q", uid)


def _token_key(token: str) -> bytes:
    return KeyPrefix.USER.value + b"T" + token.encode()


def _user_scan_range() -> Tuple[bytes, bytes]:
    p = KeyPrefix.USER.value + b"U"
    return p, p + b"\xff" * 9


@dataclass
class UserRecord:
    uid: int = 0
    name: str = ""
    gid: int = 0
    groups: List[int] = field(default_factory=list)
    token: str = ""
    admin: bool = False
    root: bool = False
    # tenant binding: the ONE tenant this user's bearer token may declare
    # on the wire (``u1.<tenant>`` envelope token). "" = unbound — any
    # declared tenant passes (legacy users / internal daemons). Trailing
    # field on purpose: serde decoders default missing trailing fields,
    # so records written before the binding existed stay readable
    # (docs/tenancy.md "binding tenant ids to the user layer").
    tenant: str = ""

    def as_user(self) -> User:
        return User(uid=self.uid, gid=self.gid,
                    groups=tuple(self.groups), root=self.root)


class UserStore:
    """CRUD + token lookup over the shared KV (ref UserStore.cc)."""

    def __init__(self, engine: IKVEngine):
        self._engine = engine

    @staticmethod
    def new_token() -> str:
        return secrets.token_hex(16)

    def add_user(self, uid: int, name: str, *, gid: Optional[int] = None,
                 groups: Optional[List[int]] = None, admin: bool = False,
                 root: bool = False, token: Optional[str] = None,
                 tenant: str = "") -> UserRecord:
        rec = UserRecord(
            uid=uid, name=name, gid=uid if gid is None else gid,
            groups=list(groups or []), token=token or self.new_token(),
            admin=admin, root=root, tenant=tenant,
        )

        def op(txn: ITransaction) -> UserRecord:
            if txn.get(_user_key(uid)) is not None:
                raise _err(Code.META_EXISTS, f"uid {uid}")
            if txn.get(_token_key(rec.token)) is not None:
                raise _err(Code.META_EXISTS, "token already in use")
            txn.set(_user_key(uid), serialize(rec))
            txn.set(_token_key(rec.token), struct.pack(">Q", uid))
            return rec

        return with_transaction(self._engine, op)

    def get_user(self, uid: int) -> Optional[UserRecord]:
        def op(txn: ITransaction):
            raw = txn.get(_user_key(uid))
            return deserialize(raw, UserRecord) if raw else None

        return with_transaction(self._engine, op, read_only=True)

    def list_users(self) -> List[UserRecord]:
        def op(txn: ITransaction):
            begin, end = _user_scan_range()
            return [deserialize(p.value, UserRecord)
                    for p in txn.get_range(begin, end)]

        return with_transaction(self._engine, op, read_only=True)

    def remove_user(self, uid: int) -> bool:
        def op(txn: ITransaction) -> bool:
            raw = txn.get(_user_key(uid))
            if raw is None:
                return False
            rec = deserialize(raw, UserRecord)
            txn.clear(_user_key(uid))
            txn.clear(_token_key(rec.token))
            return True

        return with_transaction(self._engine, op)

    def rotate_token(self, uid: int) -> str:
        """Issue a fresh token, invalidating the old one (ref UserToken)."""
        token = self.new_token()

        def op(txn: ITransaction) -> str:
            raw = txn.get(_user_key(uid))
            if raw is None:
                raise _err(Code.META_NOT_FOUND, f"uid {uid}")
            rec = deserialize(raw, UserRecord)
            txn.clear(_token_key(rec.token))
            rec.token = token
            txn.set(_user_key(uid), serialize(rec))
            txn.set(_token_key(token), struct.pack(">Q", uid))
            return token

        return with_transaction(self._engine, op)

    def set_tenant(self, uid: int, tenant: str) -> UserRecord:
        """Bind (or clear, with "") the one tenant this user's token may
        declare on the wire. Takes effect within the AclCache TTL."""

        def op(txn: ITransaction) -> UserRecord:
            raw = txn.get(_user_key(uid))
            if raw is None:
                raise _err(Code.META_NOT_FOUND, f"uid {uid}")
            rec = deserialize(raw, UserRecord)
            rec.tenant = tenant
            txn.set(_user_key(uid), serialize(rec))
            return rec

        return with_transaction(self._engine, op)

    def authenticate(self, token: str) -> UserRecord:
        """token -> UserRecord; raises META_NO_PERMISSION on a bad token."""
        if not token:
            raise _err(Code.META_NO_PERMISSION, "missing token")

        def op(txn: ITransaction):
            raw = txn.get(_token_key(token))
            if raw is None:
                return None
            (uid,) = struct.unpack(">Q", raw)
            urow = txn.get(_user_key(uid))
            return deserialize(urow, UserRecord) if urow else None

        rec = with_transaction(self._engine, op, read_only=True)
        if rec is None:
            raise _err(Code.META_NO_PERMISSION, "invalid token")
        return rec


class AclCache:
    """TTL cache of token -> UserRecord (ref AclCache.h): the meta hot path
    resolves tokens from memory; misses and expiries fall through to the
    store. Invalid tokens are NOT negatively cached, so a token rotation
    takes effect immediately for the new token and within ttl for the old."""

    def __init__(self, store: UserStore, *, ttl_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        self._store = store
        self._ttl = ttl_s
        self._clock = clock
        self._lock = threading.Lock()
        self._cache: Dict[str, Tuple[float, UserRecord]] = {}

    def authenticate(self, token: str) -> UserRecord:
        now = self._clock()
        with self._lock:
            hit = self._cache.get(token)
            if hit is not None and hit[0] > now:
                return hit[1]
        rec = self._store.authenticate(token)  # raises on bad token
        with self._lock:
            self._cache[token] = (now + self._ttl, rec)
            if len(self._cache) > 4096:  # bound growth
                self._cache = {
                    k: v for k, v in self._cache.items() if v[0] > now
                }
                if len(self._cache) > 4096:
                    # all live: evict the soonest-to-expire half so the
                    # prune actually shrinks the dict (else every insert
                    # rebuilds it O(n))
                    keep = sorted(self._cache.items(),
                                  key=lambda kv: kv[1][0])[2048:]
                    self._cache = dict(keep)
        return rec

    def invalidate(self, token: Optional[str] = None) -> None:
        with self._lock:
            if token is None:
                self._cache.clear()
            else:
                self._cache.pop(token, None)
