from tpu3fs.core.user import AclCache, UserRecord, UserStore

__all__ = ["AclCache", "UserRecord", "UserStore"]
