"""Client stub factory — one place that builds service stubs from a
transport choice (the reference's stub/DI layer, src/stubs/: each service
exposes a Stub interface plus factories producing real-RPC or mock
implementations, and consumers take the factory, never a concrete stub).

    stubs = StubFactory(transport="tcp", mgmtd_addr=("host", port))
    meta = stubs.meta_client()
    storage = stubs.storage_client("client-1")
    admin = stubs.mgmtd_admin()

Transports:
  "tcp"    — Python socket transport (rpc.net.RpcClient)
  "native" — native epoll/writev transport (rpc.native_net.NativeRpcClient)
  "inmem"  — no cluster at all: StorageClientInMem + MemKV-backed MetaStore
             (unit-test doubles, ref StorageClientInMem.h / mgmtd mocks)

Every stub built by one factory shares one pooled RPC client, mirroring
the reference sharing one net::Client across stubs.
"""

from __future__ import annotations

from typing import Optional, Tuple

from tpu3fs.utils.result import Code, FsError, Status


class StubFactory:
    def __init__(
        self,
        transport: str = "tcp",
        *,
        mgmtd_addr: Optional[Tuple[str, int]] = None,
        meta_addr: Optional[Tuple[str, int]] = None,
        connect_timeout: float = 5.0,
        call_timeout: float = 30.0,
    ):
        if transport not in ("tcp", "native", "inmem"):
            raise FsError(Status(Code.INVALID_ARG,
                                 f"unknown transport {transport!r}"))
        self.transport = transport
        self.mgmtd_addr = mgmtd_addr
        self.meta_addr = meta_addr
        self._rpc = None
        self._mgmtd_cli = None
        self._inmem_kv = None
        self._timeouts = (connect_timeout, call_timeout)

    # -- shared plumbing -----------------------------------------------------
    def rpc_client(self):
        """The one pooled connection client every stub shares."""
        if self.transport == "inmem":
            raise FsError(Status(Code.INVALID_ARG,
                                 "inmem stubs have no RPC client"))
        if self._rpc is None:
            if self.transport == "native":
                from tpu3fs.rpc.native_net import NativeRpcClient

                self._rpc = NativeRpcClient(*self._timeouts)
            else:
                from tpu3fs.rpc.net import RpcClient

                self._rpc = RpcClient(*self._timeouts)
        return self._rpc

    def _mgmtd(self):
        if self._mgmtd_cli is None:
            if self.mgmtd_addr is None:
                raise FsError(Status(Code.INVALID_ARG, "mgmtd_addr required"))
            from tpu3fs.rpc.services import MgmtdRpcClient

            self._mgmtd_cli = MgmtdRpcClient(self.mgmtd_addr,
                                             self.rpc_client())
        return self._mgmtd_cli

    # -- stubs ---------------------------------------------------------------
    def mgmtd_client(self):
        """Routing/heartbeat/registration stub."""
        if self.transport == "inmem":
            raise FsError(Status(Code.INVALID_ARG,
                                 "inmem mode has no mgmtd; use the fabric"))
        return self._mgmtd()

    def mgmtd_admin(self):
        from tpu3fs.rpc.services import MgmtdAdminRpcClient

        if self.mgmtd_addr is None:
            raise FsError(Status(Code.INVALID_ARG, "mgmtd_addr required"))
        return MgmtdAdminRpcClient(self.mgmtd_addr, self.rpc_client())

    def storage_client(self, client_id: str = "stub-client", **kw):
        if self.transport == "inmem":
            from tpu3fs.client.inmem import StorageClientInMem

            return StorageClientInMem(client_id)
        from tpu3fs.client.storage_client import StorageClient
        from tpu3fs.rpc.services import RpcMessenger

        mcli = self._mgmtd()
        messenger = RpcMessenger(mcli.refresh_routing, self.rpc_client())
        return StorageClient(client_id, mcli.refresh_routing, messenger,
                             **kw)

    def file_client(self, client_id: str = "stub-client", **kw):
        from tpu3fs.client.file_io import FileIoClient

        return FileIoClient(self.storage_client(client_id, **kw))

    def meta_client(self, token: str = ""):
        if self.transport == "inmem":
            from tpu3fs.kv.mem import MemKVEngine
            from tpu3fs.meta.store import ChainAllocator, MetaStore

            if self._inmem_kv is None:
                self._inmem_kv = MemKVEngine()
            return MetaStore(self._inmem_kv, ChainAllocator(1, [1]))
        from tpu3fs.rpc.services import MetaRpcClient

        if self.meta_addr is None:
            raise FsError(Status(Code.INVALID_ARG, "meta_addr required"))
        return MetaRpcClient([self.meta_addr], self.rpc_client(),
                             token=token)

    def serving_peer_client(self, **kw):
        """Serving peerRead/fillClaim stub (tpu3fs/serving/service.py) —
        shares the factory's pooled RPC client like every other stub;
        pass ``usrbio=False`` to force sockets for non-co-located use."""
        if self.transport == "inmem":
            raise FsError(Status(Code.INVALID_ARG,
                                 "inmem mode has no serving peers"))
        from tpu3fs.serving.service import ServingPeerClient

        return ServingPeerClient(self.rpc_client(), **kw)

    def close(self) -> None:
        if self._rpc is not None:
            self._rpc.close()
            self._rpc = None
