"""Readahead prefetcher for FileIoClient: sequential-run detection plus a
bounded async prefetch cache.

The client-side analogue of the kernel page cache's readahead window over
the served read path (the reference leans on FUSE/kernel readahead for its
sequential loads; USRBIO and our RPC clients bypass the kernel, so they
need their own): when a file descriptor's reads advance sequentially, the
NEXT window is fetched in the background over the same node-grouped
batch-read pipeline, so the network/server round trip overlaps the
caller's compute instead of stalling it.

Correctness contract:
- consistency is CLIENT-LOCAL: windows are invalidated by THIS client's
  write/truncate/remove (FileIoClient calls invalidate); writes from other
  clients are not seen until the entry is evicted or invalidated — same
  staleness class as the FUSE attr cache, documented in docs/readpath.md.
- memory is bounded: a global LRU cap (max_cache_bytes) across all inodes,
  plus at most max_inflight fetches in flight; adversarial access patterns
  (random offsets, many files) never arm the window, so they cache
  nothing.
- shuffled access does not thrash: arming requires BOTH a sequential run
  (min_run) and a mostly-sequential recent history per inode (the jump
  fraction over a sliding window stays under 1/2). A shuffled/random
  reader — e.g. the dataload loader's SORTED per-batch extents, where
  occasional records happen to be file-adjacent — sees jumps dominate its
  window and never arms, so no 4 MiB windows are fetched for reads that
  will not come back. A genuinely sequential reader re-arms within ~one
  window of reads after a seek.
- QoS: a prefetch runs under the TRAFFIC CLASS of the read that armed it
  (captured at schedule time, restored in the worker via qos.tagged), so
  background-class readers cannot smuggle foreground-priced readahead.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from tpu3fs.monitor.recorder import CounterRecorder


@dataclass
class PrefetchConfig:
    window_bytes: int = 4 << 20    # bytes fetched per readahead trigger
    min_run: int = 2               # sequential reads before arming
    max_cache_bytes: int = 64 << 20
    max_inflight: int = 2
    workers: int = 2


class ReadaheadPrefetcher:
    """Sequential-run detector + bounded async window cache.

    fetch(inode, offset, size) -> bytes is the uncached read (supplied by
    FileIoClient); it runs on background workers only.
    """

    #: sliding-window length (reads) for the jump-fraction thrash guard
    _HIST_WINDOW = 16

    def __init__(self, fetch: Callable, config: Optional[PrefetchConfig] = None):
        self._fetch = fetch
        self.config = config or PrefetchConfig()
        self._mu = threading.Lock()
        # inode id -> [(start, bytes)] sorted by start (few windows/inode)
        self._windows: Dict[int, List[Tuple[int, bytes]]] = {}
        # LRU order of (inode_id, start) keys; total byte accounting
        self._lru: List[Tuple[int, int]] = []
        self._bytes = 0
        # inode id -> (next expected offset, run length)
        self._runs: Dict[int, Tuple[int, int]] = {}
        # inode id -> (jumps, total) over a sliding read window: the
        # thrash guard (see module docstring). Halved when total reaches
        # _HIST_WINDOW so old history decays instead of pinning a verdict.
        self._hist: Dict[int, Tuple[int, int]] = {}
        # invalidation generation per inode: a fetch completing after its
        # inode was invalidated must NOT install a stale window
        self._gen: Dict[int, int] = {}
        # (inode_id, start) -> (end, Event, gen): windows being fetched.
        # lookup() WAITS on a covering in-flight window instead of
        # missing — that is what turns readahead into a double buffer
        # (window K+1 fetches while the caller consumes window K); a
        # fire-and-forget cache would lose every race against a fast
        # sequential reader and readahead would never pay. The gen stamp
        # keeps STALE fetches (invalidated while in flight) from being
        # waited on or from blocking a fresh schedule of the same window.
        self._inflight: Dict[Tuple[int, int], Tuple[int, object, int]] = {}
        self._pool = None
        self.hits = CounterRecorder("prefetch.hits")
        self.misses = CounterRecorder("prefetch.misses")
        self.prefetched_bytes = CounterRecorder("prefetch.bytes")
        self.invalidations = CounterRecorder("prefetch.invalidations")

    # -- cache lookup --------------------------------------------------------
    def _lookup_locked(self, inode_id, offset, size) -> Optional[bytes]:
        for start, blob in self._windows.get(inode_id, ()):
            if start <= offset and offset + size <= start + len(blob):
                key = (inode_id, start)
                if key in self._lru:  # LRU refresh
                    self._lru.remove(key)
                    self._lru.append(key)
                lo = offset - start
                return blob[lo:lo + size]
        return None

    def lookup(self, inode_id: int, offset: int, size: int,
               wait_s: float = 30.0) -> Optional[bytes]:
        """Serve [offset, offset+size) if one cached window fully contains
        it (no partial stitching — windows are large and runs sequential,
        so split ranges are rare and fall through to the normal path). A
        covering IN-FLIGHT window is waited for: the fetch was issued a
        whole window ago, so the wait is the pipelined remainder, not a
        fresh round trip."""
        if size <= 0:
            return None
        with self._mu:
            blob = self._lookup_locked(inode_id, offset, size)
            if blob is not None:
                self.hits.add()
                return blob
            ev = None
            cur_gen = self._gen.get(inode_id, 0)
            for (ino, start), (end, event, gen) in self._inflight.items():
                if ino == inode_id and gen == cur_gen \
                        and start <= offset and offset + size <= end:
                    ev = event
                    break
        if ev is not None:
            ev.wait(wait_s)
            with self._mu:
                blob = self._lookup_locked(inode_id, offset, size)
            if blob is not None:
                self.hits.add()
                return blob
        self.misses.add()
        return None

    # -- run detection + scheduling ------------------------------------------
    def record_read(self, inode, offset: int, size: int) -> None:
        """Note a served read; arm and schedule readahead when the access
        pattern is sequential. Called AFTER the read was served (cache hit
        or not) with the caller's thread still tagged with its class."""
        if size <= 0:
            return
        cfg = self.config
        end = offset + size
        with self._mu:
            expected, run = self._runs.get(inode.id, (None, 0))
            sequential = expected == offset
            run = run + 1 if sequential else 1
            self._runs[inode.id] = (end, run)
            # thrash guard: a JUMP is any read that breaks the expected
            # sequence (the first-ever read of an inode is neither). Arm
            # only while jumps stay a strict minority of the recent
            # window — a shuffled reader whose sorted batches contain the
            # odd adjacent pair can satisfy min_run, but never this.
            jumps, total = self._hist.get(inode.id, (0, 0))
            total += 1
            if expected is not None and not sequential:
                jumps += 1
            if total >= self._HIST_WINDOW:
                jumps //= 2
                total //= 2
            self._hist[inode.id] = (jumps, total)
            if run < cfg.min_run or jumps * 2 > total:
                return
            # the next window begins where cached/in-flight coverage of
            # the current position ends — back-to-back windows, no overlap
            gen = self._gen.get(inode.id, 0)
            start = end
            for wstart, blob in self._windows.get(inode.id, ()):
                if wstart <= start < wstart + len(blob):
                    start = wstart + len(blob)
            live = 0
            for (ino, wstart), (wend, _ev, wgen) in self._inflight.items():
                if ino == inode.id and wgen != gen:
                    continue  # doomed stale fetch: ignore entirely
                live += 1
                if ino == inode.id and wstart <= start < wend:
                    start = wend
            length = getattr(inode, "length", 0) or 0
            if length and start >= length:
                return
            window = cfg.window_bytes
            if length:
                window = min(window, length - start)
            if window <= 0:
                return
            key = (inode.id, start)
            cur = self._inflight.get(key)
            if (cur is not None and cur[2] == gen) or \
                    live >= cfg.max_inflight:
                return
            import threading as _threading

            event = _threading.Event()
            self._inflight[key] = (start + window, event, gen)
        from tpu3fs.qos.core import current_class
        from tpu3fs.tenant.identity import current_tenant

        self._submit(inode, start, window, gen, current_class(),
                     current_tenant(), event)

    def _submit(self, inode, start, window, gen, tclass, tenant,
                event) -> None:
        import contextlib

        from tpu3fs.qos.core import tagged
        from tpu3fs.tenant.identity import tenant_scope

        def job() -> None:
            key = (inode.id, start)
            with self._mu:
                doomed = self._gen.get(inode.id, 0) != gen
            if doomed:
                # invalidated while queued: abort BEFORE fetching, or a
                # stale window would hog a worker at the head of the
                # queue while fresh windows starve behind it
                blob = None
            else:
                try:
                    from tpu3fs.analytics import spans as _spans

                    ctx = (tagged(tclass) if tclass is not None
                           else contextlib.nullcontext())
                    # trace DETACHED: a readahead completes long after the
                    # arming reader's op span closed — its RPCs must not
                    # append to (or re-sample) that finished trace. The
                    # TENANT is carried like the class: readahead is IO on
                    # the arming reader's behalf, so its quota pays
                    with ctx, tenant_scope(tenant), \
                            _spans.trace_scope(None):
                        blob = self._fetch(inode, start, window)
                except BaseException:
                    blob = None  # best-effort: a failed readahead serves
                    # nobody
            with self._mu:
                cur = self._inflight.get(key)
                if cur is not None and cur[1] is event:
                    # pop OUR entry only: a stale fetch must not evict a
                    # fresh reschedule of the same window
                    del self._inflight[key]
                if blob is not None and self._gen.get(inode.id, 0) == gen:
                    self._install_locked(inode.id, start, bytes(blob))
                    installed = True
                else:
                    installed = False  # invalidated while in flight: drop
            event.set()  # AFTER install: waiters re-check and hit
            if installed:
                self.prefetched_bytes.add(window)

        pool = self._ensure_pool()
        try:
            pool.submit(job, block=False)
        except Exception:
            with self._mu:  # queue full: skip this window
                key = (inode.id, start)
                cur = self._inflight.get(key)
                if cur is not None and cur[1] is event:
                    del self._inflight[key]
            event.set()

    def _ensure_pool(self):
        with self._mu:
            if self._pool is None:
                from tpu3fs.utils.executor import WorkerPool

                self._pool = WorkerPool("prefetch",
                                        num_workers=self.config.workers,
                                        queue_cap=16)
            return self._pool

    def _install_locked(self, inode_id: int, start: int, blob: bytes) -> None:
        wins = self._windows.setdefault(inode_id, [])
        wins.append((start, blob))
        wins.sort(key=lambda w: w[0])
        key = (inode_id, start)
        if key in self._lru:
            self._lru.remove(key)
        self._lru.append(key)
        self._bytes += len(blob)
        while self._bytes > self.config.max_cache_bytes and self._lru:
            old_ino, old_start = self._lru.pop(0)
            old = self._windows.get(old_ino, [])
            for i, (s, b) in enumerate(old):
                if s == old_start:
                    self._bytes -= len(b)
                    del old[i]
                    break
            if not old:
                self._windows.pop(old_ino, None)

    # -- invalidation --------------------------------------------------------
    def invalidate(self, inode_id: int) -> None:
        """Drop every cached/in-flight window of the inode (called on
        write/truncate/remove through this client)."""
        with self._mu:
            self._gen[inode_id] = self._gen.get(inode_id, 0) + 1
            self._runs.pop(inode_id, None)
            self._hist.pop(inode_id, None)
            wins = self._windows.pop(inode_id, None)
            if wins:
                for start, blob in wins:
                    self._bytes -= len(blob)
                    try:
                        self._lru.remove((inode_id, start))
                    except ValueError:
                        pass
                self.invalidations.add()

    def invalidate_all(self) -> None:
        with self._mu:
            for ino in list(self._windows):
                self._gen[ino] = self._gen.get(ino, 0) + 1
            self._windows.clear()
            self._lru.clear()
            self._runs.clear()
            self._hist.clear()
            self._bytes = 0

    def cached_bytes(self) -> int:
        with self._mu:
            return self._bytes

    def close(self) -> None:
        with self._mu:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)
