"""In-memory StorageClient double (ref src/client/storage/
StorageClientInMem.h:23-80): the full client surface backed by plain
per-chain dicts — no chains, no sockets, no engines. Consumers of the
client interface (FileIoClient, meta length settlement, tools) unit-test
against this double without standing up a fabric, exactly how the
reference uses its InMem client in meta unit tests.

Semantics mirrored from the real client where they matter to consumers:
chunk-granular storage keyed by (chain_id, chunk_id), offset writes extend
chunks, reads clamp to the written length, remove/truncate/stat/space are
chunk-table operations. Chain/target routing, channels and retries do not
exist here by design — that is the point of the double.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from tpu3fs.ops.crc32c import crc32c
from tpu3fs.storage.craq import ReadReply, UpdateReply
from tpu3fs.storage.types import ChunkId, Checksum, SpaceInfo
from tpu3fs.utils.result import Code


class StorageClientInMem:
    """Drop-in for StorageClient in consumers that only move bytes."""

    def __init__(self, client_id: str = "inmem", *,
                 capacity: int = 1 << 40):
        self.client_id = client_id
        self._chunks: Dict[Tuple[int, Tuple], bytearray] = {}
        self._vers: Dict[Tuple[int, Tuple], int] = {}
        self._mu = threading.Lock()
        self._capacity = capacity

    # -- helpers -------------------------------------------------------------
    @staticmethod
    def _key(chain_id: int, chunk_id: ChunkId) -> Tuple[int, Tuple]:
        return (chain_id, (chunk_id.file_id, chunk_id.index))

    def _chain(self, chain_id: int):
        """Every chain exists and is a plain CR chain (consumers probe
        is_ec through this; the double has no EC plane)."""
        from tpu3fs.mgmtd.types import ChainInfo

        return ChainInfo(chain_id=chain_id, chain_version=1, targets=[])

    def _reply(self, data: bytes) -> ReadReply:
        return ReadReply(Code.OK, data=data,
                         checksum=Checksum(value=crc32c(data)))

    # -- writes --------------------------------------------------------------
    def write_chunk(self, chain_id: int, chunk_id: ChunkId, offset: int,
                    data: bytes, *, chunk_size: int = 1 << 20) -> UpdateReply:
        if offset + len(data) > chunk_size:
            return UpdateReply(Code.INVALID_ARG, message="write past chunk")
        key = self._key(chain_id, chunk_id)
        with self._mu:
            buf = self._chunks.setdefault(key, bytearray())
            if len(buf) < offset + len(data):
                buf.extend(b"\x00" * (offset + len(data) - len(buf)))
            buf[offset:offset + len(data)] = data
            ver = self._vers.get(key, 0) + 1
            self._vers[key] = ver
            crc = crc32c(bytes(buf))
        return UpdateReply(Code.OK, update_ver=ver, commit_ver=ver,
                           checksum=Checksum(value=crc))

    def batch_write(self, writes: List[Tuple[int, ChunkId, int, bytes]], *,
                    chunk_size: int = 1 << 20) -> List[UpdateReply]:
        return [self.write_chunk(c, ck, off, d, chunk_size=chunk_size)
                for c, ck, off, d in writes]

    def remove_chunk(self, chain_id: int, chunk_id: ChunkId) -> bool:
        key = self._key(chain_id, chunk_id)
        with self._mu:
            self._vers.pop(key, None)
            return self._chunks.pop(key, None) is not None

    # -- reads ---------------------------------------------------------------
    def read_chunk(self, chain_id: int, chunk_id: ChunkId, offset: int = 0,
                   length: int = -1) -> ReadReply:
        key = self._key(chain_id, chunk_id)
        with self._mu:
            buf = self._chunks.get(key)
            if buf is None:
                return ReadReply(Code.CHUNK_NOT_FOUND)
            end = len(buf) if length < 0 else min(len(buf), offset + length)
            data = bytes(buf[offset:end])
        return self._reply(data)

    def batch_read(self, reqs) -> List[ReadReply]:
        return [self.read_chunk(r.chain_id, r.chunk_id, r.offset, r.length)
                for r in reqs]

    # -- metadata-facing surface ---------------------------------------------
    def query_last_chunk(self, chain_id: int, file_id: int
                         ) -> Tuple[int, int]:
        """(last index, last chunk's byte length); (-1, 0) when empty."""
        with self._mu:
            idxs = [ck[1] for (c, ck) in self._chunks
                    if c == chain_id and ck[0] == file_id]
            if not idxs:
                return -1, 0
            last = max(idxs)
            buf = self._chunks[(chain_id, (file_id, last))]
            return last, len(buf)

    def remove_file_chunks(self, chain_id: int, file_id: int) -> int:
        with self._mu:
            keys = [k for k in self._chunks
                    if k[0] == chain_id and k[1][0] == file_id]
            for k in keys:
                del self._chunks[k]
                self._vers.pop(k, None)
            return len(keys)

    def truncate_file_chunks(self, chain_id: int, file_id: int,
                             last_index: int, last_length: int) -> int:
        removed = 0
        with self._mu:
            for k in list(self._chunks):
                if k[0] != chain_id or k[1][0] != file_id:
                    continue
                if k[1][1] > last_index:
                    del self._chunks[k]
                    self._vers.pop(k, None)
                    removed += 1
                elif k[1][1] == last_index:
                    del self._chunks[k][last_length:]
        return removed

    def space_info(self) -> SpaceInfo:
        with self._mu:
            used = sum(len(b) for b in self._chunks.values())
            count = len(self._chunks)
        return SpaceInfo(capacity=self._capacity, used=used,
                         chunk_count=count)

    def close(self) -> None:
        pass
